"""Zone maps: per-segment, per-column min/max/null_count/NDV.

Built once at segment build time over ALL physical rows of the
segment's range (live and dead MVCC versions alike), so they bound any
visibility subset a scan can see — a pruned segment is provably
row-free for the predicate under every read timestamp, delta overlay,
or delete pattern. NULL rows never satisfy a comparison (SQL UNKNOWN is
filtered), so min/max over the valid slots is sufficient.

Bound collection (`collect_prune_bounds`) mirrors the comparison
semantics of `expression/compiler.py` exactly:

  * non-DECIMAL kinds compare raw device reprs (dates as day counts,
    strings as dictionary codes — the binder already lowered string
    predicates to integer code compares), so literal values apply to
    zone min/max directly;
  * DECIMAL comparisons happen at the max of both scales; the bound
    carries the exact python-int rescale factors for each side, so an
    18-digit decimal prunes without a float round trip;
  * FLOAT literals compare exactly (python int-vs-float comparison is
    exact, no 2^53 truncation).

Anything the collector does not understand contributes no bound —
pruning degrades to "scan it", never to a wrong skip.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from tidb_tpu.types import TypeKind

__all__ = ["ZoneMap", "Bound", "build_zone_map", "collect_prune_bounds",
           "segment_pruned"]


@dataclass(frozen=True)
class ZoneMap:
    rows: int
    null_count: int
    min: Optional[object] = None   # python int/float over valid slots
    max: Optional[object] = None
    ndv: int = 0                   # exact distinct count at build time


def build_zone_map(data: np.ndarray, valid: np.ndarray) -> ZoneMap:
    n = len(data)
    vals = data[valid]
    if len(vals) == 0:
        return ZoneMap(rows=n, null_count=n)
    if data.dtype.kind == "f":
        mn, mx = float(vals.min()), float(vals.max())
    else:
        mn, mx = int(vals.min()), int(vals.max())
    return ZoneMap(rows=n, null_count=n - len(vals), min=mn, max=mx,
                   ndv=int(len(np.unique(vals))))


@dataclass(frozen=True)
class Bound:
    """One zone-consultable conjunct of a pushed-down filter.

    kind: "eq" | "lt" | "le" | "gt" | "ge" | "in" | "isnull"
        | "notnull" | "never" ("never" = the conjunct is statically
        row-free, e.g. a NULL literal comparison: every segment prunes).
    `col_scale_mul` rescales zone min/max into the comparison space
    (DECIMAL alignment); `value` is already in that space.
    """

    col: str
    kind: str
    value: object = None
    values: Tuple = ()
    col_scale_mul: int = 1


_CMP_OPS = {"eq", "lt", "le", "gt", "ge"}
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}


def _literal_in_cmp_space(col_type, lit_type, value):
    """(value in comparison space, column rescale factor), or None when
    the pair doesn't compare by plain device-repr order.

    Float literals against int64-backed columns (INT and DECIMAL alike)
    contribute NO bound: the device compares those in float64 (lossy
    past 2^53) while zone maps hold exact python ints — and a DECIMAL
    rescale can push even a small literal past 2^53 — so the two
    orderings can disagree, and a bound that disagrees with the
    executor is a wrong skip. Float-vs-float stays: both sides are the
    same float64s the device compares."""
    ck, lk = col_type.kind, lit_type.kind
    if ck == TypeKind.FLOAT:
        if lk == TypeKind.DECIMAL:
            # DECIMAL literal vs FLOAT column: the compiler aligns on
            # the decimal scale (float side multiplied by 10**scale,
            # literal stays the scaled int, compared in float64) — the
            # bound must live in that same space, so the zone min/max
            # get the 10**scale factor and the scaled literal is cast
            # to the float64 the device promotes it to
            return float(value), 10 ** lit_type.scale
        return float(value), 1
    if isinstance(value, (float, np.floating)):
        return None
    if ck == TypeKind.DECIMAL or lk == TypeKind.DECIMAL:
        cs = col_type.scale if ck == TypeKind.DECIMAL else 0
        ls = lit_type.scale if lk == TypeKind.DECIMAL else 0
        s = max(cs, ls)
        return int(value) * (10 ** (s - ls)), 10 ** (s - cs)
    if isinstance(value, (bool, np.bool_)):
        return int(value), 1
    if isinstance(value, (int, np.integer, float)):
        v = int(value)
        if not (-(1 << 63) <= v < (1 << 63)):
            # the executor can't even build such a literal (int64
            # overflow at compile); pruning must not silently answer a
            # query whose raw path errors — no bound, same behavior
            # either way
            return None
        return v, 1
    return None


def collect_prune_bounds(cond, uid_map) -> Tuple[Bound, ...]:
    """Extract zone-consultable bounds from the AND-tree of a pushed
    filter. `uid_map`: ColumnRef name -> (storage column name, SQLType).
    Conjuncts that aren't simple col-vs-literal shapes are skipped."""
    from tidb_tpu.expression.expr import Call, ColumnRef, InList, Literal

    out = []

    def col_of(e):
        hit = uid_map.get(e.name) if isinstance(e, ColumnRef) else None
        return hit

    def visit(e):
        if isinstance(e, Call) and e.op == "and":
            for a in e.args:
                visit(a)
            return
        if isinstance(e, Call) and e.op in _CMP_OPS and len(e.args) == 2:
            a, b = e.args
            op = e.op
            if isinstance(a, Literal) and isinstance(b, ColumnRef):
                a, b = b, a
                op = _FLIP[op]
            hit = col_of(a)
            if hit is None or not isinstance(b, Literal):
                return
            name, ctype = hit
            if b.value is None:
                # col <op> NULL is UNKNOWN for every row: statically
                # row-free, prune everything (the delta path still
                # scans and yields nothing)
                out.append(Bound(col=name, kind="never"))
                return
            conv = _literal_in_cmp_space(ctype, b.type_, b.value)
            if conv is None:
                return
            v, mul = conv
            out.append(Bound(col=name, kind=op, value=v, col_scale_mul=mul))
            return
        if isinstance(e, InList) and not e.negated:
            hit = col_of(e.arg)
            if hit is None:
                return
            name, ctype = hit
            # mirror the compiler exactly: it casts the literal list to
            # the column's dtype before comparing (np.asarray(values,
            # dtype=arg.np_dtype)), so the bound must hold the CAST
            # values, not the raw python ones
            vals = [v for v in e.values if v is not None]
            if not vals or not all(
                    isinstance(v, (int, np.integer, float, np.floating))
                    for v in vals):
                return
            try:
                cast = np.asarray(vals, dtype=e.arg.type_.np_dtype)
            except (OverflowError, ValueError):
                return
            out.append(Bound(col=name, kind="in",
                             values=tuple(cast.tolist())))
            return
        if isinstance(e, Call) and e.op in ("is_null", "is_not_null") \
                and len(e.args) == 1:
            hit = col_of(e.args[0])
            if hit is not None:
                out.append(Bound(
                    col=hit[0],
                    kind="isnull" if e.op == "is_null" else "notnull"))

    if cond is not None:
        visit(cond)
    return tuple(out)


def segment_pruned(zmaps: Dict[str, ZoneMap], bounds) -> bool:
    """True when at least one bound proves the segment row-free for the
    whole AND of the pushed filter."""
    for b in bounds:
        if b.kind == "never":
            return True
        z = zmaps.get(b.col)
        if z is None:
            continue
        if b.kind == "isnull":
            if z.null_count == 0:
                return True
            continue
        if z.min is None:  # every row NULL: no comparison ever passes
            return True    # (notnull included: there is no non-NULL row)
        if b.kind == "notnull":
            continue
        mn, mx = z.min * b.col_scale_mul, z.max * b.col_scale_mul
        if b.kind == "in":
            if all(v < mn or v > mx for v in b.values):
                return True
            continue
        v = b.value
        if ((b.kind == "eq" and (v < mn or v > mx))
                or (b.kind == "ge" and mx < v)
                or (b.kind == "gt" and mx <= v)
                or (b.kind == "le" and mn > v)
                or (b.kind == "lt" and mn >= v)):
            return True
    return False
