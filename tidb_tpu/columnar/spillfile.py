"""Disk-backed segment payloads.

One ``.npz`` per spilled segment holding every column's encoded data
and validity arrays. Encodings, zone maps and the row range stay in
memory (they are tiny and pruning must keep working while the payload
is cold); only the bulk arrays round-trip through disk. Files are
written once — segment payloads are immutable until the store's epoch
invalidates them — so a re-evicted segment that already has a file
just drops its arrays.
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["SegmentSpillFile", "make_spill_dir"]


def make_spill_dir(spill_dir: Optional[str]) -> str:
    """A fresh private directory for one store's spill files, under the
    configured tidb_tpu_columnar_spill_dir (system tmp when unset)."""
    return tempfile.mkdtemp(prefix="tidb_tpu_seg_", dir=spill_dir or None)


class SegmentSpillFile:
    """The on-disk form of one segment's encoded payload."""

    def __init__(self, dir_: str, tag: str):
        self.path = os.path.join(dir_, f"{tag}.npz")
        self.nbytes = 0

    @property
    def written(self) -> bool:
        return self.nbytes > 0

    def save(self, cols: List[Tuple[str, np.ndarray, np.ndarray]]) -> int:
        """Write (name, data, valid) triples; returns bytes written.
        Array keys are positional (d0/v0, ...) so column names never
        need filesystem escaping; the caller re-zips by its own column
        order, which is immutable for a segment's lifetime."""
        payload = {}
        total = 0
        for i, (_name, data, valid) in enumerate(cols):
            payload[f"d{i}"] = data
            payload[f"v{i}"] = valid
            total += data.nbytes + valid.nbytes
        np.savez(self.path, **payload)
        self.nbytes = total
        return total

    def load(self, n_cols: int) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Read back the positional (data, valid) pairs."""
        with np.load(self.path) as z:
            return [(z[f"d{i}"], z[f"v{i}"]) for i in range(n_cols)]

    def close(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self.nbytes = 0
