"""Background delta->segment compaction (ISSUE 17).

One process-wide worker thread watches for stores whose appended delta
crossed ``tidb_tpu_segment_delta_rows`` and rebuilds their trailing
segments OFF the statement path. The statement-side contract lives in
``SegmentStore._refresh_locked``: when compaction is on, crossing the
delta threshold marks the store pending and returns without building —
scans keep serving the current segment generation plus the raw-merge
delta (bounded staleness of the *encoded* view only; visibility is
MVCC-exact either way because the delta is always merged at scan time).

Worker protocol per job (PR 8's refcount/retire discipline, leaf-lock
rule intact):

  1. SNAPSHOT under ``store._lock``: epoch / generation / covered and
     the rebuild range. Nothing is built under the lock.
  2. BUILD outside every lock. Safe because ``table.n`` is published
     only after the rows below it are fully written, and row payloads
     are immutable once published (MVCC updates append new versions;
     begin/end timestamps are read fresh at stage time, never baked
     into segments). A GC/TRUNCATE/re-encode racing the build bumps
     ``data_epoch`` — detected at cutover, the build is discarded.
  3. CUTOVER under ``store._lock``: install only if the snapshot still
     describes the store (epoch, generation, covered unchanged); the
     trailing partial segment retires through ``_discard_locked`` so a
     scan that planned it keeps its spill file alive.

Backpressure: the job queue is bounded. ``submit`` refuses when the
queue is full or the worker died, and the caller degrades — typed,
counted as ``tidb_tpu_compaction_total{outcome="inline_fallback"}`` —
to today's inline rebuild on the statement path.

The worker never holds its own condition lock while taking a store
lock (jobs pop first, compact after), so no lock-order edge exists
between the queue and any store.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from tidb_tpu.utils.failpoint import inject

__all__ = ["CompactionWorker", "submit", "default_worker",
           "reset_for_tests", "MAX_QUEUED"]

# bounded job queue: one entry per store awaiting rebuild. Deep queues
# only delay the inline fallback the caller would prefer once the
# worker is this far behind.
MAX_QUEUED = 8


class CompactionWorker:
    """The background rebuild thread plus its bounded job queue."""

    def __init__(self, max_queued: int = MAX_QUEUED):
        self.max_queued = max_queued
        self._cv = threading.Condition()
        self._pending: List[object] = []   # stores awaiting compaction
        self._busy = 0                     # jobs popped, not yet finished
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- submission (statement path) ------------------------------------

    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and not self._stop

    def submit(self, store) -> bool:
        """Queue `store` for a background rebuild; False when the queue
        is full or the worker is dead (caller falls back inline). Never
        blocks — this runs on the statement path."""
        with self._cv:
            if self._stop:
                return False
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="tidb-tpu-compaction",
                    daemon=True)
                self._thread.start()
            elif not self._thread.is_alive():
                return False
            if len(self._pending) >= self.max_queued:
                return False
            self._pending.append(store)
            self._cv.notify()
        return True

    # -- worker loop -----------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
                store = self._pending.pop(0)
                self._busy += 1
            try:
                outcome, nbytes = self._compact(store)
            except BaseException:
                # a job must never kill the thread silently mid-flight;
                # the store's pending flag was cleared (or will fail
                # closed at the next inline fallback)
                outcome, nbytes = "failed", 0
            finally:
                with self._cv:
                    self._busy -= 1
                    self._cv.notify_all()
            from tidb_tpu.utils.metrics import (
                COMPACTION_BYTES,
                COMPACTION_TOTAL,
            )

            COMPACTION_TOTAL.inc(outcome=outcome)
            if nbytes:
                COMPACTION_BYTES.inc(nbytes)

    @staticmethod
    def _compact(store):
        """One store's rebuild: snapshot -> build outside locks ->
        validated cutover. Returns (outcome, installed_bytes)."""
        from tidb_tpu.columnar.store import _build_segment

        t = store.table
        with store._lock:
            epoch = getattr(t, "data_epoch", 0)
            gen = store.generation
            covered0 = store.covered
            seg_rows = store.segment_rows
            if epoch != store.built_epoch:
                # epoch moved while queued: the next statement-path
                # refresh owns the drop-all; building now would encode
                # rows about to be discarded
                store._compact_pending = False
                return "discarded", 0
            start = covered0
            if store.segments and store.segments[-1].rows < seg_rows:
                start = store.segments[-1].start
            n = t.n
        if n <= start:
            with store._lock:
                store._compact_pending = False
            return "discarded", 0
        built = []
        try:
            inject("compact.rebuild")
            for s in range(start, n, seg_rows):
                e = min(s + seg_rows, n)
                built.append(_build_segment(t, s, e))
        except BaseException:
            with store._lock:
                store._compact_pending = False
            return "failed", 0
        nbytes = sum(g.nbytes for g in built)
        with store._lock:
            ok = (getattr(t, "data_epoch", 0) == epoch
                  and store.built_epoch == epoch
                  and store.generation == gen
                  and store.covered == covered0)
            if ok:
                # same install sequence as the inline rebuild: the
                # trailing partial retires if a planned scan holds it
                if store.segments and store.segments[-1].rows < seg_rows:
                    last = store.segments.pop()
                    store._discard_locked(last)
                    store.covered = last.start
                for seg in built:
                    seg.seq = store._seg_seq
                    store._seg_seq += 1
                    store.segments.append(seg)
                    store.covered = seg.end
                store._stats_view = None
            store._compact_pending = False
        if not ok:
            return "discarded", 0
        return "background", nbytes

    # -- test/lifecycle hooks ---------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the queue is empty and no job is in flight (or
        the worker died / `timeout` expired). Test determinism hook."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cv:
            while self._pending or self._busy:
                t = self._thread
                if t is None or not t.is_alive():
                    return not (self._pending or self._busy)
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._pending = []
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10)


_worker_lock = threading.Lock()
_worker: Optional[CompactionWorker] = None


def default_worker() -> CompactionWorker:
    global _worker
    with _worker_lock:
        if _worker is None:
            _worker = CompactionWorker()
        return _worker


def submit(store) -> bool:
    """Queue `store` on the process worker; on refusal (backpressure /
    dead worker) degrade to the inline statement-path rebuild, typed
    and counted."""
    if default_worker().submit(store):
        return True
    store.compact_inline_fallback()
    return False


def reset_for_tests() -> None:
    """Stop and forget the process worker (chaos tests restart it)."""
    global _worker
    with _worker_lock:
        w, _worker = _worker, None
    if w is not None:
        w.stop()
