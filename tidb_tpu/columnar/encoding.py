"""Per-column segment encodings.

Encoding selection is value-driven, per segment, per column:

  * integer-backed device reprs (INT, DECIMAL scaled ints, DATE day
    counts, DATETIME/TIME micros, ENUM/SET ordinals, and dictionary
    codes for STRING/JSON — the dictionary itself lives on the table)
    encode **frame-of-reference**: store ``value - min`` in the
    narrowest signed dtype that holds the range (int8/int16/int32),
    falling back to raw int64 when the range spans more than 31 bits
    (the full-int64-range case must round-trip exactly);
  * FLOAT and BOOL store raw (float narrowing is lossy; bool is
    already one byte).

NULL slots store 0 and are carried by the validity mask, exactly like
the uncompressed path. Decoding is ``ref + stored`` — cheap enough to
fuse into the jitted scan program (`ops/segment_scan.py`), so the
device sees full-width columns while the host→device transfer moves
the narrow bytes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from tidb_tpu.types import SQLType, TypeKind

__all__ = ["Encoding", "encode_column", "decode_host", "INT_BACKED_KINDS"]

# kinds whose device repr is an int64-family array eligible for FoR
INT_BACKED_KINDS = frozenset({
    TypeKind.INT, TypeKind.DECIMAL, TypeKind.DATE, TypeKind.DATETIME,
    TypeKind.TIME, TypeKind.ENUM, TypeKind.SET, TypeKind.STRING,
    TypeKind.JSON,
})

_NARROW = ((np.int8, 1 << 7), (np.int16, 1 << 15), (np.int32, 1 << 31))


@dataclass(frozen=True)
class Encoding:
    """Static descriptor of one encoded column payload."""

    kind: str          # "for" | "raw"
    dtype: str         # numpy dtype name of the stored array
    ref: int = 0       # frame-of-reference base (device-repr units)


def encode_column(data: np.ndarray, valid: np.ndarray,
                  type_: SQLType) -> Tuple[Encoding, np.ndarray]:
    """(encoding, stored array) for one column slice. The stored array
    is always a fresh buffer (segments must not alias table storage —
    the table may grow/rewrite its buffers later)."""
    if type_.kind not in INT_BACKED_KINDS or len(data) == 0:
        return Encoding("raw", str(data.dtype)), np.array(data, copy=True)
    vals = data[valid]
    if len(vals) == 0:
        # all-NULL: nothing to reference; one byte per row of zeros
        return (Encoding("for", "int8", 0),
                np.zeros(len(data), dtype=np.int8))
    mn = int(vals.min())
    mx = int(vals.max())
    span = mx - mn  # python ints: immune to int64 overflow
    for dt, lim in _NARROW:
        if span < lim:
            shifted = np.where(valid, data, mn).astype(np.int64) - np.int64(mn)
            return Encoding("for", np.dtype(dt).name, mn), shifted.astype(dt)
    return Encoding("raw", str(data.dtype)), np.array(data, copy=True)


def decode_host(enc: Encoding, stored: np.ndarray,
                type_: Optional[SQLType] = None) -> np.ndarray:
    """Host-side decode (the test oracle and spill re-materialization
    sanity check; the hot path decodes on device inside the fused scan
    program). NULL slots decode to the reference value — callers mask
    them via the validity array like every other read path."""
    if enc.kind == "raw":
        return stored
    out_dtype = type_.np_dtype if type_ is not None else np.int64
    return stored.astype(np.int64) + np.int64(enc.ref) \
        if out_dtype == np.int64 \
        else (stored.astype(np.int64) + np.int64(enc.ref)).astype(out_dtype)
