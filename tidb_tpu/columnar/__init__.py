"""Columnar segment store (ISSUE 8).

The layer between host table storage (`storage/table.py` /
`storage/delta.py`) and the executors: a table's physical rows are
sliced into fixed-capacity immutable **segments**, each holding

  * per-column ENCODED payloads — dictionary codes (strings are already
    int32 codes via `chunk/dictionary.py`) and integer-backed kinds
    (INT/DECIMAL/DATE/DATETIME/TIME/ENUM/SET) stored frame-of-reference
    with the narrowest bit width that holds the value range, floats and
    bools raw — so the bytes staged host→device shrink with the data,
    not just host RSS (`encoding.py`);
  * per-column **zone maps** (min/max/null_count/NDV estimate) consulted
    by scan planning against pushed-down range/equality predicates to
    skip whole segments before any staging (`zonemap.py`), and doubling
    as the planner's fallback statistics (`statistics.zone_map_stats`);
  * a spill lifecycle: cold segments serialize to disk
    (`spillfile.py`) under memory pressure through the statement-
    anchored MemTracker spill protocol and re-materialize on demand
    (`store.py`), so a budget-capped scan completes by evicting instead
    of dying.

Delta rows — physical rows appended after the last segment build
(inserts and MVCC update versions) — stay in the existing raw scan path
and merge at scan time; MVCC visibility (`begin_ts`/`end_ts`) is always
read live from the table, so deletes and txn markers need no segment
maintenance. In-place rewrites of existing rows (dictionary growth
re-encodes, GC compaction, MODIFY/ADD/DROP COLUMN, TRUNCATE) bump
`Table.data_epoch`, which invalidates the whole store; DML past
`tidb_tpu_segment_delta_rows` appended rows triggers an incremental
coverage extension with fresh zone maps.
"""

from tidb_tpu.columnar.store import (  # noqa: F401
    SegmentStore,
    build_for_result,
    scan_counts,
    store_for,
)
from tidb_tpu.columnar.zonemap import collect_prune_bounds  # noqa: F401

__all__ = ["SegmentStore", "store_for", "build_for_result", "scan_counts",
           "collect_prune_bounds"]
