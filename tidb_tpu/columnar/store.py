"""The per-table segment store and its scan/spill lifecycle.

One `SegmentStore` hangs off each (base) `storage.table.Table` that has
grown past one segment of rows. It owns an ordered list of immutable
`Segment`s covering physical rows ``[0, covered)``; rows past
`covered` are the delta — scanned through the existing raw slice path
and merged at scan time. Stale stores rebuild lazily:

  * ``table.data_epoch`` moved (dictionary re-encode, GC compaction,
    column DDL, TRUNCATE): every segment is discarded and rebuilt;
  * appended delta reached ``tidb_tpu_segment_delta_rows``: coverage
    extends incrementally (the trailing partial segment, if any, is
    rebuilt to full size) with fresh zone maps. The plan cache's
    stats-freshness invalidation already keys on ``table.version``, so
    cached plans re-verify against the refreshed maps for free.

Memory protocol (the PR 7 statement-anchored MemTracker contract):
scans charge each segment's encoded bytes to their statement tracker as
they touch it, through a `ScanPin` registered as a spillable on the
statement's spill root. Under pressure the tracker calls back into
``ScanPin.spill``, which evicts this statement's least-recently-touched
unpinned segment to a `SegmentSpillFile` — another statement's
pressure never evicts a segment the current chunk is decoding (pin
counts), and re-materialization reloads from disk on the next touch.

Locking: ONE leaf lock (`SegmentStore._lock`) guards segment list and
residency state. It is never held across tracker.consume() (which can
re-enter ScanPin.spill) — touch pins under the lock, releases it, then
charges.
"""

from __future__ import annotations

import shutil
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from tidb_tpu.analysis import sanitizer as _san
from tidb_tpu.columnar.encoding import Encoding, encode_column
from tidb_tpu.columnar.spillfile import SegmentSpillFile, make_spill_dir
from tidb_tpu.columnar.zonemap import ZoneMap, build_zone_map, segment_pruned

__all__ = ["Segment", "SegmentStore", "ScanPin", "store_for",
           "build_for_result", "scan_counts", "compact_counts"]

# smallest table (rows) that earns a store at all; matches the sysvar
# floor so tiny unit-test tables stay on the raw path with zero overhead
MIN_STORE_ROWS = 1024

# -- per-thread scan counters (EXPLAIN ANALYZE / slow-log deltas) -----------

_tls = threading.local()


def _count_scan(scanned: int, pruned: int) -> None:
    _tls.scanned = getattr(_tls, "scanned", 0) + scanned
    _tls.pruned = getattr(_tls, "pruned", 0) + pruned
    from tidb_tpu.utils.metrics import (
        SCAN_SEGMENTS_PRUNED_TOTAL,
        SCAN_SEGMENTS_SCANNED_TOTAL,
    )

    if scanned:
        SCAN_SEGMENTS_SCANNED_TOTAL.inc(scanned)
    if pruned:
        SCAN_SEGMENTS_PRUNED_TOTAL.inc(pruned)


def scan_counts() -> Tuple[int, int]:
    """Cumulative (scanned, pruned) on this thread; the session diffs
    around each statement for the slow log."""
    return (getattr(_tls, "scanned", 0), getattr(_tls, "pruned", 0))


def _count_compact_wait(seconds: float, nbytes: int) -> None:
    _tls.compact_wait = getattr(_tls, "compact_wait", 0.0) + seconds
    _tls.compact_bytes = getattr(_tls, "compact_bytes", 0) + nbytes


def compact_counts() -> Tuple[float, int]:
    """Cumulative (inline rebuild wait seconds, rebuilt bytes) paid by
    THIS thread's statements; the session diffs around each statement
    so write-induced scan stalls surface as ``compaction_wait_ms`` in
    EXPLAIN ANALYZE and the slow log instead of vanishing into scan
    time (ISSUE 17)."""
    return (getattr(_tls, "compact_wait", 0.0),
            getattr(_tls, "compact_bytes", 0))


class Segment:
    """An immutable encoded slice of a table's physical rows.

    `cols` maps column name -> (Encoding, data, valid); data/valid are
    None while the payload is spilled. Zone maps and encodings stay
    resident regardless — pruning must work on cold segments.

    `refs` counts ScanPins whose scan PLANNED this segment (bumped in
    plan_scan, dropped at pin close): a store invalidation must not
    close a referenced segment's spill file out from under an in-flight
    scan — it RETIRES the segment instead, and the last release frees
    it. `pins` counts in-flight chunk stagings/evictions: a pinned
    segment's arrays are never dropped."""

    __slots__ = ("start", "end", "names", "encs", "data", "valid",
                 "zmaps", "nbytes", "pins", "refs", "retired",
                 "last_touch", "spill", "seq")

    def __init__(self, start: int, end: int, names: List[str],
                 encs: List[Encoding], data: List[np.ndarray],
                 valid: List[np.ndarray], zmaps: Dict[str, ZoneMap]):
        self.start = start
        self.end = end
        self.names = names
        self.encs = encs
        self.data: Optional[List[np.ndarray]] = data
        self.valid: Optional[List[np.ndarray]] = valid
        self.zmaps = zmaps
        self.nbytes = int(sum(d.nbytes + v.nbytes
                              for d, v in zip(data, valid)))
        self.pins = 0
        self.refs = 0
        self.retired = False
        self.last_touch = 0
        self.spill: Optional[SegmentSpillFile] = None
        # store-assigned unique id: the spill file tag. A retired old-
        # generation segment and its same-row-range successor must
        # never share a path (the retiree's file outlives the rebuild).
        self.seq = 0

    @property
    def rows(self) -> int:
        return self.end - self.start

    @property
    def resident(self) -> bool:
        return self.data is not None

    def col(self, name: str) -> Tuple[Encoding, np.ndarray, np.ndarray]:
        i = self.names.index(name)
        return self.encs[i], self.data[i], self.valid[i]


def _build_segment(table, start: int, end: int) -> Segment:
    names, encs, data, valid, zmaps = [], [], [], [], {}
    for c in table.schema.columns:
        d = table.data[c.name][start:end]
        v = table.valid[c.name][start:end]
        enc, stored = encode_column(d, v, c.type_)
        names.append(c.name)
        encs.append(enc)
        data.append(stored)
        valid.append(np.array(v, copy=True))
        zmaps[c.name] = build_zone_map(d, v)
    return Segment(start, end, names, encs, data, valid, zmaps)


class SegmentStore:
    def __init__(self, table, segment_rows: int,
                 spill_dir: Optional[str] = None):
        self.table = table
        self.segment_rows = max(int(segment_rows), MIN_STORE_ROWS)
        self.delta_rows = self.segment_rows
        self.spill_dir = spill_dir or None
        self.segments: List[Segment] = []
        self.covered = 0
        self.built_epoch = getattr(table, "data_epoch", 0)
        self.generation = 0          # bumps on every full rebuild
        # background compaction (ISSUE 17): follows the latest caller's
        # tidb_tpu_compaction through store_for; while a job is pending
        # the non-force refresh keeps serving the current generation
        self.compaction_on = False
        self._compact_pending = False
        # CLUSTER BY ordered compaction (ISSUE 18): True while one
        # statement thread runs the physical re-sort — concurrent
        # planners skip instead of double-permuting
        self._recluster_busy = False
        self._touch_seq = 0
        self._seg_seq = 0            # unique per segment: spill file tags
        self._tmp: Optional[str] = None
        self._stats_view = None      # (generation, covered) -> TableStats
        # invalidated segments still referenced by in-flight scans;
        # freed by the last release_planned
        self._retired: List[Segment] = []
        # the LEAF lock (module doc); registered with the sanitizer's
        # runtime order witness so a violation of leaf-ness through any
        # callback path shows up as a witnessed edge/cycle
        self._lock = _san.tracked_lock("SegmentStore._lock")

    # -- build / refresh ---------------------------------------------------

    def _discard_locked(self, seg: Segment) -> None:
        """A segment leaving `self.segments`: free it now, unless an
        in-flight scan still references it — then RETIRE it (the last
        `release_planned` frees it), so a concurrent rebuild can never
        close a spill file another statement is about to load."""
        if seg.refs > 0:
            seg.retired = True
            self._retired.append(seg)
            return
        if seg.spill is not None:
            seg.spill.close()
        seg.data = None
        seg.valid = None

    def _drop_all_locked(self) -> None:
        for seg in self.segments:
            self._discard_locked(seg)
        self.segments = []
        self.covered = 0
        self.generation += 1
        self._stats_view = None

    def _refresh_locked(self, force: bool = False) -> Tuple[bool, int]:
        """Returns ``(want_background, inline_bytes_built)``. The
        background request is only DECIDED here; the caller submits it
        to the worker AFTER releasing the lock, so the store lock stays
        a leaf and never blocks on the worker's queue."""
        t = self.table
        epoch = getattr(t, "data_epoch", 0)
        if epoch != self.built_epoch:
            self._drop_all_locked()
            self.built_epoch = epoch
        tail = t.n - self.covered
        if tail <= 0:
            return False, 0
        if not force and self.covered > 0 and tail < max(self.delta_rows, 1):
            return False, 0  # small delta: stays on the raw merge path
        if not force and self.covered == 0 and t.n < self.segment_rows:
            return False, 0
        if not force and self.compaction_on and self.covered > 0:
            # background path (ISSUE 17): scans keep serving the
            # current generation + raw-merge delta while the worker
            # folds the delta in; a pending job suppresses re-requests.
            # Only DELTA folding defers — the initial segmentation
            # (covered == 0) still builds inline so the first scan of a
            # table sees encoded, zone-mapped segments, exactly as with
            # compaction off
            if self._compact_pending:
                return False, 0
            self._compact_pending = True
            return True, 0
        return False, self._inline_rebuild_locked()

    def _inline_rebuild_locked(self) -> int:
        """Today's statement-path rebuild; returns encoded bytes built
        (charged to the scanning statement by plan_scan) and records
        the wall time on the thread's compaction-wait counter."""
        import time as _time

        t = self.table
        t0 = _time.perf_counter()
        built = 0
        # the trailing partial segment (if any) re-builds at full size
        if self.segments and self.segments[-1].rows < self.segment_rows:
            last = self.segments.pop()
            self._discard_locked(last)
            self.covered = last.start
        for s in range(self.covered, t.n, self.segment_rows):
            e = min(s + self.segment_rows, t.n)
            seg = _build_segment(t, s, e)
            seg.seq = self._seg_seq
            self._seg_seq += 1
            self.segments.append(seg)
            self.covered = e
            built += seg.nbytes
        self._stats_view = None
        _count_compact_wait(_time.perf_counter() - t0, built)
        return built

    @staticmethod
    def _note_inline(built: int, outcome: str = "inline") -> None:
        """Metric side of an inline rebuild — called with the store
        lock RELEASED (the counter has its own lock; keep the store
        lock a leaf)."""
        if built <= 0:
            return
        from tidb_tpu.utils.metrics import (
            COMPACTION_BYTES,
            COMPACTION_TOTAL,
        )

        COMPACTION_TOTAL.inc(outcome=outcome)
        COMPACTION_BYTES.inc(built)

    def compact_inline_fallback(self) -> None:
        """Backpressure degradation (worker queue full / worker dead):
        clear the pending mark and rebuild inline on the statement
        path, exactly as with tidb_tpu_compaction=0 — typed, counted."""
        with self._lock:
            self._compact_pending = False
            built = self._inline_rebuild_locked()
        self._note_inline(built, outcome="inline_fallback")

    def _want_recluster_locked(self, force: bool) -> bool:
        """Is an ordered (CLUSTER BY) rewrite due before the next fold?
        Piggybacks on the fold cadence: the delta threshold that would
        trigger a rebuild is also what makes re-sorting worthwhile."""
        t = self.table
        if not getattr(getattr(t, "schema", None), "cluster_by", None):
            return False
        if getattr(t, "clustered_rows", 0) >= t.n:
            return False
        if force:
            return True
        if self.covered > 0:
            return t.n - self.covered >= max(self.delta_rows, 1)
        return t.n >= self.segment_rows

    def _maybe_recluster(self, force: bool = False) -> None:
        """CLUSTER BY ordered compaction (ISSUE 18). A scan that
        notices the fold cadence made a re-sort worthwhile must NOT
        permute here: the caller is a lock-free reader (plan_scan), and
        other statements may be mid-scan of the very arrays the permute
        moves — torn rows with no lock to stop them. Instead the due
        permute is QUEUED on the owning catalog and performed by
        Session at a statement boundary, under the catalog writer lock
        with the reader registry quiescent (run_pending_reclusters).
        Catalog-less tables (unit fixtures, single-owner by
        construction) keep the immediate permute."""
        with self._lock:
            want = self._want_recluster_locked(force) \
                and not self._recluster_busy
        if not want:
            return
        guard = getattr(self.table, "txn_guard", None)
        if guard is None:
            self.recluster_now()
        else:
            guard.note_recluster_due(self)

    def recluster_now(self, quiesced: bool = False) -> bool:
        """The permute body, with the STORE lock released (leaf rule;
        the busy flag keeps a second caller from double-permuting). It
        is Table.recluster that takes the CATALOG writer lock, refuses
        while any transaction is open (row positions may only move with
        no write log holding positional row ids) and — unless the
        caller already quiesced the reader registry — refuses while any
        statement or scan is in flight. The resulting data_epoch bump
        makes the next _refresh_locked rebuild every segment in the new
        order. Returns True when the queued work is DONE (rows moved,
        or the table no longer wants sorting); False = retry later."""
        with self._lock:
            if self._recluster_busy:
                return False
            if not self._want_recluster_locked(True):
                return True  # raced: sorted (or hint dropped) meanwhile
            self._recluster_busy = True
        import time as _time

        t0 = _time.perf_counter()
        try:
            moved = self.table.recluster(quiesced=quiesced)
        finally:
            with self._lock:
                self._recluster_busy = False
        if moved:
            _count_compact_wait(_time.perf_counter() - t0, 0)
            from tidb_tpu.utils.metrics import COMPACTION_TOTAL

            COMPACTION_TOTAL.inc(outcome="recluster")
        t = self.table
        return bool(moved) or getattr(t, "clustered_rows", 0) >= t.n

    def refresh(self, force: bool = False) -> None:
        self._maybe_recluster(force)
        with self._lock:
            want, built = self._refresh_locked(force=force)
        self._note_inline(built)
        if want:
            from tidb_tpu.columnar.compaction import submit

            submit(self)

    # -- scan planning -----------------------------------------------------

    def plan_scan(self, bounds, pin: Optional["ScanPin"] = None
                  ) -> Tuple[List[Segment], int, int]:
        """(segments to scan, segments pruned, covered row count) for a
        scan whose pushed filter yielded `bounds`. With a `pin`, every
        snapshot segment is reference-counted against invalidation
        until the pin closes. Counts flow to the engine metrics and the
        per-thread statement counters."""
        self._maybe_recluster()
        with self._lock:
            want, built = self._refresh_locked()
            segs = list(self.segments)
            covered = self.covered
            if pin is not None:
                for s in segs:
                    # lifecycle: each ref is handed to the pin (extended
                    # into pin.planned below under this same lock);
                    # ScanPin.close() -> release_planned drops them all
                    s.refs += 1
                pin.planned.extend(segs)
        self._note_inline(built)
        if want:
            from tidb_tpu.columnar.compaction import submit

            submit(self)
        if built and pin is not None:
            # the inline rebuild ran under THIS statement's budget:
            # charge the encoded bytes transiently so the stall is
            # attributable (max_mem, OOM actions); the resident bytes
            # themselves are charged per segment on touch
            from tidb_tpu.utils.memory import QueryOOMError

            try:
                pin.tracker.consume(built)
            except QueryOOMError:
                # attribution, not admission control: the built bytes
                # are store-resident and shared across statements, so
                # the statement cannot shed them — consume() already
                # spilled what it could and recorded the peak, which is
                # all this transient charge is for
                pass
            finally:
                # consume() records the charge BEFORE the budget check
                # can raise OOM, so the release must run even then
                pin.tracker.release(built)
        if bounds:
            kept = [s for s in segs if not segment_pruned(s.zmaps, bounds)]
        else:
            kept = segs
        pruned = len(segs) - len(kept)
        _count_scan(len(kept), pruned)
        return kept, pruned, covered

    def release_planned(self, segs) -> None:
        """Drop a closing pin's references; free retired segments whose
        last reference this was."""
        with self._lock:
            for seg in segs:
                seg.refs = max(seg.refs - 1, 0)
                if seg.retired and seg.refs == 0 and seg.pins == 0:
                    if seg.spill is not None:
                        seg.spill.close()
                    seg.data = None
                    seg.valid = None
                    if seg in self._retired:
                        self._retired.remove(seg)

    # -- residency / spill -------------------------------------------------

    def pin_segment(self, seg: Segment) -> int:
        """Make `seg` resident and pin it against eviction. Returns the
        bytes loaded from disk (0 when it was already resident). Like
        evict_segment, the disk read happens OUTSIDE the store lock —
        the pin taken first keeps eviction off; a racing loader that
        loses the install simply discards its copy."""
        with self._lock:
            seg.pins += 1
            self._touch_seq += 1
            seg.last_touch = self._touch_seq
            if seg.resident:
                return 0
            spill = seg.spill
        try:
            pairs = spill.load(len(seg.names))
        except BaseException:
            with self._lock:
                seg.pins -= 1  # a failed load must not pin forever
            raise
        loaded = 0
        with self._lock:
            if not seg.resident:
                seg.data = [d for d, _v in pairs]
                seg.valid = [v for _d, v in pairs]
                loaded = seg.nbytes
        if loaded:
            from tidb_tpu.utils.metrics import SPILL_SEGMENT_BYTES

            SPILL_SEGMENT_BYTES.inc(loaded, dir="in")
        return loaded

    def unpin_segment(self, seg: Segment) -> None:
        with self._lock:
            seg.pins = max(seg.pins - 1, 0)

    def evict_segment(self, seg: Segment) -> int:
        """Evict one resident, unpinned segment to disk; returns bytes
        freed (0 when it was pinned/non-resident, or got touched while
        the file was being written — callers try their next candidate).
        The payload write happens OUTSIDE the store lock (payloads are
        immutable; the pin taken here keeps every other path off the
        arrays), so one statement's spill never stalls other sessions'
        planning and scanning behind disk I/O."""
        with self._lock:
            if not seg.resident or seg.pins != 0:
                return 0
            seg.pins += 1  # guards the arrays while the lock is dropped
            data, valid = seg.data, seg.valid
            spill = seg.spill
            need_write = spill is None or not spill.written
            if need_write and spill is None:
                if self._tmp is None:
                    self._tmp = make_spill_dir(self.spill_dir)
                    # the table (and so this store) can be dropped with
                    # spilled payloads on disk: tie the directory's
                    # lifetime to the store object, not the process
                    import weakref

                    weakref.finalize(self, shutil.rmtree, self._tmp,
                                     ignore_errors=True)
                spill = seg.spill = SegmentSpillFile(
                    self._tmp, f"seg{seg.seq}")
        ok = False
        try:
            if need_write:
                spill.save(list(zip(seg.names, data, valid)))
            ok = True
        finally:
            freed = 0
            with self._lock:
                seg.pins -= 1  # an ENOSPC etc. must not pin forever
                if ok and seg.pins == 0 and seg.resident:
                    seg.data = None
                    seg.valid = None
                    freed = seg.nbytes
                # a touch raced the write: leave it resident (the file
                # is written, so the NEXT eviction of it is free)
        if freed:
            from tidb_tpu.utils.metrics import SPILL_SEGMENT_BYTES

            SPILL_SEGMENT_BYTES.inc(freed, dir="out")
        return freed

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(s.nbytes for s in self.segments if s.resident)

    def close(self) -> None:
        """Release every unreferenced segment and (when no in-flight
        scan holds retired ones) the spill directory. Called on DROP
        TABLE; the weakref finalizer minted with the directory removes
        it at store GC regardless, so a close() racing a live scan
        just defers the directory cleanup."""
        with self._lock:
            self._drop_all_locked()
            retired = bool(self._retired)
            tmp = self._tmp
        if tmp is not None and not retired:
            shutil.rmtree(tmp, ignore_errors=True)

    # -- statistics view ---------------------------------------------------

    def stats_view(self):
        """Aggregate the zone maps into a TableStats the planner's
        selectivity/NDV heuristics consume when no fresh ANALYZE stats
        exist (statistics.zone_map_stats). min/max become a two-point
        equi-depth histogram; NDV sums per-segment counts (an upper
        bound — the safe direction for join estimates)."""
        from tidb_tpu.statistics import ColumnStats, TableStats

        with self._lock:
            key = (self.generation, self.covered)
            if self._stats_view is not None and self._stats_view[0] == key:
                return self._stats_view[1]
            segs = list(self.segments)
        if not segs:
            return None
        n_rows = sum(s.rows for s in segs)
        stats = TableStats(n_rows=n_rows, version=self.table.version)
        for name in segs[0].names:
            zs = [s.zmaps[name] for s in segs if name in s.zmaps]
            if not zs:
                continue
            mins = [z.min for z in zs if z.min is not None]
            maxs = [z.max for z in zs if z.max is not None]
            nulls = sum(z.null_count for z in zs)
            ndv = min(sum(z.ndv for z in zs), max(n_rows - nulls, 0))
            if mins:
                mn, mx = float(min(mins)), float(max(maxs))
                cs = ColumnStats(ndv=max(ndv, 1), null_count=nulls,
                                 min=mn, max=mx,
                                 bounds=np.array([mn, mx]))
            else:
                cs = ColumnStats(ndv=0, null_count=nulls)
            stats.cols[name] = cs
        with self._lock:
            self._stats_view = (key, stats)
        return stats


class ScanPin:
    """One scan's residency + accounting handle on a store.

    Registered as a spillable on the statement's spill-root tracker
    (the SpillableRuns protocol, via memory.spill_root_of): ``touch``
    charges a segment's bytes once per statement, ``spill`` evicts the
    coldest charged segment when the tracker calls back under
    pressure, and ``close`` returns every charge and drops the scan's
    segment references at statement end."""

    def __init__(self, store: SegmentStore, tracker):
        from tidb_tpu.utils.memory import spill_root_of

        self.store = store
        self.tracker = tracker
        root = spill_root_of(tracker)
        self._root = root
        if root.spill_enabled:
            root.register_spillable(self)
        self.charged: Dict[int, Tuple[Segment, int]] = {}
        self.planned: List[Segment] = []  # ref-counted via plan_scan
        self._current: Optional[Segment] = None
        self.closed = False
        if _san.enabled():
            _san.note_pin_open(self)  # balanced at statement end

    def touch(self, seg: Segment) -> None:
        """Pin `seg` for staging (unpins the previously staged one) and
        charge its bytes to the statement on first touch."""
        prev, self._current = self._current, seg
        self.store.pin_segment(seg)
        if prev is not None:
            self.store.unpin_segment(prev)
        if id(seg) not in self.charged:
            self.charged[id(seg)] = (seg, seg.nbytes)
            # may re-enter self.spill(); the store lock is NOT held here
            self.tracker.consume(seg.nbytes)

    def spillable_bytes(self) -> int:
        # snapshot like spill(): the pipeline staging thread touch()-
        # inserts into `charged` while budget pressure walks it
        return sum(b for s, b in list(self.charged.values())
                   if s.resident and s.pins == 0)

    def spill(self) -> int:
        """Evict charged segments coldest-first until one actually
        frees bytes (a concurrent toucher can race one candidate;
        retired segments remain evictable — their files outlive the
        segment list). Returns the bytes released from this
        statement's accounting."""
        # snapshot first: the pipeline staging thread (ISSUE 9) may be
        # touch()-inserting into `charged` while another thread's
        # budget pressure walks it
        charged = list(self.charged.values())
        order = sorted((s for s, _b in charged
                        if s.resident and s.pins == 0),
                       key=lambda s: s.last_touch)
        for seg in order:
            freed = self.store.evict_segment(seg)
            if freed <= 0:
                continue
            _seg, b = self.charged.pop(id(seg), (None, 0))
            if b:
                self.tracker.release(b)
            from tidb_tpu.utils import dispatch as _dsp

            _dsp.record_spill(b or freed)  # per-stmt profile (ISSUE 16)
            return b or freed
        return 0

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if _san.enabled():
            _san.note_pin_close(self)
        if self._current is not None:
            self.store.unpin_segment(self._current)
            self._current = None
        total = sum(b for _s, b in self.charged.values())
        self.charged = {}
        if total:
            self.tracker.release(total)
        self._root.unregister_spillable(self)
        planned, self.planned = self.planned, []
        self.store.release_planned(planned)


# -- store lifecycle --------------------------------------------------------

_CREATE_LOCK = _san.tracked_lock("columnar._CREATE_LOCK")


def _base_of(table):
    """The underlying columnar `Table` (the delta engine's memtable has
    already compacted by the time a scan reads `table.n`)."""
    return getattr(table, "_base", table)


def store_for(table, segment_rows: int, delta_rows: Optional[int] = None,
              spill_dir: Optional[str] = None,
              min_rows: Optional[int] = None,
              compaction: Optional[bool] = None) -> Optional[SegmentStore]:
    """The table's segment store, creating it on first use once the
    table has at least `min_rows` (default: one segment) of rows.
    Returns None for engines without `data_epoch` (foreign table
    objects) and for small tables. The first creator's `segment_rows`
    wins for the store's lifetime; `delta_rows`/`spill_dir`/
    `compaction` follow the latest caller."""
    base = _base_of(table)
    if getattr(base, "data_epoch", None) is None:
        return None
    store = getattr(base, "_segment_store", None)
    if store is None:
        floor = max(int(segment_rows), MIN_STORE_ROWS) \
            if min_rows is None else max(int(min_rows), 1)
        if base.n < floor:
            return None
        with _CREATE_LOCK:
            store = getattr(base, "_segment_store", None)
            if store is None:
                store = SegmentStore(base, segment_rows, spill_dir)
                base._segment_store = store
    if delta_rows is not None:
        store.delta_rows = max(int(delta_rows), 1)
    if spill_dir:
        store.spill_dir = spill_dir
    if compaction is not None:
        store.compaction_on = bool(compaction)
    return store


def build_for_result(table, segment_rows: int = 1 << 16) -> None:
    """Eagerly segment a materialized result table (CTE materialization
    reuse): every consumer then scans the encoded, zone-mapped form.
    Tiny results stay raw — a store would cost more than it saves."""
    store = store_for(table, segment_rows, min_rows=MIN_STORE_ROWS)
    if store is not None:
        store.refresh(force=True)
