"""Error hierarchy for tidb_tpu.

Mirrors the error classes a MySQL-compatible engine needs at the surface
(parse / plan / execution / schema errors) without the full MySQL errno
catalogue; codes follow MySQL numbering where one exists.
"""


class TiDBTPUError(Exception):
    """Base class for all framework errors."""

    code = 1105  # ER_UNKNOWN_ERROR

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


class ParseError(TiDBTPUError):
    code = 1064  # ER_PARSE_ERROR


class PlanError(TiDBTPUError):
    code = 1105


class ExecutionError(TiDBTPUError):
    code = 1105


class WriteConflictError(ExecutionError):
    """A write hit another transaction's lock or a newer commit (ref:
    kv.ErrWriteConflict — drives the resolve-lock/backoff retry)."""


class PrivilegeError(TiDBTPUError):
    """Authorization failure (ref: privilege/ RequestVerification)."""

    code = 1142  # ER_TABLEACCESS_DENIED_ERROR


class UnsupportedError(TiDBTPUError):
    """Feature understood by the grammar but not yet implemented."""

    code = 1235  # ER_NOT_SUPPORTED_YET


class SchemaError(TiDBTPUError):
    code = 1146  # ER_NO_SUCH_TABLE


class DuplicateTableError(SchemaError):
    code = 1050  # ER_TABLE_EXISTS_ERROR


class UnknownColumnError(PlanError):
    code = 1054  # ER_BAD_FIELD_ERROR


class AmbiguousColumnError(PlanError):
    code = 1052  # ER_NON_UNIQ_ERROR


class TypeError_(TiDBTPUError):
    code = 1366  # ER_TRUNCATED_WRONG_VALUE_FOR_FIELD


class OOMError(ExecutionError):
    """Memory tracker budget exceeded (ref: util/memory OOM actions)."""

    code = 1105


class QueryKilledError(ExecutionError):
    """Statement cancelled by KILL QUERY / KILL CONNECTION (ref:
    ER_QUERY_INTERRUPTED — the executor's chunk loop and the DCN
    coordinator both raise it so a kill is typed end to end)."""

    code = 1317  # ER_QUERY_INTERRUPTED


class QueryTimeoutError(ExecutionError):
    """max_execution_time deadline exceeded (ref: ER_QUERY_TIMEOUT;
    MySQL's "maximum statement execution time exceeded"). Raised by the
    local chunk loop, by DCN workers that received the statement's
    remaining budget, and by the coordinator when an RPC outlives it."""

    code = 3024  # ER_QUERY_TIMEOUT


class AdmissionRejectedError(ExecutionError):
    """The statement scheduler refused to enqueue this statement (queue
    full, server memory quota exhausted, or the scheduler is draining
    for shutdown). TiDB-style "server is busy" — the client should back
    off and retry; the statement never started executing."""

    code = 9008  # TiKV ServerIsBusy as surfaced by TiDB


class SchedulerQueueTimeoutError(ExecutionError):
    """The statement was admitted but no scheduler worker picked it up
    within tidb_tpu_sched_queue_timeout_ms. It was removed from the
    queue without executing — safe to retry."""

    code = 9008  # same busy-class error: the server is saturated


class SLOShedError(AdmissionRejectedError):
    """Shed at admission under queue pressure because the statement's
    digest is burning its latency SLO budget fastest
    (tidb_tpu_sched_slo_shed, ISSUE 16). The statement never started —
    safe to retry; results are never affected, only who waits."""

    code = 9008  # the same busy class: back off and retry


class TwoPhaseCommitIncomplete(ExecutionError):
    """A distributed transaction passed its commit point (the decision
    is durably recorded) but one or more participants missed the COMMIT
    message. The writes ARE committed — recover_txns() re-drives the
    commit idempotently. Callers must NOT retry the statement: it would
    double-apply. Distinguished from pre-decision failures (plain
    ExecutionError), where every shard aborted and a retry is safe."""

    code = 1105  # ER_UNKNOWN_ERROR (operational; resolved by recovery)


class SanitizerError(ExecutionError):
    """The runtime invariant sanitizer (tidb_tpu_sanitize, ISSUE 12)
    witnessed a broken engine invariant during this statement: a leaked
    pin, a tracker double-release, a lock-order cycle, a blown
    host-sync budget, or a raced process global. Debug mode only — the
    statement's RESULT was produced normally; the error reports the
    invariant breach so it fails loudly in sanitized runs."""

    code = 1105  # ER_UNKNOWN_ERROR (engine-internal diagnostic)


