"""Metrics registry (ref: metrics/ — Prometheus collectors per layer,
served on the HTTP status port).

Counters and histograms with optional labels, exposed in the Prometheus
text format by server/status.py. A process-global REGISTRY mirrors the
reference's package-level collectors; everything is thread-safe under
one lock (metric updates are far off the hot device path).

Fleet aggregation (ISSUE 16): ``snapshot()`` produces a DCN-codec-safe
wire form of every registered metric; the coordinator merges per-worker
snapshots (counters sum, gauges ship per-worker only, histograms merge
bucket-wise, exemplars keep the worst observation) and renders
``/metrics?scope=cluster`` with per-worker ``worker`` labels plus a
merged ``worker="fleet"`` view. An unreachable worker contributes a
``tidb_tpu_cluster_scrape_error`` sample (and an error row on
``information_schema.cluster_metrics``) instead of failing the scrape —
the ``dcn_worker_stats`` rule."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["Counter", "Histogram", "Gauge", "REGISTRY", "Registry",
           "render_prometheus", "snapshot", "merge_snapshots",
           "render_cluster", "cluster_rows", "SNAPSHOT_SCHEMA"]

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0)


class Registry:
    def __init__(self):
        self.lock = threading.Lock()
        self.metrics: "List[object]" = []

    def register(self, m) -> None:
        with self.lock:
            self.metrics.append(m)


REGISTRY = Registry()


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str = "",
                 registry: Optional[Registry] = None):
        self.name = name
        self.help = help_
        self.lock = threading.Lock()
        (registry or REGISTRY).register(self)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._values: Dict[Tuple, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self.lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels) -> float:
        with self.lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def remove(self, **labels) -> None:
        """Drop one label set (an LRU-evicted digest's gauge must not
        render a stale value forever)."""
        with self.lock:
            self._values.pop(tuple(sorted(labels.items())), None)

    def samples(self):
        with self.lock:  # snapshot: writers may insert new label keys
            items = sorted(self._values.items())
        for key, v in items:
            yield dict(key), v


class Gauge(Counter):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self.lock:
            self._values[tuple(sorted(labels.items()))] = v

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)


# a stored exemplar older than this stops shielding its (possibly
# smaller) value: the "worst recent observation" window
_EXEMPLAR_WINDOW_S = 60.0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_="", buckets=_DEFAULT_BUCKETS,
                 registry=None, exemplars: bool = False):
        super().__init__(name, help_, registry)
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        # exemplars=True: each observation under an active trace may
        # become the label set's exemplar — the trace_id of the worst
        # recent observation, rendered OpenMetrics-style on the +Inf
        # bucket so /metrics links straight to /trace?id=<trace_id>
        self.exemplars_enabled = exemplars
        self._exemplars: Dict[Tuple, Tuple[float, str, float]] = {}

    def observe(self, v: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        trace_id = ""
        if self.exemplars_enabled:
            from tidb_tpu.utils import tracing

            trace_id = tracing.current_trace_id()
        with self.lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v
            if trace_id:
                import time as _time

                now = _time.time()
                cur = self._exemplars.get(key)
                if cur is None or v >= cur[0] \
                        or now - cur[2] > _EXEMPLAR_WINDOW_S:
                    self._exemplars[key] = (v, trace_id, now)

    def exemplar(self, **labels) -> Optional[Tuple[float, str]]:
        """(value, trace_id) of the worst recent observation, or None."""
        with self.lock:
            e = self._exemplars.get(tuple(sorted(labels.items())))
        return (e[0], e[1]) if e is not None else None

    def count(self, **labels) -> int:
        with self.lock:
            return sum(self._counts.get(tuple(sorted(labels.items())), []))

    def samples(self):
        with self.lock:  # snapshot under the lock (see Counter.samples)
            items = [(k, list(self._counts[k]), self._sums.get(k, 0.0),
                      self._exemplars.get(k))
                     for k in sorted(self._counts)]
        for key, counts, total, ex in items:
            yield dict(key), counts, total, ex


def _fmt_labels(labels: Dict, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _exemplar_kept(trace_id: str) -> int:
    """1 when the exemplar's trace is currently readable on /trace?id=.
    Exemplars record trace_id at OBSERVATION time; the trace may later
    be discarded (head sampling) or ring-evicted — annotating the
    rendered exemplar stops operators chasing 404s for those."""
    from tidb_tpu.utils import tracing

    return 1 if tracing.STORE.get(trace_id) is not None else 0


def _exemplar_tail(ex) -> str:
    """OpenMetrics exemplar rendering: the worst recent observation's
    trace_id (+ whether that trace is still fetchable), on +Inf."""
    if ex is None:
        return ""
    kept = ex[2] if len(ex) > 2 else _exemplar_kept(ex[1])
    return (f' # {{trace_id="{ex[1]}",kept="{int(kept)}"}}'
            f' {round(float(ex[0]), 6)}')


def render_prometheus(registry: Optional[Registry] = None) -> str:
    """Prometheus text exposition of every registered metric."""
    reg = registry or REGISTRY
    out = []
    with reg.lock:
        metrics = list(reg.metrics)
    for m in metrics:
        out.append(f"# HELP {m.name} {m.help}")
        out.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            for labels, counts, total, ex in m.samples():
                acc = 0
                for b, c in zip(m.buckets, counts):
                    acc += c
                    le = _fmt_labels(labels, f'le="{b}"')
                    out.append(f"{m.name}_bucket{le} {acc}")
                acc += counts[-1]
                le = _fmt_labels(labels, 'le="+Inf"')
                ex2 = (ex[0], ex[1]) if ex is not None else None
                out.append(f"{m.name}_bucket{le} {acc}"
                           f"{_exemplar_tail(ex2)}")
                out.append(f"{m.name}_sum{_fmt_labels(labels)} {total}")
                out.append(f"{m.name}_count{_fmt_labels(labels)} {acc}")
        else:
            for labels, v in m.samples():
                out.append(f"{m.name}{_fmt_labels(labels)} {v}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# fleet aggregation (ISSUE 16): snapshot wire form + merge + renderers
# ---------------------------------------------------------------------------

SNAPSHOT_SCHEMA = 1


def snapshot(registry: Optional[Registry] = None) -> Dict:
    """DCN-codec-safe wire form of every registered metric (the
    ``metrics_snapshot`` RPC payload): name/kind/help per metric, label
    dicts + scalar values per sample; histograms carry their bucket
    bounds, per-bucket counts, sum, and the exemplar as
    ``[value, trace_id, kept]`` — ``kept`` is stamped HERE because only
    the observing process's trace store can answer it."""
    reg = registry or REGISTRY
    with reg.lock:
        metrics = list(reg.metrics)
    out = []
    for m in metrics:
        d: Dict = {"name": m.name, "kind": m.kind, "help": m.help}
        if isinstance(m, Histogram):
            d["buckets"] = [float(b) for b in m.buckets]
            d["samples"] = [
                [labels, list(counts), float(total),
                 None if ex is None
                 else [float(ex[0]), str(ex[1]), _exemplar_kept(ex[1])]]
                for labels, counts, total, ex in m.samples()]
        else:
            d["samples"] = [[labels, float(v)]
                            for labels, v in m.samples()]
        out.append(d)
    return {"schema": SNAPSHOT_SCHEMA, "metrics": out}


def _iter_snap_metrics(entries):
    """(worker_label, metric_dict) over every well-formed snapshot in
    scrape entries [(label, snapshot|None, error)] — malformed or
    errored entries contribute nothing here (their error surfaces
    separately)."""
    for label, snap, _err in entries:
        if not isinstance(snap, dict):
            continue
        for m in snap.get("metrics") or ():
            if isinstance(m, dict) and m.get("name"):
                yield label, m


def merge_snapshots(entries) -> List[Dict]:
    """Fleet-merged metric list from scrape entries
    ``[(worker_label, snapshot|None, error)]``:

      * counters — label-set values SUM across workers
      * gauges — per-worker readings only (a summed queue depth or
        health state is a lie); merged output omits them
      * histograms — per-bucket counts and sums merge bucket-wise
        (requires identical bucket bounds — all processes run the same
        collectors; a mismatched snapshot's sample is skipped)
      * exemplars — the worst (max-value) observation wins

    Returns metric dicts in the snapshot shape, first-seen order."""
    merged: "Dict[str, Dict]" = {}
    order: List[str] = []
    for _label, m in _iter_snap_metrics(entries):
        name, kind = m["name"], m.get("kind", "untyped")
        if kind == "gauge":
            continue
        cur = merged.get(name)
        if cur is None:
            cur = merged[name] = {"name": name, "kind": kind,
                                  "help": m.get("help", ""),
                                  "samples": {}}
            if kind == "histogram":
                cur["buckets"] = list(m.get("buckets") or ())
            order.append(name)
        for s in m.get("samples") or ():
            try:
                labels = dict(s[0])
                key = tuple(sorted(labels.items()))
            except (TypeError, IndexError):
                continue
            if kind == "histogram":
                if list(m.get("buckets") or ()) != cur["buckets"]:
                    continue  # foreign bucket layout: unmergeable
                counts, total = list(s[1]), float(s[2])
                ex = s[3] if len(s) > 3 else None
                hit = cur["samples"].get(key)
                if hit is None:
                    cur["samples"][key] = [labels, counts, total, ex]
                else:
                    hit[1] = [a + b for a, b in zip(hit[1], counts)]
                    hit[2] += total
                    if ex is not None and (hit[3] is None
                                           or ex[0] >= hit[3][0]):
                        hit[3] = ex
            else:
                v = float(s[1])
                hit = cur["samples"].get(key)
                if hit is None:
                    cur["samples"][key] = [labels, v]
                else:
                    hit[1] += v
    out = []
    for name in order:
        m = merged[name]
        m["samples"] = list(m["samples"].values())
        out.append(m)
    return out


def _snap_sample_lines(m: Dict, labels: Dict, s, out: List[str]) -> None:
    """Exposition lines of one snapshot-form sample (histogram or
    scalar), shared by the per-worker and fleet sections."""
    name = m["name"]
    if m.get("kind") == "histogram":
        counts, total = s[1], s[2]
        ex = s[3] if len(s) > 3 else None
        acc = 0
        for b, c in zip(m.get("buckets") or (), counts):
            acc += c
            le = _fmt_labels(labels, f'le="{b}"')
            out.append(f"{name}_bucket{le} {acc}")
        acc += counts[-1] if counts else 0
        le = _fmt_labels(labels, 'le="+Inf"')
        out.append(f"{name}_bucket{le} {acc}{_exemplar_tail(ex)}")
        out.append(f"{name}_sum{_fmt_labels(labels)} {total}")
        out.append(f"{name}_count{_fmt_labels(labels)} {acc}")
    else:
        out.append(f"{name}{_fmt_labels(labels)} {s[1]}")


def render_cluster(entries) -> str:
    """Prometheus text exposition of a cluster scrape: every worker's
    samples labeled ``worker=<label>``, the merged fleet view labeled
    ``worker="fleet"`` (counters/histograms only — see
    merge_snapshots), and one ``tidb_tpu_cluster_scrape_error`` gauge
    sample per unreachable worker (the scrape itself never fails)."""
    out: List[str] = []
    seen_meta = set()
    by_name: "Dict[str, List]" = {}
    order: List[str] = []
    for label, m in _iter_snap_metrics(entries):
        if m["name"] not in by_name:
            by_name[m["name"]] = []
            order.append(m["name"])
        by_name[m["name"]].append((label, m))
    fleet = {m["name"]: m for m in merge_snapshots(entries)}
    for name in order:
        first = by_name[name][0][1]
        if name not in seen_meta:
            seen_meta.add(name)
            out.append(f"# HELP {name} {first.get('help', '')}")
            out.append(f"# TYPE {name} {first.get('kind', 'untyped')}")
        for label, m in by_name[name]:
            for s in m.get("samples") or ():
                try:
                    labels = dict(s[0])
                except (TypeError, IndexError):
                    continue
                labels["worker"] = label
                _snap_sample_lines(m, labels, s, out)
        fm = fleet.get(name)
        if fm is not None:
            for s in fm["samples"]:
                labels = dict(s[0])
                labels["worker"] = "fleet"
                _snap_sample_lines(fm, labels, s, out)
    errs = [(label, err) for label, snap, err in entries if err]
    if errs:
        out.append("# HELP tidb_tpu_cluster_scrape_error Workers whose "
                   "metrics_snapshot RPC failed during this cluster "
                   "scrape (error row, not a failed scrape)")
        out.append("# TYPE tidb_tpu_cluster_scrape_error gauge")
        for label, err in errs:
            lbl = _fmt_labels({"worker": label,
                               "error": err.replace('"', "'")})
            out.append(f"tidb_tpu_cluster_scrape_error{lbl} 1")
    return "\n".join(out) + "\n"


def cluster_rows(entries) -> List[tuple]:
    """information_schema.cluster_metrics rows from scrape entries:
    ``(worker, metric, labels, value, error)``. Histograms contribute
    their ``_count`` and ``_sum`` series (the SQL surface is for
    totals; bucket shapes live on /metrics). Fleet-merged rows carry
    ``worker='fleet'``; an unreachable worker yields one row whose
    ``error`` is set and whose metric columns are NULL."""
    rows: List[tuple] = []

    def sample_rows(worker: str, m: Dict) -> None:
        name = m["name"]
        for s in m.get("samples") or ():
            try:
                labels = dict(s[0])
            except (TypeError, IndexError):
                continue
            lbl = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            if m.get("kind") == "histogram":
                rows.append((worker, f"{name}_count", lbl,
                             float(sum(s[1])), ""))
                rows.append((worker, f"{name}_sum", lbl, float(s[2]), ""))
            else:
                rows.append((worker, name, lbl, float(s[1]), ""))

    for label, snap, err in entries:
        if err:
            rows.append((label, None, None, None, err))
            continue
        if not isinstance(snap, dict):
            continue
        for m in snap.get("metrics") or ():
            if isinstance(m, dict) and m.get("name"):
                sample_rows(label, m)
    for m in merge_snapshots(entries):
        sample_rows("fleet", m)
    return rows


# -- engine collectors (ref: metrics/*.go one file per layer) ---------------

QUERY_TOTAL = Counter("tidb_tpu_query_total", "Statements executed, by type/status")
QUERY_DURATION = Histogram("tidb_tpu_query_duration_seconds",
                           "Statement wall time, by type")
SLOW_QUERY_TOTAL = Counter("tidb_tpu_slow_query_total",
                           "Statements exceeding tidb_slow_log_threshold")
TXN_TOTAL = Counter("tidb_tpu_txn_total", "Transaction outcomes")
GC_RECLAIMED = Counter("tidb_tpu_gc_reclaimed_rows_total",
                       "MVCC versions reclaimed by GC")
CONN_GAUGE = Gauge("tidb_tpu_connections", "Open server connections")
FRAGMENT_DISPATCH = Counter("tidb_tpu_fragment_dispatch_total",
                            "Distributed fragment executions, by kind")
EXTERNAL_AGG = Counter("tidb_tpu_external_agg_total",
                       "Key-range external aggregation merges (group "
                       "state exceeded the memory budget)")

# -- distributed-execution telemetry (fragments, DCN, memory) ---------------
# The engine-reported side of what bench.py used to measure externally:
# per-dispatch accounting, fragment wall time, DCN traffic, and
# memory-quota events all render on /metrics.

DISPATCH_TOTAL = Counter(
    "tidb_tpu_device_dispatch_total",
    "Device round trips (kernel launches + transfers), by site — the "
    "process-wide mirror of utils.dispatch's thread-local counter")
FRAGMENT_SECONDS = Histogram(
    "tidb_tpu_fragment_seconds",
    "Wall time of one mesh-fragment dispatch, by kind (async dispatch: "
    "measures launch + any synchronous trace/compile, not device busy); "
    "carries a trace_id exemplar for the worst recent dispatch",
    exemplars=True)
FRAGMENT_COMPILE = Counter(
    "tidb_tpu_fragment_compile_total",
    "Fragment programs compiled from plan subtrees, by output kind")
COLLECTIVE_MERGE_SECONDS = Histogram(
    "tidb_tpu_collective_merge_seconds",
    "Host-driven merge of per-shard collective (psum) states across "
    "streamed fragment batches")
DCN_BYTES = Counter(
    "tidb_tpu_dcn_bytes_total",
    "DCN tier wire traffic through this process, by direction")
DCN_RTT = Histogram(
    "tidb_tpu_dcn_rtt_seconds",
    "Coordinator-observed round-trip time of one DCN worker call")
PLAN_CACHE_TOTAL = Counter(
    "tidb_tpu_plan_cache_total",
    "Plan-cache events by kind: hit, miss, bypass (ineligible or "
    "known-uncacheable statement), evict (LRU), invalidate (schema/"
    "stats change)")
PARSE_SECONDS = Histogram(
    "tidb_tpu_parse_seconds",
    "SQL text -> AST wall time per parse() call")
PLAN_SECONDS = Histogram(
    "tidb_tpu_plan_seconds",
    "Logical optimization + physical lowering wall time per "
    "plan_statement call (cache hits skip this entirely)")
JOIN_COMPILE_TOTAL = Counter(
    "tidb_tpu_join_compile_total",
    "Join kernel (re)traces by kernel (build_sort/probe/expand) — "
    "incremented at TRACE time inside the fused join kernels, so a "
    "steady-state repeated join must not move it (the retrace guard "
    "test and EXPLAIN ANALYZE's recompiles field both read it)")
JOIN_PROBE_MODE_TOTAL = Counter(
    "tidb_tpu_join_probe_mode_total",
    "Probe chunks resolved per strategy, by mode: sorted (searchsorted "
    "range lookup), xla / pallas (open-addressing hash table, window-"
    "scan / VMEM kernel), direct (dense-domain direct-address index), "
    "host (numpy tier), fused_* (same strategies inside a fused "
    "scan->probe program) — captures show which path actually ran")
JOIN_PROBE_SECONDS = Histogram(
    "tidb_tpu_join_probe_seconds",
    "Wall time of one fused probe+expand pass over a probe chunk, by "
    "join kind; carries a trace_id exemplar for the worst recent pass",
    exemplars=True)
JOIN_BUILD_SECONDS = Histogram(
    "tidb_tpu_join_build_seconds",
    "Wall time of one hash-join build phase (drain + pack + sort), by "
    "tier: host (numpy probe path), device (fused on-device sort), "
    "host_sorted (tidb_tpu_join_device_build=0 escape hatch)")
DCN_RETRY_TOTAL = Counter(
    "tidb_tpu_dcn_retry_total",
    "DCN recovery actions by kind: rpc (idempotent call re-sent on a "
    "fresh connection), reconnect (worker socket re-established by the "
    "health machine), cancel_dial (side-channel connection opened to "
    "deliver a cancel)")
DCN_FAILOVER_TOTAL = Counter(
    "tidb_tpu_dcn_failover_total",
    "Partition partials re-run on a replica worker after the primary "
    "(and its retry) was unreachable")
WORKER_STATE = Gauge(
    "tidb_tpu_dcn_worker_state",
    "Per-worker health-machine state: 0=up, 1=suspect, 2=down")
DCN_CANCEL_TOTAL = Counter(
    "tidb_tpu_dcn_cancel_total",
    "Coordinator-initiated cancels of in-flight worker partials "
    "(KILL propagation / statement deadline expiry)")
DEADLINE_EXCEEDED_TOTAL = Counter(
    "tidb_tpu_deadline_exceeded_total",
    "Statements aborted because max_execution_time expired")
MEM_QUOTA_ENGAGED = Counter(
    "tidb_tpu_mem_quota_engaged_total",
    "Queries whose host memory consumption crossed tidb_mem_quota_query "
    "(spill or cancel followed)")
SPILL_TOTAL = Counter(
    "tidb_tpu_spill_total", "Operator-state spill events to tmp storage")
SPILL_BYTES = Counter(
    "tidb_tpu_spill_bytes_total", "Bytes shed to tmp storage by spills")

# -- sharded placement + cross-process shuffle (ISSUE 13) -------------------

SHUFFLE_BYTES_TOTAL = Counter(
    "tidb_tpu_shuffle_bytes_total",
    "Cross-worker shuffle exchange payload bytes (FoR-encoded batches) "
    "by direction: out = shipped to a peer worker, in = staged into "
    "the local inbox from a peer")
SHARD_SCAN_TOTAL = Counter(
    "tidb_tpu_shard_scan_total",
    "Distributed statements planned against SHARD BY placement, by "
    "whether owner pruning skipped part of the fleet (pruned=yes: at "
    "least one non-owner worker received no RPC and did no work)")
RESHARD_SHARDS_TOTAL = Counter(
    "tidb_tpu_reshard_shards_total",
    "Per-shard online-reshard steps completed, by phase: backfill = "
    "shard snapshot staged at its new owner (double-write window "
    "opened), cutover = shard validated and flipped to the new "
    "placement")
RESHARD_ACTIVE = Gauge(
    "tidb_tpu_reshard_active",
    "1 while the labeled table has an online reshard in flight "
    "(statements keep serving by the old map; DML double-writes moved "
    "shards), 0 once the new placement is installed or the run "
    "abandoned")
MEMBERSHIP_TOTAL = Counter(
    "tidb_tpu_membership_total",
    "Cluster membership changes completed, by kind: join = "
    "add_worker admitted a new worker into the serving fleet, remove "
    "= remove_worker drained one out")

# -- columnar segment store (ISSUE 8) ---------------------------------------

SCAN_SEGMENTS_SCANNED_TOTAL = Counter(
    "tidb_tpu_scan_segments_scanned_total",
    "Columnar segments staged by table scans (after zone-map pruning); "
    "with ..._pruned_total this gives the engine-reported pruning "
    "fraction the Q6 perf floor asserts on")
SCAN_SEGMENTS_PRUNED_TOTAL = Counter(
    "tidb_tpu_scan_segments_pruned_total",
    "Columnar segments skipped before host->device staging because the "
    "scan's pushed range/equality predicates cannot match the "
    "segment's zone maps (min/max/null_count)")
SPILL_SEGMENT_BYTES = Counter(
    "tidb_tpu_spill_segment_bytes_total",
    "Encoded segment payload bytes moved across the disk spill "
    "boundary, by direction: out = evicted to a segment spill file "
    "under the statement memory budget, in = re-materialized from "
    "disk on a later touch")

# -- pipelined device-resident execution (ISSUE 9) --------------------------

PIPELINE_PREFETCH_TOTAL = Counter(
    "tidb_tpu_pipeline_prefetch_total",
    "Chunk staging events through the double-buffered pipeline, by "
    "outcome: hit (buffer was already staged when the compute loop "
    "asked), wait (the loop blocked on in-flight staging), inline "
    "(prefetch disabled or depth exhausted — staged synchronously), "
    "cancelled (KILL/deadline stopped the staging thread mid-fragment), "
    "error (staging died on quota OOM or another fault — relayed typed "
    "to the compute loop)")
PIPELINE_PREFETCH_BYTES = Counter(
    "tidb_tpu_pipeline_prefetch_bytes_total",
    "Host->device bytes moved by the pipeline staging thread ahead of "
    "compute (double-buffered overlap; inline stagings count too)")
DEVICE_CACHE_TOTAL = Counter(
    "tidb_tpu_device_cache_total",
    "Cross-statement device buffer cache events, by kind: hit (a warm "
    "statement reused staged device buffers and moved zero bytes), "
    "miss, evict (LRU under tidb_tpu_device_buffer_cache_bytes), "
    "invalidate (table version/data_epoch/stats moved, or a schema "
    "change cleared the cache — the plan cache's invalidation rules)")

# -- distributed tracing (ISSUE 5) ------------------------------------------

DCN_RPC_SECONDS = Histogram(
    "tidb_tpu_dcn_rpc_seconds",
    "One coordinator->worker RPC round trip, by rpc command; carries a "
    "trace_id exemplar for the worst recent call so /metrics links "
    "straight to the offending trace on /trace?id=",
    exemplars=True)
TRACE_KEPT_TOTAL = Counter(
    "tidb_tpu_trace_kept_total",
    "Traces retained in the tail-sampled store, by first keep reason "
    "(sampled, slow, error:*, retry, failover, trace)")

# -- plan feedback (ISSUE 15) -----------------------------------------------

PLAN_EST_DRIFT = Histogram(
    "tidb_tpu_plan_est_drift",
    "Per-statement worst-operator estimation drift: max(actual/est, "
    "est/actual) over every operator whose actual row count the "
    "feedback harvest knew — 1.0 means every estimate was exact, 100 a "
    "hundredfold misestimate; carries a trace_id exemplar for the "
    "worst recent statement so /metrics links the drift straight to "
    "its trace",
    buckets=(1.0, 1.5, 2.0, 4.0, 10.0, 30.0, 100.0, 1000.0),
    exemplars=True)

# -- serving tier: admission-controlled scheduler + micro-batching ----------

SCHED_QUEUE_DEPTH = Gauge(
    "tidb_tpu_sched_queue_depth",
    "Statements admitted but not yet claimed by a scheduler worker "
    "(queued singletons + members of still-gathering batch groups)")
SCHED_ADMISSION_TOTAL = Counter(
    "tidb_tpu_sched_admission_total",
    "Scheduler admission decisions, by outcome: admitted, rejected "
    "(queue full / server memory quota / draining), timed_out (admitted "
    "but evicted after tidb_tpu_sched_queue_timeout_ms unclaimed)")
BATCH_SIZE = Histogram(
    "tidb_tpu_batch_size",
    "Members per coalesced device dispatch (1 = a batchable statement "
    "whose gather window closed alone)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
BATCH_COALESCE_TOTAL = Counter(
    "tidb_tpu_batch_coalesce_total",
    "Statements that rode a multi-statement coalesced dispatch (members "
    "of batches with n >= 2; singleton executions never count)")

# -- write path: group-commit DML + background compaction (ISSUE 17) --------

DML_BATCH_SIZE = Histogram(
    "tidb_tpu_dml_batch_size",
    "Members per group-committed DML window (1 = a batchable write "
    "whose gather window closed alone); mirrors tidb_tpu_batch_size "
    "for the read path",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
COMPACTION_TOTAL = Counter(
    "tidb_tpu_compaction_total",
    "Delta->segment rebuild passes, by outcome: background (worker "
    "build installed at cutover), inline (statement-path rebuild — the "
    "pre-compaction behavior, still used when tidb_tpu_compaction=0 or "
    "force-refresh), inline_fallback (worker queue full or dead: typed "
    "degradation back to the statement path), discarded (the store "
    "changed under the worker's snapshot; built segments dropped), "
    "failed (background build raised)")
COMPACTION_BYTES = Counter(
    "tidb_tpu_compaction_bytes_total",
    "Encoded segment bytes produced by delta->segment rebuilds "
    "(background and inline alike); with tidb_tpu_compaction_total "
    "this gives bytes-per-pass and the write amplification trend")

# -- cluster observability plane (ISSUE 16) ---------------------------------

XFER_BYTES = Counter(
    "tidb_tpu_xfer_bytes_total",
    "Host<->device transfer bytes observed at the EXISTING staging/"
    "fetch choke points (prefetcher stagings, probe-window and agg "
    "drains), by dir: h2d, d2h — the process-wide mirror of the "
    "per-statement profile accounting; no new device syncs are paid "
    "to collect it")
COMPILE_SECONDS = Counter(
    "tidb_tpu_compile_seconds_total",
    "Wall seconds spent in first-invocation kernel/fragment "
    "trace+compile, attributed to the triggering statement's profile "
    "(warm statements add zero)")
DIGEST_P99 = Gauge(
    "tidb_tpu_digest_p99_seconds",
    "Sliding-window p99 statement latency per digest (the SLO store's "
    "view; label sets follow the store's LRU — an evicted digest's "
    "series is removed)")
SLO_SHED_TOTAL = Counter(
    "tidb_tpu_slo_shed_total",
    "Statements shed at admission under queue pressure because their "
    "digest was burning its latency SLO budget fastest "
    "(tidb_tpu_sched_slo_shed; plans and results are never affected)")
