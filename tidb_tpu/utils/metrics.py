"""Metrics registry (ref: metrics/ — Prometheus collectors per layer,
served on the HTTP status port).

Counters and histograms with optional labels, exposed in the Prometheus
text format by server/status.py. A process-global REGISTRY mirrors the
reference's package-level collectors; everything is thread-safe under
one lock (metric updates are far off the hot device path)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["Counter", "Histogram", "Gauge", "REGISTRY", "Registry",
           "render_prometheus"]

_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0)


class Registry:
    def __init__(self):
        self.lock = threading.Lock()
        self.metrics: "List[object]" = []

    def register(self, m) -> None:
        with self.lock:
            self.metrics.append(m)


REGISTRY = Registry()


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str = "",
                 registry: Optional[Registry] = None):
        self.name = name
        self.help = help_
        self.lock = threading.Lock()
        (registry or REGISTRY).register(self)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._values: Dict[Tuple, float] = {}

    def inc(self, n: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self.lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels) -> float:
        with self.lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def samples(self):
        with self.lock:  # snapshot: writers may insert new label keys
            items = sorted(self._values.items())
        for key, v in items:
            yield dict(key), v


class Gauge(Counter):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        with self.lock:
            self._values[tuple(sorted(labels.items()))] = v

    def dec(self, n: float = 1.0, **labels) -> None:
        self.inc(-n, **labels)


# a stored exemplar older than this stops shielding its (possibly
# smaller) value: the "worst recent observation" window
_EXEMPLAR_WINDOW_S = 60.0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_="", buckets=_DEFAULT_BUCKETS,
                 registry=None, exemplars: bool = False):
        super().__init__(name, help_, registry)
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        # exemplars=True: each observation under an active trace may
        # become the label set's exemplar — the trace_id of the worst
        # recent observation, rendered OpenMetrics-style on the +Inf
        # bucket so /metrics links straight to /trace?id=<trace_id>
        self.exemplars_enabled = exemplars
        self._exemplars: Dict[Tuple, Tuple[float, str, float]] = {}

    def observe(self, v: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        trace_id = ""
        if self.exemplars_enabled:
            from tidb_tpu.utils import tracing

            trace_id = tracing.current_trace_id()
        with self.lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            i = 0
            while i < len(self.buckets) and v > self.buckets[i]:
                i += 1
            counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + v
            if trace_id:
                import time as _time

                now = _time.time()
                cur = self._exemplars.get(key)
                if cur is None or v >= cur[0] \
                        or now - cur[2] > _EXEMPLAR_WINDOW_S:
                    self._exemplars[key] = (v, trace_id, now)

    def exemplar(self, **labels) -> Optional[Tuple[float, str]]:
        """(value, trace_id) of the worst recent observation, or None."""
        with self.lock:
            e = self._exemplars.get(tuple(sorted(labels.items())))
        return (e[0], e[1]) if e is not None else None

    def count(self, **labels) -> int:
        with self.lock:
            return sum(self._counts.get(tuple(sorted(labels.items())), []))

    def samples(self):
        with self.lock:  # snapshot under the lock (see Counter.samples)
            items = [(k, list(self._counts[k]), self._sums.get(k, 0.0),
                      self._exemplars.get(k))
                     for k in sorted(self._counts)]
        for key, counts, total, ex in items:
            yield dict(key), counts, total, ex


def _fmt_labels(labels: Dict, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(registry: Optional[Registry] = None) -> str:
    """Prometheus text exposition of every registered metric."""
    reg = registry or REGISTRY
    out = []
    with reg.lock:
        metrics = list(reg.metrics)
    for m in metrics:
        out.append(f"# HELP {m.name} {m.help}")
        out.append(f"# TYPE {m.name} {m.kind}")
        if isinstance(m, Histogram):
            for labels, counts, total, ex in m.samples():
                acc = 0
                for b, c in zip(m.buckets, counts):
                    acc += c
                    le = _fmt_labels(labels, f'le="{b}"')
                    out.append(f"{m.name}_bucket{le} {acc}")
                acc += counts[-1]
                le = _fmt_labels(labels, 'le="+Inf"')
                # OpenMetrics exemplar: the worst recent observation's
                # trace_id, linking the histogram to /trace?id=...
                tail = (f' # {{trace_id="{ex[1]}"}} {round(ex[0], 6)}'
                        if ex is not None else "")
                out.append(f"{m.name}_bucket{le} {acc}{tail}")
                out.append(f"{m.name}_sum{_fmt_labels(labels)} {total}")
                out.append(f"{m.name}_count{_fmt_labels(labels)} {acc}")
        else:
            for labels, v in m.samples():
                out.append(f"{m.name}{_fmt_labels(labels)} {v}")
    return "\n".join(out) + "\n"


# -- engine collectors (ref: metrics/*.go one file per layer) ---------------

QUERY_TOTAL = Counter("tidb_tpu_query_total", "Statements executed, by type/status")
QUERY_DURATION = Histogram("tidb_tpu_query_duration_seconds",
                           "Statement wall time, by type")
SLOW_QUERY_TOTAL = Counter("tidb_tpu_slow_query_total",
                           "Statements exceeding tidb_slow_log_threshold")
TXN_TOTAL = Counter("tidb_tpu_txn_total", "Transaction outcomes")
GC_RECLAIMED = Counter("tidb_tpu_gc_reclaimed_rows_total",
                       "MVCC versions reclaimed by GC")
CONN_GAUGE = Gauge("tidb_tpu_connections", "Open server connections")
FRAGMENT_DISPATCH = Counter("tidb_tpu_fragment_dispatch_total",
                            "Distributed fragment executions, by kind")
EXTERNAL_AGG = Counter("tidb_tpu_external_agg_total",
                       "Key-range external aggregation merges (group "
                       "state exceeded the memory budget)")

# -- distributed-execution telemetry (fragments, DCN, memory) ---------------
# The engine-reported side of what bench.py used to measure externally:
# per-dispatch accounting, fragment wall time, DCN traffic, and
# memory-quota events all render on /metrics.

DISPATCH_TOTAL = Counter(
    "tidb_tpu_device_dispatch_total",
    "Device round trips (kernel launches + transfers), by site — the "
    "process-wide mirror of utils.dispatch's thread-local counter")
FRAGMENT_SECONDS = Histogram(
    "tidb_tpu_fragment_seconds",
    "Wall time of one mesh-fragment dispatch, by kind (async dispatch: "
    "measures launch + any synchronous trace/compile, not device busy); "
    "carries a trace_id exemplar for the worst recent dispatch",
    exemplars=True)
FRAGMENT_COMPILE = Counter(
    "tidb_tpu_fragment_compile_total",
    "Fragment programs compiled from plan subtrees, by output kind")
COLLECTIVE_MERGE_SECONDS = Histogram(
    "tidb_tpu_collective_merge_seconds",
    "Host-driven merge of per-shard collective (psum) states across "
    "streamed fragment batches")
DCN_BYTES = Counter(
    "tidb_tpu_dcn_bytes_total",
    "DCN tier wire traffic through this process, by direction")
DCN_RTT = Histogram(
    "tidb_tpu_dcn_rtt_seconds",
    "Coordinator-observed round-trip time of one DCN worker call")
PLAN_CACHE_TOTAL = Counter(
    "tidb_tpu_plan_cache_total",
    "Plan-cache events by kind: hit, miss, bypass (ineligible or "
    "known-uncacheable statement), evict (LRU), invalidate (schema/"
    "stats change)")
PARSE_SECONDS = Histogram(
    "tidb_tpu_parse_seconds",
    "SQL text -> AST wall time per parse() call")
PLAN_SECONDS = Histogram(
    "tidb_tpu_plan_seconds",
    "Logical optimization + physical lowering wall time per "
    "plan_statement call (cache hits skip this entirely)")
JOIN_COMPILE_TOTAL = Counter(
    "tidb_tpu_join_compile_total",
    "Join kernel (re)traces by kernel (build_sort/probe/expand) — "
    "incremented at TRACE time inside the fused join kernels, so a "
    "steady-state repeated join must not move it (the retrace guard "
    "test and EXPLAIN ANALYZE's recompiles field both read it)")
JOIN_PROBE_MODE_TOTAL = Counter(
    "tidb_tpu_join_probe_mode_total",
    "Probe chunks resolved per strategy, by mode: sorted (searchsorted "
    "range lookup), xla / pallas (open-addressing hash table, window-"
    "scan / VMEM kernel), direct (dense-domain direct-address index), "
    "host (numpy tier), fused_* (same strategies inside a fused "
    "scan->probe program) — captures show which path actually ran")
JOIN_PROBE_SECONDS = Histogram(
    "tidb_tpu_join_probe_seconds",
    "Wall time of one fused probe+expand pass over a probe chunk, by "
    "join kind; carries a trace_id exemplar for the worst recent pass",
    exemplars=True)
JOIN_BUILD_SECONDS = Histogram(
    "tidb_tpu_join_build_seconds",
    "Wall time of one hash-join build phase (drain + pack + sort), by "
    "tier: host (numpy probe path), device (fused on-device sort), "
    "host_sorted (tidb_tpu_join_device_build=0 escape hatch)")
DCN_RETRY_TOTAL = Counter(
    "tidb_tpu_dcn_retry_total",
    "DCN recovery actions by kind: rpc (idempotent call re-sent on a "
    "fresh connection), reconnect (worker socket re-established by the "
    "health machine), cancel_dial (side-channel connection opened to "
    "deliver a cancel)")
DCN_FAILOVER_TOTAL = Counter(
    "tidb_tpu_dcn_failover_total",
    "Partition partials re-run on a replica worker after the primary "
    "(and its retry) was unreachable")
WORKER_STATE = Gauge(
    "tidb_tpu_dcn_worker_state",
    "Per-worker health-machine state: 0=up, 1=suspect, 2=down")
DCN_CANCEL_TOTAL = Counter(
    "tidb_tpu_dcn_cancel_total",
    "Coordinator-initiated cancels of in-flight worker partials "
    "(KILL propagation / statement deadline expiry)")
DEADLINE_EXCEEDED_TOTAL = Counter(
    "tidb_tpu_deadline_exceeded_total",
    "Statements aborted because max_execution_time expired")
MEM_QUOTA_ENGAGED = Counter(
    "tidb_tpu_mem_quota_engaged_total",
    "Queries whose host memory consumption crossed tidb_mem_quota_query "
    "(spill or cancel followed)")
SPILL_TOTAL = Counter(
    "tidb_tpu_spill_total", "Operator-state spill events to tmp storage")
SPILL_BYTES = Counter(
    "tidb_tpu_spill_bytes_total", "Bytes shed to tmp storage by spills")

# -- sharded placement + cross-process shuffle (ISSUE 13) -------------------

SHUFFLE_BYTES_TOTAL = Counter(
    "tidb_tpu_shuffle_bytes_total",
    "Cross-worker shuffle exchange payload bytes (FoR-encoded batches) "
    "by direction: out = shipped to a peer worker, in = staged into "
    "the local inbox from a peer")
SHARD_SCAN_TOTAL = Counter(
    "tidb_tpu_shard_scan_total",
    "Distributed statements planned against SHARD BY placement, by "
    "whether owner pruning skipped part of the fleet (pruned=yes: at "
    "least one non-owner worker received no RPC and did no work)")

# -- columnar segment store (ISSUE 8) ---------------------------------------

SCAN_SEGMENTS_SCANNED_TOTAL = Counter(
    "tidb_tpu_scan_segments_scanned_total",
    "Columnar segments staged by table scans (after zone-map pruning); "
    "with ..._pruned_total this gives the engine-reported pruning "
    "fraction the Q6 perf floor asserts on")
SCAN_SEGMENTS_PRUNED_TOTAL = Counter(
    "tidb_tpu_scan_segments_pruned_total",
    "Columnar segments skipped before host->device staging because the "
    "scan's pushed range/equality predicates cannot match the "
    "segment's zone maps (min/max/null_count)")
SPILL_SEGMENT_BYTES = Counter(
    "tidb_tpu_spill_segment_bytes_total",
    "Encoded segment payload bytes moved across the disk spill "
    "boundary, by direction: out = evicted to a segment spill file "
    "under the statement memory budget, in = re-materialized from "
    "disk on a later touch")

# -- pipelined device-resident execution (ISSUE 9) --------------------------

PIPELINE_PREFETCH_TOTAL = Counter(
    "tidb_tpu_pipeline_prefetch_total",
    "Chunk staging events through the double-buffered pipeline, by "
    "outcome: hit (buffer was already staged when the compute loop "
    "asked), wait (the loop blocked on in-flight staging), inline "
    "(prefetch disabled or depth exhausted — staged synchronously), "
    "cancelled (KILL/deadline stopped the staging thread mid-fragment), "
    "error (staging died on quota OOM or another fault — relayed typed "
    "to the compute loop)")
PIPELINE_PREFETCH_BYTES = Counter(
    "tidb_tpu_pipeline_prefetch_bytes_total",
    "Host->device bytes moved by the pipeline staging thread ahead of "
    "compute (double-buffered overlap; inline stagings count too)")
DEVICE_CACHE_TOTAL = Counter(
    "tidb_tpu_device_cache_total",
    "Cross-statement device buffer cache events, by kind: hit (a warm "
    "statement reused staged device buffers and moved zero bytes), "
    "miss, evict (LRU under tidb_tpu_device_buffer_cache_bytes), "
    "invalidate (table version/data_epoch/stats moved, or a schema "
    "change cleared the cache — the plan cache's invalidation rules)")

# -- distributed tracing (ISSUE 5) ------------------------------------------

DCN_RPC_SECONDS = Histogram(
    "tidb_tpu_dcn_rpc_seconds",
    "One coordinator->worker RPC round trip, by rpc command; carries a "
    "trace_id exemplar for the worst recent call so /metrics links "
    "straight to the offending trace on /trace?id=",
    exemplars=True)
TRACE_KEPT_TOTAL = Counter(
    "tidb_tpu_trace_kept_total",
    "Traces retained in the tail-sampled store, by first keep reason "
    "(sampled, slow, error:*, retry, failover, trace)")

# -- plan feedback (ISSUE 15) -----------------------------------------------

PLAN_EST_DRIFT = Histogram(
    "tidb_tpu_plan_est_drift",
    "Per-statement worst-operator estimation drift: max(actual/est, "
    "est/actual) over every operator whose actual row count the "
    "feedback harvest knew — 1.0 means every estimate was exact, 100 a "
    "hundredfold misestimate; carries a trace_id exemplar for the "
    "worst recent statement so /metrics links the drift straight to "
    "its trace",
    buckets=(1.0, 1.5, 2.0, 4.0, 10.0, 30.0, 100.0, 1000.0),
    exemplars=True)

# -- serving tier: admission-controlled scheduler + micro-batching ----------

SCHED_QUEUE_DEPTH = Gauge(
    "tidb_tpu_sched_queue_depth",
    "Statements admitted but not yet claimed by a scheduler worker "
    "(queued singletons + members of still-gathering batch groups)")
SCHED_ADMISSION_TOTAL = Counter(
    "tidb_tpu_sched_admission_total",
    "Scheduler admission decisions, by outcome: admitted, rejected "
    "(queue full / server memory quota / draining), timed_out (admitted "
    "but evicted after tidb_tpu_sched_queue_timeout_ms unclaimed)")
BATCH_SIZE = Histogram(
    "tidb_tpu_batch_size",
    "Members per coalesced device dispatch (1 = a batchable statement "
    "whose gather window closed alone)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128))
BATCH_COALESCE_TOTAL = Counter(
    "tidb_tpu_batch_coalesce_total",
    "Statements that rode a multi-statement coalesced dispatch (members "
    "of batches with n >= 2; singleton executions never count)")
