"""Cross-cutting utilities (ref: util/ — memory tracking, execdetails,
plan cache)."""
