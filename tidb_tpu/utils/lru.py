"""Tiny bounded-LRU get-or-build over an OrderedDict — shared by the
jit-fragment cache, the shard cache, and the exchange-growth memo so the
recency/eviction discipline lives in exactly one place."""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, TypeVar

__all__ = ["get_or_build", "touch"]

V = TypeVar("V")


def get_or_build(od: "OrderedDict", key, build: Callable[[], V], max_entries: int) -> V:
    v = od.get(key)
    if v is None and key not in od:
        v = build()
        od[key] = v
    od.move_to_end(key)
    while len(od) > max_entries:
        od.popitem(last=False)
    return od[key]


def touch(od: "OrderedDict", key, value, max_entries: int) -> None:
    """Insert/overwrite `key` as most-recently-used and trim."""
    od[key] = value
    od.move_to_end(key)
    while len(od) > max_entries:
        od.popitem(last=False)
