"""Device-dispatch accounting.

Per-dispatch round-trip latency is the dominant cost on a tunneled or
remote accelerator (VERDICT r4: the on-chip join path paid a ~500 ms
floor per dispatch and nothing surfaced the count). This module keeps a
process-global counter incremented at the engine's device choke points:

  - every invocation of a ``cached_jit`` kernel (the local executor
    engine's compiled expression/sort/join/agg programs)
  - every mesh fragment dispatch (``ShardCache.get_fragment``)
  - every host->device staging transfer (``parallel.partition``)

``execdetails`` snapshots the counter around each operator's open/next
so EXPLAIN ANALYZE shows per-operator dispatch counts — the visibility
knob the reference gets from its coprocessor request counters
(ref: util/execdetails CopRuntimeStats' distsql request counts).
"""

from __future__ import annotations

import time
from typing import Callable

import jax

__all__ = ["record", "count", "counted_jit", "record_xfer", "xfer_bytes",
           "record_fetch", "record_spill", "spill_bytes",
           "compile_seconds"]

import threading

# thread-local: the server runs each connection's queries on its own
# thread, so per-operator EXPLAIN ANALYZE deltas must not absorb a
# concurrent session's kernel launches
_tls = threading.local()


def record(n: int = 1, site: str = "other") -> None:
    """Count n device round trips (program launches or transfers)."""
    _tls.count = getattr(_tls, "count", 0) + n
    by = getattr(_tls, "by_site", None)
    if by is None:
        by = _tls.by_site = {}
    by[site] = by.get(site, 0) + n
    # process-wide mirror: /metrics exposes dispatch totals so external
    # drivers (bench.py) read the engine's own figure instead of
    # re-deriving it — the thread-local stays the per-query source for
    # EXPLAIN ANALYZE deltas
    from tidb_tpu.utils.metrics import DISPATCH_TOTAL

    DISPATCH_TOTAL.inc(n, site=site)


def event(site: str) -> None:
    """Count a per-site EVENT without touching the device round-trip
    totals: by_site() observers (tests, profiling) see it, but EXPLAIN
    ANALYZE dispatch deltas, stmt-summary dispatch counts, and the
    /metrics dispatch totals stay honest. Used for engine milestones
    that are observable like dispatches but aren't one (e.g. one CTE
    materialization per WITH body)."""
    by = getattr(_tls, "by_site", None)
    if by is None:
        by = _tls.by_site = {}
    by[site] = by.get(site, 0) + 1


def count() -> int:
    return getattr(_tls, "count", 0)


def record_xfer(nbytes: int, direction: str = "h2d") -> None:
    """Count host↔device transfer BYTES on this thread (ISSUE 16
    resource profiles). Called at the existing staging/fetch choke
    points AFTER the transfer completes — never a new device sync. The
    thread-local feeds the per-statement profile; the process-wide
    mirror feeds /metrics."""
    n = int(nbytes)
    if n <= 0:
        return
    _tls.xfer = getattr(_tls, "xfer", 0) + n
    from tidb_tpu.utils.metrics import XFER_BYTES

    XFER_BYTES.inc(n, dir=direction)


def xfer_bytes() -> int:
    return getattr(_tls, "xfer", 0)


def record_fetch(tree):
    """Record a COMPLETED device→host fetch's bytes (d2h) and return
    the tree unchanged — wraps the sanctioned ``jax.device_get`` sites
    (the arrays are host-resident by the time this sums nbytes, so the
    accounting itself never blocks)."""
    n = sum(getattr(leaf, "nbytes", 0)
            for leaf in jax.tree_util.tree_leaves(tree))
    record_xfer(n, "d2h")
    return tree


def record_spill(nbytes: int) -> None:
    """Count bytes this thread's statement spilled to disk (the
    process-wide SPILL_BYTES/SPILL_SEGMENT_BYTES metrics move at the
    spill sites themselves)."""
    _tls.spill = getattr(_tls, "spill", 0) + int(nbytes)


def spill_bytes() -> int:
    return getattr(_tls, "spill", 0)


def record_compile(kernel: str = "join") -> None:
    """Count one kernel (re)trace on this thread. Called from inside
    traced jit bodies (they only execute at trace time), so the counter
    moves on real XLA compilations — EXPLAIN ANALYZE diffs it around
    each operator to surface per-operator recompiles, and the statement
    trace (if one is active) gets the event as a span annotation."""
    _tls.compiles = getattr(_tls, "compiles", 0) + 1
    from tidb_tpu.utils import tracing

    tracing.annotate(f"recompile:{kernel}")


def compile_count() -> int:
    return getattr(_tls, "compiles", 0)


def _record_compile_seconds(s: float) -> None:
    _tls.compile_s = getattr(_tls, "compile_s", 0.0) + float(s)
    from tidb_tpu.utils.metrics import COMPILE_SECONDS

    COMPILE_SECONDS.inc(float(s))


def compile_seconds() -> float:
    """Wall seconds this thread spent tracing+compiling fragments
    (first invocation per jit entry per shape — where XLA compiles
    synchronously), attributed to the statement that triggered them."""
    return getattr(_tls, "compile_s", 0.0)


def by_site() -> dict:
    """Cumulative per-site breakdown (for profiling, not EXPLAIN)."""
    return dict(getattr(_tls, "by_site", {}))


def counted_jit(fn: Callable, site: str = "jit", **jit_kwargs) -> Callable:
    """jax.jit with dispatch accounting on every invocation."""
    # lint: disable=jit-hygiene -- this IS the counting wrapper the
    # pass audits call sites of; identity discipline is the caller's
    jitted = jax.jit(fn, **jit_kwargs)
    sizer = getattr(jitted, "_cache_size", None)

    def counted(*args, **kwargs):
        record(site=site)
        if sizer is None:
            return jitted(*args, **kwargs)
        # compile-seconds attribution (ISSUE 16): a growing executable
        # cache means THIS invocation paid a trace+compile — charge its
        # wall time to the triggering statement's thread. Warm calls
        # pay two perf_counter reads and one C++ cache-size probe.
        n0 = sizer()
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        if sizer() > n0:
            _record_compile_seconds(time.perf_counter() - t0)
        return out

    return counted
