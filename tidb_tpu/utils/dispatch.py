"""Device-dispatch accounting.

Per-dispatch round-trip latency is the dominant cost on a tunneled or
remote accelerator (VERDICT r4: the on-chip join path paid a ~500 ms
floor per dispatch and nothing surfaced the count). This module keeps a
process-global counter incremented at the engine's device choke points:

  - every invocation of a ``cached_jit`` kernel (the local executor
    engine's compiled expression/sort/join/agg programs)
  - every mesh fragment dispatch (``ShardCache.get_fragment``)
  - every host->device staging transfer (``parallel.partition``)

``execdetails`` snapshots the counter around each operator's open/next
so EXPLAIN ANALYZE shows per-operator dispatch counts — the visibility
knob the reference gets from its coprocessor request counters
(ref: util/execdetails CopRuntimeStats' distsql request counts).
"""

from __future__ import annotations

from typing import Callable

import jax

__all__ = ["record", "count", "counted_jit"]

import threading

# thread-local: the server runs each connection's queries on its own
# thread, so per-operator EXPLAIN ANALYZE deltas must not absorb a
# concurrent session's kernel launches
_tls = threading.local()


def record(n: int = 1, site: str = "other") -> None:
    """Count n device round trips (program launches or transfers)."""
    _tls.count = getattr(_tls, "count", 0) + n
    by = getattr(_tls, "by_site", None)
    if by is None:
        by = _tls.by_site = {}
    by[site] = by.get(site, 0) + n
    # process-wide mirror: /metrics exposes dispatch totals so external
    # drivers (bench.py) read the engine's own figure instead of
    # re-deriving it — the thread-local stays the per-query source for
    # EXPLAIN ANALYZE deltas
    from tidb_tpu.utils.metrics import DISPATCH_TOTAL

    DISPATCH_TOTAL.inc(n, site=site)


def event(site: str) -> None:
    """Count a per-site EVENT without touching the device round-trip
    totals: by_site() observers (tests, profiling) see it, but EXPLAIN
    ANALYZE dispatch deltas, stmt-summary dispatch counts, and the
    /metrics dispatch totals stay honest. Used for engine milestones
    that are observable like dispatches but aren't one (e.g. one CTE
    materialization per WITH body)."""
    by = getattr(_tls, "by_site", None)
    if by is None:
        by = _tls.by_site = {}
    by[site] = by.get(site, 0) + 1


def count() -> int:
    return getattr(_tls, "count", 0)


def record_compile(kernel: str = "join") -> None:
    """Count one kernel (re)trace on this thread. Called from inside
    traced jit bodies (they only execute at trace time), so the counter
    moves on real XLA compilations — EXPLAIN ANALYZE diffs it around
    each operator to surface per-operator recompiles, and the statement
    trace (if one is active) gets the event as a span annotation."""
    _tls.compiles = getattr(_tls, "compiles", 0) + 1
    from tidb_tpu.utils import tracing

    tracing.annotate(f"recompile:{kernel}")


def compile_count() -> int:
    return getattr(_tls, "compiles", 0)


def by_site() -> dict:
    """Cumulative per-site breakdown (for profiling, not EXPLAIN)."""
    return dict(getattr(_tls, "by_site", {}))


def counted_jit(fn: Callable, site: str = "jit", **jit_kwargs) -> Callable:
    """jax.jit with dispatch accounting on every invocation."""
    # lint: disable=jit-hygiene -- this IS the counting wrapper the
    # pass audits call sites of; identity discipline is the caller's
    jitted = jax.jit(fn, **jit_kwargs)

    def counted(*args, **kwargs):
        record(site=site)
        return jitted(*args, **kwargs)

    return counted
