"""Memory tracking + spill (ref: util/memory.Tracker tree with OOM
actions, and util/chunk.RowContainer's spill-to-disk).

A MemTracker forms a tree (query root -> operator trackers). consume()
propagates to the root, where the budget lives. On exceeding the budget
the tracker first asks its registered spillables to shed host memory
(largest consumer first — the reference's SpillDiskAction); if nothing
can spill, it cancels the query (the reference's PanicOnExceed/Cancel
action).

Only *host-side* state is tracked: device HBM is governed by the static
chunk capacity and XLA; host accumulation (sort runs, join build, agg
state) is what can grow without bound with cardinality.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Callable, List, Optional

from tidb_tpu.analysis import sanitizer as _san
from tidb_tpu.errors import ExecutionError

__all__ = ["MemTracker", "QueryOOMError", "SpillFile", "SpillableRuns",
           "spill_root_of"]

# One process-wide reentrant lock for tracker-tree accounting: the
# pipeline staging thread (ISSUE 9) and the serving tier's concurrent
# statements both consume() into shared parent trackers, and the
# read-modify-write on `consumed` must not interleave across threads.
# Reentrant because _on_exceed -> spill() re-enters release()/consume()
# on the same thread. Spill I/O under the lock is acceptable: it only
# happens past the budget, where correctness beats concurrency.
# Registered with the sanitizer's lock witness (ISSUE 12) so orders
# threaded through the staging/scheduler threads are recorded.
_ACCOUNT_LOCK = _san.tracked_lock("memory._ACCOUNT_LOCK", threading.RLock)


def spill_root_of(tracker: "MemTracker") -> "MemTracker":
    """The tracker spillables anchor on: the nearest statement-level
    spill root up the parent chain (falling back to the chain's top).
    The ONE definition of the protocol walk — SpillableRuns and the
    columnar ScanPin both register through it."""
    root = tracker
    while root.parent is not None and not root.spill_root:
        root = root.parent
    return root


class QueryOOMError(ExecutionError):
    pass


class MemTracker:
    def __init__(self, label: str = "query", budget: Optional[int] = None,
                 parent: Optional["MemTracker"] = None, spill_enabled: bool = True,
                 spill_root: bool = False):
        self.label = label
        self.budget = budget
        self.parent = parent
        self.spill_enabled = spill_enabled
        # marks the statement-level tracker: spillables anchor here even
        # when the serving tier parents it under session/server trackers
        # (those aggregate accounting only — operator state from one
        # statement must never spill in response to ANOTHER statement's
        # pressure, and their budgets cancel rather than spill)
        self.spill_root = spill_root
        self.consumed = 0
        self.max_consumed = 0
        self._quota_engaged = False  # first budget crossing counted once
        self._spillables: List[object] = []  # objects with spill() -> int

    def child(self, label: str) -> "MemTracker":
        return MemTracker(label, parent=self)

    def detach(self) -> None:
        """Disconnect from the parent chain, returning any un-released
        residual consumption to the ancestors. Statement end under the
        serving tier: operator state the statement never release()d
        (freed wholesale with the executor tree) must not leak into the
        session/server accounting forever."""
        with _ACCOUNT_LOCK:
            p, self.parent = self.parent, None
            if p is None or self.consumed == 0:
                return
            n = self.consumed
            if _san.enabled() and n > 0:
                # leak witness (typed at detach, per ISSUE 12): bytes
                # the statement consumed and never released — detach
                # reclaims them, the sanitizer makes them visible
                _san.note_tracker_detach(self.label, n)
            node = p
            while node is not None:
                node.consumed -= n
                node = node.parent

    def register_spillable(self, obj) -> None:
        self._spillables.append(obj)

    def unregister_spillable(self, obj) -> None:
        if obj in self._spillables:
            self._spillables.remove(obj)

    def consume(self, nbytes: int) -> None:
        with _ACCOUNT_LOCK:
            node = self
            while node is not None:
                node.consumed += nbytes
                node.max_consumed = max(node.max_consumed, node.consumed)
                if node.budget is not None and node.consumed > node.budget:
                    # lint: disable=blocking-under-lock -- deliberate:
                    # past the budget, spill I/O runs under the account
                    # lock — correctness beats concurrency there (module
                    # doc); re-entrancy is why the lock is an RLock
                    node._on_exceed()
                node = node.parent

    def release(self, nbytes: int) -> None:
        with _ACCOUNT_LOCK:
            if _san.enabled() and nbytes > 0 and \
                    self.consumed - nbytes < 0 <= self.consumed:
                # crossing zero on THIS release = some charge returned
                # twice (fatal finding; reported once per crossing)
                _san.note_tracker_release(self.label,
                                          self.consumed - nbytes)
            node = self
            while node is not None:
                node.consumed -= nbytes
                node = node.parent

    # ------------------------------------------------------------------

    def _on_exceed(self) -> None:
        if not self._quota_engaged:
            self._quota_engaged = True
            from tidb_tpu.utils.metrics import MEM_QUOTA_ENGAGED

            MEM_QUOTA_ENGAGED.inc()
        # shed the largest spillable first until we're back under budget;
        # spillables register on the budget-holding (root) tracker
        while self.budget is not None and self.consumed > self.budget:
            candidates = [s for s in self._spillables if s.spillable_bytes() > 0]
            if not candidates:
                raise QueryOOMError(
                    f"Out Of Memory Quota! [budget={self.budget} consumed={self.consumed}]"
                )
            biggest = max(candidates, key=lambda s: s.spillable_bytes())
            freed = biggest.spill()
            if freed <= 0:
                raise QueryOOMError(
                    f"Out Of Memory Quota! [budget={self.budget} consumed={self.consumed}]"
                )


class SpillFile:
    """A spilled batch of named numpy arrays, one .npy per array so reads
    can be mmap-backed (row gathers touch only the needed pages)."""

    def __init__(self, arrays: dict, spill_dir: Optional[str] = None):
        import numpy as np

        self.dir = tempfile.mkdtemp(prefix="tidb_tpu_spill_", dir=spill_dir)
        self.names = list(arrays)
        self.nbytes = 0
        self.rows = 0
        for name, a in arrays.items():
            np.save(os.path.join(self.dir, f"{name}.npy"), a)
            self.nbytes += a.nbytes
            self.rows = len(a)

    def load(self, name: str):
        import numpy as np

        return np.load(os.path.join(self.dir, f"{name}.npy"), mmap_mode="r")

    def close(self) -> None:
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)


class SpillableRuns:
    """Chunk-wise accumulator of named numpy arrays that can shed its
    buffer to disk under memory pressure (the RowContainer analogue).

    Arrays in one append() call must share a row count. Registered on the
    budget-holding tracker; consume() may re-enter via spill()."""

    def __init__(self, tracker: MemTracker, label: str = "runs"):
        self.tracker = tracker
        root = spill_root_of(tracker)
        self._root = root
        if root.spill_enabled:
            root.register_spillable(self)
        self.buf: dict = {}
        self.buf_bytes = 0
        self.files: List[SpillFile] = []
        self.closed = False
        self._frozen: Optional[dict] = None

    def append(self, named: dict) -> None:
        for k, a in named.items():
            self.buf.setdefault(k, []).append(a)
        b = int(sum(a.nbytes for a in named.values()))
        self.buf_bytes += b
        self.tracker.consume(b)  # may call back into self.spill()

    def spillable_bytes(self) -> int:
        return self.buf_bytes

    def spill(self) -> int:
        if self.buf_bytes == 0:
            return 0
        import numpy as np

        if self._frozen is not None:
            # appends may have landed after a reader froze the buffer —
            # spill both, or rows would silently vanish
            arrays = {
                k: (np.concatenate([self._frozen[k]] + self.buf[k])
                    if self.buf.get(k) else self._frozen[k])
                for k in self._frozen
            }
        else:
            arrays = {k: np.concatenate(v) for k, v in self.buf.items()}
        if not arrays:
            return 0
        self.files.append(SpillFile(arrays))
        freed = self.buf_bytes
        self.buf = {}
        self._frozen = None
        self.buf_bytes = 0
        self.tracker.release(freed)
        from tidb_tpu.utils import dispatch as _dsp
        from tidb_tpu.utils.metrics import SPILL_BYTES, SPILL_TOTAL

        SPILL_TOTAL.inc()
        SPILL_BYTES.inc(freed)
        _dsp.record_spill(freed)  # per-statement profile (ISSUE 16)
        return freed

    @property
    def spilled(self) -> bool:
        return bool(self.files)

    def freeze(self) -> None:
        """Collapse the chunk-list buffer into single arrays (call once,
        after the last append; repeated all_runs() calls then share them)."""
        import numpy as np

        if self._frozen is None and any(self.buf.values()):
            self._frozen = {k: np.concatenate(v) for k, v in self.buf.items()}
            self.buf = {}

    def in_memory(self) -> dict:
        self.freeze()
        return self._frozen or {}

    def all_runs(self):
        """[(loader, rows)] across spilled files + the resident buffer.
        loader(name) returns that run's array (mmap-backed for files)."""
        runs = [(f.load, f.rows) for f in self.files]
        mem = self.in_memory()
        if mem:
            rows = len(next(iter(mem.values())))
            runs.append((lambda name, _m=mem: _m[name], rows))
        return runs

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for f in self.files:
            f.close()
        self.files = []
        self.tracker.release(self.buf_bytes)
        self.buf = {}
        self.buf_bytes = 0
        self._root.unregister_spillable(self)
