"""Failpoint injection (ref: pingcap/failpoint — `failpoint.Inject`
annotations compiled into the reference, letting tests trigger commit
failures, retry paths, and OOM actions).

Call sites sprinkle `inject("name")` at interesting boundaries (2PC
phases, exchange staging, spill). Tests arm them:

    with failpoint("commit.before_secondaries", CrashError):
        ...

Disabled failpoints cost one dict lookup."""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, Optional

__all__ = ["inject", "enable", "disable", "failpoint", "FailpointError"]


class FailpointError(RuntimeError):
    """Default injected failure (stands in for a crash/network fault)."""


_active: Dict[str, Callable[[], None]] = {}
_lock = threading.Lock()


def inject(name: str) -> None:
    """Trigger point — no-op unless a test armed `name`."""
    hook = _active.get(name)
    if hook is not None:
        hook()


def enable(name: str, action: Optional[Callable[[], None]] = None,
           exc: Optional[type] = None, times: Optional[int] = None) -> None:
    """Arm a failpoint: run `action`, or raise `exc` (default
    FailpointError). `times` limits how many triggers fire."""
    state = {"left": times}

    def hook():
        if state["left"] is not None:
            if state["left"] <= 0:
                return
            state["left"] -= 1
        if action is not None:
            action()
        else:
            raise (exc or FailpointError)(f"failpoint {name!r}")

    with _lock:
        _active[name] = hook


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


@contextlib.contextmanager
def failpoint(name: str, exc: Optional[type] = None,
              action: Optional[Callable[[], None]] = None,
              times: Optional[int] = None):
    enable(name, action=action, exc=exc, times=times)
    try:
        yield
    finally:
        disable(name)
