"""Failpoint injection (ref: pingcap/failpoint — `failpoint.Inject`
annotations compiled into the reference, letting tests trigger commit
failures, retry paths, and OOM actions).

Call sites sprinkle `inject("name")` at interesting boundaries (2PC
phases, exchange staging, spill, every DCN protocol edge). Tests arm
them:

    with failpoint("commit.before_secondaries", CrashError):
        ...

Arming modes (composable, mirroring the reference's term grammar
`N%return` / `Nth.return`):

  * times=N   — fire at most N times, then go quiet
  * nth=N     — fire only on the N-th trigger (1-based); earlier and
                later hits pass through
  * prob=p    — fire with probability p per hit, from a seeded private
                RNG so chaos runs are reproducible

`hits(name)` counts how often an ARMED call site was reached since its
enable() (unarmed reaches stay free and uncounted) — chaos tests arm a
point, drive the workload, then assert the injection point actually sat
on the executed path. Disabled failpoints cost one dict lookup."""

from __future__ import annotations

import contextlib
import random
import threading
from typing import Callable, Dict, Optional

__all__ = ["inject", "enable", "disable", "failpoint", "FailpointError",
           "hits", "active_names"]


class FailpointError(RuntimeError):
    """Default injected failure (stands in for a crash/network fault)."""


_active: Dict[str, Callable[[], None]] = {}
_hits: Dict[str, int] = {}
_lock = threading.Lock()


def inject(name: str) -> None:
    """Trigger point — no-op unless a test armed `name`."""
    hook = _active.get(name)
    if hook is not None:
        hook()


def hits(name: str) -> int:
    """Times an ARMED `name` call site was reached since enable()."""
    with _lock:
        return _hits.get(name, 0)


def active_names():
    with _lock:
        return sorted(_active)


def enable(name: str, action: Optional[Callable[[], None]] = None,
           exc: Optional[type] = None, times: Optional[int] = None,
           prob: Optional[float] = None, nth: Optional[int] = None,
           seed: int = 0) -> None:
    """Arm a failpoint: run `action`, or raise `exc` (default
    FailpointError). `times` limits how many firings happen; `nth`
    fires only on the N-th trigger; `prob` fires probabilistically per
    hit (seeded — reruns see the same fault schedule)."""
    state = {"left": times, "hit": 0}
    rng = random.Random(seed) if prob is not None else None

    def hook():
        with _lock:
            state["hit"] += 1
            _hits[name] = state["hit"]
            n = state["hit"]
            if nth is not None and n != nth:
                return
            if rng is not None and rng.random() >= prob:
                return
            if state["left"] is not None:
                if state["left"] <= 0:
                    return
                state["left"] -= 1
        if action is not None:
            action()
        else:
            raise (exc or FailpointError)(f"failpoint {name!r}")

    with _lock:
        _active[name] = hook
        _hits[name] = 0


def disable(name: str) -> None:
    with _lock:
        _active.pop(name, None)


@contextlib.contextmanager
def failpoint(name: str, exc: Optional[type] = None,
              action: Optional[Callable[[], None]] = None,
              times: Optional[int] = None, prob: Optional[float] = None,
              nth: Optional[int] = None, seed: int = 0):
    enable(name, action=action, exc=exc, times=times, prob=prob, nth=nth,
           seed=seed)
    try:
        yield
    finally:
        disable(name)
