"""Always-on, tail-sampled distributed tracing (ref: util/tracing +
the Dapper span model production OLAP engines ship: every statement
records a cheap span tree; head sampling decides whether an UNEVENTFUL
statement keeps it, tail rules retroactively keep exactly the traces
worth reading — slow statements, deadline/kill victims, retry/failover
survivors, and errors).

Building blocks:

  * ``Span`` — monotonic-clock interval with a parent link, a process
    label, and free-form annotations. ``start_us`` is relative to the
    owning trace's anchor, so spans from concurrent threads render with
    real overlap instead of as-if-sequential.
  * ``Trace`` — one statement's bounded span collection. trace_id is
    ``<digest16>-<seq>`` (statement digest + process-wide sequence).
    Lock-cheap: span-id allocation and list appends ride CPython
    atomicity; the lock is only taken to graft remote spans and to
    export.
  * ``graft`` — re-anchors spans shipped back by a DCN worker under the
    coordinator RPC span that carried them, remapping the worker's
    process-local span ids so one cross-process tree comes out.
  * ``TraceStore`` — capacity-bounded ring of KEPT traces, surfaced by
    the status port's ``/trace`` endpoint and
    ``information_schema.cluster_trace``.

Thread-local context: ``push``/``pop`` install a trace (plus current
parent span) on the calling thread; ``span()``/``annotate()``/
``current()`` read it. Code running on other threads (DCN dispatch
fan-out) records spans directly on the Trace object with explicit
parent ids instead.

The off path must stay near-free: with no trace installed every hook is
one thread-local read and a None check — the bench.py warm join
microbench gates tracing overhead with sampling off at <= 2%.
"""

from __future__ import annotations

import contextlib
import itertools
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Span", "Trace", "TraceStore", "STORE", "current", "push",
           "pop", "span", "begin", "finish", "annotate",
           "current_span_id", "head_sampled", "make_trace_id", "keep",
           "current_trace_id"]

_SEQ = itertools.count(1)

# a runaway statement must not turn its trace into a memory leak: past
# the cap spans are counted (``dropped``) but not retained
DEFAULT_MAX_SPANS = 512

_tls = threading.local()


def make_trace_id(digest: str) -> str:
    """trace_id = statement digest (16 hex chars) + process-wide seq."""
    return f"{(digest or 'anon')[:16]}-{next(_SEQ)}"


def head_sampled(rate: float) -> bool:
    """One head-sampling coin flip; rate<=0 never pays the RNG call."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return random.random() < rate


class Span:
    __slots__ = ("span_id", "parent_id", "name", "start_us", "dur_us",
                 "proc", "notes")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 start_us: int):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_us = start_us
        self.dur_us = -1  # -1: still open
        self.proc = ""    # "" = this process; set on graft to the endpoint
        self.notes: List[str] = []


class _NullNotes(list):
    """Append sink for the dropped-span sentinel: callers annotate
    spans unconditionally, and the shared sentinel must not accumulate
    (or leak) their notes."""

    def append(self, _x) -> None:
        pass

    def extend(self, _xs) -> None:
        pass


# sentinel returned once a trace is over its span budget: timing it is
# skipped and end() is a no-op, so hot loops never branch on fullness
_DROPPED = Span(-1, None, "<dropped>", 0)
_DROPPED.notes = _NullNotes()


class Trace:
    """One statement's span tree (see module docstring)."""

    def __init__(self, trace_id: str, sampled: bool = False,
                 max_spans: int = DEFAULT_MAX_SPANS):
        self.trace_id = trace_id
        self.sampled = sampled
        self.max_spans = max_spans
        self.t0_perf = time.perf_counter()
        self.start_ts = time.time()
        self.spans: List[Span] = []
        self.dropped = 0
        self.keep_reasons: List[str] = []
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # -- recording ------------------------------------------------------

    def _now_us(self) -> int:
        return int((time.perf_counter() - self.t0_perf) * 1e6)

    def begin(self, name: str, parent_id: Optional[int] = None) -> Span:
        if len(self.spans) >= self.max_spans:
            # lint: disable=lock-discipline -- lock-cheap by design (see
            # module doc): appends/counts ride CPython atomicity; the
            # lock only guards export/graft snapshots
            self.dropped += 1
            return _DROPPED
        s = Span(next(self._ids), parent_id, name, self._now_us())
        # lint: disable=lock-discipline -- CPython-atomic append; the
        # hot record path must not pay a lock per span (module doc)
        self.spans.append(s)
        return s

    def end(self, s: Span) -> None:
        if s is _DROPPED:
            return
        s.dur_us = self._now_us() - s.start_us

    def add_complete(self, name: str, t0_perf: float, dur_s: float,
                     parent_id: Optional[int] = None,
                     notes: Optional[List[str]] = None) -> Span:
        """Record an already-measured interval (fragment dispatches and
        other code that timed itself with perf_counter)."""
        if len(self.spans) >= self.max_spans:
            # lint: disable=lock-discipline -- lock-cheap by design (see
            # module doc and begin())
            self.dropped += 1
            return _DROPPED
        s = Span(next(self._ids), parent_id, name,
                 int((t0_perf - self.t0_perf) * 1e6))
        s.dur_us = int(dur_s * 1e6)
        if notes:
            s.notes.extend(notes)
        # lint: disable=lock-discipline -- CPython-atomic append (see
        # module doc and begin())
        self.spans.append(s)
        return s

    def keep(self, reason: str) -> None:
        """Tail rule: this trace survives regardless of head sampling."""
        if reason not in self.keep_reasons:
            self.keep_reasons.append(reason)

    @property
    def kept(self) -> bool:
        return bool(self.keep_reasons)

    # -- cross-process assembly -----------------------------------------

    def export(self) -> List[Dict]:
        """Wire form of every FINISHED span (codec-safe scalars only) —
        a DCN worker piggybacks this on its RPC response."""
        with self._lock:
            spans = list(self.spans)
        out = []
        for s in spans:
            out.append({"i": s.span_id, "p": s.parent_id or 0,
                        "n": s.name,
                        "s": s.start_us,
                        "d": s.dur_us if s.dur_us >= 0 else
                        self._now_us() - s.start_us,
                        "a": list(s.notes)})
        return out

    def graft(self, remote: List[Dict], base: Span, proc: str) -> None:
        """Attach a worker's exported spans under `base` (the RPC span
        that carried them). Remote span ids are process-local — remap
        them to fresh local ids; remote roots (parent unknown here)
        hang off `base`. Remote offsets are relative to the worker's
        request-receipt anchor, so they re-anchor at the RPC span's
        start (the error is one network one-way — unobservable without
        a clock sync protocol, and small on a DCN link)."""
        if base is _DROPPED or not remote:
            return
        idmap: Dict[int, int] = {}
        with self._lock:
            for r in remote:
                if len(self.spans) >= self.max_spans:
                    self.dropped += len(remote) - len(idmap)
                    return
                try:
                    s = Span(next(self._ids), None, str(r["n"]),
                             base.start_us + int(r["s"]))
                    s.dur_us = int(r["d"])
                    s.proc = proc
                    notes = r.get("a") or []
                    s.notes = [str(a) for a in notes]
                    idmap[int(r["i"])] = s.span_id
                    parent = int(r.get("p") or 0)
                    s.parent_id = idmap.get(parent, base.span_id)
                except (KeyError, TypeError, ValueError):
                    continue  # malformed remote span: skip, keep the rest
                self.spans.append(s)

    # -- read side ------------------------------------------------------

    def duration_ms(self) -> float:
        roots = [s for s in self.spans if s.parent_id is None]
        end = 0
        for s in self.spans:
            end = max(end, s.start_us + max(s.dur_us, 0))
        start = min((s.start_us for s in roots), default=0)
        return round((end - start) / 1e3, 3)

    def summary(self) -> Dict:
        root = next((s for s in self.spans if s.parent_id is None), None)
        return {
            "trace_id": self.trace_id,
            "start": time.strftime("%Y-%m-%d %H:%M:%S",
                                   time.localtime(self.start_ts)),
            "root": root.name if root is not None else "",
            "duration_ms": self.duration_ms(),
            "spans": len(self.spans),
            "dropped": self.dropped,
            "sampled": self.sampled,
            "keep": list(self.keep_reasons),
        }

    def to_dict(self) -> Dict:
        """Full JSON form: summary + the span TREE (children nested)."""
        with self._lock:
            spans = list(self.spans)
        nodes = {}
        for s in spans:
            nodes[s.span_id] = {
                "span_id": s.span_id, "name": s.name, "proc": s.proc,
                "start_us": s.start_us, "duration_us": max(s.dur_us, 0),
                "annotations": list(s.notes), "children": [],
            }
        roots = []
        for s in spans:
            node = nodes[s.span_id]
            parent = nodes.get(s.parent_id)
            (roots if parent is None else parent["children"]).append(node)
        out = self.summary()
        out["tree"] = roots
        return out


# ---------------------------------------------------------------------------
# thread-local context
# ---------------------------------------------------------------------------


def push(trace: Trace, span_: Optional[Span] = None) -> None:
    """Install `trace` as this thread's current trace; `span_` (if any)
    becomes the parent for subsequently opened spans."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((trace, [span_] if span_ is not None else []))


def pop() -> Optional[Trace]:
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    return stack.pop()[0]


def current() -> Optional[Trace]:
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    return stack[-1][0]


def current_span_id() -> Optional[int]:
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    spans = stack[-1][1]
    return spans[-1].span_id if spans else None


def current_trace_id() -> str:
    tr = current()
    return tr.trace_id if tr is not None else ""


def begin(name: str) -> Optional[Span]:
    """Open a span under the thread's current trace and make it the
    parent for subsequent spans. Pair with finish(); for block-scoped
    spans prefer the span() context manager. None without a trace."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    trace, spans = stack[-1]
    s = trace.begin(name, spans[-1].span_id if spans else None)
    spans.append(s)
    return s


def finish(s: Optional[Span]) -> None:
    stack = getattr(_tls, "stack", None)
    if s is None or not stack:
        return
    trace, spans = stack[-1]
    if s in spans:
        # pop through any child spans a non-local exit left open
        while spans and spans[-1] is not s:
            trace.end(spans.pop())
        spans.pop()
    trace.end(s)


@contextlib.contextmanager
def span(name: str):
    """Span under the thread's current trace; no-op when none is
    installed (the off path: one TLS read + None check)."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        yield None
        return
    trace, spans = stack[-1]
    s = trace.begin(name, spans[-1].span_id if spans else None)
    spans.append(s)
    try:
        yield s
    finally:
        spans.pop()
        trace.end(s)


def annotate(note: str) -> None:
    """Attach a note to the thread's current span (or the trace root
    when no span is open). No-op without a trace."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    trace, spans = stack[-1]
    target = spans[-1] if spans else (trace.spans[0] if trace.spans else None)
    if target is not None and target is not _DROPPED:
        target.notes.append(note)


def keep(reason: str) -> None:
    """Tail-keep the thread's current trace, if any."""
    tr = current()
    if tr is not None:
        tr.keep(reason)


# ---------------------------------------------------------------------------
# tail-sampled store
# ---------------------------------------------------------------------------


class TraceStore:
    """Capacity-bounded ring of kept traces (newest wins)."""

    def __init__(self, capacity: int = 64):
        self.lock = threading.Lock()
        self.capacity = capacity
        self._ring: deque = deque()

    def add(self, trace: Trace, capacity: Optional[int] = None) -> None:
        from tidb_tpu.utils.metrics import TRACE_KEPT_TOTAL

        reason = trace.keep_reasons[0] if trace.keep_reasons else "sampled"
        TRACE_KEPT_TOTAL.inc(reason=reason)
        with self.lock:
            if capacity is not None:
                self.capacity = max(1, int(capacity))
            self._ring.append(trace)
            while len(self._ring) > self.capacity:
                self._ring.popleft()

    def get(self, trace_id: str) -> Optional[Trace]:
        with self.lock:
            for t in reversed(self._ring):
                if t.trace_id == trace_id:
                    return t
        return None

    def list(self, n: int = 50) -> List[Dict]:
        if n <= 0:
            return []  # [-0:] would be the FULL ring, not none
        with self.lock:
            traces = list(self._ring)[-n:]
        return [t.summary() for t in reversed(traces)]

    def traces(self) -> List[Trace]:
        with self.lock:
            return list(self._ring)

    def clear(self) -> None:
        with self.lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self.lock:
            return len(self._ring)


# process-global like the metrics REGISTRY: the status port, I_S, and
# every session/cluster in this process share one tail-sampled store
STORE = TraceStore()


def apply_tail_rules(tr: Trace, dur_s: float, threshold_ms: int,
                     error=None, capacity: Optional[int] = None) -> str:
    """The ONE end-of-statement keep sequence, shared by
    Session._execute_timed and standalone Cluster.query (two copies
    would drift): error keep -> slow keep -> pop off the thread ->
    head-sample keep -> store if kept. Returns the trace_id."""
    if error is not None:
        tr.keep(f"error:{type(error).__name__}")
    if dur_s * 1e3 >= threshold_ms:
        tr.keep("slow")
    if current() is tr:
        pop()
    if tr.sampled:
        tr.keep("sampled")
    if tr.kept:
        STORE.add(tr, capacity=capacity)
    return tr.trace_id
