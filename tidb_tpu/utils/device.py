"""Device-placement policy for the execution runtime.

The TPU-first execution contract (ref: SURVEY.md §7 hard part 5 —
host<->device staging costs): all hot-loop compute runs inside a small
number of *compiled* fragments dispatched to the accelerator mesh, and
everything outside those fragments (operator glue, final ORDER BY over a
handful of groups, result decode) runs on the host. On real hardware a
device round-trip costs ~100-500ms of latency when the chip is reached
over a network tunnel, and even locally each eager op dispatch +
transfer is pure overhead — a query must cost O(1) device round-trips,
not O(ops).

`host_eager()` pins jax's *default* device to the CPU backend for the
duration of the executor tree walk. Compiled mesh fragments are
unaffected: their inputs are committed, sharded device arrays, and
explicit shardings/meshes always win over the default-device hint. Only
uncommitted eager ops (numpy inputs) land on CPU.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax

__all__ = ["host_eager", "host_cpu_device"]

_cpu_device: Optional[object] = None
_probed = False


def host_cpu_device():
    """The host CPU backend device, or None when the default backend is
    already CPU (tests pin jax_platforms=cpu; no second backend exists)."""
    global _cpu_device, _probed
    if not _probed:
        _probed = True
        try:
            if jax.default_backend() != "cpu":
                _cpu_device = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            _cpu_device = None
    return _cpu_device


def host_eager():
    """Context manager: eager ops go to host CPU; compiled mesh
    fragments keep their explicit placement."""
    dev = host_cpu_device()
    if dev is None:
        return contextlib.nullcontext()
    return jax.default_device(dev)
