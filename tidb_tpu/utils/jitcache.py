"""Compiled-fragment cache (the plan-cache/prepared-statement analogue,
ref: planner plan cache reusing compiled plans across executions).

jax.jit keys on Python function identity, and the executors build fresh
closures per open() — without this cache every execution of the same
query would re-trace and re-compile its device fragments. Keys are reprs
of the compiled IR: binder uids are deterministic per statement (a fresh
Binder numbers from zero for every plan), so the same SQL text always
produces the same key, while any difference in baked constants (e.g.
dictionary codes for string literals) changes it.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Tuple

import jax

from tidb_tpu.utils.lru import get_or_build

__all__ = ["cached_jit", "clear", "size"]

# LRU-bounded: keys bake in value-level constants (dictionary codes for
# string literals), so mutating workloads mint new keys over time — old
# executables must age out rather than accumulate for the process lifetime.
MAX_ENTRIES = 512

_CACHE: "OrderedDict[Tuple[str, str], Callable]" = OrderedDict()


def cached_jit(ns: str, key: str, build: Callable[[], Callable], **jit_kwargs) -> Callable:
    """Return a jitted fn for (ns, key), building it on first use.

    `build` returns the raw python function; it is only called on a miss.
    The jitted fn itself remains shape-polymorphic (jax retraces per
    shape under the same identity), so one entry serves all chunk sizes.
    Every invocation is dispatch-counted (utils.dispatch) so EXPLAIN
    ANALYZE can surface per-operator device round trips.
    """
    from tidb_tpu.utils import dispatch

    return get_or_build(
        _CACHE, (ns, key),
        # lint: disable=jit-hygiene -- the sanctioned signature-keyed
        # cache: identity is (ns, key) covering every baked constant,
        # so a hit can never see a stale closure (module doc)
        lambda: dispatch.counted_jit(build(), site=f"jit:{ns}", **jit_kwargs),
        MAX_ENTRIES
    )


def clear() -> None:
    _CACHE.clear()


def size() -> int:
    return len(_CACHE)
