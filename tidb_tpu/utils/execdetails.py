"""Per-operator runtime statistics for EXPLAIN ANALYZE
(ref: util/execdetails RuntimeStats + EXPLAIN ANALYZE's actRows/time/loops
columns on every operator).

Instrumentation wraps each executor's open/next in place; row counts force
a device sync per chunk, which is exactly the accuracy/overhead trade
EXPLAIN ANALYZE makes in the reference too.
"""

from __future__ import annotations

import time
from typing import List, Tuple

import numpy as np

__all__ = ["instrument", "analyze_text"]


def instrument(root) -> List:
    """Wrap open/next of every executor in the tree; returns the node list."""
    nodes = []
    stack = [root]
    while stack:
        e = stack.pop()
        nodes.append(e)
        _wrap(e)
        stack.extend(e.children)
    return nodes


def _wrap(e) -> None:
    from tidb_tpu.utils import dispatch

    orig_open, orig_next = e.open, e.next
    st = e.stats
    # instrumented row counts are exact (every emitted chunk is summed);
    # the builder's plan annotation pairs them with the node's estimate
    # for the est/drift columns and the plan-feedback harvest
    st.measured = True
    p = getattr(e, "_feedback_plan", None)
    if p is not None:
        st.est_rows = float(getattr(p, "est_rows", -1.0))

    def open_(ctx):
        t0 = time.perf_counter()
        if st.first_ts is None:
            st.first_ts = t0
        d0 = dispatch.count()
        c0 = dispatch.compile_count()
        try:
            return orig_open(ctx)
        finally:
            st.open_wall += time.perf_counter() - t0
            st.dispatches += dispatch.count() - d0
            st.recompiles += dispatch.compile_count() - c0

    def next_():
        t0 = time.perf_counter()
        if st.first_ts is None:
            st.first_ts = t0
        d0 = dispatch.count()
        c0 = dispatch.compile_count()
        ch = orig_next()
        st.next_wall += time.perf_counter() - t0
        st.dispatches += dispatch.count() - d0
        st.recompiles += dispatch.compile_count() - c0
        if ch is not None:
            st.chunks += 1
            st.rows += int(np.asarray(ch.sel).sum())
        return ch

    e.open, e.next = open_, next_


_GANTT_W = 10  # character width of the proportional start-offset column


def analyze_text(root) -> str:
    """TiDB-style EXPLAIN ANALYZE table over an executed executor tree.

    The `start` column is each operator's first-activity offset from
    the earliest operator start (stats.first_ts), rendered with a
    proportional gutter — overlapping async fragment executors used to
    render as if they ran sequentially. The `staged` column counts the
    chunks whose device buffers were already in place when the compute
    loop asked (prefetch overlap + device-buffer-cache hits) out of the
    chunks the operator staged — the observability face of the
    pipelined staging path (ISSUE 9).

    `estRows` and `drift` (ISSUE 15) put the planner's estimate next to
    what actually happened: drift = actRows/estRows, so 1.00 is a
    perfect estimate, 100.00 a hundredfold underestimate — the same
    ratio the plan-feedback store records and PLAN_EST_DRIFT exposes.
    Operators the builder couldn't annotate (peeled-away interior
    nodes) show "-"."""
    rows: List[Tuple[str, str, str, str, str, str, str, str]] = []
    anchor = min((e_ts for e_ts in _walk_first_ts(root)), default=None)
    span_total = 0.0
    if anchor is not None:
        for ts in _walk_first_ts(root):
            span_total = max(span_total, ts - anchor)

    def visit(e, depth: int, last: bool):
        indent = ""
        if depth:
            indent = "  " * (depth - 1) + ("└─" if last else "├─")
        kids = _actual_children(e)
        total = e.stats.open_wall + e.stats.next_wall
        child_total = sum(c.stats.open_wall + c.stats.next_wall for c in kids)
        own = max(total - child_total, 0.0)
        own_disp = max(
            e.stats.dispatches - sum(c.stats.dispatches for c in kids), 0)
        own_rc = max(
            e.stats.recompiles - sum(c.stats.recompiles for c in kids), 0)
        if anchor is not None and e.stats.first_ts is not None:
            off = e.stats.first_ts - anchor
            pos = (round(off / span_total * (_GANTT_W - 1))
                   if span_total > 0 else 0)
            start = "·" * pos + "|" + f" +{off * 1e6:.0f}us"
        else:
            start = "|"
        staged = str(e.stats.staged) if e.stats.staged else "-"
        est = e.stats.est_rows
        if est >= 0:
            est_s = f"{est:.2f}"
            drift_s = f"{e.stats.rows / est:.2f}" if est > 0 else "-"
        else:
            est_s = drift_s = "-"
        name = type(e).__name__.replace("Exec", "")
        # a fused exec that delegated to its classic fallback must not
        # render as if the fused path ran: mark it and show the classic
        # subtree that actually executed (kept via _fallback_taken —
        # run_plan closes the tree before EXPLAIN ANALYZE renders)
        if hasattr(e, "_ran_fused") and not e._ran_fused:
            name += "[classic]"
        rows.append((
            indent + name,
            est_s,
            str(e.stats.rows),
            drift_s,
            f"{total * 1e3:.1f}ms",
            start,
            staged,
            f"open:{e.stats.open_wall * 1e3:.1f}ms own:{own * 1e3:.1f}ms "
            f"loops:{e.stats.chunks} dispatches:{own_disp}"
            + (f" recompiles:{own_rc}" if own_rc else "")
            # columnar segment store: staged vs zone-map-pruned counts
            # per scan operator (absent on non-segmented scans)
            + (f" segs_scanned:{e.stats.segs_scanned}"
               f" segs_pruned:{e.stats.segs_pruned}"
               if e.stats.segs_scanned or e.stats.segs_pruned else ""),
        ))
        for i, c in enumerate(kids):
            visit(c, depth + 1, i == len(kids) - 1)

    visit(root, 0, True)
    heads = ("id", "estRows", "actRows", "drift", "time", "start",
             "staged")
    widths = [max(max(len(r[i]) for r in rows), len(heads[i])) + 2
              for i in range(len(heads))]
    lines = ["".join(f"{h:<{w}}" for h, w in zip(heads, widths))
             + "execution info"]
    for r in rows:
        lines.append("".join(f"{r[i]:<{w}}" for i, w in enumerate(widths))
                     + r[len(heads)])
    return "\n".join(lines)


def _actual_children(e):
    """Render children plus any classic fallback subtree a fused exec
    actually ran (live ``_delegate`` pre-close, ``_fallback_taken``
    after — the normal EXPLAIN ANALYZE path renders post-close)."""
    kids = list(getattr(e, "children", ()))
    d = getattr(e, "_delegate", None)
    if d is None:
        d = getattr(e, "_fallback_taken", None)
    if d is not None:
        kids.append(d)
    return kids


def _walk_first_ts(root):
    stack = [root]
    while stack:
        e = stack.pop()
        if e.stats.first_ts is not None:
            yield e.stats.first_ts
        stack.extend(_actual_children(e))
