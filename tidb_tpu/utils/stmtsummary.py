"""Statement-digest summaries (ref: the statements-summary tables fed
by stmtsummary/ — per-digest aggregates over normalized SQL).

Every executed statement is normalized (literals -> ``?`` via the
bindinfo normalizer), hashed to a digest, and folded into one bounded
in-memory entry carrying exec count, latency aggregates (sum/max and a
p95 over a recent-latency ring), max memory, rows sent, error count,
and the distributed-execution figures (device dispatches, mesh
fragments). The store is an LRU capped by the
``tidb_stmt_summary_max_stmt_count`` sysvar — the simple stand-in for
the reference's SUMMARY BEGIN TIME window rotation; evictions are
counted so a truncated view is visible as such.

Surfaced as ``information_schema.statements_summary`` and as the
status port's ``/statements`` JSON endpoint."""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import List, Optional

__all__ = ["StmtSummary", "DEFAULT_MAX_STMT_COUNT"]

DEFAULT_MAX_STMT_COUNT = 200

# recent-latency ring per digest: enough for a stable p95 without
# unbounded growth on hot statements
_LATENCY_RING = 128


class _Entry:
    __slots__ = ("digest", "digest_text", "stmt_type", "plan_digest",
                 "exec_count", "sum_latency", "max_latency", "latencies",
                 "max_mem", "rows_sent", "errors", "dispatches",
                 "fragments", "first_seen", "last_seen",
                 "plan_cache_hits", "sum_plan_latency",
                 "max_drift", "sum_drift", "drift_samples",
                 "worst_drift_op", "xfer_bytes", "compile_ms",
                 "spill_bytes")

    def __init__(self, digest: str, digest_text: str, stmt_type: str):
        self.digest = digest
        self.digest_text = digest_text
        self.stmt_type = stmt_type
        self.plan_digest = ""
        self.exec_count = 0
        self.sum_latency = 0.0
        self.max_latency = 0.0
        self.latencies: deque = deque(maxlen=_LATENCY_RING)
        self.max_mem = 0
        self.rows_sent = 0
        self.errors = 0
        self.dispatches = 0
        self.fragments = 0
        self.first_seen = time.time()
        self.last_seen = self.first_seen
        # plan-cache observability: executions whose plan came from the
        # cache, and cumulative plan-acquisition wall time (cold plans
        # dominate it; hits contribute near-zero — the cache's win is
        # visible per digest, not just end-to-end)
        self.plan_cache_hits = 0
        self.sum_plan_latency = 0.0
        # plan feedback (ISSUE 15): per-digest estimation-drift
        # aggregates — chronic misestimates are findable here without
        # tracing. Drift is the worst per-operator actual/est row ratio
        # of one execution; 0.0 samples (no actual known) don't count.
        self.max_drift = 0.0
        self.sum_drift = 0.0
        self.drift_samples = 0
        self.worst_drift_op = ""
        # resource profile (ISSUE 16): cumulative host↔device transfer
        # bytes, fragment compile wall time, and spill bytes across this
        # digest's executions — all host-side accounting, no new syncs
        self.xfer_bytes = 0
        self.compile_ms = 0.0
        self.spill_bytes = 0

    def p95(self) -> float:
        if not self.latencies:
            return 0.0
        xs = sorted(self.latencies)
        return xs[min(len(xs) - 1, int(0.95 * (len(xs) - 1) + 0.5))]


def _fmt_ts(ts: float) -> str:
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))


class StmtSummary:
    """Bounded per-digest aggregate store (LRU on last execution)."""

    def __init__(self, max_stmt_count: int = DEFAULT_MAX_STMT_COUNT):
        self.lock = threading.Lock()
        self.max_stmt_count = max_stmt_count
        self._by_digest: "OrderedDict[str, _Entry]" = OrderedDict()
        self.evicted = 0

    def record(self, digest: str, digest_text: str, stmt_type: str,
               plan_digest: str, latency_s: float, *, max_mem: int = 0,
               rows_sent: int = 0, dispatches: int = 0, fragments: int = 0,
               error: bool = False, plan_from_cache: bool = False,
               plan_latency_s: float = 0.0,
               worst_drift: float = 0.0, worst_drift_op: str = "",
               xfer_bytes: int = 0, compile_ms: float = 0.0,
               spill_bytes: int = 0,
               max_stmt_count: Optional[int] = None) -> None:
        with self.lock:
            if max_stmt_count is not None:
                self.max_stmt_count = max(1, int(max_stmt_count))
            e = self._by_digest.get(digest)
            if e is None:
                # bound the retained text like the slow-query log does:
                # a megabyte bulk INSERT must not pin its normalized
                # form in every I_S row / /statements payload
                e = _Entry(digest, digest_text[:2048], stmt_type)
                self._by_digest[digest] = e
            self._by_digest.move_to_end(digest)
            e.exec_count += 1
            e.sum_latency += latency_s
            e.max_latency = max(e.max_latency, latency_s)
            e.latencies.append(latency_s)
            e.max_mem = max(e.max_mem, int(max_mem))
            e.rows_sent += int(rows_sent)
            e.errors += 1 if error else 0
            e.dispatches += int(dispatches)
            e.fragments += int(fragments)
            e.plan_cache_hits += 1 if plan_from_cache else 0
            e.sum_plan_latency += plan_latency_s
            if worst_drift > 0:
                drift = abs(worst_drift)
                sym = max(drift, 1.0 / drift) if drift > 0 else 0.0
                if sym > e.max_drift:
                    e.max_drift = sym
                    e.worst_drift_op = worst_drift_op
                e.sum_drift += sym
                e.drift_samples += 1
            e.xfer_bytes += int(xfer_bytes)
            e.compile_ms += float(compile_ms)
            e.spill_bytes += int(spill_bytes)
            e.last_seen = time.time()
            if plan_digest:
                e.plan_digest = plan_digest
            while len(self._by_digest) > self.max_stmt_count:
                self._by_digest.popitem(last=False)
                self.evicted += 1

    def __len__(self) -> int:
        with self.lock:
            return len(self._by_digest)

    def clear(self) -> None:
        with self.lock:
            self._by_digest.clear()
            self.evicted = 0

    def rows(self) -> List[tuple]:
        """information_schema.statements_summary rows (latencies in
        seconds), ordered by cumulative latency descending."""
        with self.lock:
            entries = list(self._by_digest.values())
        entries.sort(key=lambda e: e.sum_latency, reverse=True)
        out = []
        for e in entries:
            out.append((
                e.digest, e.stmt_type, e.digest_text, e.plan_digest,
                e.exec_count, round(e.sum_latency, 6),
                round(e.sum_latency / max(e.exec_count, 1), 6),
                round(e.max_latency, 6), round(e.p95(), 6),
                e.max_mem, e.rows_sent, e.errors, e.dispatches,
                e.fragments, _fmt_ts(e.first_seen), _fmt_ts(e.last_seen),
                e.plan_cache_hits, round(e.sum_plan_latency, 6),
                round(e.max_drift, 4),
                round(e.sum_drift / max(e.drift_samples, 1), 4),
                e.worst_drift_op,
                e.xfer_bytes, round(e.compile_ms, 3), e.spill_bytes,
            ))
        return out

    def top(self, n: int = 50) -> List[dict]:
        """JSON-ready top-N by cumulative latency (the /statements
        endpoint's payload)."""
        cols = ("digest", "stmt_type", "digest_text", "plan_digest",
                "exec_count", "sum_latency", "avg_latency", "max_latency",
                "p95_latency", "max_mem", "rows_sent", "errors",
                "dispatches", "fragments", "first_seen", "last_seen",
                "plan_cache_hits", "sum_plan_latency", "max_drift",
                "mean_drift", "worst_drift_op", "xfer_bytes",
                "compile_ms", "spill_bytes")
        return [dict(zip(cols, r)) for r in self.rows()[:max(0, n)]]
