"""Shared 64-bit mixing hash (splitmix64 finalizer).

One definition serves every host-side hashing consumer — the hash-join
key combiner (executor/join.py) and the NDV sketches (statistics/) —
so the constants and shift schedule can never silently diverge.
"""

from __future__ import annotations

import numpy as np

SM_ADD = np.uint64(0x9E3779B97F4A7C15)
SM_MUL1 = np.uint64(0xBF58476D1CE4E5B9)
SM_MUL2 = np.uint64(0x94D049BB133111EB)


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 input (adds the
    golden-ratio increment, then shift-mixes)."""
    with np.errstate(over="ignore"):
        x = (x + SM_ADD).astype(np.uint64)
        x ^= x >> np.uint64(30)
        x *= SM_MUL1
        x ^= x >> np.uint64(27)
        x *= SM_MUL2
        x ^= x >> np.uint64(31)
    return x
