"""Untyped SQL AST (ref: the reference's `ast` package under parser/).

Dataclasses only — no behavior. Names are unresolved strings; the planner
binds them. Expression nodes are deliberately close to MySQL's grammar
shapes (IN with either a value list or a subquery, BETWEEN, IS NULL, ...)
so the planner owns all semantics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

__all__ = [
    # expressions
    "EName", "ENum", "EStr", "ENull", "EBool", "EStar", "EParam",
    "EBinary", "EUnary", "EFunc", "ECase", "ECast", "EIn", "EBetween",
    "ELike", "ERegexp", "EExists", "ESubquery", "EInterval", "EIsNull",
    "EVar", "EWindow",
    # query structure
    "SelectItem", "TableName", "SubqueryTable", "Join", "OrderItem",
    "SelectStmt", "UnionStmt", "CTE",
    # statements
    "InsertStmt", "UpdateStmt", "DeleteStmt", "ColumnDef", "CreateTableStmt",
    "DropTableStmt", "CreateIndexStmt", "DropIndexStmt", "AlterTableStmt",
    "ExplainStmt", "TraceStmt", "SetStmt", "ShowStmt", "KillStmt",
    "BeginStmt", "CommitStmt",
    "RollbackStmt", "SavepointStmt", "RollbackToStmt", "ReleaseSavepointStmt",
    "UseStmt", "TruncateStmt", "LoadDataStmt", "IntoOutfile",
    "AnalyzeStmt",
    "CreateDatabaseStmt", "DropDatabaseStmt",
    "CreateUserStmt", "DropUserStmt", "GrantStmt", "RevokeStmt",
    "InstallPluginStmt", "UninstallPluginStmt",
    "CreateBindingStmt", "DropBindingStmt",
    "CreateViewStmt", "DropViewStmt",
]


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

@dataclass
class EName:
    name: str
    qualifier: Optional[str] = None  # table or alias

    def __str__(self):
        return f"{self.qualifier}.{self.name}" if self.qualifier else self.name


@dataclass
class ENum:
    text: str  # literal text; planner decides int/decimal/float

@dataclass
class EStr:
    value: str

@dataclass
class ENull:
    pass

@dataclass
class EBool:
    value: bool

@dataclass
class EStar:
    qualifier: Optional[str] = None  # t.* or bare *

@dataclass
class EParam:
    index: int

@dataclass
class EVar:
    name: str       # @@sysvar or @uservar (text includes @ prefix)
    scope: str = ""  # "global"/"session"/"" from @@global.x syntax


@dataclass
class EBinary:
    op: str  # +,-,*,/,div,mod,=,<>,<,<=,>,>=,<=>,and,or,xor
    left: "Expr"
    right: "Expr"

@dataclass
class EUnary:
    op: str  # -, +, not, ~
    arg: "Expr"

@dataclass
class EFunc:
    name: str  # lowercased
    args: List["Expr"] = field(default_factory=list)
    distinct: bool = False  # COUNT(DISTINCT x)
    # GROUP_CONCAT extras: [(expr, desc)] ORDER BY keys and SEPARATOR
    agg_order: Optional[List[Tuple["Expr", bool]]] = None
    separator: Optional[str] = None

@dataclass
class ECase:
    operand: Optional["Expr"]  # CASE x WHEN ... (simple) vs CASE WHEN (searched)
    whens: List[Tuple["Expr", "Expr"]] = field(default_factory=list)
    else_: Optional["Expr"] = None

@dataclass
class ECast:
    arg: "Expr"
    type_name: str
    type_args: Tuple[int, ...] = ()

@dataclass
class EIn:
    arg: "Expr"
    values: Optional[List["Expr"]] = None       # IN (1,2,3)
    subquery: Optional["SelectStmt"] = None     # IN (SELECT ...)
    negated: bool = False

@dataclass
class EBetween:
    arg: "Expr"
    low: "Expr"
    high: "Expr"
    negated: bool = False

@dataclass
class ELike:
    arg: "Expr"
    pattern: "Expr"
    negated: bool = False
    escape: Optional[str] = None

@dataclass
class ERegexp:
    arg: "Expr"
    pattern: "Expr"
    negated: bool = False

@dataclass
class EExists:
    subquery: "SelectStmt"
    negated: bool = False

@dataclass
class ESubquery:
    """Scalar subquery in expression position."""
    select: "SelectStmt"

@dataclass
class EInterval:
    value: "Expr"
    unit: str  # day, month, year, ...

@dataclass
class EIsNull:
    arg: "Expr"
    negated: bool = False


@dataclass
class EWindow:
    """func(args) OVER (PARTITION BY ... ORDER BY ...). Default frame
    semantics (RANGE UNBOUNDED PRECEDING .. CURRENT ROW when ordered,
    whole partition otherwise)."""

    func: str  # row_number | rank | dense_rank | count | sum | avg | min | max
    args: List["Expr"] = field(default_factory=list)
    partition_by: List["Expr"] = field(default_factory=list)
    order_by: List["OrderItem"] = field(default_factory=list)
    # explicit frame: ("rows"|"range", lo_bound, hi_bound), each bound
    # one of ("unbounded_preceding",) ("unbounded_following",)
    # ("current",) ("preceding", k) ("following", k); None = defaults
    frame: Optional[Tuple] = None


Expr = Union[
    EName, ENum, EStr, ENull, EBool, EStar, EParam, EVar, EBinary, EUnary,
    EFunc, ECase, ECast, EIn, EBetween, ELike, EExists, ESubquery,
    EInterval, EIsNull, EWindow,
]


# ---------------------------------------------------------------------------
# query structure
# ---------------------------------------------------------------------------

@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None

@dataclass
class TableName:
    name: str
    schema: Optional[str] = None
    alias: Optional[str] = None

@dataclass
class SubqueryTable:
    select: Union["SelectStmt", "UnionStmt"]
    alias: str

@dataclass
class Join:
    kind: str  # inner, left, right, cross, semi (planner-only)
    left: "TableSource"
    right: "TableSource"
    on: Optional[Expr] = None
    using: Optional[List[str]] = None

TableSource = Union[TableName, SubqueryTable, Join]

@dataclass
class OrderItem:
    expr: Expr
    desc: bool = False

@dataclass
class CTE:
    name: str
    columns: Optional[List[str]]
    select: Union["SelectStmt", "UnionStmt"]

@dataclass
class SelectStmt:
    items: List[SelectItem] = field(default_factory=list)
    from_: Optional[TableSource] = None
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    ctes: List[CTE] = field(default_factory=list)
    hints: List[Tuple[str, List[str]]] = field(default_factory=list)
    # (HINT_NAME_lower, [args]) from /*+ ... */ after SELECT
    into_outfile: Optional["IntoOutfile"] = None  # SELECT ... INTO OUTFILE
    # locking read: None | "update" (FOR UPDATE) | "share" (FOR SHARE /
    # LOCK IN SHARE MODE); NOWAIT fails instead of waiting
    lock_mode: Optional[str] = None
    lock_nowait: bool = False

@dataclass
class UnionStmt:
    left: Union["SelectStmt", "UnionStmt"]
    right: Union["SelectStmt", "UnionStmt"]
    all: bool = False
    op: str = "union"  # union | except | intersect
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


# ---------------------------------------------------------------------------
# statements
# ---------------------------------------------------------------------------

@dataclass
class InsertStmt:
    table: TableName
    columns: Optional[List[str]] = None
    rows: Optional[List[List[Expr]]] = None
    select: Optional[Union[SelectStmt, UnionStmt]] = None
    replace: bool = False
    # ON DUPLICATE KEY UPDATE assignments: (EName, value expr); the
    # value may use VALUES(col) to reference the would-be-inserted row
    on_dup: Optional[List[Tuple["EName", "Expr"]]] = None

@dataclass
class UpdateStmt:
    table: TableName
    sets: List[Tuple[EName, Expr]] = field(default_factory=list)
    where: Optional[Expr] = None
    # multi-table form (UPDATE t1 JOIN t2 ...): the full table-refs tree;
    # `table` then names the single UPDATED target
    from_: Optional["TableSource"] = None

@dataclass
class DeleteStmt:
    table: TableName
    where: Optional[Expr] = None
    # multi-table form (DELETE t FROM ... / DELETE FROM t USING ...)
    from_: Optional["TableSource"] = None

@dataclass
class ColumnDef:
    name: str
    type_name: str
    type_args: Tuple[int, ...] = ()
    not_null: bool = False
    primary_key: bool = False
    unique: bool = False
    default: Optional[Expr] = None
    auto_increment: bool = False
    # column-level CHECK constraints: (expr, verbatim sql text)
    checks: List[Tuple["Expr", str]] = field(default_factory=list)
    # COLLATE clause (None = the engine default, utf8mb4_general_ci)
    collation: Optional[str] = None
    # GENERATED ALWAYS AS: (expr ast, verbatim sql, stored?) or None
    generated: Optional[tuple] = None
    # clauses accepted but not implemented (-> SHOW WARNINGS)
    ignored: List[str] = field(default_factory=list)

@dataclass
class CreateTableStmt:
    table: TableName
    columns: List[ColumnDef] = field(default_factory=list)
    primary_key: Optional[List[str]] = None
    unique_keys: List[Tuple[str, List[str]]] = field(default_factory=list)
    indexes: List[Tuple[str, List[str]]] = field(default_factory=list)
    if_not_exists: bool = False
    engine: Optional[str] = None  # storage engine (kvapi.ENGINES)
    collation: Optional[str] = None  # table default COLLATE
    # PARTITION BY: ("range", col, [(pname, upper_or_None_for_MAXVALUE)])
    # or ("hash", col, n_partitions)
    partition: Optional[tuple] = None
    # SHARD BY: ("hash", col, n_shards) or ("range", col, [bounds...]) —
    # cross-worker placement metadata (tidb_tpu/sharding), orthogonal to
    # the single-process PARTITION BY pruning above
    shard: Optional[tuple] = None
    # CLUSTER BY (col): keep the table physically ordered by this column
    # at delta->segment compaction so zone maps prune (ISSUE 18)
    cluster: Optional[str] = None
    temporary: bool = False  # CREATE TEMPORARY TABLE (session-local)
    # table options accepted but not implemented (-> SHOW WARNINGS)
    ignored: List[str] = field(default_factory=list)
    # FOREIGN KEY clauses: (fk_columns, referenced TableName, ref_columns)
    foreign_keys: List[Tuple[List[str], TableName, List[str]]] = \
        field(default_factory=list)
    # table-level CHECK constraints: (name, expr, verbatim sql text)
    checks: List[Tuple[str, "Expr", str]] = field(default_factory=list)
    like: Optional[TableName] = None           # CREATE TABLE t LIKE src
    as_select: Optional["SelectStmt"] = None   # CREATE TABLE t AS SELECT

@dataclass
class DropTableStmt:
    tables: List[TableName] = field(default_factory=list)
    if_exists: bool = False

@dataclass
class CreateIndexStmt:
    name: str
    table: TableName = None
    columns: List[str] = field(default_factory=list)
    unique: bool = False

@dataclass
class DropIndexStmt:
    name: str
    table: TableName = None

@dataclass
class AlterTableStmt:
    table: TableName
    action: str = ""          # add_column | drop_column | rename | add_index
                              # | add_foreign_key | drop_foreign_key
                              # | add_check | drop_check | reshard
                              # | cluster
    column: Optional[ColumnDef] = None
    # reshard: new SHARD BY spec, same shape as CreateTableStmt.shard
    shard: Optional[tuple] = None
    # cluster: new CLUSTER BY column (None = CLUSTER BY NONE, clears it)
    cluster: Optional[str] = None
    old_name: Optional[str] = None
    new_name: Optional[str] = None
    index: Optional[Tuple[str, List[str]]] = None
    unique: bool = False      # ADD UNIQUE [INDEX|KEY]
    fk: Optional[Tuple[List[str], TableName, List[str]]] = None
    check: Optional[Tuple[str, "Expr", str]] = None

@dataclass
class TraceStmt:
    stmt: object

@dataclass
class ExplainStmt:
    stmt: object
    analyze: bool = False

@dataclass
class SetStmt:
    assignments: List[Tuple[str, str, Expr]] = field(default_factory=list)
    # (scope 'global'|'session'|'user', name, value)

@dataclass
class KillStmt:
    conn_id: int
    query_only: bool = False  # KILL QUERY vs KILL [CONNECTION]


@dataclass
class ShowStmt:
    kind: str  # databases | tables | columns | variables | status | create_table
    target: Optional[str] = None
    like: Optional[str] = None

@dataclass
class CreateViewStmt:
    name: str
    columns: Optional[List[str]]
    select: Union["SelectStmt", "UnionStmt"]
    select_sql: str
    or_replace: bool = False
    schema: Optional[str] = None


@dataclass
class DropViewStmt:
    names: List["TableName"]
    if_exists: bool = False


@dataclass
class CreateBindingStmt:
    scope: str       # global | session
    target_sql: str  # the statement pattern to match (normalized)
    using_sql: str   # the hinted statement to plan instead


@dataclass
class DropBindingStmt:
    scope: str
    target_sql: str


@dataclass
class InstallPluginStmt:
    name: str
    module: str  # SONAME: python module path


@dataclass
class UninstallPluginStmt:
    name: str


@dataclass
class BeginStmt:
    pass

@dataclass
class CommitStmt:
    pass

@dataclass
class RollbackStmt:
    pass

@dataclass
class UseStmt:
    db: str

@dataclass
class IntoOutfile:
    path: str
    fields_term: str = "\t"
    enclosed: Optional[str] = None
    lines_term: str = "\n"

@dataclass
class LoadDataStmt:
    path: str
    table: TableName
    columns: Optional[List[str]] = None
    fields_term: str = "\t"      # MySQL LOAD DATA defaults
    enclosed: Optional[str] = None
    lines_term: str = "\n"
    ignore_lines: int = 0
    local: bool = False

@dataclass
class SavepointStmt:
    name: str

@dataclass
class RollbackToStmt:
    name: str

@dataclass
class ReleaseSavepointStmt:
    name: str

@dataclass
class TruncateStmt:
    table: TableName = None

@dataclass
class AnalyzeStmt:
    tables: List[TableName] = field(default_factory=list)

@dataclass
class CreateUserStmt:
    user: str
    password: str = ""
    if_not_exists: bool = False

@dataclass
class DropUserStmt:
    user: str
    if_exists: bool = False

@dataclass
class GrantStmt:
    privs: List[str]        # lowercase names; ["all"] for ALL PRIVILEGES
    db: str                 # "*" = global
    table: str              # "*" = whole schema
    user: str

@dataclass
class RevokeStmt:
    privs: List[str]
    db: str
    table: str
    user: str

@dataclass
class CreateDatabaseStmt:
    name: str
    if_not_exists: bool = False

@dataclass
class DropDatabaseStmt:
    name: str
    if_exists: bool = False
