"""MySQL-dialect SQL front-end (ref: parser/ — yacc-generated in the
reference; hand-written lexer + recursive-descent/Pratt here, which keeps
the grammar we actually execute auditable and dependency-free).

    parse(sql)      -> list of statement AST nodes
    parse_one(sql)  -> exactly one statement

The AST is untyped (names unresolved); the planner binds names against the
catalog and lowers expressions to the typed IR in tidb_tpu.expression.
"""

from tidb_tpu.parser.ast import *  # noqa: F401,F403
from tidb_tpu.parser.lexer import Lexer, Token
from tidb_tpu.parser.parser import Parser, parse, parse_one

__all__ = ["Lexer", "Token", "Parser", "parse", "parse_one"]
