"""SQL lexer: text -> token stream.

Handles MySQL-isms the benchmarks need: backtick identifiers, both quote
styles for strings with '' escaping, `--`/`#` line and C block comments,
and multi-char operators (<=, >=, <>, !=, <=>, ||, &&, :=).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from tidb_tpu.errors import ParseError

__all__ = ["Token", "Lexer", "KEYWORDS"]

# Reserved words recognized by the grammar. Non-reserved words (function
# names etc.) lex as IDENT and are resolved contextually.
KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "xor", "in", "between", "like",
    "is", "null", "true", "false", "distinct", "all", "union", "except",
    "intersect", "join", "inner", "left", "right", "full", "outer", "cross",
    "on", "using", "exists", "case", "when", "then", "else", "end", "cast",
    "insert", "into", "values", "update", "set", "delete", "create", "table",
    "drop", "if", "primary", "key", "unique", "index", "default", "replace",
    "explain", "analyze", "describe", "desc", "asc", "show", "databases",
    "tables", "columns", "begin", "start", "transaction", "commit",
    "rollback", "use", "truncate", "interval", "date", "time", "timestamp",
    "with", "recursive", "global", "session", "database", "schema",
    "constraint", "foreign", "references", "comment", "engine", "charset",
    "character", "collate", "auto_increment", "unsigned", "zerofill",
    "variables", "status", "grant", "grants", "revoke", "flush", "privileges",
    "alter", "add", "modify", "change", "rename", "to", "extract", "column",
    "user", "identified", "trace", "install", "uninstall", "plugin",
    "soname", "plugins", "binding", "bindings", "for", "view", "duplicate",
    "over", "partition",
}


@dataclass(frozen=True)
class Token:
    kind: str  # IDENT, QIDENT, NUM, STR, OP, KW, EOF, PARAM
    text: str  # raw text (keywords lowercased)
    pos: int   # byte offset, for error messages

    def __repr__(self):
        return f"{self.kind}:{self.text}"


_TWO_CHAR = {"<=", ">=", "<>", "!=", "||", "&&", ":=", "->", "<<", ">>"}
_THREE_CHAR = {"<=>", "->>"}
_SINGLE = set("+-*/%(),.;=<>!@&|^~?")


class Lexer:
    def __init__(self, sql: str):
        self.sql = sql
        self.n = len(sql)
        self.i = 0

    def error(self, msg: str) -> ParseError:
        line = self.sql.count("\n", 0, self.i) + 1
        return ParseError(f"lex error at line {line} (offset {self.i}): {msg}")

    def tokens(self) -> List[Token]:
        out: List[Token] = []
        while True:
            t = self._next()
            out.append(t)
            if t.kind == "EOF":
                return out

    def _skip_ws(self):
        s, n = self.sql, self.n
        while self.i < n:
            c = s[self.i]
            if c in " \t\r\n":
                self.i += 1
            elif c == "#" or s.startswith("--", self.i):
                j = s.find("\n", self.i)
                self.i = n if j < 0 else j + 1
            elif s.startswith("/*", self.i):
                if s.startswith("/*+", self.i):
                    return  # optimizer hint: lexed as a HINT token
                j = s.find("*/", self.i + 2)
                if j < 0:
                    raise self.error("unterminated block comment")
                self.i = j + 2
            else:
                return

    def _next(self) -> Token:
        self._skip_ws()
        s, n = self.sql, self.n
        if self.i >= n:
            return Token("EOF", "", self.i)
        start = self.i
        c = s[start]

        # optimizer hint comment /*+ ... */ -> one HINT token (inner text)
        if s.startswith("/*+", start):
            j = s.find("*/", start + 3)
            if j < 0:
                raise self.error("unterminated hint comment")
            self.i = j + 2
            return Token("HINT", s[start + 3 : j].strip(), start)

        # numbers: 123, 1.5, .5, 1e-3, 0x1F
        if c.isdigit() or (c == "." and start + 1 < n and s[start + 1].isdigit()):
            i = start
            if s.startswith("0x", i) or s.startswith("0X", i):
                i += 2
                while i < n and (s[i].isdigit() or s[i].lower() in "abcdef"):
                    i += 1
                self.i = i
                return Token("NUM", s[start:i], start)
            seen_dot = seen_e = False
            while i < n:
                ch = s[i]
                if ch.isdigit():
                    i += 1
                elif ch == "." and not seen_dot and not seen_e:
                    seen_dot = True
                    i += 1
                elif ch in "eE" and not seen_e and i > start:
                    seen_e = True
                    i += 1
                    if i < n and s[i] in "+-":
                        i += 1
                else:
                    break
            self.i = i
            return Token("NUM", s[start:i], start)

        # strings '...' or "..." with doubled-quote and backslash escapes
        if c in "'\"":
            q = c
            i = start + 1
            buf = []
            while i < n:
                ch = s[i]
                if ch == "\\" and i + 1 < n:
                    esc = s[i + 1]
                    buf.append({"n": "\n", "t": "\t", "r": "\r", "0": "\0"}.get(esc, esc))
                    i += 2
                elif ch == q:
                    if i + 1 < n and s[i + 1] == q:  # '' escape
                        buf.append(q)
                        i += 2
                    else:
                        self.i = i + 1
                        return Token("STR", "".join(buf), start)
                else:
                    buf.append(ch)
                    i += 1
            raise self.error("unterminated string")

        # backtick identifier
        if c == "`":
            j = s.find("`", start + 1)
            if j < 0:
                raise self.error("unterminated identifier")
            self.i = j + 1
            return Token("QIDENT", s[start + 1 : j], start)

        # identifiers / keywords (incl. @@sysvar and @uservar)
        if c.isalpha() or c == "_" or c == "@":
            i = start
            if c == "@":
                i += 1
                if i < n and s[i] == "@":
                    i += 1
            while i < n and (s[i].isalnum() or s[i] in "_$."):
                # '.' stays out of ident: qualified names are parsed as
                # IDENT '.' IDENT so 'a.b' isn't one token — except @@x.y
                if s[i] == "." and not s[start] == "@":
                    break
                i += 1
            text = s[start:i]
            self.i = i
            low = text.lower()
            if low in KEYWORDS and c != "@":
                return Token("KW", low, start)
            return Token("IDENT", text, start)

        # parameter placeholder
        if c == "?":
            self.i = start + 1
            return Token("PARAM", "?", start)

        # operators
        for trio in _THREE_CHAR:
            if s.startswith(trio, start):
                self.i = start + 3
                return Token("OP", trio, start)
        for duo in _TWO_CHAR:
            if s.startswith(duo, start):
                self.i = start + 2
                return Token("OP", duo, start)
        if c in _SINGLE:
            self.i = start + 1
            return Token("OP", c, start)

        raise self.error(f"unexpected character {c!r}")
