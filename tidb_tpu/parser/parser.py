"""Recursive-descent statement parser + Pratt expression parser.

Grammar shape follows MySQL's, with precedence levels matching the MySQL
manual (OR < XOR < AND < NOT < comparison/IN/BETWEEN/LIKE/IS < | < & <
shifts < +- < */DIV/MOD < ^ < unary). Only the productions the engine
executes are implemented; everything else raises ParseError with position.
"""

from __future__ import annotations

from typing import List, Optional, Union

from tidb_tpu.errors import ParseError
from tidb_tpu.parser.ast import *  # noqa: F403
from tidb_tpu.parser.lexer import Lexer, Token

__all__ = ["Parser", "parse", "parse_one"]


def parse(sql: str) -> list:
    return Parser(sql).parse_statements()


def parse_one(sql: str):
    stmts = parse(sql)
    if len(stmts) != 1:
        raise ParseError(f"expected exactly one statement, got {len(stmts)}")
    return stmts[0]


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        toks = Lexer(sql).tokens()
        # hints are only meaningful right after SELECT; elsewhere they
        # behave like the comments they are (TiDB likewise ignores
        # DML-position hints it doesn't implement)
        self.toks = [
            t for i, t in enumerate(toks)
            if t.kind != "HINT"
            or (i > 0 and toks[i - 1].kind == "KW" and toks[i - 1].text == "select")
        ]
        self.pos = 0
        self.param_count = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        i = min(self.pos + ahead, len(self.toks) - 1)
        return self.toks[i]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "EOF":
            self.pos += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "KW" and t.text in kws

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.text in ops

    def accept_kw(self, *kws: str) -> Optional[Token]:
        if self.at_kw(*kws):
            return self.next()
        return None

    def accept_op(self, *ops: str) -> Optional[Token]:
        if self.at_op(*ops):
            return self.next()
        return None

    def expect_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            raise self.error(f"expected {kw.upper()}")
        return self.next()

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            raise self.error(f"expected {op!r}")
        return self.next()

    def expect_ident(self) -> str:
        t = self.peek()
        if t.kind in ("IDENT", "QIDENT"):
            self.next()
            return t.text
        # non-reserved-ish keywords usable as identifiers in practice
        if t.kind == "KW" and t.text in _IDENTISH_KW:
            self.next()
            return t.text
        raise self.error("expected identifier")

    def error(self, msg: str) -> ParseError:
        t = self.peek()
        line = self.sql.count("\n", 0, t.pos) + 1
        return ParseError(f"{msg} at line {line} near {t.text or '<eof>'!r}")

    # -- statements --------------------------------------------------------

    def parse_statements(self) -> list:
        out = []
        while self.peek().kind != "EOF":
            if self.accept_op(";"):
                continue
            start = self.peek().pos
            stmt = self.parse_statement()
            # statement source text (plan bindings normalize + match it)
            try:
                stmt._source = self.sql[start : self.peek().pos].strip()
            except AttributeError:  # frozen/slotted nodes don't need it
                pass
            out.append(stmt)
            if not self.accept_op(";") and self.peek().kind != "EOF":
                raise self.error("expected ';' or end of input")
        return out

    def parse_statement(self):
        if self.at_op("("):  # parenthesized SELECT statement
            return self.parse_select_or_union()
        t = self.peek()
        if t.kind == "IDENT" and t.text.lower() == "load":
            return self.parse_load_data()
        if t.kind == "IDENT" and t.text.lower() == "savepoint":
            self.next()
            return SavepointStmt(self.expect_ident())
        if t.kind == "IDENT" and t.text.lower() == "kill":
            self.next()
            query_only = bool(self._accept_word("query"))
            self._accept_word("connection")
            return KillStmt(self._int_literal("connection id"), query_only)
        if t.kind == "IDENT" and t.text.lower() == "release":
            self.next()
            self._expect_word("savepoint")
            return ReleaseSavepointStmt(self.expect_ident())
        if t.kind != "KW":
            raise self.error("expected statement keyword")
        kw = t.text
        if kw in ("select", "with"):
            return self.parse_select_or_union()
        handler = {
            "insert": self.parse_insert,
            "replace": self.parse_insert,
            "update": self.parse_update,
            "delete": self.parse_delete,
            "create": self.parse_create,
            "drop": self.parse_drop,
            "alter": self.parse_alter,
            "explain": self.parse_explain,
            "describe": self.parse_explain,
            "desc": self.parse_explain,
            "set": self.parse_set,
            "show": self.parse_show,
            "begin": lambda: (self.next(), BeginStmt())[1],
            "start": self.parse_start_txn,
            "commit": lambda: (self.next(), CommitStmt())[1],
            "rollback": self.parse_rollback,
            "use": self.parse_use,
            "truncate": self.parse_truncate,
            "analyze": self.parse_analyze,
            "trace": lambda: (self.next(), TraceStmt(self.parse_statement()))[1],
            "grant": self.parse_grant,
            "revoke": self.parse_revoke,
            "install": self.parse_install,
            "uninstall": self.parse_uninstall,
        }.get(kw)
        if handler is None:
            raise self.error(f"unsupported statement {kw.upper()}")
        return handler()

    # -- SELECT ------------------------------------------------------------

    def parse_select_or_union(self):
        ctes: List[CTE] = []
        if self.accept_kw("with"):
            self.accept_kw("recursive")  # accepted, not yet executed
            while True:
                name = self.expect_ident()
                cols = None
                if self.accept_op("("):
                    cols = [self.expect_ident()]
                    while self.accept_op(","):
                        cols.append(self.expect_ident())
                    self.expect_op(")")
                self.expect_kw("as")
                self.expect_op("(")
                sel = self.parse_select_or_union()
                self.expect_op(")")
                ctes.append(CTE(name, cols, sel))
                if not self.accept_op(","):
                    break

        node = self._parse_intersect_chain()
        while self.at_kw("union", "except"):
            op = self.next().text
            all_ = bool(self.accept_kw("all"))
            if not all_:
                self.accept_kw("distinct")
            right = self._parse_intersect_chain()
            node = UnionStmt(node, right, all=all_, op=op)
            self._hoist_set_tail(node, right)
        if ctes:
            if isinstance(node, SelectStmt):
                node.ctes = ctes
            else:
                # hang CTEs off the leftmost select of the union
                left = node
                while isinstance(left, UnionStmt):
                    left = left.left
                left.ctes = ctes
        return node

    def _parse_intersect_chain(self):
        """INTERSECT binds tighter than UNION/EXCEPT (SQL standard and
        MySQL 8)."""
        node = self.parse_select_core()
        while self.at_kw("intersect"):
            self.next()
            all_ = bool(self.accept_kw("all"))
            if not all_:
                self.accept_kw("distinct")
            right = self.parse_select_core()
            node = UnionStmt(node, right, all=all_, op="intersect")
            self._hoist_set_tail(node, right)
        return node

    def _hoist_set_tail(self, node: UnionStmt, right) -> None:
        """An unparenthesized trailing ORDER BY/LIMIT was consumed by
        the rightmost operand but binds to the whole compound statement
        (MySQL semantics); a parenthesized operand keeps its own.
        `right` may itself be a set-op chain whose tail was hoisted."""
        if getattr(right, "_parenthesized", False):
            return
        if self.at_kw("union", "except", "intersect"):
            return
        node.order_by, right.order_by = right.order_by, []
        node.limit, node.offset = right.limit, right.offset
        right.limit = right.offset = None

    def parse_select_core(self) -> Union[SelectStmt, "UnionStmt"]:
        if self.accept_op("("):
            sel = self.parse_select_or_union()
            self.expect_op(")")
            sel._parenthesized = True
            return sel
        self.expect_kw("select")
        stmt = SelectStmt()
        if self.peek().kind == "HINT":
            stmt.hints = self._parse_hints(self.next().text)
        if self.accept_kw("distinct"):
            stmt.distinct = True
        else:
            self.accept_kw("all")
        stmt.items = [self.parse_select_item()]
        while self.accept_op(","):
            stmt.items.append(self.parse_select_item())
        if self.accept_kw("from"):
            stmt.from_ = self.parse_table_sources()
        if self.accept_kw("where"):
            stmt.where = self.parse_expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            stmt.group_by = [self.parse_expr()]
            while self.accept_op(","):
                stmt.group_by.append(self.parse_expr())
        if self.accept_kw("having"):
            stmt.having = self.parse_expr()
        if self.accept_kw("order"):
            self.expect_kw("by")
            stmt.order_by = self.parse_order_items()
        if self.accept_kw("limit"):
            stmt.limit, stmt.offset = self.parse_limit_clause()
        if self.accept_kw("into"):
            # SELECT ... INTO OUTFILE 'path' [FIELDS ...] [LINES ...]
            self._expect_word("outfile")
            if self.peek().kind != "STR":
                raise self.error("expected a quoted file path after OUTFILE")
            into = IntoOutfile(self.next().text)
            self._parse_field_options(into)
            if self._accept_word("lines"):
                self._expect_word("terminated")
                self.expect_kw("by")
                into.lines_term = self.next().text
            stmt.into_outfile = into
        # locking reads: FOR UPDATE / FOR SHARE / LOCK IN SHARE MODE
        # (ref: pessimistic SELECT locking over the 2PC row locks)
        if self.accept_kw("for"):
            if self.accept_kw("update"):
                stmt.lock_mode = "update"
            elif self._accept_word("share"):
                stmt.lock_mode = "share"
            else:
                raise self.error("expected UPDATE or SHARE after FOR")
            if self._accept_word("nowait"):
                stmt.lock_nowait = True
        elif self._accept_word("lock"):
            self.expect_kw("in")
            self._expect_word("share")
            self._expect_word("mode")
            stmt.lock_mode = "share"
        return stmt

    def _parse_field_options(self, target) -> None:
        """FIELDS TERMINATED / [OPTIONALLY] ENCLOSED / ESCAPED BY —
        shared by LOAD DATA and SELECT ... INTO OUTFILE."""
        if not (self._accept_word("fields") or self._accept_word("columns")):
            return
        while True:
            if self._accept_word("terminated"):
                self.expect_kw("by")
                target.fields_term = self.next().text
            elif self._accept_word("optionally"):
                self._expect_word("enclosed")
                self.expect_kw("by")
                target.enclosed = self.next().text
            elif self._accept_word("enclosed"):
                self.expect_kw("by")
                target.enclosed = self.next().text
            elif self._accept_word("escaped"):
                self.expect_kw("by")
                self.next()  # accepted; backslash semantics built in
            else:
                break

    def parse_select_item(self) -> SelectItem:
        if self.at_op("*"):
            self.next()
            return SelectItem(EStar())
        # t.* qualified star
        t = self.peek()
        if (
            t.kind in ("IDENT", "QIDENT")
            and self.peek(1).kind == "OP"
            and self.peek(1).text == "."
            and self.peek(2).kind == "OP"
            and self.peek(2).text == "*"
        ):
            self.next(); self.next(); self.next()
            return SelectItem(EStar(qualifier=t.text))
        expr = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident_or_string()
        else:
            nt = self.peek()
            if nt.kind in ("IDENT", "QIDENT") or (nt.kind == "KW" and nt.text in _IDENTISH_KW):
                alias = self.expect_ident()
        return SelectItem(expr, alias)

    def expect_ident_or_string(self) -> str:
        if self.peek().kind == "STR":
            return self.next().text
        return self.expect_ident()

    def parse_order_items(self) -> List[OrderItem]:
        items = [self.parse_order_item()]
        while self.accept_op(","):
            items.append(self.parse_order_item())
        return items

    def parse_order_item(self) -> OrderItem:
        e = self.parse_expr()
        desc = False
        if self.accept_kw("desc"):
            desc = True
        else:
            self.accept_kw("asc")
        return OrderItem(e, desc)

    def parse_limit_clause(self):
        a = int(self.next().text)
        offset = None
        if self.accept_op(","):  # LIMIT offset, count
            b = int(self.next().text)
            return b, a
        if self.accept_kw("offset"):
            offset = int(self.next().text)
        return a, offset

    # -- FROM / joins --------------------------------------------------------

    def parse_table_sources(self) -> TableSource:
        left = self.parse_joined_table()
        while self.accept_op(","):  # comma join == cross join
            right = self.parse_joined_table()
            left = Join("cross", left, right)
        return left

    def parse_joined_table(self) -> TableSource:
        left = self.parse_table_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.parse_table_primary()
                left = Join("cross", left, right)
                continue
            kind = None
            if self.accept_kw("inner"):
                kind = "inner"
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                kind = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                kind = "full"
            if kind is None:
                if not self.at_kw("join"):
                    return left
                kind = "inner"
            self.expect_kw("join")
            right = self.parse_table_primary()
            on = None
            using = None
            if self.accept_kw("on"):
                on = self.parse_expr()
            elif self.accept_kw("using"):
                self.expect_op("(")
                using = [self.expect_ident()]
                while self.accept_op(","):
                    using.append(self.expect_ident())
                self.expect_op(")")
            left = Join(kind, left, right, on=on, using=using)

    def parse_table_primary(self) -> TableSource:
        if self.accept_op("("):
            if self.at_kw("select", "with") or self.at_op("("):
                sel = self.parse_select_or_union()
                self.expect_op(")")
                self.accept_kw("as")
                alias = self.expect_ident()
                return SubqueryTable(sel, alias)
            src = self.parse_table_sources()
            self.expect_op(")")
            return src
        name = self.expect_ident()
        schema = None
        if self.accept_op("."):
            schema, name = name, self.expect_ident()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        else:
            nt = self.peek()
            if nt.kind in ("IDENT", "QIDENT"):
                alias = self.next().text
        return TableName(name, schema=schema, alias=alias)

    # -- DML -----------------------------------------------------------------

    def parse_insert(self) -> InsertStmt:
        replace = self.peek().text == "replace"
        self.next()  # insert/replace
        self.accept_kw("into")
        table = self._table_name()
        columns = None
        if self.at_op("(") and not self._paren_starts_select():
            self.expect_op("(")
            columns = [self.expect_ident()]
            while self.accept_op(","):
                columns.append(self.expect_ident())
            self.expect_op(")")
        if self.accept_kw("values"):
            rows = [self._value_row()]
            while self.accept_op(","):
                rows.append(self._value_row())
            on_dup = self._on_duplicate()
            if replace and on_dup:
                raise self.error("REPLACE cannot have ON DUPLICATE KEY UPDATE")
            return InsertStmt(table, columns, rows=rows, replace=replace,
                              on_dup=on_dup)
        sel = self.parse_select_or_union()
        on_dup = self._on_duplicate()
        if replace and on_dup:
            raise self.error("REPLACE cannot have ON DUPLICATE KEY UPDATE")
        return InsertStmt(table, columns, select=sel, replace=replace,
                          on_dup=on_dup)

    def _on_duplicate(self):
        if not self.accept_kw("on"):
            return None
        self.expect_kw("duplicate")
        self.expect_kw("key")
        self.expect_kw("update")
        sets = []
        while True:
            name = EName(self.expect_ident())
            self.expect_op("=")
            sets.append((name, self.parse_expr()))
            if not self.accept_op(","):
                break
        return sets

    def _paren_starts_select(self) -> bool:
        t1 = self.peek(1)
        return t1.kind == "KW" and t1.text in ("select", "with")

    def _value_row(self) -> List:
        self.expect_op("(")
        row = [self.parse_expr()]
        while self.accept_op(","):
            row.append(self.parse_expr())
        self.expect_op(")")
        return row

    def _table_name(self) -> TableName:
        name = self.expect_ident()
        schema = None
        if self.accept_op("."):
            schema, name = name, self.expect_ident()
        return TableName(name, schema=schema)

    def _accept_word(self, word: str) -> bool:
        """Accept an IDENT-or-keyword token by lowercase text (LOAD DATA
        options like FIELDS/LINES/TERMINATED aren't reserved words)."""
        t = self.peek()
        if t.kind in ("IDENT", "KW") and t.text.lower() == word:
            self.next()
            return True
        return False

    def _expect_word(self, word: str):
        if not self._accept_word(word):
            raise self.error(f"expected {word.upper()}")

    def parse_rollback(self):
        self.expect_kw("rollback")
        if self.accept_kw("to"):
            self._accept_word("savepoint")
            return RollbackToStmt(self.expect_ident())
        return RollbackStmt()

    def parse_load_data(self) -> LoadDataStmt:
        self._expect_word("load")
        self._expect_word("data")
        local = self._accept_word("local")
        self._expect_word("infile")
        if self.peek().kind != "STR":
            raise self.error("expected a quoted file path after INFILE")
        path = self.next().text
        self.expect_kw("into")
        self.expect_kw("table")
        table = self._table_name()
        stmt = LoadDataStmt(path, table, local=local)
        self._parse_field_options(stmt)
        if self._accept_word("lines"):
            while True:
                if self._accept_word("terminated"):
                    self.expect_kw("by")
                    stmt.lines_term = self.next().text
                elif self._accept_word("starting"):
                    self.expect_kw("by")
                    self.next()
                else:
                    break
        if self._accept_word("ignore"):
            if self.peek().kind != "NUM":
                raise self.error("expected a line count after IGNORE")
            stmt.ignore_lines = int(self.next().text)
            if not (self._accept_word("lines") or self._accept_word("rows")):
                raise self.error("expected LINES/ROWS")
        if self.accept_op("("):
            cols = [self.expect_ident()]
            while self.accept_op(","):
                cols.append(self.expect_ident())
            self.expect_op(")")
            stmt.columns = cols
        return stmt

    def parse_update(self) -> UpdateStmt:
        self.expect_kw("update")
        refs = self.parse_table_sources()
        self.expect_kw("set")
        sets = []
        while True:
            name = self.expect_ident()
            qual = None
            if self.accept_op("."):
                qual, name = name, self.expect_ident()
            self.expect_op("=")
            sets.append((EName(name, qual), self.parse_expr()))
            if not self.accept_op(","):
                break
        where = self.parse_expr() if self.accept_kw("where") else None
        if isinstance(refs, TableName):
            return UpdateStmt(refs, sets, where)
        # multi-table: the single updated target resolves from the SET
        # column qualifiers at execution time (placeholder name here)
        return UpdateStmt(TableName(""), sets, where, from_=refs)

    def parse_delete(self) -> DeleteStmt:
        self.expect_kw("delete")
        if self.accept_kw("from"):
            table = self._table_name()
            if self.accept_kw("using"):
                # DELETE FROM t USING <table_refs> WHERE ...
                refs = self.parse_table_sources()
                where = self.parse_expr() if self.accept_kw("where") else None
                return DeleteStmt(table, where, from_=refs)
            where = self.parse_expr() if self.accept_kw("where") else None
            return DeleteStmt(table, where)
        # DELETE t FROM <table_refs> WHERE ...  (single target supported)
        table = self._table_name()
        self.expect_kw("from")
        refs = self.parse_table_sources()
        where = self.parse_expr() if self.accept_kw("where") else None
        return DeleteStmt(table, where, from_=refs)

    # -- DDL -----------------------------------------------------------------

    def parse_create(self):
        self.expect_kw("create")
        scope = "session"
        if self.at_kw("global", "session") and self.peek(1).text == "binding":
            scope = self.next().text
        if self.accept_kw("binding"):
            self.expect_kw("for")
            t_start = self.peek().pos
            self.parse_statement()  # validated, matched by normalized text
            t_sql = self.sql[t_start : self.peek().pos].strip()
            self.expect_kw("using")
            u_start = self.peek().pos
            self.parse_statement()
            u_sql = self.sql[u_start : self.peek().pos].strip()
            return CreateBindingStmt(scope, t_sql, u_sql)
        or_replace = False
        if self.at_kw("or") and self.peek(1).text == "replace":
            self.next()
            self.next()
            or_replace = True
            self.expect_kw("view")
            return self._create_view_tail(or_replace)
        if self.accept_kw("view"):
            return self._create_view_tail(False)
        if self.accept_kw("database") or self.accept_kw("schema"):
            ine = self._if_not_exists()
            return CreateDatabaseStmt(self.expect_ident(), ine)
        if self.accept_kw("user"):
            ine = self._if_not_exists()
            user = self._user_name()
            password = ""
            if self.accept_kw("identified"):
                self.expect_kw("by")
                password = self.next().text
            return CreateUserStmt(user, password, ine)
        temporary = self._accept_word("temporary")
        if temporary:
            self.expect_kw("table")
            ine = self._if_not_exists()
            stmt = CreateTableStmt(self._table_name(), if_not_exists=ine,
                                   temporary=True)
            return self._create_table_tail(stmt)
        unique = bool(self.accept_kw("unique"))
        if self.accept_kw("index"):
            name = self.expect_ident()
            self.expect_kw("on")
            table = self._table_name()
            self.expect_op("(")
            cols = [self.expect_ident()]
            while self.accept_op(","):
                cols.append(self.expect_ident())
            self.expect_op(")")
            return CreateIndexStmt(name, table, cols, unique)
        self.expect_kw("table")
        ine = self._if_not_exists()
        table = self._table_name()
        stmt = CreateTableStmt(table, if_not_exists=ine)
        return self._create_table_tail(stmt)

    def _create_table_tail(self, stmt):
        if self.accept_kw("like"):
            stmt.like = self._table_name()
            return stmt
        if self.at_op("(") and self.peek(1).kind == "KW" \
                and self.peek(1).text == "like":
            self.next()  # (
            self.next()  # LIKE
            stmt.like = self._table_name()
            self.expect_op(")")
            return stmt
        if self.at_kw("as", "select", "with"):
            # CREATE TABLE t AS SELECT ... (AS optional, like MySQL)
            self.accept_kw("as")
            stmt.as_select = self.parse_select_or_union()
            return stmt
        self.expect_op("(")
        while True:
            if self.accept_kw("primary"):
                self.expect_kw("key")
                stmt.primary_key = self._paren_name_list()
            elif self.accept_kw("unique"):
                self.accept_kw("key") or self.accept_kw("index")
                kname = ""
                if self.peek().kind in ("IDENT", "QIDENT"):
                    kname = self.expect_ident()
                stmt.unique_keys.append((kname, self._paren_name_list()))
            elif self.accept_kw("key") or self.accept_kw("index"):
                kname = ""
                if self.peek().kind in ("IDENT", "QIDENT"):
                    kname = self.expect_ident()
                stmt.indexes.append((kname, self._paren_name_list()))
            elif self.peek().kind == "IDENT" and \
                    self.peek().text.lower() == "check":
                self.next()
                stmt.checks.append(("", *self._parse_check_expr()))
            elif self.accept_kw("constraint"):
                # named constraint: swallow FOREIGN KEY / etc. for parse-compat
                cname = ""
                if self.peek().kind in ("IDENT", "QIDENT") and \
                        self.peek().text.lower() != "check":
                    cname = self.expect_ident()
                if self.peek().kind == "IDENT" and \
                        self.peek().text.lower() == "check":
                    self.next()
                    stmt.checks.append((cname, *self._parse_check_expr()))
                elif self.accept_kw("primary"):
                    self.expect_kw("key")
                    stmt.primary_key = self._paren_name_list()
                elif self.accept_kw("unique"):
                    stmt.unique_keys.append(("", self._paren_name_list()))
                elif self.accept_kw("foreign"):
                    self.expect_kw("key")
                    stmt.foreign_keys.append(self._parse_fk_spec())
            elif self.accept_kw("foreign"):
                self.expect_kw("key")
                stmt.foreign_keys.append(self._parse_fk_spec())
            else:
                stmt.columns.append(self.parse_column_def())
            if not self.accept_op(","):
                break
        self.expect_op(")")
        # table options: ENGINE=... selects the storage engine
        # (kvapi.make_table); COLLATE=... sets the default collation for
        # columns without an explicit one; CHARSET/COMMENT accepted
        # (charset is always utf8mb4 here)
        while self.peek().kind == "KW" and self.peek().text in (
                "engine", "charset", "character", "comment", "collate",
                "default"):
            opt = self.next().text
            if opt == "default":
                continue  # DEFAULT CHARSET=... / DEFAULT COLLATE=...
            self.accept_kw("set")
            self.accept_op("=")
            val = self.next().text
            if opt == "engine":
                stmt.engine = val.lower()
            elif opt == "collate":
                stmt.collation = val.lower()
            else:
                # accepted-and-ignored: surfaced via SHOW WARNINGS
                # instead of vanishing silently (r4 review weak #8)
                stmt.ignored.append(f"table option {opt.upper()}")
        # PARTITION BY RANGE (col) (PARTITION p VALUES LESS THAN (n)...)
        # | PARTITION BY HASH (col) PARTITIONS n   (ref: table partitions
        # pruned like the reference's partition pruning)
        if self._accept_word("partition"):
            self.expect_kw("by")
            if self._accept_word("range"):
                self.expect_op("(")
                col = self.expect_ident()
                self.expect_op(")")
                self.expect_op("(")
                parts = []
                while True:
                    self._expect_word("partition")
                    pname = self.expect_ident()
                    self._expect_word("values")
                    self._expect_word("less")
                    self._expect_word("than")
                    if self.accept_op("("):
                        upper = self._int_literal("partition bound")
                        self.expect_op(")")
                    else:
                        self._expect_word("maxvalue")
                        upper = None
                    parts.append((pname, upper))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                stmt.partition = ("range", col, parts)
            elif self._accept_word("hash"):
                self.expect_op("(")
                col = self.expect_ident()
                self.expect_op(")")
                self._expect_word("partitions")
                n = self._int_literal("partition count")
                if n <= 0:
                    raise self.error("PARTITIONS must be positive")
                stmt.partition = ("hash", col, n)
            else:
                raise self.error("expected RANGE or HASH after PARTITION BY")
        # Trailing table options, composable in ANY order (each at most
        # once):
        #   SHARD BY HASH (col) SHARDS n | SHARD BY RANGE (col) SHARDS
        #   (b1, b2, ...) — cross-worker placement (tidb_tpu/sharding):
        #   k ascending bounds make k+1 shards, shard i = [b_{i-1}, b_i)
        #   CLUSTER BY (col) — keep the table physically ordered by
        #   this column at delta->segment compaction so zone maps prune
        #   without hand-ordered ingest (ISSUE 18)
        seen = set()
        while True:
            if self._accept_word("shard"):
                opt = "shard"
            elif self._accept_word("cluster"):
                opt = "cluster"
            else:
                break
            if opt in seen:
                raise self.error(f"duplicate {opt.upper()} BY clause")
            seen.add(opt)
            self.expect_kw("by")
            if opt == "shard":
                stmt.shard = self._parse_shard_spec()
            else:
                stmt.cluster = self._parse_cluster_spec()
        return stmt

    def _parse_cluster_spec(self) -> Optional[str]:
        if self._accept_word("none"):
            return None
        self.expect_op("(")
        col = self.expect_ident()
        self.expect_op(")")
        return col

    def _parse_shard_spec(self) -> tuple:
        if self._accept_word("hash"):
            self.expect_op("(")
            col = self.expect_ident()
            self.expect_op(")")
            self._expect_word("shards")
            n = self._int_literal("shard count")
            if n <= 0:
                raise self.error("SHARDS must be positive")
            return ("hash", col, n)
        if self._accept_word("range"):
            self.expect_op("(")
            col = self.expect_ident()
            self.expect_op(")")
            self._expect_word("shards")
            self.expect_op("(")
            bounds = [self._int_literal("shard bound")]
            while self.accept_op(","):
                bounds.append(self._int_literal("shard bound"))
            self.expect_op(")")
            if any(a >= b for a, b in zip(bounds, bounds[1:])):
                raise self.error("SHARD BY RANGE bounds must be strictly "
                                 "increasing")
            return ("range", col, bounds)
        raise self.error("expected RANGE or HASH after SHARD BY")

    def _int_literal(self, what: str) -> int:
        """A (possibly negative) integer literal token."""
        neg = bool(self.accept_op("-"))
        t = self.peek()
        if t.kind != "NUM" or "." in t.text:
            raise self.error(f"expected integer {what}")
        self.next()
        return -int(t.text) if neg else int(t.text)

    def _if_not_exists(self) -> bool:
        if self.accept_kw("if"):
            self.expect_kw("not")
            # EXISTS lexes as KW
            self.expect_kw("exists")
            return True
        return False

    def _paren_name_list(self) -> List[str]:
        self.expect_op("(")
        out = [self.expect_ident()]
        while self.accept_op(","):
            out.append(self.expect_ident())
        self.expect_op(")")
        return out

    def parse_column_def(self) -> ColumnDef:
        name = self.expect_ident()
        t = self.peek()
        if t.kind not in ("IDENT", "KW"):
            raise self.error("expected column type")
        type_name = self.next().text.lower()
        args = ()
        if self.accept_op("("):
            def type_arg():
                t = self.next()
                # ENUM/SET member lists are quoted strings; numeric
                # lengths everywhere else
                return t.text if t.kind == "STR" else int(t.text)

            a = [type_arg()]
            while self.accept_op(","):
                a.append(type_arg())
            self.expect_op(")")
            args = tuple(a)
        self.accept_kw("unsigned")
        self.accept_kw("zerofill")
        collation = None
        if self.accept_kw("character"):
            self.expect_kw("set")
            cs = self.next().text.lower()
            if cs == "binary":
                collation = "utf8mb4_bin"
        if self.accept_kw("collate"):
            collation = self.next().text.lower()
        col = ColumnDef(name, type_name, args)
        col.collation = collation
        # generated column: [GENERATED ALWAYS] AS (expr) [VIRTUAL|STORED]
        if self._accept_word("generated"):
            self._expect_word("always")
            self.expect_kw("as")
            col.generated = self._parse_generated_expr()
        elif self.accept_kw("as"):
            col.generated = self._parse_generated_expr()
        while True:
            if self.accept_kw("not"):
                self.expect_kw("null")
                col.not_null = True
            elif self.accept_kw("null"):
                pass
            elif self.accept_kw("primary"):
                self.expect_kw("key")
                col.primary_key = True
            elif self.accept_kw("unique"):
                self.accept_kw("key")
                col.unique = True
            elif self.accept_kw("default"):
                col.default = self.parse_primary()
            elif self.accept_kw("auto_increment"):
                col.auto_increment = True
            elif self.accept_kw("comment"):
                self.next()
                col.ignored.append(f"column {name!r} COMMENT")
            elif self.peek().kind == "IDENT" and \
                    self.peek().text.lower() == "check":
                self.next()
                col.checks.append(self._parse_check_expr())
            else:
                return col

    def _parse_fk_spec(self):
        """FOREIGN KEY (...) REFERENCES t (...) [ON DELETE act] [ON
        UPDATE act] -> (cols, ref_table, ref_cols, on_delete, on_update)."""
        cols = self._paren_name_list()
        self.expect_kw("references")
        ref = self._table_name()
        refcols = self._paren_name_list()
        on_delete = on_update = "restrict"
        while self.accept_kw("on"):
            if self.accept_kw("delete"):
                tgt = "delete"
            else:
                self.expect_kw("update")
                tgt = "update"
            if self._accept_word("cascade"):
                act = "cascade"
            elif self._accept_word("restrict"):
                act = "restrict"
            elif self.accept_kw("set"):
                self.expect_kw("null")
                act = "set_null"
            elif self._accept_word("no"):
                self._expect_word("action")
                act = "restrict"  # NO ACTION == RESTRICT here (no
                # deferred checking exists)
            else:
                raise self.error("expected FK referential action")
            if tgt == "delete":
                on_delete = act
            else:
                on_update = act
        return cols, ref, refcols, on_delete, on_update

    def _parse_generated_expr(self):
        """(expr) [VIRTUAL | STORED] -> (ast, verbatim sql, stored)."""
        self.expect_op("(")
        p0 = self.peek().pos
        e = self.parse_expr()
        p1 = self.peek().pos
        self.expect_op(")")
        stored = True
        if self._accept_word("virtual"):
            stored = False
        elif self._accept_word("stored"):
            stored = True
        return e, self.sql[p0:p1].strip(), stored

    def _parse_check_expr(self):
        """CHECK ( expr ) -> (ast expr, verbatim sql text)."""
        self.expect_op("(")
        p0 = self.peek().pos
        e = self.parse_expr()
        p1 = self.peek().pos
        self.expect_op(")")
        return e, self.sql[p0:p1].strip()

    def _user_name(self) -> str:
        """'user'[@'host'] — host accepted and ignored (single node)."""
        t = self.next()
        user = t.text
        if self.accept_op("@"):
            self.next()  # host part
        return user

    def _create_view_tail(self, or_replace: bool) -> CreateViewStmt:
        schema = None
        name = self.expect_ident()
        if self.accept_op("."):
            schema, name = name, self.expect_ident()
        cols = None
        if self.accept_op("("):
            cols = [self.expect_ident()]
            while self.accept_op(","):
                cols.append(self.expect_ident())
            self.expect_op(")")
        self.expect_kw("as")
        start = self.peek().pos
        sel = self.parse_select_or_union()
        sql = self.sql[start : self.peek().pos].strip()
        return CreateViewStmt(name, cols, sel, sql, or_replace, schema)

    def parse_drop(self):
        self.expect_kw("drop")
        scope = "session"
        if self.at_kw("global", "session") and self.peek(1).text == "binding":
            scope = self.next().text
        if self.accept_kw("binding"):
            self.expect_kw("for")
            start = self.peek().pos
            self.parse_statement()
            sql = self.sql[start : self.peek().pos].strip()
            return DropBindingStmt(scope, sql)
        if self.accept_kw("view"):
            ie = self._if_exists()
            names = [self._table_name()]
            while self.accept_op(","):
                names.append(self._table_name())
            return DropViewStmt(names, ie)
        if self.accept_kw("database") or self.accept_kw("schema"):
            ie = self._if_exists()
            return DropDatabaseStmt(self.expect_ident(), ie)
        if self.accept_kw("user"):
            ie = self._if_exists()
            return DropUserStmt(self._user_name(), ie)
        if self.accept_kw("index"):
            name = self.expect_ident()
            self.expect_kw("on")
            return DropIndexStmt(name, self._table_name())
        self.expect_kw("table")
        ie = self._if_exists()
        tables = [self._table_name()]
        while self.accept_op(","):
            tables.append(self._table_name())
        return DropTableStmt(tables, ie)

    def _if_exists(self) -> bool:
        if self.accept_kw("if"):
            self.expect_kw("exists")
            return True
        return False

    def parse_alter(self) -> AlterTableStmt:
        self.expect_kw("alter")
        self.expect_kw("table")
        table = self._table_name()
        if self.accept_kw("add"):
            uniq = bool(self.accept_kw("unique"))
            if self.accept_kw("index") or self.accept_kw("key") or uniq:
                name = ""
                if self.peek().kind in ("IDENT", "QIDENT"):
                    name = self.expect_ident()
                return AlterTableStmt(table, "add_index",
                                      index=(name, self._paren_name_list()),
                                      unique=uniq)
            cname = ""
            if self.accept_kw("constraint"):
                if self.peek().kind in ("IDENT", "QIDENT") and \
                        self.peek().text.lower() != "check":
                    cname = self.expect_ident()
            if self.accept_kw("foreign"):
                self.expect_kw("key")
                return AlterTableStmt(table, "add_foreign_key",
                                      fk=self._parse_fk_spec(),
                                      new_name=cname)
            if self.peek().kind == "IDENT" and \
                    self.peek().text.lower() == "check":
                self.next()
                e, txt = self._parse_check_expr()
                return AlterTableStmt(table, "add_check", check=(cname, e, txt))
            self.accept_kw("column")
            return AlterTableStmt(table, "add_column", column=self.parse_column_def())
        if self.accept_kw("drop"):
            if self.accept_kw("foreign"):
                self.expect_kw("key")
                return AlterTableStmt(table, "drop_foreign_key",
                                      old_name=self.expect_ident())
            if self.peek().kind == "IDENT" and \
                    self.peek().text.lower() == "check":
                self.next()
                return AlterTableStmt(table, "drop_check",
                                      old_name=self.expect_ident())
            self.accept_kw("column")
            return AlterTableStmt(table, "drop_column", old_name=self.expect_ident())
        if self.accept_kw("rename"):
            self.accept_kw("to")
            return AlterTableStmt(table, "rename", new_name=self.expect_ident())
        if self.accept_kw("modify"):
            self.accept_kw("column")
            return AlterTableStmt(table, "modify_column", column=self.parse_column_def())
        if self._accept_word("shard"):
            # ALTER TABLE t SHARD BY ... — resharding DDL: new placement
            # metadata, schema_version bump (plan caches + placement
            # snapshots invalidate)
            self.expect_kw("by")
            return AlterTableStmt(table, "reshard",
                                  shard=self._parse_shard_spec())
        if self._accept_word("cluster"):
            # ALTER TABLE t CLUSTER BY (col) | CLUSTER BY NONE — ordered
            # compaction hint: the next delta->segment fold physically
            # re-sorts the table by this column (ISSUE 18)
            self.expect_kw("by")
            return AlterTableStmt(table, "cluster",
                                  cluster=self._parse_cluster_spec())
        raise self.error("unsupported ALTER TABLE action")

    # -- misc statements -----------------------------------------------------

    def parse_explain(self):
        self.next()  # explain/describe/desc
        t = self.peek()
        # DESCRIBE <table> is SHOW COLUMNS (MySQL shorthand) — but a
        # statement keyword (EXPLAIN REPLACE ..., EXPLAIN TRUNCATE ...)
        # still explains that statement
        if t.kind in ("IDENT", "QIDENT") or (
                t.kind == "KW" and t.text in _IDENTISH_KW
                and t.text not in _STMT_KWS):
            return ShowStmt("columns", target=self.expect_ident())
        analyze = bool(self.accept_kw("analyze"))
        start = self.peek().pos
        inner = self.parse_statement()
        try:
            inner._source = self.sql[start : self.peek().pos].strip()
        except AttributeError:
            pass
        return ExplainStmt(inner, analyze)

    def parse_set(self) -> SetStmt:
        self.expect_kw("set")
        assignments = []
        while True:
            scope = "session"
            if self.accept_kw("global"):
                scope = "global"
            elif self.accept_kw("session"):
                scope = "session"
            t = self.peek()
            if t.kind == "IDENT" and t.text.startswith("@@"):
                self.next()
                name = t.text[2:]
                for pre in ("global.", "session."):
                    if name.startswith(pre):
                        scope = pre[:-1]
                        name = name[len(pre):]
            elif t.kind == "IDENT" and t.text.startswith("@"):
                self.next()
                scope, name = "user", t.text[1:]
            else:
                name = self.expect_ident()
            self.accept_op("=") or self.accept_op(":=")
            value = self.parse_expr()
            assignments.append((scope, name, value))
            if not self.accept_op(","):
                break
        return SetStmt(assignments)

    def parse_show(self) -> ShowStmt:
        self.expect_kw("show")
        if self.accept_kw("databases"):
            return ShowStmt("databases")
        if self.accept_kw("tables"):
            like = self.next().text if self.accept_kw("like") else None
            return ShowStmt("tables", like=like)
        if self.accept_kw("columns"):
            self.expect_kw("from")
            return ShowStmt("columns", target=self.expect_ident())
        if self.accept_kw("create"):
            if self.accept_kw("view"):
                return ShowStmt("create_view", target=self.expect_ident())
            self.expect_kw("table")
            return ShowStmt("create_table", target=self.expect_ident())
        if self.accept_kw("global") or self.accept_kw("session"):
            pass
        if self.accept_kw("variables"):
            like = self.next().text if self.accept_kw("like") else None
            return ShowStmt("variables", like=like)
        if self.accept_kw("status"):
            return ShowStmt("status")
        if self._accept_word("processlist"):
            return ShowStmt("processlist")
        if self._accept_word("warnings"):
            return ShowStmt("warnings")
        if self.accept_kw("plugins"):
            return ShowStmt("plugins")
        if self.accept_kw("index") or (
                self.peek().kind == "IDENT"
                and self.peek().text.lower() in ("indexes", "keys")
                and self.next()):
            self.expect_kw("from")
            return ShowStmt("index", target=self.expect_ident())
        if self.accept_kw("bindings"):
            return ShowStmt("bindings")
        if self.accept_kw("grants"):
            user = None
            if self.accept_kw("for"):
                user = self._user_name()
            return ShowStmt("grants", target=user)
        raise self.error("unsupported SHOW")

    def _parse_priv_list(self):
        """SELECT, INSERT ... | ALL [PRIVILEGES] — lowercase names."""
        from tidb_tpu.privilege import PRIV_KINDS

        if self.accept_kw("all"):
            self.accept_kw("privileges")
            return ["all"]
        privs = []
        while True:
            name = self.next().text.lower()
            if name not in PRIV_KINDS:
                raise self.error(f"unknown privilege {name!r}")
            privs.append(name)
            if not self.accept_op(","):
                return privs

    def _parse_priv_object(self):
        """*.* | db.* | db.table | table (current db resolved later)."""
        if self.accept_op("*"):
            if self.accept_op("."):
                self.expect_op("*")
                return "*", "*"
            return None, "*"  # MySQL: bare * = current database
        first = self.expect_ident()
        if self.accept_op("."):
            if self.accept_op("*"):
                return first, "*"
            return first, self.expect_ident()
        return None, first  # db = session default, filled by the executor

    def parse_grant(self):
        self.expect_kw("grant")
        privs = self._parse_priv_list()
        self.expect_kw("on")
        db, table = self._parse_priv_object()
        self.expect_kw("to")
        return GrantStmt(privs, db, table, self._user_name())

    def parse_revoke(self):
        self.expect_kw("revoke")
        privs = self._parse_priv_list()
        self.expect_kw("on")
        db, table = self._parse_priv_object()
        self.expect_kw("from")
        return RevokeStmt(privs, db, table, self._user_name())

    def _parse_over(self, fname: str, args, distinct: bool) -> EWindow:
        self.expect_kw("over")
        self.expect_op("(")
        if distinct:
            raise self.error("DISTINCT in window functions")
        part, order = [], []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            part.append(self.parse_expr())
            while self.accept_op(","):
                part.append(self.parse_expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.accept_kw("desc"):
                    desc = True
                else:
                    self.accept_kw("asc")
                order.append(OrderItem(e, desc))
                if not self.accept_op(","):
                    break
        frame = None
        is_rows = self._accept_word("rows")
        if is_rows or self._accept_word("range"):
            def bound():
                if self._accept_word("unbounded"):
                    if self._accept_word("preceding"):
                        return ("unbounded_preceding",)
                    self._expect_word("following")
                    return ("unbounded_following",)
                if self._accept_word("current"):
                    self._expect_word("row")
                    return ("current",)
                if self.peek().kind != "NUM" or \
                        not self.peek().text.isdigit():
                    raise self.error("expected an integer frame bound")
                k = int(self.next().text)
                if self._accept_word("preceding"):
                    return ("preceding", k)
                self._expect_word("following")
                return ("following", k)

            if self.accept_kw("between"):
                lo = bound()
                self.expect_kw("and")
                hi = bound()
            else:
                lo, hi = bound(), ("current",)
            # MySQL ER_WINDOW_FRAME_*_ILLEGAL: bound CATEGORIES must be
            # ordered (offsets within a category are not validated,
            # matching MySQL — 5 PRECEDING AND 2 PRECEDING is legal)
            rank = {"unbounded_preceding": 0, "preceding": 1, "current": 2,
                    "following": 3, "unbounded_following": 4}
            if rank[lo[0]] > rank[hi[0]]:
                raise self.error(
                    "frame start cannot come after its end "
                    f"({lo[0].upper()} .. {hi[0].upper()})")
            kind = "rows" if is_rows else "range"
            if kind == "range" and any(
                    b[0] in ("preceding", "following") for b in (lo, hi)):
                raise self.error(
                    "RANGE frames with value offsets are not supported "
                    "(use ROWS)")
            frame = (kind, lo, hi)
        self.expect_op(")")
        return EWindow(fname, args, part, order, frame=frame)

    def _parse_hints(self, text: str):
        """'LEADING(a, b) MEMORY_QUOTA(1048576)' -> [(name, [args])]."""
        import re as _re

        out = []
        for m in _re.finditer(r"(\w+)\s*\(([^)]*)\)", text):
            args = [a.strip().strip("`") for a in m.group(2).split(",") if a.strip()]
            out.append((m.group(1).lower(), args))
        return out

    def parse_install(self) -> InstallPluginStmt:
        self.expect_kw("install")
        self.expect_kw("plugin")
        name = self.expect_ident()
        self.expect_kw("soname")
        module = self.next()
        if module.kind != "STR":
            raise self.error("SONAME needs a quoted module name")
        return InstallPluginStmt(name, module.text)

    def parse_uninstall(self) -> UninstallPluginStmt:
        self.expect_kw("uninstall")
        self.expect_kw("plugin")
        return UninstallPluginStmt(self.expect_ident())

    def parse_start_txn(self) -> BeginStmt:
        self.expect_kw("start")
        self.expect_kw("transaction")
        return BeginStmt()

    def parse_use(self) -> UseStmt:
        self.expect_kw("use")
        return UseStmt(self.expect_ident())

    def parse_truncate(self) -> TruncateStmt:
        self.expect_kw("truncate")
        self.accept_kw("table")
        return TruncateStmt(self._table_name())

    def parse_analyze(self) -> AnalyzeStmt:
        self.expect_kw("analyze")
        self.expect_kw("table")
        tables = [self._table_name()]
        while self.accept_op(","):
            tables.append(self._table_name())
        return AnalyzeStmt(tables)

    # -- expressions (Pratt) -------------------------------------------------

    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        left = self.parse_xor()
        while self.at_kw("or") or self.at_op("||"):
            self.next()
            left = EBinary("or", left, self.parse_xor())
        return left

    def parse_xor(self):
        left = self.parse_and()
        while self.at_kw("xor"):
            self.next()
            left = EBinary("xor", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.at_kw("and") or self.at_op("&&"):
            self.next()
            left = EBinary("and", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept_kw("not"):
            return EUnary("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self):
        left = self.parse_bitor()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">=", "<=>"):
                op = self.next().text
                op = {"!=": "<>"}.get(op, op)
                right = self.parse_bitor()
                left = EBinary(op, left, right)
                continue
            negated = False
            save = self.pos
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    sub = self.parse_select_or_union()
                    self.expect_op(")")
                    left = EIn(left, subquery=sub, negated=negated)
                else:
                    vals = [self.parse_expr()]
                    while self.accept_op(","):
                        vals.append(self.parse_expr())
                    self.expect_op(")")
                    left = EIn(left, values=vals, negated=negated)
                continue
            if self.accept_kw("between"):
                low = self.parse_bitor()
                self.expect_kw("and")
                high = self.parse_bitor()
                left = EBetween(left, low, high, negated=negated)
                continue
            if self.accept_kw("like"):
                pattern = self.parse_bitor()
                escape = None
                t = self.peek()
                if t.kind == "IDENT" and t.text.lower() == "escape":
                    self.next()
                    escape = self.next().text
                left = ELike(left, pattern, negated=negated, escape=escape)
                continue
            t = self.peek()
            if t.kind == "IDENT" and t.text.lower() in ("regexp", "rlike"):
                self.next()
                pattern = self.parse_bitor()
                left = ERegexp(left, pattern, negated=negated)
                continue
            if negated:
                self.pos = save
                break
            if self.accept_kw("is"):
                neg = bool(self.accept_kw("not"))
                if self.accept_kw("null"):
                    left = EIsNull(left, negated=neg)
                elif self.accept_kw("true"):
                    e = EBinary("<=>", left, EBool(True))
                    left = EUnary("not", e) if neg else e
                elif self.accept_kw("false"):
                    e = EBinary("<=>", left, EBool(False))
                    left = EUnary("not", e) if neg else e
                else:
                    raise self.error("expected NULL/TRUE/FALSE after IS")
                continue
            break
        return left

    def parse_bitor(self):
        left = self.parse_bitand()
        while self.at_op("|"):
            self.next()
            left = EBinary("|", left, self.parse_bitand())
        return left

    def parse_bitand(self):
        left = self.parse_shift()
        while self.at_op("&"):
            self.next()
            left = EBinary("&", left, self.parse_shift())
        return left

    def parse_shift(self):
        left = self.parse_additive()
        while self.at_op("<<", ">>"):
            op = self.next().text
            left = EBinary(op, left, self.parse_additive())
        return left

    def parse_additive(self):
        left = self.parse_multiplicative()
        while self.at_op("+", "-"):
            op = self.next().text
            left = EBinary(op, left, self.parse_multiplicative())
        return left

    def parse_multiplicative(self):
        left = self.parse_bitxor()
        while True:
            if self.at_op("*", "/", "%"):
                op = self.next().text
                left = EBinary({"%": "mod"}.get(op, op), left, self.parse_bitxor())
            elif self.peek().kind == "IDENT" and self.peek().text.lower() in ("div", "mod"):
                op = self.next().text.lower()
                left = EBinary(op, left, self.parse_bitxor())
            else:
                return left

    def parse_bitxor(self):
        # MySQL: ^ binds tighter than * /
        left = self.parse_unary()
        while self.at_op("^"):
            self.next()
            left = EBinary("^", left, self.parse_unary())
        return left

    def parse_unary(self):
        if self.at_op("-"):
            self.next()
            return EUnary("-", self.parse_unary())
        if self.at_op("+"):
            self.next()
            return self.parse_unary()
        if self.at_op("~"):
            self.next()
            return EUnary("~", self.parse_unary())
        if self.at_op("!"):
            self.next()
            return EUnary("not", self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self):
        left = self.parse_primary()
        # MySQL JSON path operators: col->'$.a' / col->>'$.a'
        while self.at_op("->", "->>"):
            op = self.next().text
            t = self.next()
            if t.kind != "STR":
                raise self.error("JSON path must be a quoted string")
            left = EFunc("json_extract", [left, EStr(t.text)])
            if op == "->>":
                left = EFunc("json_unquote", [left])
        return left

    def parse_primary(self):
        t = self.peek()

        if t.kind == "NUM":
            self.next()
            return ENum(t.text)
        if t.kind == "STR":
            self.next()
            return EStr(t.text)
        if t.kind == "PARAM":
            self.next()
            idx = self.param_count
            self.param_count += 1
            return EParam(idx)

        if t.kind == "KW":
            if self.accept_kw("null"):
                return ENull()
            if self.accept_kw("true"):
                return EBool(True)
            if self.accept_kw("false"):
                return EBool(False)
            if self.accept_kw("case"):
                return self.parse_case()
            if self.accept_kw("cast"):
                self.expect_op("(")
                arg = self.parse_expr()
                self.expect_kw("as")
                tt = self.next()
                ty = tt.text.lower()
                targs = ()
                if self.accept_op("("):
                    a = [int(self.next().text)]
                    while self.accept_op(","):
                        a.append(int(self.next().text))
                    self.expect_op(")")
                    targs = tuple(a)
                self.expect_op(")")
                return ECast(arg, ty, targs)
            if self.accept_kw("exists"):
                self.expect_op("(")
                sub = self.parse_select_or_union()
                self.expect_op(")")
                return EExists(sub)
            if self.accept_kw("extract"):
                # EXTRACT(unit FROM expr) -> unit(expr)
                self.expect_op("(")
                unit = self.next().text.lower()
                self.expect_kw("from")
                arg = self.parse_expr()
                self.expect_op(")")
                return EFunc(unit, [arg])
            if self.accept_kw("not"):
                return EUnary("not", self.parse_not())
            if self.accept_kw("interval"):
                val = self.parse_expr()
                unit = self.next().text.lower()
                return EInterval(val, unit)
            if self.at_kw("date", "time", "timestamp") and self.peek(1).kind == "STR":
                kw = self.next().text
                s = self.next().text
                return EFunc(kw, [EStr(s)])
            if t.text in _IDENTISH_KW:
                # keyword usable as function/identifier (e.g. LEFT(x,1))
                return self.parse_name_or_call()
            raise self.error(f"unexpected keyword {t.text.upper()} in expression")

        if t.kind in ("IDENT", "QIDENT"):
            if t.text.startswith("@@"):
                self.next()
                name = t.text[2:]
                scope = ""
                for pre in ("global.", "session."):
                    if name.startswith(pre):
                        scope, name = pre[:-1], name[len(pre):]
                return EVar(name, scope)
            if t.text.startswith("@"):
                self.next()
                return EVar(t.text, "user")
            return self.parse_name_or_call()

        if self.accept_op("("):
            if self.at_kw("select", "with"):
                sub = self.parse_select_or_union()
                self.expect_op(")")
                return ESubquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e

        if self.at_op("*"):
            self.next()
            return EStar()

        raise self.error("unexpected token in expression")

    def parse_name_or_call(self):
        name = self.expect_ident()
        if self.accept_op("("):
            fname = name.lower()
            if fname == "position":
                # POSITION(substr IN str) = LOCATE(substr, str); the
                # needle parses below IN precedence so IN stays the
                # separator
                sub = self.parse_bitor()
                self.expect_kw("in")
                s = self.parse_expr()
                self.expect_op(")")
                return EFunc("locate", [sub, s])
            distinct = bool(self.accept_kw("distinct"))
            args: List = []
            if not self.at_op(")"):
                if self.at_op("*"):
                    self.next()
                    args.append(EStar())
                else:
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
            if fname == "group_concat":
                # GROUP_CONCAT(x [ORDER BY k [ASC|DESC], ...]
                #              [SEPARATOR 'sep'])
                agg_order = None
                sep = None
                if self.accept_kw("order"):
                    self.expect_kw("by")
                    agg_order = []
                    while True:
                        k = self.parse_expr()
                        desc = bool(self.accept_kw("desc"))
                        if not desc:
                            self.accept_kw("asc")
                        agg_order.append((k, desc))
                        if not self.accept_op(","):
                            break
                t = self.peek()
                if t.kind == "IDENT" and t.text.lower() == "separator":
                    self.next()
                    sep = self.next().text
                self.expect_op(")")
                return EFunc(fname, args, distinct=distinct,
                             agg_order=agg_order, separator=sep)
            self.expect_op(")")
            if self.at_kw("over"):
                return self._parse_over(fname, args, distinct)
            return EFunc(fname, args, distinct=distinct)
        if self.accept_op("."):
            t = self.peek()
            if self.at_op("*"):
                self.next()
                return EStar(qualifier=name)
            col = self.expect_ident()
            return EName(col, qualifier=name)
        return EName(name)

    def parse_case(self) -> ECase:
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            whens.append((cond, self.parse_expr()))
        else_ = None
        if self.accept_kw("else"):
            else_ = self.parse_expr()
        self.expect_kw("end")
        return ECase(operand, whens, else_)


# keywords that may appear where identifiers/functions are expected
# keywords that start a parsable statement: EXPLAIN <stmt> keeps its
# meaning for these even though some double as identifiers
_STMT_KWS = {
    "select", "with", "insert", "replace", "update", "delete", "create",
    "drop", "alter", "set", "show", "begin", "start", "commit", "rollback",
    "use", "truncate", "analyze", "trace", "install", "uninstall",
}

_IDENTISH_KW = {
    "date", "time", "timestamp", "left", "right", "if", "replace", "values",
    "database", "schema", "comment", "status", "key", "engine", "truncate",
    # table/column positions (INFORMATION_SCHEMA names, user accounts)
    "tables", "columns", "column", "user", "variables", "trace",
    # non-reserved in MySQL: usable as identifiers
    "binding", "bindings", "plugin", "plugins", "soname",
    "install", "uninstall", "view", "duplicate",
    # INSERT(str, pos, len, newstr) the string function
    "insert",
    # non-reserved statement-leading words usable as column names
    "start", "begin", "rollback", "commit",
}
