"""AST -> SQL text (the tipb-serialization role: plan fragments shipped
to remote coprocessor workers travel as SQL over the DCN RPC tier, and
round-trip through the worker's own parser).

Only expression printing is needed — fragment SELECTs are assembled by
the coordinator from printed pieces. Strings re-quote with '' doubling;
everything prints fully parenthesized so precedence never needs
reconstruction."""

from __future__ import annotations

from tidb_tpu.errors import UnsupportedError
from tidb_tpu.parser import ast as A

__all__ = ["expr_to_sql"]


def _q(s: str) -> str:
    return "'" + str(s).replace("'", "''") + "'"


def expr_to_sql(e) -> str:
    if isinstance(e, A.EName):
        if e.qualifier:
            return f"`{e.qualifier}`.`{e.name}`"
        return f"`{e.name}`"
    if isinstance(e, A.ENum):
        return e.text
    if isinstance(e, A.EStr):
        return _q(e.value)
    if isinstance(e, A.ENull):
        return "NULL"
    if isinstance(e, A.EBool):
        return "TRUE" if e.value else "FALSE"
    if isinstance(e, A.EStar):
        return f"`{e.qualifier}`.*" if e.qualifier else "*"
    if isinstance(e, A.EBinary):
        return f"({expr_to_sql(e.left)} {e.op} {expr_to_sql(e.right)})"
    if isinstance(e, A.EUnary):
        op = {"not": "NOT "}.get(e.op, e.op)
        return f"({op}{expr_to_sql(e.arg)})"
    if isinstance(e, A.EFunc):
        inner = ", ".join(expr_to_sql(a) for a in e.args)
        if e.distinct:
            inner = "DISTINCT " + inner
        return f"{e.name}({inner})"
    if isinstance(e, A.ECase):
        parts = ["CASE"]
        if e.operand is not None:
            parts.append(expr_to_sql(e.operand))
        for w, t in e.whens:
            parts.append(f"WHEN {expr_to_sql(w)} THEN {expr_to_sql(t)}")
        if e.else_ is not None:
            parts.append(f"ELSE {expr_to_sql(e.else_)}")
        parts.append("END")
        return "(" + " ".join(parts) + ")"
    if isinstance(e, A.ECast):
        args = f"({', '.join(str(a) for a in e.type_args)})" if e.type_args else ""
        return f"CAST({expr_to_sql(e.arg)} AS {e.type_name}{args})"
    if isinstance(e, A.EIn):
        if e.values is None:
            raise UnsupportedError("cannot print IN (subquery)")
        vals = ", ".join(expr_to_sql(v) for v in e.values)
        return f"({expr_to_sql(e.arg)} {'NOT ' if e.negated else ''}IN ({vals}))"
    if isinstance(e, A.EBetween):
        return (f"({expr_to_sql(e.arg)} {'NOT ' if e.negated else ''}BETWEEN "
                f"{expr_to_sql(e.low)} AND {expr_to_sql(e.high)})")
    if isinstance(e, A.ELike):
        esc = f" ESCAPE {_q(e.escape)}" if e.escape else ""
        return (f"({expr_to_sql(e.arg)} {'NOT ' if e.negated else ''}LIKE "
                f"{expr_to_sql(e.pattern)}{esc})")
    if isinstance(e, A.EInterval):
        return f"INTERVAL {expr_to_sql(e.value)} {e.unit}"
    if isinstance(e, A.EIsNull):
        return f"({expr_to_sql(e.arg)} IS {'NOT ' if e.negated else ''}NULL)"
    raise UnsupportedError(f"cannot print {type(e).__name__} for fragment shipping")
