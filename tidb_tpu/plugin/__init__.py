"""Plugin extension points (ref: plugin/ — audit and authentication
hook enums, plugin loading, and the north star's hook for registering an
alternate executor backend).

The reference loads Go plugins with hook enums fired from the session
and privilege layers. Here a plugin is a Python module exposing

    def plugin_init(registry: PluginRegistry) -> None

which registers one or more `Plugin` instances. Kinds:

  audit     — on_statement_begin(session, sql, stmt_type)
              on_statement_end(session, sql, stmt_type, dur_s, error)
  auth      — authenticate(user, token, salt) -> True/False/None
              (None = not my user, fall through; first non-None wins)
  executor  — build(phys_plan, session) -> executor tree; selected per
              session via the tidb_executor_plugin sysvar (the
              generalization of the tidb_enable_tpu_exec toggle)

Plugins are per-catalog (one registry per server instance, like the
reference's per-process plugin list). INSTALL PLUGIN name SONAME
'python.module' / UNINSTALL PLUGIN / SHOW PLUGINS are the SQL surface.
"""

from __future__ import annotations

import importlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from tidb_tpu.errors import ExecutionError

__all__ = ["Plugin", "PluginRegistry"]

_KINDS = ("audit", "auth", "executor")


@dataclass
class Plugin:
    name: str
    kind: str  # audit | auth | executor
    version: str = "1.0"
    # audit
    on_statement_begin: Optional[Callable] = None
    on_statement_end: Optional[Callable] = None
    # auth
    authenticate: Optional[Callable] = None
    # executor
    build: Optional[Callable] = None
    # bookkeeping
    module: str = ""
    status: str = "ACTIVE"


class PluginRegistry:
    def __init__(self):
        self._plugins: Dict[str, Plugin] = {}

    # -- registration / loading ---------------------------------------

    def register(self, plugin: Plugin) -> None:
        if plugin.kind not in _KINDS:
            raise ExecutionError(f"unknown plugin kind {plugin.kind!r}")
        if plugin.name in self._plugins:
            raise ExecutionError(f"plugin {plugin.name!r} already installed")
        self._plugins[plugin.name] = plugin

    # SQL-reachable import allowlist: module path prefixes INSTALL
    # PLUGIN may load, configured at process start (never via SQL) —
    # MySQL likewise restricts SONAME to the server-local plugin_dir.
    # None = embedding default (trusted in-process callers); the server
    # entrypoint sets it explicitly (--plugin-modules / config).
    allowed_prefixes: "Optional[tuple]" = None

    def load_module(self, name: str, module: str) -> None:
        """INSTALL PLUGIN name SONAME 'module': import and init. The
        module's plugin_init may register several plugins; `name` must
        be among them (MySQL errors likewise on a name mismatch)."""
        if self.allowed_prefixes is not None and not any(
                module == p or module.startswith(p + ".")
                for p in self.allowed_prefixes):
            raise ExecutionError(
                f"plugin module {module!r} is outside the configured "
                f"allowlist")
        try:
            mod = importlib.import_module(module)
        except ImportError as e:
            raise ExecutionError(f"cannot load plugin module {module!r}: {e}")
        init = getattr(mod, "plugin_init", None)
        if init is None:
            raise ExecutionError(f"module {module!r} has no plugin_init")
        before = set(self._plugins)
        try:
            init(self)
        except Exception:
            for n in set(self._plugins) - before:  # no partial installs
                del self._plugins[n]
            raise
        added = set(self._plugins) - before
        for n in added:
            self._plugins[n].module = module
        if name not in added:
            for n in added:
                del self._plugins[n]
            raise ExecutionError(
                f"module {module!r} did not register plugin {name!r}")

    def uninstall(self, name: str) -> None:
        if name not in self._plugins:
            raise ExecutionError(f"plugin {name!r} is not installed")
        del self._plugins[name]

    def rows(self) -> List[tuple]:
        """SHOW PLUGINS resultset rows."""
        return [(p.name, p.status, p.kind.upper(), p.module, p.version)
                for p in self._plugins.values()]

    # -- hook dispatch -------------------------------------------------

    def _of_kind(self, kind: str):
        return [p for p in self._plugins.values()
                if p.kind == kind and p.status == "ACTIVE"]

    def statement_begin(self, session, sql: str, stmt_type: str) -> None:
        for p in self._of_kind("audit"):
            if p.on_statement_begin is not None:
                p.on_statement_begin(session, sql, stmt_type)

    def statement_end(self, session, sql: str, stmt_type: str,
                      dur_s: float, error: Optional[BaseException]) -> None:
        for p in self._of_kind("audit"):
            if p.on_statement_end is not None:
                p.on_statement_end(session, sql, stmt_type, dur_s, error)

    def authenticate(self, user: str, token: bytes, salt: bytes) -> Optional[bool]:
        """First auth plugin claiming the user wins; None = builtin."""
        for p in self._of_kind("auth"):
            if p.authenticate is not None:
                verdict = p.authenticate(user, token, salt)
                if verdict is not None:
                    return bool(verdict)
        return None

    def executor_builder(self, name: str) -> Optional[Callable]:
        p = self._plugins.get(name)
        if p is not None and p.kind == "executor" and p.build is not None:
            return p.build
        return None
