"""Owner election + the async DDL job pipeline it guards (ref: owner/ —
etcd-lease election of the DDL owner — and ddl/'s job queue + worker).

The reference elects one DDL owner per cluster through etcd leases;
every instance can *submit* a DDL job (a row in a KV queue), only the
owner's worker executes them, and ownership fails over when the owner's
lease lapses. In-process, N server instances share one Catalog, so the
standing-in election is a TTL lease on the catalog (the mockstore move:
same interface and failover semantics, no etcd):

    Election   — campaign/renew/resign over a monotonic-clock lease
    DDLJob     — one queued statement (sql, db, state, error)
    DDLWorker  — a thread that campaigns and, while owner, drains the
                 catalog's job queue through its own Session

Sessions route DDL statements into the queue whenever workers are
registered (the multi-instance deployment); with no workers (embedded
single-session use) DDL executes inline, like the reference running
with a local store."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

__all__ = ["Election", "DDLJob", "DDLWorker"]


class Election:
    """TTL-lease leader election (the etcd-lease stand-in)."""

    def __init__(self, ttl: float = 3.0, clock: Callable[[], float] = time.monotonic):
        self.ttl = ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._owner: Optional[str] = None
        self._expires = 0.0

    def campaign(self, candidate: str) -> bool:
        """Become owner if the seat is free or the lease lapsed."""
        with self._lock:
            now = self._clock()
            if self._owner is None or now >= self._expires or self._owner == candidate:
                self._owner = candidate
                self._expires = now + self.ttl
                return True
            return False

    def renew(self, candidate: str) -> bool:
        with self._lock:
            if self._owner != candidate or self._clock() >= self._expires:
                return False
            self._expires = self._clock() + self.ttl
            return True

    def resign(self, candidate: str) -> None:
        with self._lock:
            if self._owner == candidate:
                self._owner = None
                self._expires = 0.0

    def owner(self) -> Optional[str]:
        with self._lock:
            if self._owner is not None and self._clock() >= self._expires:
                return None  # lapsed lease: seat open
            return self._owner


@dataclass
class DDLJob:
    """One queued DDL statement (ref: the ddl job rows in KV)."""

    job_id: int
    sql: str
    db: str
    state: str = "queued"  # queued | running | done | error
    claimed_by: Optional[str] = None
    error: Optional[BaseException] = None
    done: threading.Event = field(default_factory=threading.Event)

    def fail(self, exc: BaseException) -> None:
        self.state = "error"
        self.error = exc
        self.done.set()


class DDLWorker:
    """Campaigns for DDL ownership; while owner, executes queued jobs
    through a private Session on the shared catalog (the reference's
    ddl.worker run by the elected owner)."""

    def __init__(self, catalog, worker_id: str, poll: float = 0.05):
        self.catalog = catalog
        self.worker_id = worker_id
        self.poll = poll
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.catalog.ddl_workers[self.worker_id] = self
        self._thread = threading.Thread(
            target=self._run, name=f"ddl-worker-{self.worker_id}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # join BEFORE deregistering: once this worker leaves the
        # registry, reclaim_ddl_jobs may requeue a job it still holds —
        # two workers would then run the same DDL concurrently
        if self._thread is not None:
            self._thread.join(timeout=5)
            if self._thread.is_alive():
                # a long DDL is still executing: the worker must stay
                # registered (its claimed job must not be reclaimed or
                # drained mid-run). It exits its loop when the job ends;
                # the caller may stop() again then.
                return
        self.catalog.ddl_workers.pop(self.worker_id, None)
        self.catalog.ddl_owner.resign(self.worker_id)
        # last worker out fails everything still pending — a submitter
        # waiting on job.done (holding the statement lock) must not sit
        # out its full timeout for a DDL no one will ever run
        if not self.catalog.ddl_workers:
            self.catalog.drain_ddl_jobs("DDL owner shut down")

    # ------------------------------------------------------------------

    def _run(self) -> None:
        from tidb_tpu.session import Session

        sess = None
        while not self._stop.is_set():
            if not self.catalog.ddl_owner.campaign(self.worker_id):
                self._stop.wait(self.poll)
                continue
            # jobs claimed by a worker that no longer exists (owner died
            # mid-execution) go back to queued — failover covers
            # claimed-but-unfinished work, not just fresh submissions
            self.catalog.reclaim_ddl_jobs()
            job = self.catalog.next_ddl_job(self.worker_id)
            if job is None:
                self._stop.wait(self.poll)
                continue
            try:
                if sess is None:
                    sess = Session(catalog=self.catalog, db=job.db)
                    sess._ddl_direct = True  # never re-enqueue
                sess.db = job.db
                # NO catalog.lock here: the submitter blocks holding it
                # (server statement lock) until job.done — taking it
                # would deadlock, and its being held is exactly what
                # serializes this execution against other clients
                sess.execute(job.sql)
                job.state = "done"
            except BaseException as e:  # noqa: BLE001 — error travels to submitter
                job.state = "error"
                job.error = e
            finally:
                job.done.set()
