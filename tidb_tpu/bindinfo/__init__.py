"""SQL plan bindings (ref: bindinfo/ — BindHandle: normalized-SQL ->
hinted statement, session- and global-scoped).

A binding maps the *normalized* form of a statement (literals
parameterized, whitespace collapsed, hints stripped) to a replacement
statement carrying optimizer hints. At plan time the session looks up
the incoming SELECT's normalized text and, on a hit, plans the bound
statement instead — the reference's mechanism for pinning plans without
editing application SQL."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from tidb_tpu.parser.lexer import Lexer

__all__ = ["normalize_sql", "sql_digest", "Binding", "BindHandle"]


def normalize_sql(sql: str) -> str:
    """Token-level normalization: numeric/string literals -> '?', hints
    dropped, keywords lowercased (the lexer already lowercases them),
    single-space joined. Mirrors the reference's parameterized digest."""
    out = []
    for t in Lexer(sql).tokens():
        if t.kind == "EOF":
            break
        if t.kind == "HINT":
            continue
        if t.kind in ("NUM", "STR"):
            out.append("?")
        elif t.kind == "OP" and t.text == ";":
            continue
        else:
            out.append(t.text)
    return " ".join(out)


def sql_digest(normalized: str) -> str:
    """Statement digest: hex SHA-256 of the normalized text (truncated —
    32 hex chars keep full practical collision resistance while staying
    readable in I_S rows and log lines). Shared by the statements-summary
    store and the slow-query log so their digests always join."""
    import hashlib

    return hashlib.sha256(normalized.encode()).hexdigest()[:32]


@dataclass
class Binding:
    original_sql: str
    bind_sql: str
    scope: str  # global | session
    status: str = "enabled"
    stmt: object = None  # parsed bind_sql, cached at create() time


class BindHandle:
    """One scope's bindings (the catalog holds the global handle, each
    session its own)."""

    def __init__(self, scope: str):
        self.scope = scope
        self._by_norm: Dict[str, Binding] = {}
        # bumped on every create/drop; the plan cache keys on it so a
        # binding change can never serve a stale (differently-hinted)
        # cached plan
        self.version = 0

    def create(self, target_sql: str, using_sql: str) -> None:
        from tidb_tpu.parser import parse

        norm = normalize_sql(target_sql)
        stmts = parse(using_sql)
        stmt = stmts[0] if len(stmts) == 1 else None
        self._by_norm[norm] = Binding(target_sql, using_sql, self.scope, stmt=stmt)
        self.version += 1

    def drop(self, target_sql: str) -> bool:
        hit = self._by_norm.pop(normalize_sql(target_sql), None) is not None
        if hit:
            self.version += 1
        return hit

    def match(self, norm: str) -> Optional[Binding]:
        b = self._by_norm.get(norm)
        return b if b is not None and b.status == "enabled" else None

    def rows(self) -> List[tuple]:
        return [(b.original_sql, b.bind_sql, b.scope, b.status)
                for b in self._by_norm.values()]

    def __len__(self):
        return len(self._by_norm)
