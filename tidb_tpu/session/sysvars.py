"""System variables (ref: sessionctx/variable — the two-tier GLOBAL /
SESSION variable system, incl. the `tidb_enable_tpu_exec`-style switch the
north star registers for toggling the device executor).

Globals live on the Catalog (the cluster-state analogue of
mysql.global_variables); sessions overlay them. New sessions snapshot
nothing — reads fall through session -> global -> default, like the
reference's cached global + session copy."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

from tidb_tpu.errors import ExecutionError

__all__ = ["SysVar", "SYSVARS", "SysVarStore", "canonical"]


def _sanitizer_env_gate() -> bool:
    """TIDB_TPU_SANITIZE env seed for the sanitize sysvar default —
    ONE parser (analysis/sanitizer.env_gate) so `=0` disables here and
    in the sanitizer's own process gate identically."""
    from tidb_tpu.analysis.sanitizer import env_gate

    return env_gate()

GLOBAL, SESSION, BOTH = "global", "session", "both"


@dataclass(frozen=True)
class SysVar:
    name: str
    default: object
    scope: str = BOTH
    kind: str = "str"  # bool | int | float | str | enum
    min_: Optional[int] = None
    max_: Optional[int] = None
    enum_values: Optional[tuple] = None  # kind == "enum": allowed (lowercase)


SYSVARS: Dict[str, SysVar] = {}


def _reg(*vs: SysVar) -> None:
    for v in vs:
        SYSVARS[v.name] = v


_reg(
    # the north-star switch: route eligible fragments to the device mesh
    SysVar("tidb_enable_tpu_exec", True, BOTH, "bool"),
    # auto: full device fragments on accelerators and multi-device
    # meshes; on a degenerate single-CPU backend, joins and generic
    # aggregation route to the vectorized host engine instead (XLA:CPU
    # sorts lose to numpy's by 5-10x and a 1-device mesh has no
    # parallelism to win back). force/off override the heuristic.
    SysVar("tidb_device_engine_mode", "auto", BOTH, "enum",
           enum_values=("auto", "force", "off")),
    # non-empty: name of an installed executor plugin that builds the
    # operator tree instead of the built-in builders (ref: plugin/)
    SysVar("tidb_executor_plugin", "", BOTH, "str"),
    # memo-based exhaustive join-order search (ref: planner/cascades
    # and the sysvar of the same name); greedy ordering otherwise
    SysVar("tidb_enable_cascades_planner", False, BOTH, "bool"),
    # eager aggregation (partial agg below joins); stats-gated, so ON by
    # default unlike the reference's blind-push variant
    SysVar("tidb_opt_agg_push_down", True, BOTH, "bool"),
    SysVar("group_concat_max_len", 1024, BOTH, "int"),
    SysVar("tidb_gc_enable", True, BOTH, "bool"),
    # stats lifecycle (ref: statistics auto-analyze): after DML commits,
    # re-ANALYZE a table whose modified-row count crossed ratio * rows
    SysVar("tidb_enable_auto_analyze", True, BOTH, "bool"),
    SysVar("tidb_auto_analyze_ratio", 0.5, BOTH, "float"),
    # statements slower than this (ms) go to the slow-query log
    SysVar("tidb_slow_log_threshold", 300, BOTH, "int", min_=0, max_=1 << 31),
    # head-sampling rate for always-on statement tracing: every
    # statement RECORDS a trace; this decides whether an uneventful one
    # is kept. Tail rules (slow, error, deadline/kill, retry/failover)
    # keep their traces regardless, so 0 still captures the interesting
    # statements — see utils/tracing.py
    SysVar("tidb_trace_sample_rate", 0.01, BOTH, "float"),
    # ring capacity of the tail-sampled trace store (/trace +
    # information_schema.cluster_trace); GLOBAL: one store per process
    SysVar("tidb_trace_store_capacity", 64, GLOBAL, "int",
           min_=1, max_=4096),
    # LRU cap on distinct digests kept by the statements-summary store
    # (ref: tidb_stmt_summary_max_stmt_count); evictions are counted.
    # GLOBAL-only like the reference: the store is catalog-wide, so a
    # session-local cap would evict other sessions' diagnostics
    SysVar("tidb_stmt_summary_max_stmt_count", 200, GLOBAL, "int",
           min_=1, max_=1 << 16),
    # digest-keyed plan cache (ref: tidb_enable_prepared_plan_cache /
    # the instance plan cache): prepared statements reuse verified plans
    # by default; non-prepared SELECT reuse is opt-in like the reference
    SysVar("tidb_enable_prepared_plan_cache", True, BOTH, "bool"),
    SysVar("tidb_enable_non_prepared_plan_cache", False, BOTH, "bool"),
    # LRU cap on the instance-wide plan cache; GLOBAL-only for the same
    # reason as the statements-summary cap (one shared store)
    SysVar("tidb_prepared_plan_cache_size", 256, GLOBAL, "int",
           min_=1, max_=1 << 16),
    # whether the previous SELECT's plan came from the plan cache
    # (read via @@last_plan_from_cache, like the reference)
    SysVar("last_plan_from_cache", False, SESSION, "bool"),
    # non-empty: wrap query execution in jax.profiler.trace(dir)
    SysVar("tidb_profile_dir", "", BOTH, "str"),
    # tables above this size stream through fixed [P,R] staging batches
    # instead of residing wholly in device memory (the >HBM path)
    SysVar("tidb_device_cache_bytes", 8 << 30, BOTH, "int",
           min_=1 << 20, max_=1 << 45),
    # partitioned device join (ISSUE 3): device-resident build sort,
    # fused-expand tile budget, and the fragment broadcast-build ceiling
    SysVar("tidb_tpu_join_device_build", True, BOTH, "bool"),
    SysVar("tidb_tpu_join_tiles_per_dispatch", 8, BOTH, "int",
           min_=1, max_=64),
    # join probe strategy (ISSUE 10): how probe chunks resolve (lo, hi)
    # match ranges over the sorted build keys. off = searchsorted always;
    # auto = open-addressing hash table when the computation targets TPU
    # (trace-time force_platform aware, like segment_sum), searchsorted
    # on CPU where its cache-friendly binary rounds measure faster;
    # xla/pallas force the table everywhere (window-scan probe / Pallas
    # VMEM kernel). Dense packed-key domains keep the O(1) direct-address
    # index regardless. Threaded per-statement through ExecContext into
    # BOTH tiers (fragment programs take it as a trace-time static in
    # their cache key) — the hash_probe process global is only the
    # offline default (ISSUE 12 fixed the set_mode race).
    SysVar("tidb_tpu_join_probe_mode", "auto", BOTH, "enum",
           enum_values=("off", "auto", "xla", "pallas")),
    # -- runtime invariant sanitizer (ISSUE 12) ------------------------
    # debug mode: wrap the registered locks in the runtime order
    # witness, audit tracker/pin balances at statement end, count
    # device_get round trips against the declared budget, and raise a
    # typed SanitizerError on fatal findings. Seeded by the
    # TIDB_TPU_SANITIZE env var for whole-process runs.
    SysVar("tidb_tpu_sanitize", _sanitizer_env_gate(), BOTH, "bool"),
    # per-statement ceiling on sanctioned device_get round trips while
    # sanitizing — the runtime form of the host-sync chunk-loop budget
    SysVar("tidb_tpu_sanitize_sync_budget", 4096, BOTH, "int",
           min_=1, max_=1 << 20),
    SysVar("tidb_broadcast_join_threshold_count", 1 << 21, BOTH, "int",
           min_=1 << 10, max_=1 << 28),
    # -- plan feedback (ISSUE 15) --------------------------------------
    # close the estimate->actual loop: record per-digest est-vs-actual
    # operator cardinalities at statement end and let the next planning
    # of the same digest consume them (join ordering, eager-agg push-
    # down exploration, fused-probe tile sizing, dcn broadcast-vs-
    # shuffle). Off = plans are byte-identical to the heuristic-only
    # planner and nothing is recorded. Feedback changes PLANS only,
    # never results.
    SysVar("tidb_tpu_plan_feedback", True, BOTH, "bool"),
    # LRU cap on distinct statement digests the feedback store retains;
    # GLOBAL: one store per process, like the statements summary
    SysVar("tidb_tpu_plan_feedback_capacity", 512, GLOBAL, "int",
           min_=1, max_=1 << 16),
    # -- serving tier (ISSUE 7): admission-controlled scheduler +
    # cross-session micro-batched dispatch -----------------------------
    # wire-connection cap enforced at the accept loop; over-limit
    # handshakes get MySQL error 1040 (ER_CON_COUNT_ERROR). 0 = unbounded
    SysVar("tidb_max_connections", 0, GLOBAL, "int", min_=0, max_=1 << 20),
    # gather window for cross-session micro-batching: the first
    # coalescible statement waits up to this long for same-shaped
    # followers before the batch dispatches. 0 disables coalescing
    # (every statement runs singleton through the scheduler)
    SysVar("tidb_tpu_batch_window_us", 250, GLOBAL, "int",
           min_=0, max_=1_000_000),
    # hard cap on members per coalesced dispatch; a full group seals
    # immediately without waiting out the window
    SysVar("tidb_tpu_max_batch_size", 64, GLOBAL, "int", min_=1, max_=4096),
    # scheduler worker-pool width (read at scheduler construction)
    SysVar("tidb_tpu_scheduler_workers", 4, GLOBAL, "int", min_=1, max_=256),
    # admission control: statements queued beyond this are rejected with
    # a typed "server is busy" error instead of queuing unboundedly
    SysVar("tidb_tpu_sched_max_queue", 256, GLOBAL, "int",
           min_=1, max_=1 << 20),
    # admitted statements not claimed by a worker within this budget are
    # evicted from the queue with a typed queue-timeout error (they
    # never started, so retry is always safe)
    SysVar("tidb_tpu_sched_queue_timeout_ms", 10_000, GLOBAL, "int",
           min_=1, max_=1 << 31),
    # server-wide host-memory budget across all in-flight statements
    # (the scheduler's root MemTracker); 0 = unlimited. New statements
    # are rejected at admission while consumption sits above it
    SysVar("tidb_tpu_sched_mem_quota", 0, GLOBAL, "int",
           min_=0, max_=1 << 45),
    # per-session host-memory budget across that session's in-flight
    # statement (a child of the server tracker); 0 = unlimited
    SysVar("tidb_tpu_mem_quota_session", 0, BOTH, "int",
           min_=0, max_=1 << 45),
    # -- per-digest latency SLOs (ISSUE 16) ----------------------------
    # latency objective per statement execution: the SLO store counts a
    # window observation over this target as a budget breach and
    # derives the burn ratio from the breach fraction (99% objective)
    SysVar("tidb_tpu_slo_target_ms", 300, GLOBAL, "int",
           min_=1, max_=1 << 31),
    # LRU cap on distinct digests the SLO store retains; GLOBAL: one
    # store per process, like the plan-feedback capacity
    SysVar("tidb_tpu_slo_capacity", 512, GLOBAL, "int",
           min_=1, max_=1 << 16),
    # the first SLO consumer (default OFF): under admission queue
    # pressure (queue >= 3/4 of tidb_tpu_sched_max_queue) shed the
    # statements whose digest is burning its SLO budget fastest, with
    # a typed 9008 rejection. Plans and results are never affected —
    # off leaves admission decisions byte-identical
    SysVar("tidb_tpu_sched_slo_shed", False, GLOBAL, "bool"),
    # -- columnar segment store (ISSUE 8) ------------------------------
    # scans over stored tables stage encoded, zone-mapped segments with
    # decompression fused into the jitted scan program; off = raw slices
    SysVar("tidb_tpu_columnar_enable", True, BOTH, "bool"),
    # fixed segment capacity in rows; the first store built for a table
    # pins its value for that table's lifetime
    SysVar("tidb_tpu_segment_rows", 1 << 16, BOTH, "int",
           min_=1 << 10, max_=1 << 22),
    # appended (delta) rows that trigger a coverage extension + zone-map
    # refresh at the next scan; smaller = fresher zone maps, more
    # build churn
    SysVar("tidb_tpu_segment_delta_rows", 1 << 16, BOTH, "int",
           min_=1 << 10, max_=1 << 24),
    # directory for spilled segment files (empty = system tmp); cold
    # segments evicted under the statement memory budget land here
    SysVar("tidb_tpu_columnar_spill_dir", "", BOTH, "str"),
    # background delta->segment compaction (ISSUE 17): a worker thread
    # rebuilds trailing segments off the statement path and cuts over
    # at the store lock; 0 = today's inline rebuild-at-scan behavior
    SysVar("tidb_tpu_compaction", True, BOTH, "bool"),
    # -- pipelined device-resident execution (ISSUE 9) -----------------
    # fuse scan->filter->project->partial-agg into ONE jitted program
    # per fragment, accumulating agg state on device across chunks with
    # a single fetch at finalize; off = the per-operator chunk pipeline
    SysVar("tidb_tpu_pipeline_fuse", True, BOTH, "bool"),
    # staging chunks the prefetch thread keeps in flight ahead of the
    # compute loop (jax.device_put of chunk k+1 while k computes);
    # 0 disables the thread and stages inline
    SysVar("tidb_tpu_pipeline_prefetch_depth", 2, BOTH, "int",
           min_=0, max_=16),
    # byte budget of the cross-statement device buffer cache (staged
    # scan inputs kept device-resident between statements, invalidated
    # like the plan cache); 0 disables it. GLOBAL: one cache per process
    SysVar("tidb_tpu_device_buffer_cache_bytes", 256 << 20, GLOBAL, "int",
           min_=0, max_=1 << 40),
    # stage fragment inputs as frame-of-reference-encoded narrow arrays
    # (decode fused into the fragment program) instead of raw int64
    SysVar("tidb_tpu_stage_encoded", True, BOTH, "bool"),
    # fixed device batch capacity (ref: tidb_max_chunk_size)
    SysVar("tidb_max_chunk_size", 1 << 16, BOTH, "int", min_=1 << 10, max_=1 << 24),
    # per-query host-side memory budget in bytes (ref: tidb_mem_quota_query)
    SysVar("tidb_mem_quota_query", 1 << 31, BOTH, "int", min_=1 << 20, max_=1 << 45),
    # spill host operator state to disk instead of cancelling on OOM
    SysVar("tidb_enable_tmp_storage_on_oom", True, BOTH, "bool"),
    SysVar("autocommit", True, BOTH, "bool"),
    # pessimistic locking-read wait bound (seconds; MySQL default is 50,
    # shortened here — analytics sessions should fail fast)
    SysVar("innodb_lock_wait_timeout", 5, BOTH, "int"),
    SysVar("sql_mode", "STRICT_TRANS_TABLES", BOTH, "str"),
    SysVar("version", "8.0.11-tidb-tpu-0.1.0", GLOBAL, "str"),
    SysVar("version_comment", "tidb_tpu: TPU-native SQL execution engine", GLOBAL, "str"),
    SysVar("time_zone", "SYSTEM", BOTH, "str"),
    SysVar("max_execution_time", 0, BOTH, "int", min_=0, max_=1 << 31),
    # per-RPC socket deadline on the DCN tier, ms; 0 disables. Distinct
    # from max_execution_time: the statement deadline bounds the whole
    # query, this bounds any SINGLE coordinator<->worker round trip (a
    # hung worker must not pin a statement for the full statement budget)
    SysVar("tidb_tpu_dcn_rpc_timeout", 30000, BOTH, "int",
           min_=0, max_=1 << 31),
    # a partition whose primary AND replica are unreachable: fail the
    # query (default, exact results) or serve the reachable partitions
    # with a warning (availability over completeness)
    SysVar("tidb_tpu_dcn_partial_results", False, BOTH, "bool"),
    # bound on a statement's wait for a topology-change gate (online
    # reshard backfill/cutover window, membership finalize), ms: past
    # it the statement degrades TYPED ("topology change in progress")
    # instead of hanging behind a stuck cutover
    SysVar("tidb_tpu_reshard_gate_wait_ms", 10000, BOTH, "int",
           min_=0, max_=1 << 31),
    SysVar("tx_isolation", "REPEATABLE-READ", BOTH, "str"),
    SysVar("transaction_isolation", "REPEATABLE-READ", BOTH, "str"),
    SysVar("character_set_client", "utf8mb4", BOTH, "str"),
    SysVar("character_set_results", "utf8mb4", BOTH, "str"),
    SysVar("character_set_connection", "utf8mb4", BOTH, "str"),
    SysVar("collation_connection", "utf8mb4_bin", BOTH, "str"),
)

_TRUTHY = {"1", "on", "true", "yes"}
_FALSY = {"0", "off", "false", "no"}


def canonical(var: SysVar, value) -> object:
    """Validate + canonicalize a SET value per the variable's kind."""
    if var.kind == "bool":
        s = str(value).strip().lower()
        if s in _TRUTHY:
            return True
        if s in _FALSY:
            return False
        raise ExecutionError(f"invalid boolean value {value!r} for {var.name}")
    if var.kind == "int":
        try:
            n = int(value)
        except (TypeError, ValueError):
            raise ExecutionError(f"invalid integer value {value!r} for {var.name}")
        if var.min_ is not None and n < var.min_:
            n = var.min_
        if var.max_ is not None and n > var.max_:
            n = var.max_
        return n
    if var.kind == "float":
        try:
            return float(value)
        except (TypeError, ValueError):
            raise ExecutionError(
                f"invalid float value {value!r} for {var.name}")
    if var.kind == "enum":
        s = str(value).strip().lower()
        if s not in (var.enum_values or ()):
            raise ExecutionError(
                f"invalid value {value!r} for {var.name} "
                f"(allowed: {', '.join(var.enum_values or ())})")
        return s
    return str(value)


def display(value) -> str:
    if isinstance(value, bool):
        return "ON" if value else "OFF"
    return str(value)


class SysVarStore:
    """Session-side view: overlay dict over the catalog's global dict."""

    def __init__(self, globals_: Dict[str, object]):
        self._globals = globals_
        self._session: Dict[str, object] = {}

    def get(self, name: str):
        name = name.lower()
        if name in self._session:
            return self._session[name]
        if name in self._globals:
            return self._globals[name]
        var = SYSVARS.get(name)
        if var is None:
            raise ExecutionError(f"unknown system variable {name!r}")
        return var.default

    def set(self, name: str, value, scope: str = SESSION) -> None:
        name = name.lower()
        var = SYSVARS.get(name)
        if var is None:
            raise ExecutionError(f"unknown system variable {name!r}")
        value = canonical(var, value)
        if scope == GLOBAL:
            if var.scope == SESSION:
                raise ExecutionError(f"{name} is a SESSION-only variable")
            self._globals[name] = value
        else:
            if var.scope == GLOBAL:
                raise ExecutionError(
                    f"{name} is a GLOBAL variable; use SET GLOBAL")
            self._session[name] = value

    def all_effective(self) -> Dict[str, object]:
        out = {name: v.default for name, v in SYSVARS.items()}
        out.update(self._globals)
        out.update(self._session)
        return out
