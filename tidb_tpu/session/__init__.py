"""Session layer (ref: session/ — Execute's parse->compile->run loop).

Round-1 scope: statement dispatch for SELECT/DML/DDL/EXPLAIN/SET/SHOW over
an in-process catalog, with the subquery-execution callback the planner
needs. Sysvars, domain, privileges and the full variable system widen in
session/sysvars.py.
"""

from tidb_tpu.session.session import Session

__all__ = ["Session"]
