"""Session: the SQL entry point."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from tidb_tpu.errors import (ExecutionError, PlanError, SchemaError,
                             UnsupportedError, WriteConflictError)
from tidb_tpu.executor import ExecContext, ResultSet, build_executor, run_plan
from tidb_tpu.executor.base import Executor
from tidb_tpu.parser import ast as A
from tidb_tpu.parser import parse
from tidb_tpu.planner.logical import BuildContext, build_select
from tidb_tpu.planner.optimizer import plan_statement
from tidb_tpu.planner.physical import PProjection, explain_text, lower
from tidb_tpu.planner.rules import optimize_logical
from tidb_tpu.session.sysvars import SysVarStore
from tidb_tpu.storage.catalog import Catalog
from tidb_tpu.storage.table import ColumnInfo, TableSchema
from tidb_tpu.types import TypeKind, parse_type_name

__all__ = ["Session", "TxnState"]


_LOAD_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", "0": "\0", "b": "\b",
                 "Z": "\x1a"}


def _split_load_fields(line: str, delim: str, quote):
    """Split one LOAD DATA line into fields with MySQL semantics the csv
    module cannot express: backslash escapes delimiters/specials
    (\\t \\n \\\\, an escaped delimiter stays inside the field), the NULL
    sentinel is the two-character sequence \\N standing ALONE unquoted
    (a quoted "N" or literal N is data), and an optional enclosure char
    with doubled- or backslash-escaped quotes. Returns a list of
    str-or-None."""
    out = []
    i, n = 0, len(line)
    while True:
        buf = []
        is_null = False
        if quote and i < n and line[i] == quote:
            i += 1
            while i < n:
                c = line[i]
                if c == "\\" and i + 1 < n:
                    nxt = line[i + 1]
                    buf.append(_LOAD_ESCAPES.get(nxt, nxt))
                    i += 2
                    continue
                if c == quote:
                    if i + 1 < n and line[i + 1] == quote:  # doubled
                        buf.append(quote)
                        i += 2
                        continue
                    i += 1
                    break
                buf.append(c)
                i += 1
        else:
            start = i
            while i < n and line[i] != delim:
                c = line[i]
                if c == "\\" and i + 1 < n:
                    nxt = line[i + 1]
                    if (nxt == "N" and i == start
                            and (i + 2 == n or line[i + 2] == delim)):
                        is_null = True
                        i += 2
                        continue
                    # an escaped delimiter is the delimiter, even when
                    # the delimiter char is also an escape-table key
                    buf.append(delim if nxt == delim
                               else _LOAD_ESCAPES.get(nxt, nxt))
                    i += 2
                    continue
                buf.append(c)
                i += 1
        out.append(None if is_null else "".join(buf))
        if i >= n:
            break
        i += 1  # consume the delimiter
    return out


def _ast_names(e):
    """Every EName in an expression AST (dataclass walk)."""
    import dataclasses as _dc

    out = []
    stack = [e]
    while stack:
        x = stack.pop()
        if isinstance(x, A.EName):
            out.append(x)
        if _dc.is_dataclass(x) and not isinstance(
                x, (A.SelectStmt, A.UnionStmt)):
            for f in _dc.fields(x):
                v = getattr(x, f.name)
                vs = v if isinstance(v, (list, tuple)) else [v]
                for item in vs:
                    if isinstance(item, tuple):
                        stack.extend(item)
                    else:
                        stack.append(item)
    return out


def _union_arms(u):
    """Leaf SelectStmts of a UnionStmt tree."""
    for side in (u.left, u.right):
        if isinstance(side, A.UnionStmt):
            yield from _union_arms(side)
        else:
            yield side


def _nested_into_outfile(node, top) -> bool:
    """INTO OUTFILE anywhere except the top-level SelectStmt (inside a
    UNION arm, derived table, or subquery) is a silent-no-op hazard —
    detect it so execute() can refuse loudly (MySQL errors likewise)."""
    import dataclasses as _dc

    stack = [node]
    seen = set()
    while stack:
        e = stack.pop()
        if id(e) in seen or not _dc.is_dataclass(e):
            continue
        seen.add(id(e))
        if isinstance(e, A.SelectStmt) and e is not top \
                and e.into_outfile is not None:
            return True
        for f in _dc.fields(e):
            v = getattr(e, f.name)
            vs = v if isinstance(v, (list, tuple)) else [v]
            for item in vs:
                if isinstance(item, tuple):
                    stack.extend(item)
                else:
                    stack.append(item)
    return False


def _has_eager_partial(phys) -> bool:
    """Does this physical plan contain an eager-agg partial (a HashAgg
    whose outputs carry the rule's derived 'eagg' uids)?"""
    from tidb_tpu.planner.physical import PHashAgg

    stack = [phys]
    while stack:
        p = stack.pop()
        if isinstance(p, PHashAgg) and any(
                a.uid.startswith("eagg.") for a in p.aggs):
            return True
        stack.extend(p.children)
    return False


def _dist_engaged(root) -> bool:
    """Did the dist builder actually place mesh executors (vs a silent
    full host fallback)?"""
    stack = [root]
    while stack:
        e = stack.pop()
        if type(e).__name__.startswith("Dist"):
            return True
        stack.extend(e.children)
    return False


@dataclasses.dataclass
class TxnState:
    """An open transaction (ref: session txn lifecycle over the Percolator
    model — here the marker doubles as the provisional ts and row lock)."""

    marker: int
    read_ts: int
    # id(table) -> (table, TableTxnLog): commit/rollback touch only the
    # logged rows, not whole version arrays
    logs: dict = dataclasses.field(default_factory=dict)
    # tables holding this txn's pessimistic row locks (FOR UPDATE/SHARE)
    lock_tables: dict = dataclasses.field(default_factory=dict)
    # ordered savepoints: (name, {table_id: (n_ranges, n_ended)})
    savepoints: list = dataclasses.field(default_factory=list)

    def log_for(self, table):
        from tidb_tpu.storage.table import TableTxnLog

        entry = self.logs.get(id(table))
        if entry is None:
            entry = (table, TableTxnLog())
            self.logs[id(table)] = entry
        return entry[1]

    def set_savepoint(self, name: str) -> None:
        """Snapshot per-table log positions (ref: the txn memdb's
        staging checkpoints backing SAVEPOINT). Delta-engine buffers
        compact first so every pre-savepoint write has a logged range
        a later partial rollback will never touch."""
        for table, _log in list(self.logs.values()):
            _ = table.n  # delta tables compact on this read
        snap = {tid: (len(log.ranges), len(log.ended))
                for tid, (_t, log) in self.logs.items()}
        # re-declaring a name moves it (MySQL: old one is deleted)
        self.savepoints = [(n, s) for n, s in self.savepoints if n != name]
        self.savepoints.append((name, snap))

    def rollback_to(self, name: str) -> bool:
        """Undo every write made after `name` (kept, per MySQL).
        Inserted versions after the snapshot die; provisional deletes
        after it are restored; logs truncate to the snapshot."""
        import numpy as np

        from tidb_tpu.storage.table import MAX_TS

        idx = next((i for i, (n, _s) in enumerate(self.savepoints)
                    if n == name), None)
        if idx is None:
            return False
        snap = self.savepoints[idx][1]
        for tid, (table, log) in self.logs.items():
            _ = table.n  # compact delta buffers so undo sees every row
            nr, ne = snap.get(tid, (0, 0))
            if len(log.ranges) == nr and len(log.ended) == ne:
                continue  # untouched since the savepoint: keep caches
            # restore deletes first, then kill inserted versions (a row
            # both inserted and deleted after the savepoint ends dead)
            for ids in log.ended[ne:]:
                e_ = table.end_ts[ids]
                table.end_ts[ids] = np.where(
                    e_ == self.marker, MAX_TS, e_)
            for s, e in log.ranges[nr:]:
                b = table.begin_ts[s:e]
                dead = b == self.marker
                table.end_ts[s:e][dead] = 0
                b[dead] = 0
            del log.ranges[nr:]
            del log.ended[ne:]
            log.contiguous = False  # version window no longer this txn's own
            # prune _txn_dead to the restored delete set: stale ids would
            # let REPLACE treat rows as this-txn-deleted (unique holes)
            if self.marker in table._txn_dead:
                keep = set()
                for ids in log.ended:
                    keep.update(int(i) for i in ids)
                table._txn_dead[self.marker] = [
                    i for i in table._txn_dead[self.marker] if i in keep]
            table.version += 1
        del self.savepoints[idx + 1:]
        return True

    def release_savepoint(self, name: str) -> bool:
        """Drop `name` and every later savepoint (MySQL semantics); the
        txn's changes are untouched."""
        idx = next((i for i, (n, _s) in enumerate(self.savepoints)
                    if n == name), None)
        if idx is None:
            return False
        del self.savepoints[idx:]
        return True


class Session:
    def __init__(self, catalog: Optional[Catalog] = None, db: str = "test",
                 chunk_capacity: Optional[int] = None, mesh=None):
        from tidb_tpu.storage.catalog import SessionCatalog

        # per-session overlay: TEMPORARY-table namespace over the shared
        # catalog (unwraps another session's proxy to the common base)
        self.catalog = SessionCatalog(catalog if catalog is not None
                                      else Catalog())
        self.db = db
        self._chunk_capacity = chunk_capacity  # explicit override; else sysvar
        self.sysvars = SysVarStore(self.catalog.global_vars)
        self.user_vars: dict = {}
        # authenticated account for privilege checks (ref: privilege/
        # RequestVerification); in-process sessions default to the
        # bootstrap superuser, the wire server sets this after handshake
        self.user = "root"
        from tidb_tpu.bindinfo import BindHandle

        self._bindings = BindHandle("session")
        self._prepared: dict = {}  # stmt_id -> (ast, n_params)
        self._stmt_id = 0
        self.txn: Optional[TxnState] = None
        # set while a FOR UPDATE/SHARE read runs: reads latest committed
        # instead of the txn snapshot (MySQL locking reads are current)
        self._lock_read = False
        # processlist registration (ref: server/ connection registry)
        self.conn_id = self.catalog.next_conn_id()
        self.catalog.processes[self.conn_id] = self
        import weakref

        object.__setattr__(self.catalog, "_viewer", weakref.ref(self))
        self._current_sql: Optional[str] = None
        self._current_t0: float = 0.0
        # per-statement diagnostics context (statements-summary + slow
        # log enrichment): trackers created this statement and the last
        # SELECT's plan digest
        self._stmt_trackers: list = []
        self._last_plan_digest: Optional[str] = None
        # plan-cache per-statement context: is the current statement a
        # prepared execution (picks the enable sysvar), did its plan
        # come from the cache, and the plan-acquisition wall time
        self._exec_prepared = False
        self._plan_from_cache_stmt = False
        self._stmt_plan_s = 0.0
        # (source, normalized, digest) computed by the plan-cache probe,
        # reused by _record_stmt so the hot path lexes the text once
        self._stmt_digest_memo = None
        # plan feedback (ISSUE 15): the executed (phys, root, rows)
        # parked for harvest, the worst est-vs-actual drift of the last
        # statement (slow-log column), and the effective eager-agg
        # setting the plan was acquired with (exploration may differ
        # from the sysvar)
        self._fb_capture = None
        self._fb_worst_drift = (0.0, "")
        self._fb_last_apd = None
        # resource profile of the last recorded statement (ISSUE 16):
        # (mem_max, xfer_bytes, compile_ms, spill_bytes) or None
        self._stmt_profile = None
        # prepare-time (sql, norm, digest, StmtInfo) for the current
        # prepared execution: the probe skips lexing + AST analysis
        self._ps_ctx = None
        # deferred parameter binding: on the prepared SELECT hot path
        # the template AST flows through unchanged (a cache hit never
        # reads it); any path that actually PLANS materializes the
        # bound AST through _materialize_stmt first
        self._ps_params = None
        self._ps_materialized = None
        self._killed = False       # KILL <id>: connection is dead
        self._kill_query = False   # KILL QUERY <id>: one-shot cancel
        # serving-tier seams (tidb_tpu/serving): a coalesced batch
        # member executes through _execute_timed with the REAL executor
        # replaced by a runner returning the pre-demuxed result, so
        # every per-statement semantic (warnings reset, kill/deadline,
        # tracing, summary, slow log, plugin hooks) stays exact
        self._stmt_runner = None
        # parent for per-statement MemTrackers (the scheduler's
        # session-level tracker, itself a child of the server tracker)
        self._mem_parent = None
        # scheduler queue wait of the statement about to execute
        # (seconds); _execute_timed consumes it into a sched.queue span
        self._sched_queue_s = 0.0
        # statement deadline (monotonic seconds) armed per statement
        # from max_execution_time; None = unbounded
        self._stmt_deadline: Optional[float] = None
        # external cancellation hooks: a DCN worker serving an RPC arms
        # these so a coordinator-sent cancel or the RPC's shipped
        # deadline aborts the local execution at its next chunk boundary
        self._ext_cancel = None            # callable -> truthy to cancel
        self._ext_deadline: Optional[float] = None  # monotonic seconds
        # diagnostics area for SHOW WARNINGS (cleared per statement)
        self._warnings: list = []
        self.mesh = mesh
        self._shard_cache = None
        if mesh is not None:
            from tidb_tpu.parallel.executor import ShardCache

            self._shard_cache = ShardCache(mesh)

    @property
    def chunk_capacity(self) -> int:
        if self._chunk_capacity is not None:
            return self._chunk_capacity
        return int(self.sysvars.get("tidb_max_chunk_size"))

    # -- transactions ------------------------------------------------------

    def _begin(self) -> None:
        if self.txn is not None:
            self._commit()  # MySQL: BEGIN implicitly commits the open txn
        marker, read_ts = self.catalog.begin_txn()  # registers for GC safepoint
        self.txn = TxnState(marker=marker, read_ts=read_ts)

    def _ensure_txn(self):
        """(txn, implicit): implicit txns commit at statement end."""
        if self.txn is not None:
            return self.txn, False
        self._begin()
        if not self.sysvars.get("autocommit"):
            return self.txn, False
        return self.txn, True

    def _commit(self) -> None:
        txn, self.txn = self.txn, None
        if txn is None:
            return
        with self.catalog.lock:  # single-writer commit point
            self._commit_locked(txn)

    def _commit_locked(self, txn) -> None:
        from tidb_tpu.storage.txn2pc import TwoPhaseCommitter

        committer = TwoPhaseCommitter(
            self.catalog, txn.marker, list(txn.logs.values()))
        try:
            committer.execute()
        except Exception:
            # UNDECIDED failure (prewrite error / crash before the commit
            # point): abort so the row locks can't leak — without a status
            # record resolve_locks could never clean them up. A DECIDED
            # txn (status recorded) is committed; leave its residue for
            # resolve_locks, never roll it back.
            if self.catalog.txn_status(txn.marker) is None:
                committer.rollback()
            raise
        finally:
            # the txn is decided either way: pessimistic locks release
            for t in txn.lock_tables.values():
                t.release_locks(txn.marker)
        from tidb_tpu.utils.metrics import TXN_TOTAL

        TXN_TOTAL.inc(outcome="commit")
        if txn.logs and self.sysvars.get("tidb_gc_enable"):
            self.catalog.auto_gc([t for t, _ in txn.logs.values()])
        if txn.logs and self.sysvars.get("tidb_enable_auto_analyze"):
            self.catalog.maybe_auto_analyze(
                [t for t, _ in txn.logs.values()],
                ratio=float(self.sysvars.get("tidb_auto_analyze_ratio")))

    def _rollback(self) -> None:
        txn, self.txn = self.txn, None
        if txn is None:
            return
        with self.catalog.lock:
            self._rollback_locked(txn)

    def _rollback_locked(self, txn) -> None:
        from tidb_tpu.storage.txn2pc import TwoPhaseCommitter

        for t in txn.lock_tables.values():
            t.release_locks(txn.marker)
        TwoPhaseCommitter(
            self.catalog, txn.marker, list(txn.logs.values())).rollback()
        from tidb_tpu.utils.metrics import TXN_TOTAL

        TXN_TOTAL.inc(outcome="rollback")
        if txn.logs and self.sysvars.get("tidb_gc_enable"):
            self.catalog.auto_gc([t for t, _ in txn.logs.values()])

    def _run_dml(self, fn):
        """Run a write inside the session txn; implicit txns commit (or
        roll back on error) at statement end. A write conflict against a
        marker whose txn already DECIDED (crashed mid-2PC) resolves the
        stale locks and retries once — the Backoffer/resolve-lock flow.

        The mutation + implicit commit run under the catalog's writer
        lock: the storage layout is single-writer by design (ref: one
        leaseholder per region), and the wire server executes sessions
        on concurrent threads. Readers stay lock-free — MVCC timestamps
        make committed rows stable under concurrent appends."""
        txn, implicit = self._ensure_txn()
        with self.catalog.lock:
            try:
                try:
                    fn(txn)
                except WriteConflictError:
                    if self.catalog.resolve_locks():
                        fn(txn)  # stale locks cleared; one retry
                    else:
                        raise
            except Exception:
                if implicit:
                    txn2, self.txn = self.txn, None
                    if txn2 is not None:
                        self._rollback_locked(txn2)
                raise
            if implicit:
                txn2, self.txn = self.txn, None
                if txn2 is not None:
                    self._commit_locked(txn2)
        return None

    # -- execution ---------------------------------------------------------

    def _build_root(self, phys):
        # an installed executor plugin named by tidb_executor_plugin
        # takes over executor construction (the plugin/ extension point
        # the north star describes for alternate backends)
        plug_name = str(self.sysvars.get("tidb_executor_plugin"))
        if plug_name:
            build = self.catalog.plugins.executor_builder(plug_name)
            if build is not None:
                return build(phys, self)
        if self.txn is not None:
            # snapshot reads need per-row visibility masks; the sharded
            # device tables hold committed-latest — use the local executors
            return build_executor(phys)
        if self._shard_cache is not None and self.sysvars.get("tidb_enable_tpu_exec"):
            from tidb_tpu.parallel.executor import build_dist_executor

            return build_dist_executor(phys, self._shard_cache,
                                       full=self._device_engine_auto())
        return build_executor(phys)

    def _device_engine_auto(self) -> bool:
        """Cost-based engine routing (ref: the planner's cop-task vs
        root-task choice): device fragments pay off on accelerators and
        on real (multi-device) meshes; a single-CPU backend runs joins
        and generic aggregation faster on the numpy host engine."""
        mode = str(self.sysvars.get("tidb_device_engine_mode"))
        if mode == "force":
            return True
        if mode == "off":
            return False
        if self.mesh is not None:
            devs = self.mesh.devices.flat
            return devs[0].platform != "cpu" or len(devs) > 1
        import jax

        return jax.default_backend() != "cpu"

    # ------------------------------------------------------------------

    def execute(self, sql: str) -> Optional[ResultSet]:
        """Execute one or more statements; returns the last result set."""
        import time as _time

        from tidb_tpu.utils import metrics as M

        t0 = _time.perf_counter()
        stmts = parse(sql)
        M.PARSE_SECONDS.observe(_time.perf_counter() - t0)
        result = None
        for stmt in stmts:
            result = self._execute_timed(stmt, sql)
        return result

    def _execute_timed(self, stmt, sql: str) -> Optional[ResultSet]:
        """Metrics + slow-query log + optional jax.profiler around one
        statement (ref: the server-layer duration histograms and the
        slow-query log with per-phase durations)."""
        import contextlib
        import time as _time

        from tidb_tpu.utils import metrics as M

        # a DECIDED txn whose commit crashed mid-secondaries leaves rows
        # invisible behind marker timestamps; readers resolve such locks
        # at the statement boundary (the reference's reader-side
        # resolve-lock flow) — commits run under the catalog statement
        # lock, so a pending status here always means a crashed txn.
        # Lives here (not execute()) so prepared-statement execution
        # gets the same guarantee.
        if self.catalog.has_stale_txns():
            self.catalog.resolve_locks()
        if self._killed:
            from tidb_tpu.errors import QueryKilledError

            raise QueryKilledError("connection was killed")
        self._kill_query = False  # a prior KILL QUERY cancels only its query
        # arm the statement deadline: max_execution_time is a per-
        # statement budget in ms (0 = unbounded). Monotonic so wall-
        # clock jumps can't fire (or defuse) it.
        met = int(self.sysvars.get("max_execution_time"))
        self._stmt_deadline = (
            _time.monotonic() + met / 1e3) if met > 0 else None
        if not (isinstance(stmt, A.ShowStmt)
                and getattr(stmt, "kind", "") == "warnings"):
            self._warnings.clear()  # MySQL: each statement resets the area
        from tidb_tpu.utils import dispatch as _dsp

        self._current_sql = sql
        self._current_t0 = _time.time()
        stype = type(stmt).__name__.removesuffix("Stmt").lower()
        self.catalog.plugins.statement_begin(self, sql, stype)
        prof_dir = str(self.sysvars.get("tidb_profile_dir"))
        ctx = contextlib.nullcontext()
        if prof_dir:
            import jax

            ctx = jax.profiler.trace(prof_dir)
        self._stmt_trackers = []
        self._last_plan_digest = None
        self._plan_from_cache_stmt = False
        self._stmt_plan_s = 0.0
        self._stmt_digest_memo = None
        # plan feedback (ISSUE 15): _run_select parks (phys, root, rows)
        # here; the success path below harvests est-vs-actual truth from
        # it and MUST drop the reference at statement end — a parked
        # executor tree pins device arrays
        self._fb_capture = None
        self._fb_worst_drift = (0.0, "")
        self._fb_last_apd = None
        c0 = _dsp.compile_count()
        # per-statement resource profile (ISSUE 16): thread-local
        # baselines for transfer bytes / compile seconds / spill bytes —
        # all host-side accounting at existing choke points, zero new
        # device syncs (PR 14's contract)
        try:
            prof0 = (_dsp.xfer_bytes(), _dsp.compile_seconds(),
                     _dsp.spill_bytes())
        except Exception:  # noqa: BLE001 — diagnostics never fail a stmt
            prof0 = (0, 0.0, 0)
        # always-on tracing (utils/tracing.py): every statement RECORDS
        # a span tree; tail rules / head sampling decide at the end
        # whether it is kept. A statement arriving with a trace already
        # installed (a DCN worker serving a traced RPC, Cluster.query
        # inside a statement) nests instead of owning.
        from tidb_tpu.utils import tracing

        try:
            digest_now = self._stmt_digest(stmt, sql)[1]
        except Exception:  # noqa: BLE001 — diagnostics never fail a stmt
            digest_now = ""
        tr = tracing.current()
        owns_trace = tr is None
        if owns_trace:
            rate = float(self.sysvars.get("tidb_trace_sample_rate"))
            tr = tracing.Trace(tracing.make_trace_id(digest_now),
                               sampled=tracing.head_sampled(rate))
            tracing.push(tr)
        stmt_span = tracing.begin(f"stmt.{stype}")
        q_s, self._sched_queue_s = self._sched_queue_s, 0.0
        if q_s > 0 and tr is not None and stmt_span is not None:
            # the scheduler queue wait happened BEFORE this trace
            # existed; anchor the span at the trace start so offsets
            # stay non-negative and the wait is still visible
            qs = tr.add_complete("sched.queue", tr.t0_perf, q_s,
                                 parent_id=stmt_span.span_id)
            qs.notes.append(f"queued {int(q_s * 1e6)}us before execution")
        d0 = _dsp.count()
        f0 = _dsp.by_site().get("fragment", 0)
        from tidb_tpu.columnar.store import compact_counts as _cmp_counts
        from tidb_tpu.columnar.store import scan_counts as _seg_counts

        seg0 = _seg_counts()
        cw0 = _cmp_counts()
        # runtime invariant sanitizer (ISSUE 12): debug-mode statement
        # scope — pin/tracker balances, host-sync budget, lock-order
        # witness — checked at statement end; fatal findings raise a
        # typed SanitizerError on the success path
        _san_scope = None
        _san_findings: list = []
        if bool(self.sysvars.get("tidb_tpu_sanitize")):
            from tidb_tpu.analysis import sanitizer as _san

            _san.enable()
            _san_scope = _san.statement_begin(sync_budget=int(
                self.sysvars.get("tidb_tpu_sanitize_sync_budget")))
        # CLUSTER BY ordered compaction (ISSUE 18): due permutes run at
        # statement boundaries ONLY — never from a reader's plan_scan —
        # and only while the catalog's reader registry is quiescent.
        # This statement then registers as a lock-free reader so no
        # other thread's boundary can move rows out from under it.
        self.catalog.run_pending_reclusters()
        self.catalog.reader_enter()
        t0 = _time.perf_counter()
        try:
            with ctx:
                runner = self._stmt_runner
                result = (self._execute_stmt(stmt) if runner is None
                          else runner(stmt))
        except Exception as exc:
            dur = _time.perf_counter() - t0
            M.QUERY_TOTAL.inc(type=stype, status="error")
            from tidb_tpu.errors import QueryTimeoutError

            if isinstance(exc, QueryTimeoutError):
                M.DEADLINE_EXCEEDED_TOTAL.inc()
            detail = self._record_stmt(stmt, sql, stype, dur, d0, f0, None,
                                       seg0=seg0, prof0=prof0, cw0=cw0,
                                       error=True)
            self._slo_observe(dur)
            tracing.annotate(f"error:{type(exc).__name__}: {exc}")
            trace_id = self._finish_trace(tr, stmt_span, owns_trace, dur,
                                          error=exc)
            # statements that die mid-chunk-loop (deadline/kill/error)
            # used to be invisible here — they are exactly the ones
            # whose traces tail-sampling keeps, so log them with an
            # error disposition (same threshold rule as successes)
            self._maybe_log_slow(sql, dur, detail, trace_id,
                                 disposition=f"error:{type(exc).__name__}")
            self.catalog.plugins.statement_end(self, sql, stype, dur, exc)
            raise
        finally:
            self.catalog.reader_exit()
            # a permute the statement's own plan_scan queued runs now,
            # at ITS end — scans closed, cursors (if any) still counted
            self.catalog.run_pending_reclusters()
            self._current_sql = None
            # disarm: a later Cluster.query(session=...) poll must not
            # see this statement's (possibly long-expired) deadline
            self._stmt_deadline = None
            # serving tier: return residual (never-released) operator
            # consumption to the session/server trackers — an executor
            # tree freed wholesale must not leak accounting forever
            if self._mem_parent is not None:
                for t in self._stmt_trackers:
                    t.detach()
            if _san_scope is not None:
                from tidb_tpu.analysis import sanitizer as _san

                # after the detach loop so residual witnesses attribute
                # to this statement; fatal findings raise on the
                # success path below (never mask an in-flight error)
                _san_findings = _san.statement_end(_san_scope)
            # BaseException safety net (KeyboardInterrupt & co bypass
            # the except): a trace must never leak onto the thread. The
            # normal paths pop via _finish_trace before this runs.
            import sys as _sys

            if _sys.exc_info()[0] is not None:
                # failed statements don't harvest: drop the parked
                # executor tree NOW (it pins device arrays). The
                # success path consumes it in _fb_record below.
                self._fb_capture = None
            if owns_trace and _sys.exc_info()[0] is not None \
                    and tracing.current() is tr:
                tracing.pop()
        dur = _time.perf_counter() - t0
        M.QUERY_TOTAL.inc(type=stype, status="ok")
        M.QUERY_DURATION.observe(dur, type=stype)
        # plan feedback (ISSUE 15): fold this execution's est-vs-actual
        # truth into the per-digest store BEFORE the summary/slow-log/
        # trace surfaces run, so they all see the drift it computed
        self._fb_record(dur, result, _dsp.compile_count() - c0)
        detail = self._record_stmt(stmt, sql, stype, dur, d0, f0, result,
                                   seg0=seg0, prof0=prof0, cw0=cw0)
        self._slo_observe(dur)
        trace_id = self._finish_trace(tr, stmt_span, owns_trace, dur)
        self._maybe_log_slow(sql, dur, detail, trace_id)
        # plugin hooks run LAST (mirroring the error path): an audit
        # plugin that raises must not be able to skip trace
        # finalization — a never-popped trace would swallow every later
        # statement on this thread into a dead span tree
        self.catalog.plugins.statement_end(self, sql, stype, dur, None)
        fatal = [f for f in _san_findings if f.fatal]
        if fatal:
            from tidb_tpu.errors import SanitizerError

            raise SanitizerError(
                "sanitizer: engine invariant broken during this "
                "statement: " + "; ".join(f.render() for f in fatal[:4]))
        return result

    def _fb_enabled(self) -> bool:
        return bool(self.sysvars.get("tidb_tpu_plan_feedback"))

    def _fb_record(self, dur: float, result, recompiles: int) -> None:
        """Plan feedback capture (ISSUE 15): harvest the executed tree
        parked by _run_select and fold the observation into the process
        store. Runs on the SUCCESS path only (a partial execution's
        actuals are not the statement's truth) and, like every other
        diagnostic here, can never fail the statement. The feedback may
        reshape future PLANS of this digest; when a new significant
        cardinality hint landed, the digest's plan-cache entries are
        evicted so the next planning actually consults it."""
        cap, self._fb_capture = self._fb_capture, None
        self._fb_worst_drift = (0.0, "")
        if cap is None or not self._fb_enabled():
            return
        try:
            from tidb_tpu.planner import feedback as _fb
            from tidb_tpu.utils import metrics as M
            from tidb_tpu.utils import tracing

            phys, root, n_rows = cap
            memo = self._stmt_digest_memo
            digest = memo[2] if memo is not None else ""
            if not digest:
                return
            warm = self._plan_from_cache_stmt and recompiles == 0
            obs = _fb.harvest(phys, root, n_rows, dur, warm)
            apd = self._fb_last_apd if self._fb_last_apd is not None \
                else self._agg_push_down()
            new_hint = _fb.STORE.record(
                digest, self._last_plan_digest or "", apd, obs,
                capacity=int(
                    self.sysvars.get("tidb_tpu_plan_feedback_capacity")))
            if obs.worst_drift > 1.0:
                self._fb_worst_drift = (obs.worst_drift_ratio,
                                        obs.worst_drift_op)
                tracing.annotate(
                    f"worst_drift:{obs.worst_drift_op} "
                    f"{obs.worst_drift_ratio:.2f}x")
            if obs.worst_drift > 0:
                # only statements with at least one known actual
                # observe: otherwise the 1.0 bucket would conflate
                # "every estimate exact" with "no data"
                M.PLAN_EST_DRIFT.observe(_fb.drift_factor(obs))
            if new_hint:
                pc = getattr(self.catalog, "plan_cache", None)
                if pc is not None:
                    pc.invalidate_digest(digest)
        except Exception:  # noqa: BLE001 — diagnostics never fail a stmt
            pass

    def _slo_observe(self, dur: float) -> None:
        """SLO plane (ISSUE 16): fold this statement's wall time into the
        per-digest latency window. Success AND error paths — what the
        user waited is what the SLO measures. Diagnostics never fail a
        statement."""
        try:
            memo = self._stmt_digest_memo
            if memo is None or not memo[2]:
                return
            from tidb_tpu.serving import slo as _slo

            _slo.STORE.observe(
                memo[2], memo[1], dur,
                target_ms=int(self.sysvars.get("tidb_tpu_slo_target_ms")),
                capacity=int(self.sysvars.get("tidb_tpu_slo_capacity")))
        except Exception:  # noqa: BLE001 — diagnostics never fail a stmt
            pass

    def _maybe_log_slow(self, sql: str, dur: float, detail, trace_id: str,
                        disposition: str = "") -> None:
        """One slow-log decision for both the success and the error path
        of _execute_timed. Threshold in ms; 0 logs every statement
        (long_query_time=0)."""
        from tidb_tpu.utils import metrics as M

        threshold = int(self.sysvars.get("tidb_slow_log_threshold"))
        if dur * 1e3 < threshold:
            return
        M.SLOW_QUERY_TOTAL.inc()
        drift, drift_op = self._fb_worst_drift
        self.catalog.log_slow_query(
            self.db, sql, dur, digest=detail[0],
            plan_digest=self._last_plan_digest or "",
            max_mem=detail[1], dispatches=detail[2],
            segs_scanned=detail[3], segs_pruned=detail[4],
            trace_id=trace_id, disposition=disposition,
            worst_drift=drift, worst_drift_op=drift_op,
            xfer_bytes=detail[5], compile_ms=detail[6],
            spill_bytes=detail[7], compaction_wait_ms=detail[8])

    def _stmt_digest(self, stmt, sql: str):
        """(normalized_text, digest) for this statement, memoized per
        source text — computed at statement START so the trace_id can
        carry it; the plan-cache probe and _record_stmt reuse the memo,
        keeping the total at one lex per statement."""
        from tidb_tpu.bindinfo import normalize_sql, sql_digest

        src = getattr(stmt, "_source", None) or sql
        memo = self._stmt_digest_memo
        if memo is not None and memo[0] == src:
            return memo[1], memo[2]
        ps = self._ps_ctx
        if ps is not None and ps[0] == src:
            # prepared execution: prepare-time analysis already lexed —
            # the hot path must stay lex/walk-free (PR 2's contract)
            self._stmt_digest_memo = (src, ps[1], ps[2])
            return ps[1], ps[2]
        if len(src) > 16384:
            # bound the lex: per-shape dedup matters for OLTP-sized
            # statements, not megabyte bulk loads — those digest their
            # raw text and keep a prefix
            norm = src[:2048]
            digest = sql_digest(src)
        else:
            norm = normalize_sql(src)
            digest = sql_digest(norm)
        self._stmt_digest_memo = (src, norm, digest)
        return norm, digest

    def _finish_trace(self, tr, stmt_span, owns: bool, dur_s: float,
                      error=None) -> str:
        """Close the statement span; when this statement OWNS the trace,
        apply the tail rules (slow / error; retry-failover keeps were
        set where they happened), pop it off the thread, and store it if
        kept. Returns the trace_id for the slow-log row."""
        from tidb_tpu.utils import tracing

        try:
            tracing.finish(stmt_span)
            if not owns or tr is None:
                return tr.trace_id if tr is not None else ""
            return tracing.apply_tail_rules(
                tr, dur_s,
                int(self.sysvars.get("tidb_slow_log_threshold")),
                error=error,
                capacity=int(self.sysvars.get("tidb_trace_store_capacity")))
        except Exception:  # noqa: BLE001 — diagnostics never fail a stmt
            return ""

    def _record_stmt(self, stmt, sql: str, stype: str, dur: float,
                     d0: int, f0: int, result, seg0=(0, 0),
                     prof0=(0, 0.0, 0), cw0=(0.0, 0), error: bool = False):
        """Fold one execution into the per-digest statements summary;
        returns (digest, max_mem, dispatches, segs_scanned, segs_pruned,
        xfer_bytes, compile_ms, spill_bytes, compaction_wait_ms) for the
        slow-query log and the EXPLAIN ANALYZE profile line. Digests
        come from the bindinfo normalizer, so parameterized variants of
        one statement aggregate under one entry."""
        from tidb_tpu.utils import dispatch as _dsp

        self._stmt_profile = None
        try:
            # memoized: the statement-start trace_id computation (or the
            # plan-cache probe) already lexed this source
            norm, digest = self._stmt_digest(stmt, sql)
            max_mem = max((t.max_consumed for t in self._stmt_trackers),
                          default=0)
            if self._mem_parent is not None:
                for t in self._stmt_trackers:
                    t.detach()  # idempotent; the finally path re-runs it
            self._stmt_trackers = []  # don't pin operator state while idle
            dispatches = _dsp.count() - d0
            fragments = _dsp.by_site().get("fragment", 0) - f0
            from tidb_tpu.columnar.store import scan_counts as _seg_counts

            seg1 = _seg_counts()
            segs_scanned = seg1[0] - seg0[0]
            segs_pruned = seg1[1] - seg0[1]
            # resource profile deltas (ISSUE 16): host-side counters
            # moved at the existing staging/fetch/spill choke points
            xfer = _dsp.xfer_bytes() - prof0[0]
            compile_ms = (_dsp.compile_seconds() - prof0[1]) * 1e3
            spill = _dsp.spill_bytes() - prof0[2]
            # inline delta->segment rebuild time this statement paid on
            # its own scan path (ISSUE 17) — attributable write-induced
            # stall instead of anonymous scan time
            from tidb_tpu.columnar.store import (
                compact_counts as _cmp_counts,
            )

            compact_ms = (_cmp_counts()[0] - cw0[0]) * 1e3
            self._stmt_profile = (max_mem, xfer, compile_ms, spill)
            if xfer or spill or compile_ms >= 1.0:
                from tidb_tpu.utils import tracing as _tracing

                # span annotation on kept traces: the statement's
                # resource footprint travels with its trace
                _tracing.annotate(
                    f"profile: mem_max={max_mem} xfer_bytes={xfer} "
                    f"compile_ms={compile_ms:.1f} spill_bytes={spill}")
            drift, drift_op = self._fb_worst_drift
            self.catalog.stmt_summary.record(
                digest, norm, stype, self._last_plan_digest or "", dur,
                max_mem=max_mem,
                rows_sent=len(result.rows) if result is not None else 0,
                dispatches=dispatches, fragments=fragments, error=error,
                plan_from_cache=self._plan_from_cache_stmt,
                plan_latency_s=self._stmt_plan_s,
                worst_drift=drift, worst_drift_op=drift_op,
                xfer_bytes=xfer, compile_ms=compile_ms, spill_bytes=spill,
                max_stmt_count=int(
                    self.sysvars.get("tidb_stmt_summary_max_stmt_count")))
            return (digest, max_mem, dispatches, segs_scanned, segs_pruned,
                    xfer, compile_ms, spill, compact_ms)
        except Exception:  # noqa: BLE001 — diagnostics must never fail
            # (or mask) the statement; an unrecordable statement is
            # simply absent from the summary
            return "", 0, 0, 0, 0, 0, 0.0, 0, 0.0

    def query(self, sql: str) -> List[tuple]:
        rs = self.execute(sql)
        if rs is None:
            return []
        return rs.rows

    def cancel_reason(self):
        """Why the in-flight statement should stop, or None. Returns a
        TYPED exception instance (the executor raises it verbatim) so a
        KILL and a deadline expiry surface as different MySQL errors.
        Polled at every chunk boundary and by the DCN coordinator's
        dispatch/drain loops."""
        import time as _time

        from tidb_tpu.errors import QueryKilledError, QueryTimeoutError

        if self._killed:
            return QueryKilledError("connection was killed")
        if self._kill_query:
            return QueryKilledError("Query execution was interrupted (KILL)")
        now = None
        for dl in (self._stmt_deadline, self._ext_deadline):
            if dl is not None:
                now = _time.monotonic() if now is None else now
                if now > dl:
                    return QueryTimeoutError(
                        "Query execution was interrupted, maximum "
                        "statement execution time exceeded")
        ext = self._ext_cancel
        if ext is not None and ext():
            return QueryKilledError("Query execution was interrupted (KILL)")
        return None

    # ------------------------------------------------------------------

    def _plan_capacity(self, plan) -> int:
        """Chunk capacity sized to the plan, clamped to the configured
        maximum. A fixed 1M-row capacity taxes every operator of a small
        query with large-buffer allocation (TPC-DS Q95 at SF0.5 spent
        2x its sqlite runtime on it); sizing to the largest base scan
        keeps one-chunk execution for everything the plan can produce
        linearly, while oversized intermediates simply stream in chunks
        (the Volcano loop the host operators already run)."""
        cap = self.chunk_capacity
        if plan is None:
            return cap
        biggest = 0
        stack = [plan]
        while stack:
            node = stack.pop()
            t = getattr(node, "table", None)
            if t is not None:
                biggest = max(biggest, getattr(t, "n", 0))
            stack.extend(getattr(node, "children", ()))
        if biggest <= 0:
            return cap
        want = max(1 << 14, 1 << (biggest + (biggest >> 2)).bit_length())
        return min(cap, want)

    def _exec_ctx(self, hints=(), plan=None) -> ExecContext:
        from tidb_tpu.utils.memory import MemTracker

        quota = int(self.sysvars.get("tidb_mem_quota_query"))
        for hname, hargs in hints or ():
            if hname == "memory_quota" and hargs:
                q = _parse_quota(hargs[0])  # MEMORY_QUOTA(bytes | N MB | N GB)
                if q is not None:
                    quota = q  # unparseable hints are ignored, like TiDB warns
        tracker = MemTracker(
            "query",
            budget=quota,
            # serving tier: chain into the scheduler's session/server
            # trackers so per-session and server-wide quotas see this
            # statement; spill decisions stay anchored HERE (spill_root)
            parent=self._mem_parent,
            spill_enabled=bool(self.sysvars.get("tidb_enable_tmp_storage_on_oom")),
            spill_root=True,
        )
        # the statement may build several contexts (shadow rowid scans,
        # subplans): the summary reports the max over all of them
        self._stmt_trackers.append(tracker)
        for old in self._stmt_trackers[:-64]:
            old.detach()  # evicted trackers must not pin parent bytes
        del self._stmt_trackers[:-64]  # bound pathological statements
        ctx = ExecContext(
            chunk_capacity=self._plan_capacity(plan),
            group_concat_max_len=int(
                self.sysvars.get("group_concat_max_len")),
            mem_tracker=tracker,
            read_ts=(None if self._lock_read else
                     self.txn.read_ts if self.txn is not None else None),
            txn_marker=self.txn.marker if self.txn is not None else 0,
            device_agg=bool(self.sysvars.get("tidb_enable_tpu_exec"))
            and self._device_engine_auto(),
            device_cache_bytes=int(self.sysvars.get("tidb_device_cache_bytes")),
            join_device_build=bool(
                self.sysvars.get("tidb_tpu_join_device_build")),
            join_tiles=int(
                self.sysvars.get("tidb_tpu_join_tiles_per_dispatch")),
            join_probe_mode=self._wire_probe_mode(),
            broadcast_rows_limit=int(
                self.sysvars.get("tidb_broadcast_join_threshold_count")),
            columnar_enable=bool(
                self.sysvars.get("tidb_tpu_columnar_enable")),
            segment_rows=int(self.sysvars.get("tidb_tpu_segment_rows")),
            segment_delta_rows=int(
                self.sysvars.get("tidb_tpu_segment_delta_rows")),
            columnar_spill_dir=str(
                self.sysvars.get("tidb_tpu_columnar_spill_dir")),
            compaction_enable=bool(self.sysvars.get("tidb_tpu_compaction")),
            pipeline_fuse=bool(self.sysvars.get("tidb_tpu_pipeline_fuse")),
            prefetch_depth=int(
                self.sysvars.get("tidb_tpu_pipeline_prefetch_depth")),
            device_buffer_cache_bytes=int(
                self.sysvars.get("tidb_tpu_device_buffer_cache_bytes")),
            stage_encoded=bool(self.sysvars.get("tidb_tpu_stage_encoded")),
            cancel_check=self.cancel_reason,
        )
        if self._fb_enabled():
            # plan feedback consumer (c): a digest whose fused probes
            # overflowed their in-program tiles gets its tile batch
            # sized to the observed worst need — the overflow remainder
            # then expands in one batched dispatch instead of several
            memo = self._stmt_digest_memo
            if memo is not None and memo[2]:
                from tidb_tpu.planner import feedback as _fb

                need = _fb.STORE.tile_hint(memo[2])
                if need > ctx.join_tiles:
                    ctx.join_tiles = need
                # fused top-k consumer (ISSUE 18): a digest whose
                # ORDER BY+LIMIT k overflowed the device capacity gate
                # starts classic on its SECOND execution instead of
                # re-failing the gate at every open()
                if _fb.STORE.topn_overflow(memo[2]):
                    ctx.fused_topn = False
        return ctx

    def _wire_probe_mode(self) -> str:
        """Effective tidb_tpu_join_probe_mode. Carried per-statement
        through ExecContext for BOTH tiers: the single-chip join reads
        it at stage time, and the fragment tier threads it into
        build_fn as a trace-time static (part of the fragment cache
        key), so concurrent sessions with divergent session values
        never race a process global. The PR 10 wiring wrote
        ops/hash_probe.set_mode here every statement — the documented
        set_mode race; the global now only seeds offline tools and
        bare fragments, and the sanitizer's shared-mutable-global
        witness flags any statement-time write."""
        return str(self.sysvars.get("tidb_tpu_join_probe_mode"))

    def _agg_push_down(self) -> bool:
        """Effective eager-aggregation switch. Device-engine sessions
        also push: the fragment tier runs scan-rooted generic partials
        per shard (no cross-shard merge needed — the upper aggregate
        re-sums); shapes it can't take re-plan without the rewrite in
        _run_select rather than falling off the mesh."""
        return bool(self.sysvars.get("tidb_opt_agg_push_down"))

    def _execute_subplan(self, logical) -> List[tuple]:
        """Planner callback: run a bound logical subplan to completion."""
        logical = optimize_logical(
            logical,
            cascades=bool(self.sysvars.get("tidb_enable_cascades_planner")),
            agg_push_down=self._agg_push_down())
        phys = lower(logical)
        # plan-time subqueries execute before the statement-level check
        # and fold into literals, so they must be checked here or a
        # scalar subquery leaks unprivileged tables
        self._check_plan_privs(phys)
        # the subplan earns the same engine routing as a top-level query
        # (a materialized CTE body can be a heavy join)
        root = self._build_root(phys)
        n_vis = phys.n_visible if isinstance(phys, PProjection) else None
        rs = run_plan(root, self._exec_ctx(plan=phys), n_visible=n_vis)
        return rs.rows

    def _dist_expected(self) -> bool:
        """Would this session route eligible plans to the mesh tier?
        Mirrors _build_root's full routing: an executor plugin takes
        over BEFORE the dist branch, so plugin sessions never expect
        Dist executors (and must not re-plan away eager aggregation)."""
        if str(self.sysvars.get("tidb_executor_plugin")):
            return False
        return (self.txn is None and self._shard_cache is not None
                and bool(self.sysvars.get("tidb_enable_tpu_exec"))
                and self._device_engine_auto())

    def _n_parts(self) -> int:
        if self.mesh is None:
            return 1
        return int(np.prod(list(self.mesh.shape.values())))

    def _plan_select(self, stmt, agg_push_down=None, execute_subplan=None):
        import time as _time

        from tidb_tpu.utils import metrics as M

        from tidb_tpu.planner import feedback as _fb

        t0 = _time.perf_counter()
        # plan feedback (ISSUE 15): install the recorded-cardinality
        # hints for this one planning call — the estimators consult
        # them thread-locally, so EXPLAIN and TRACE show the same
        # feedback-shaped plan an execution would get
        with _fb.planning_hints(self._fb_enabled()):
            phys = plan_statement(
                stmt, self.catalog, db=self.db,
                execute_subplan=execute_subplan or self._execute_subplan,
                cascades=bool(
                    self.sysvars.get("tidb_enable_cascades_planner")),
                n_parts=self._n_parts(),
                session_info={
                    "user": self.user,
                    "conn_id": getattr(self, "conn_id", 0),
                    # columnar knobs for plan-time materialization
                    # (CTE reuse segments its result iff enabled)
                    "columnar_enable": bool(
                        self.sysvars.get("tidb_tpu_columnar_enable")),
                    "segment_rows": int(
                        self.sysvars.get("tidb_tpu_segment_rows"))},
                agg_push_down=(self._agg_push_down()
                               if agg_push_down is None
                               else agg_push_down),
            )
        M.PLAN_SECONDS.observe(_time.perf_counter() - t0)
        return phys

    def _acquire_plan(self, stmt, agg_push_down=None):
        """Physical plan for a SELECT/UNION, through the digest-keyed
        plan cache when the statement is eligible (ref: planner/core
        plan_cache*). Sets @@last_plan_from_cache and accumulates the
        acquisition wall time for the statements summary.

        Plan feedback (ISSUE 15): when the session WOULD push eager
        aggregation (sysvar on, no explicit override from the dist
        re-plan), the digest's measured push-vs-no-push decision can
        select the no-push variant instead — it caches under its own
        key (eff_apd is part of the plan-cache key), so the flip is a
        clean second entry, not a cache poison. A user pin of
        tidb_opt_agg_push_down=0 is authoritative and never consulted."""
        import time as _time

        t0 = _time.perf_counter()
        try:
            if (agg_push_down is None and self._fb_enabled()
                    and self._agg_push_down()):
                src = getattr(stmt, "_source", None)
                if src and len(src) <= 16384:
                    from tidb_tpu.planner import feedback as _fb

                    try:
                        digest = self._stmt_digest(stmt, src)[1]
                        if _fb.STORE.apd_decision(digest) is False:
                            agg_push_down = False
                    except Exception:  # noqa: BLE001 — feedback is
                        pass           # advisory, never load-bearing
            self._fb_last_apd = (self._agg_push_down()
                                 if agg_push_down is None
                                 else bool(agg_push_down))
            return self._acquire_plan_inner(stmt, agg_push_down)
        finally:
            self._stmt_plan_s += _time.perf_counter() - t0

    def _materialize_stmt(self, stmt):
        """Bind deferred prepared parameters into `stmt` (identity memo:
        the dist re-plan branch may plan the same statement twice, and
        _apply_binding may hand over a hinted COPY of the template)."""
        params = self._ps_params
        if params is None:
            return stmt
        memo = self._ps_materialized
        if memo is not None and memo[0] is stmt:
            return memo[1]
        src = getattr(stmt, "_source", None)
        out = _sub_params(stmt, params)
        if src is not None:
            out._source = src
        self._ps_materialized = (stmt, out)
        return out

    def _acquire_plan_inner(self, stmt, agg_push_down):
        from tidb_tpu.planner import plancache as _pc

        self._last_plan_digest = None  # _run_select hashes the fresh
        # plan unless a cache hit installs the entry's memoized digest
        self.sysvars.set("last_plan_from_cache", False, "session")
        enabled = bool(self.sysvars.get(
            "tidb_enable_prepared_plan_cache" if self._exec_prepared
            else "tidb_enable_non_prepared_plan_cache"))
        cache = getattr(self.catalog, "plan_cache", None)
        if not enabled or cache is None:
            return self._plan_select(self._materialize_stmt(stmt),
                                     agg_push_down=agg_push_down)

        def bypass(reason):
            cache.note_bypass(reason)
            return self._plan_select(self._materialize_stmt(stmt),
                                     agg_push_down=agg_push_down)

        if self._lock_read:
            return bypass("locking read")
        if getattr(stmt, "into_outfile", None) is not None:
            return bypass("INTO OUTFILE")
        # only parser-produced statements carry _source; synthetic ASTs
        # (DML subselects, locking-read shadow scans) must never share a
        # digest with the statement that spawned them
        src = getattr(stmt, "_source", None)
        if not src or len(src) > 16384:
            return bypass("no normalizable source")
        ps = self._ps_ctx
        if ps is not None and ps[0] == src:
            _, norm, digest, info = ps  # prepare-time analysis
        else:
            try:
                info = _pc.analyze_statement(stmt)
            except Exception:  # noqa: BLE001 — analysis is best-effort
                return bypass("analysis failed")
            memo = self._stmt_digest_memo
            if memo is not None and memo[0] == src:
                _src, norm, digest = memo  # statement start already lexed
            else:
                from tidb_tpu.bindinfo import normalize_sql, sql_digest

                norm = normalize_sql(src)
                digest = sql_digest(norm)
        if info.volatile:
            return bypass(f"volatile builtin {info.volatile}()")
        if info.unsafe:
            # a literal inside a foldable expression (abs(?), ?+1, ...)
            # can bake a derived value the patcher would overwrite with
            # the raw parameter — refuse the whole statement
            return bypass("literal in foldable expression context")
        self._stmt_digest_memo = (src, norm, digest)
        eff_apd = (self._agg_push_down() if agg_push_down is None
                   else agg_push_down)
        key = self._plan_cache_key(stmt, info, digest, eff_apd)
        sv = self.catalog.schema_version
        cap = int(self.sysvars.get("tidb_prepared_plan_cache_size"))
        entry = cache.lookup(key, sv, cap)
        if entry is not None and entry.patches is None:
            return bypass(entry.reason or "known uncacheable")
        if entry is not None and entry.n_params == len(info.params):
            try:
                phys = _pc.instantiate(entry, info.params)
            except Exception:  # noqa: BLE001 — fall back to planning
                phys = None
            if phys is not None:
                cache.note_hit(entry)
                self.sysvars.set("last_plan_from_cache", True, "session")
                self._plan_from_cache_stmt = True
                if not entry.plan_digest:
                    import hashlib as _hl

                    entry.plan_digest = _hl.sha256(
                        explain_text(entry.phys).encode()).hexdigest()[:32]
                self._last_plan_digest = entry.plan_digest
                if self._n_parts() > 1:
                    from tidb_tpu.planner.optimizer import _annotate_topn

                    _annotate_topn(phys)  # re-derive on the patched tree
                return phys
        cache.note_miss()
        used = [False]

        def _sub(logical):
            used[0] = True
            return self._execute_subplan(logical)

        stmt = self._materialize_stmt(stmt)
        phys = self._plan_select(stmt, agg_push_down=agg_push_down,
                                 execute_subplan=_sub)
        try:
            new = _pc.build_entry(
                stmt, phys, info, digest, self.db, sv,
                plan_sentinel=lambda s2: self._plan_select(
                    s2, agg_push_down=agg_push_down, execute_subplan=_sub),
                subplan_used=lambda: used[0])
            cache.store(key, new, sv)
        except Exception:  # noqa: BLE001 — the cache must never fail
            pass          # (or slow-path-block) the statement
        return phys

    def _plan_cache_key(self, stmt, info, digest, eff_apd):
        """THE plan-cache key — shared by the probe/fill path above and
        the serving tier's coalescing probe (batch_probe), so two
        statements coalesce exactly when they would share a cache entry
        (same digest, db, param-type fingerprint, structural constants,
        hints, planner sysvars, mesh width, binding versions)."""
        hints_fp = tuple((h, tuple(str(a) for a in args))
                         for h, args in getattr(stmt, "hints", ()) or ())
        return (
            digest, self.db, info.kinds, info.struct, hints_fp,
            bool(self.sysvars.get("tidb_enable_cascades_planner")),
            bool(eff_apd), self._n_parts(),
            self._bindings.version, self.catalog.bind_handle.version,
            # TEMPORARY tables shadow names without a schema_version
            # bump: a session holding any gets private entries, re-keyed
            # by the temp epoch so drop+recreate can never serve the old
            # table object's plan
            ((self.conn_id, getattr(self.catalog, "_temp_epoch", 0))
             if getattr(self.catalog, "_temp", None) else 0),
        )

    def batch_probe(self, stmt_id: int, params: list):
        """Serving-tier coalescing probe (tidb_tpu/serving/batcher.py):
        decide WITHOUT executing whether this prepared execution would
        be a plan-cache hit on a batchable plan. Returns
        (key, entry, info) when every safety gate passes, else None.
        Fallback to singleton execution is the correctness gate, so any
        doubt answers None — the statement then runs the full fidelity
        path and nothing is lost but the coalescing win."""
        ent = self._prepared.get(stmt_id)
        if ent is None:
            return None  # execute_prepared raises the real error
        stmt, n_params, sql, norm, digest, tinfo = ent
        if (tinfo is None or digest is None or len(params) != n_params
                or not isinstance(stmt, A.SelectStmt)
                or getattr(stmt, "lock_mode", None) is not None
                or getattr(stmt, "into_outfile", None) is not None):
            return None
        # session-state gates: txn snapshots, kill flags, mesh routing,
        # plugins and plan bindings all change execution — the singleton
        # path handles every one of them with full fidelity
        if (self.txn is not None or self._killed or self._kill_query
                or self._lock_read
                or not self.sysvars.get("autocommit")
                or self._shard_cache is not None
                or str(self.sysvars.get("tidb_executor_plugin"))
                or len(self._bindings) or len(self.catalog.bind_handle)
                or not self.sysvars.get("tidb_enable_prepared_plan_cache")):
            return None
        cache = getattr(self.catalog, "plan_cache", None)
        if cache is None:
            return None
        from tidb_tpu.planner import plancache as _pc

        info = _pc.bind_template_params(tinfo, params)
        if info is None or info.volatile or info.unsafe:
            return None
        key = self._plan_cache_key(stmt, info, digest,
                                   self._agg_push_down())
        entry = cache.lookup(
            key, self.catalog.schema_version,
            int(self.sysvars.get("tidb_prepared_plan_cache_size")))
        if (entry is None or entry.patches is None
                or entry.n_params != len(info.params)):
            return None
        if _pc.batchable_plan(entry):
            return None  # non-empty string = the blocking reason
        return key, entry, info

    _DML_HEADS = ("insert", "update", "delete")

    def dml_batch_probe(self, sql: str):
        """Group-commit coalescing probe (ISSUE 17, the write-path
        sibling of batch_probe): decide WITHOUT executing whether this
        autocommit text-protocol write can join a gathered DML window.
        Returns (key, spec) when every gate passes, else None — the
        statement then runs the full singleton path, which also owns
        raising the real error for anything the probe refused (bad
        values, missing privileges, unknown tables)."""
        head = sql.lstrip()[:6].lower()
        if head not in self._DML_HEADS:
            return None
        # session-state gates, mirroring batch_probe: open txns keep
        # their own commit point, sharded sessions route writes through
        # the mesh, executor plugins may intercept DML
        if (self.txn is not None or self._killed or self._kill_query
                or not self.sysvars.get("autocommit")
                or self._shard_cache is not None
                or str(self.sysvars.get("tidb_executor_plugin"))):
            return None
        if getattr(self.catalog, "_temp", None):
            # a TEMPORARY namespace is session-local; the batcher's
            # writer session could resolve the wrong table
            return None
        try:
            stmts = parse(sql)
        except Exception:  # noqa: BLE001 — singleton raises the parse error
            return None
        if len(stmts) != 1:
            return None
        stmt = stmts[0]
        from tidb_tpu.planner import plancache as _pc

        reason, parts = _pc.classify_dml(stmt)
        if reason:
            return None
        kind = parts["kind"]
        # the singleton dispatch's privilege gate, probed up front: a
        # denial falls back to singleton execution, which raises it
        self._priv_table(kind, stmt.table)
        db = stmt.table.schema or self.db
        try:
            table = self.catalog.table(db, stmt.table.name)
            spec = self._dml_spec(kind, stmt, db, table, parts)
        except Exception:  # noqa: BLE001 — any refusal -> singleton
            return None
        if spec is None:
            return None
        from tidb_tpu.bindinfo import normalize_sql, sql_digest

        digest = sql_digest(normalize_sql(sql))
        # schema_version pins the spec's bindings: a DDL between probe
        # and execution splits groups, and the batcher re-checks the
        # version at apply time under the catalog lock
        key = (digest, "dml", db, kind, self.catalog.schema_version)
        return key, spec

    def _dml_spec(self, kind, stmt, db, table, parts):
        """Schema-dependent half of the group-commit classifier: bind
        the statement's literals and resolve its point-access index.
        None = not coalescible. Built on the submitting connection
        thread; the batcher applies it under the catalog lock."""
        from tidb_tpu.planner.binder import Binder

        binder = Binder()
        gen_cols = {g.col for g in table.generated}
        spec = {"kind": kind, "db": db, "table": stmt.table.name}
        if kind == "insert":
            if stmt.columns and gen_cols & set(stmt.columns):
                return None  # singleton raises the generated-column error
            names = stmt.columns or table.insertable_names()
            rows = []
            for r_ast in stmt.rows:
                if len(r_ast) != len(names):
                    return None  # singleton raises the count mismatch
                rows.append([self._bind_const(binder, cell,
                                              table.schema.col(cname))
                             for cell, cname in zip(r_ast, names)])
            spec["columns"] = stmt.columns
            spec["rows"] = rows
            return spec
        where_col, lit_ast = parts["where"]
        col = table.schema.col(where_col)
        if col.type_.is_dict_encoded:
            # a string key's encoding can shift when the dictionary
            # grows between probe and apply; ints/dates are stable
            return None
        idx = next((ix for ix in table.indexes.values()
                    if ix.unique and ix.columns == [where_col]), None)
        if idx is None:
            return None  # no O(log n) point access; singleton scans
        v = self._bind_const(binder, lit_ast, col)
        if v is None:
            return None  # WHERE col = NULL matches nothing (MySQL)
        key_vals = table.encode_index_key(idx, {where_col: v})
        if key_vals is None:
            return None
        spec["index"] = idx.name
        spec["key"] = key_vals
        if kind == "delete":
            return spec
        indexed = {c for ix in table.indexes.values() for c in ix.columns}
        sets = []
        for set_col, how in parts["sets"]:
            tc = table.schema.col(set_col)
            if tc.name in gen_cols:
                return None  # singleton raises the generated-column error
            if tc.name in indexed:
                # a SET over an indexed column could redirect ANOTHER
                # member's point probe mid-window (serial executions
                # would observe it); uniqueness races live here too
                return None
            if how[0] == "const":
                sets.append((tc.name, "const",
                             self._bind_const(binder, how[1], tc)))
                continue
            _tag, src, op, delta_ast, _swap = how
            sc = table.schema.col(src)
            if sc.type_.is_dict_encoded or sc.type_.kind not in (
                    TypeKind.INT, TypeKind.FLOAT):
                return None  # host-side ± only over plain numerics
            if tc.type_.is_dict_encoded or tc.type_.kind not in (
                    TypeKind.INT, TypeKind.FLOAT):
                return None
            delta = self._bind_const(binder, delta_ast, sc)
            if delta is None:
                return None  # col ± NULL is NULL; keep the host eval dumb
            sets.append((tc.name, "delta", (src, op, delta)))
        spec["sets"] = sets
        return spec

    def _apply_binding(self, stmt):
        """Plan-binding lookup (ref: bindinfo BindHandle): on a match of
        the statement's normalized source, plan the bound (hinted)
        statement instead. Session bindings shadow global ones."""
        if not len(self._bindings) and not len(self.catalog.bind_handle):
            return stmt
        source = getattr(stmt, "_source", None)
        if not source:
            return stmt
        from tidb_tpu.bindinfo import normalize_sql

        norm = normalize_sql(source)
        b = self._bindings.match(norm) or self.catalog.bind_handle.match(norm)
        if b is None:
            return stmt
        # inject the binding's HINTS into the user's statement — never
        # the bound statement itself, whose literals are the ones that
        # happened to be in CREATE BINDING, not the user's. Copy instead
        # of mutating: cached prepared-statement ASTs must not keep the
        # hints after the binding is dropped.
        if (isinstance(stmt, A.SelectStmt) and isinstance(b.stmt, A.SelectStmt)
                and b.stmt.hints):
            import dataclasses as _dc

            out = _dc.replace(stmt, hints=list(b.stmt.hints))
            out._source = source  # replace() drops parser attrs; the
            # plan cache keys on (digest, hints, binding versions), so
            # a hinted copy is still safely distinguishable
            return out
        return stmt

    def _targets_temp_table(self, stmt) -> bool:
        """True when a DDL statement targets a table shadowed by this
        session's TEMPORARY namespace — such DDL must run inline (the
        DDL owner's session cannot see session-local tables)."""
        temp = getattr(self.catalog, "_temp", {})
        if not temp:
            return False
        names = []
        if isinstance(stmt, A.DropTableStmt):
            names = [(t.schema or self.db, t.name) for t in stmt.tables]
        elif isinstance(stmt, (A.TruncateStmt, A.AlterTableStmt)):
            tn = stmt.table
            names = [(tn.schema or self.db, tn.name)]
        elif isinstance(stmt, (A.CreateIndexStmt, A.DropIndexStmt)):
            tn = stmt.table
            names = [(tn.schema or self.db, tn.name)]
        elif isinstance(stmt, A.CreateTableStmt):
            # LIKE / AS SELECT reading a temp-shadowed SOURCE must also
            # stay inline: the DDL owner's session resolves the
            # permanent table instead (round-5 review)
            if stmt.like is not None:
                tn = stmt.like
                names.append((tn.schema or self.db, tn.name))
            sel = getattr(stmt, "as_select", None)
            if sel is not None:
                def walk_sources(node):
                    if isinstance(node, A.TableName):
                        names.append((node.schema or self.db, node.name))
                    elif isinstance(node, A.Join):
                        walk_sources(node.left)
                        walk_sources(node.right)
                    elif isinstance(node, A.SubqueryTable):
                        walk_select(node.select)

                def walk_select(st):
                    for arm in ([st] if isinstance(st, A.SelectStmt)
                                else list(_union_arms(st))):
                        if arm.from_ is not None:
                            walk_sources(arm.from_)

                walk_select(sel)
        return any(k in temp for k in names)

    def _run_locking_select(self, stmt) -> ResultSet:
        # NOTE on cost: the visible query runs once, plus one hidden
        # __rowid__ shadow query per base table. Folding rowids into the
        # main select is impossible in general (DISTINCT/GROUP BY/agg
        # shapes have no per-row identity), so the shadow pass is the
        # uniform mechanism; locking reads are OLTP-sized by nature.
        """SELECT ... FOR UPDATE / SHARE (ref: pessimistic locking reads
        over the 2PC row locks; SURVEY.md:174-178).

        Pessimistic protocol: under the catalog lock, (1) read at the
        LATEST committed snapshot (MySQL locking reads are current
        reads, not consistent reads), (2) collect the matched base-table
        row ids via the hidden __rowid__ columns, (3) if every row is
        free, register the locks and return. On conflict: release the
        catalog lock, wait, retry the whole read — bounded by
        innodb_lock_wait_timeout (timeout breaks any deadlock cycle);
        NOWAIT fails on the first conflict. Locks release at
        commit/rollback; without an open txn the check still serializes
        against other txns' locks but registers nothing (the statement
        is its own transaction)."""
        import time as _time

        mode = "x" if stmt.lock_mode == "update" else "s"
        targets = []
        if stmt.from_ is not None:
            # refuse shapes whose rows we cannot map back to base-table
            # row ids: silently locking NOTHING would hand the caller a
            # read-modify-write foot-gun (review r5 finding)
            def visit(src):
                if isinstance(src, A.TableName):
                    yield src
                elif isinstance(src, A.Join):
                    yield from visit(src.left)
                    yield from visit(src.right)
                else:
                    raise UnsupportedError(
                        "FOR UPDATE/SHARE over derived tables is not "
                        "supported; lock the base tables directly")
            for tn in visit(stmt.from_):
                db = tn.schema or self.db
                if any(c.name == tn.name for c in getattr(stmt, "ctes", ())):
                    raise UnsupportedError(
                        "FOR UPDATE/SHARE over a CTE is not supported")
                targets.append((tn, self.catalog.table(db, tn.name)))
        timeout = 0.0 if stmt.lock_nowait else float(
            self.sysvars.get("innodb_lock_wait_timeout"))
        deadline = _time.monotonic() + timeout
        marker = self.txn.marker if self.txn is not None else 0
        while True:
            with self.catalog.lock:
                self._lock_read = True
                try:
                    rs = self._run_select(stmt)
                    per_table = []
                    for tn, table in targets:
                        alias = tn.alias or tn.name
                        shadow = A.SelectStmt(
                            items=[A.SelectItem(
                                A.EName("__rowid__", qualifier=alias))],
                            from_=stmt.from_, where=stmt.where,
                            ctes=getattr(stmt, "ctes", []))
                        srs = self._run_select(shadow)
                        ids = np.array(
                            sorted({r[0] for r in srs.rows
                                    if r[0] is not None}),
                            dtype=np.int64)
                        per_table.append((table, ids))
                finally:
                    self._lock_read = False
                conflict = None
                for table, ids in per_table:
                    conflict = table.lock_conflict(ids, marker, mode)
                    if conflict:
                        conflict = f"{table.schema.name}: {conflict}"
                        break
                if conflict is None:
                    if self.txn is not None:
                        for table, ids in per_table:
                            table.lock_rows(ids, marker, mode)
                            self.txn.lock_tables[id(table)] = table
                    return rs
            if _time.monotonic() >= deadline:
                raise ExecutionError(
                    "Lock wait timeout exceeded; try restarting "
                    f"transaction ({conflict})")
            _time.sleep(0.02)

    def _run_select(self, stmt) -> ResultSet:
        from tidb_tpu.utils import tracing

        if self.txn is None and not self.sysvars.get("autocommit"):
            self._begin()  # consistent-snapshot reads without autocommit
        with tracing.span("session.plan"):
            phys = self._acquire_plan(stmt)
            self._check_plan_privs(phys)
            root = self._build_root(phys)
            if self._dist_expected() and _has_eager_partial(phys) \
                    and not _dist_engaged(root):
                # the eager-agg shape kept this plan off the mesh (the
                # fragment tier takes scan-rooted generic partials, not
                # every shape) — losing fragmentation costs more than the
                # rewrite saves, so re-plan without it and keep the
                # fragments (the no-push variant caches under its own key)
                phys = self._acquire_plan(stmt, agg_push_down=False)
                root = self._build_root(phys)
        # plan digest: hash of the plan's shape (explain text), paired
        # with the statement digest in statements_summary/slow log so a
        # regressed plan choice is visible as a digest change; a cache
        # hit already set the entry's memoized digest
        if self._last_plan_digest is None:
            import hashlib as _hl

            self._last_plan_digest = _hl.sha256(
                explain_text(phys).encode()).hexdigest()[:32]
        n_vis = phys.n_visible if isinstance(phys, PProjection) else None
        if n_vis is None and hasattr(phys, "children") and phys.children:
            # Sort/Limit on top of the projection keep hidden sort columns
            c = phys
            while c.children and not isinstance(c, PProjection):
                c = c.children[0]
            if isinstance(c, PProjection) and c.n_visible is not None and c.n_visible < len(phys.schema):
                n_vis = c.n_visible
        with tracing.span("session.execute"):
            rs = run_plan(root,
                          self._exec_ctx(hints=getattr(stmt, "hints", ()),
                                         plan=phys),
                          n_visible=n_vis)
        if self._fb_enabled():
            # park the executed tree for the statement-end feedback
            # harvest; _execute_timed drops the reference either way
            self._fb_capture = (phys, root, len(rs.rows))
        return rs

    # ------------------------------------------------------------------

    def _sub_vars(self, e):
        """Replace @@sysvar / @uservar references with their current values
        (ref: sessionctx/variable resolution during expression rewriting)."""
        if isinstance(e, A.EVar):
            if e.scope == "user":
                v = self.user_vars.get(e.name.lstrip("@"))
            elif e.scope == "global":
                from tidb_tpu.session.sysvars import SYSVARS

                n = e.name.lower()
                var = SYSVARS.get(n)
                if var is None:
                    raise ExecutionError(f"unknown system variable {n!r}")
                v = self.catalog.global_vars.get(n, var.default)
            else:
                v = self.sysvars.get(e.name)
            if v is None:
                return A.ENull()
            if isinstance(v, bool):
                return A.ENum("1" if v else "0")
            if isinstance(v, (int, float)):
                return A.ENum(repr(v))
            return A.EStr(str(v))
        if not hasattr(e, "__dataclass_fields__"):
            return e
        kwargs = {}
        for f in e.__dataclass_fields__:
            v = getattr(e, f)
            if isinstance(v, list):
                kwargs[f] = [
                    tuple(self._sub_vars(y) if hasattr(y, "__dataclass_fields__") else y for y in x)
                    if isinstance(x, tuple)
                    else self._sub_vars(x) if hasattr(x, "__dataclass_fields__") else x
                    for x in v
                ]
            elif hasattr(v, "__dataclass_fields__"):
                kwargs[f] = self._sub_vars(v)
            else:
                kwargs[f] = v
        return type(e)(**kwargs)

    def _priv(self, priv: str, db: str = "*", table: str = "*") -> None:
        self.catalog.privileges.require(self.user, priv, db, table)

    def _priv_table(self, priv: str, tn) -> None:
        self._priv(priv, tn.schema or self.db, tn.name)

    def _check_plan_privs(self, phys) -> None:
        """SELECT privilege on every base table the plan scans (views
        are expanded at bind time, so their underlying tables are what
        gets checked)."""
        from tidb_tpu.planner.physical import PScan

        stack = [phys]
        while stack:
            node = stack.pop()
            if isinstance(node, PScan) and node.table is not None:
                if getattr(node.table, "_anonymous", False):
                    # plan-time temp (materialized CTE): its body was
                    # privilege-checked when the subplan executed
                    stack.extend(getattr(node, "children", ()))
                    continue
                db = getattr(node, "db", None) or self.db
                if db.lower() != "information_schema":  # world-readable
                    self._priv("select", db, node.table_name)
            stack.extend(getattr(node, "children", ()))

    def _execute_stmt(self, stmt) -> Optional[ResultSet]:
        # textual fast-paths for the per-statement AST sweeps: the
        # parser can only produce EVar / into_outfile nodes from the
        # literal '@' / OUTFILE tokens, so sources without them skip
        # the walk entirely (the OLTP hot path runs these per statement)
        src_txt = getattr(stmt, "_source", None)
        if (not isinstance(stmt, A.SetStmt)
                and (src_txt is None or "@" in src_txt)
                and _ast_contains(stmt, A.EVar)):
            stmt = self._sub_vars(stmt)
            if src_txt is not None:
                stmt._source = src_txt  # the rebuild drops parser attrs
        if isinstance(stmt, (A.SelectStmt, A.UnionStmt)):
            into = getattr(stmt, "into_outfile", None)
            if ((src_txt is None or "outfile" in src_txt.lower())
                    and _nested_into_outfile(stmt, top=stmt)):
                raise UnsupportedError(
                    "INTO OUTFILE is only supported on a top-level SELECT")
            if into is not None:
                self._precheck_outfile(into)  # fail BEFORE the query runs
            if isinstance(stmt, A.UnionStmt) and any(
                    getattr(arm, "lock_mode", None)
                    for arm in _union_arms(stmt)):
                # MySQL rejects FOR UPDATE on union arms too
                raise UnsupportedError("FOR UPDATE is not allowed with UNION")
            if getattr(stmt, "lock_mode", None) is not None:
                rs = self._run_locking_select(self._apply_binding(stmt))
            else:
                rs = self._run_select(self._apply_binding(stmt))
            if into is not None:
                return self._write_outfile(rs, into)
            return rs
        if isinstance(stmt, A.CreateBindingStmt):
            from tidb_tpu.bindinfo import normalize_sql

            if normalize_sql(stmt.target_sql) != normalize_sql(stmt.using_sql):
                raise PlanError(
                    "binding statements differ after normalization")
            handle = (self.catalog.bind_handle if stmt.scope == "global"
                      else self._bindings)
            handle.create(stmt.target_sql, stmt.using_sql)
            return None
        if isinstance(stmt, A.DropBindingStmt):
            handle = (self.catalog.bind_handle if stmt.scope == "global"
                      else self._bindings)
            if not handle.drop(stmt.target_sql):
                raise ExecutionError("no such binding")
            return None
        if isinstance(stmt, A.InsertStmt):
            self._priv_table("insert", stmt.table)
            return self._run_insert(stmt)
        if isinstance(stmt, A.UpdateStmt):
            if stmt.from_ is None:
                self._priv_table("update", stmt.table)
            return self._run_update(stmt)  # multi-table checks its target
        if isinstance(stmt, A.DeleteStmt):
            if stmt.from_ is None:
                self._priv_table("delete", stmt.table)
            return self._run_delete(stmt)
        if isinstance(stmt, (A.CreateTableStmt, A.DropTableStmt, A.CreateDatabaseStmt,
                             A.DropDatabaseStmt, A.TruncateStmt, A.CreateIndexStmt,
                             A.DropIndexStmt, A.AlterTableStmt)):
            self._check_ddl_privs(stmt)
            self._commit()  # DDL implicitly commits the open txn (MySQL)
            # multi-instance deployments run DDL through the elected
            # owner's worker (ref: ddl job queue + owner election);
            # inline otherwise (embedded / the worker's own session)
            if (self.catalog.ddl_workers
                    and not getattr(self, "_ddl_direct", False)
                    and not getattr(stmt, "temporary", False)
                    and not self._targets_temp_table(stmt)):
                # TEMPORARY tables are session-local: routing them to the
                # DDL owner would create them in the WORKER's namespace
                source = getattr(stmt, "_source", None)
                if source:
                    job = self.catalog.submit_ddl(source, self.db)
                    # no arbitrary deadline: abandoning a RUNNING job
                    # would release the statement lock while its worker
                    # still mutates the catalog (unserialized). We only
                    # fail fast when no worker remains to ever run it —
                    # a genuinely stuck DDL behaves like stuck inline
                    # DDL, which also holds the lock.
                    while not job.done.wait(timeout=1):
                        if not self.catalog.ddl_workers:
                            self.catalog.drain_ddl_jobs("DDL owner shut down")
                    if job.error is not None:
                        raise job.error
                    return None
        if isinstance(stmt, A.CreateTableStmt):
            return self._run_create_table(stmt)
        if isinstance(stmt, A.DropTableStmt):
            for t in stmt.tables:
                self.catalog.drop_table(t.schema or self.db, t.name, stmt.if_exists)
            return None
        if isinstance(stmt, A.CreateDatabaseStmt):
            self.catalog.create_database(stmt.name, stmt.if_not_exists)
            return None
        if isinstance(stmt, A.DropDatabaseStmt):
            self.catalog.drop_database(stmt.name, stmt.if_exists)
            return None
        if isinstance(stmt, A.TruncateStmt):
            self.catalog.table(stmt.table.schema or self.db, stmt.table.name).truncate()
            return None
        if isinstance(stmt, A.LoadDataStmt):
            return self._run_load_data(stmt)
        if isinstance(stmt, A.UseStmt):
            self.catalog.database(stmt.db)  # raises if missing
            self.db = stmt.db
            return None
        if isinstance(stmt, A.ExplainStmt):
            return self._run_explain(stmt)
        if isinstance(stmt, A.TraceStmt):
            return self._run_trace(stmt)
        if isinstance(stmt, A.SetStmt):
            for scope, name, value in stmt.assignments:
                from tidb_tpu.planner.binder import Binder

                lit = Binder().bind_literal(value) if not isinstance(value, A.EName) else None
                v = lit.value if lit is not None else value.name
                if lit is not None and lit.type_.kind == TypeKind.DECIMAL:
                    v = v / (10 ** lit.type_.scale)
                if scope == "user":
                    self.user_vars[name.lstrip("@")] = v
                else:
                    if scope == "global":
                        self._priv("super")  # ref: SUPER for global sysvars
                    self.sysvars.set(name, v, scope or "session")
                    # MySQL: enabling autocommit commits the open txn
                    if (name.lower() == "autocommit" and scope != "global"
                            and self.sysvars.get("autocommit")):
                        self._commit()
            return None
        if isinstance(stmt, A.ShowStmt):
            return self._run_show(stmt)
        if isinstance(stmt, A.KillStmt):
            # KILL [QUERY|CONNECTION] <id> (ref: server/'s kill flow):
            # QUERY cancels the victim's in-flight statement at its next
            # chunk boundary; CONNECTION also fails every later statement
            victim = self.catalog.processes.get(stmt.conn_id)
            if victim is None:
                # existence BEFORE privilege (MySQL): a nonexistent id is
                # "Unknown thread id" for every user, not an access error
                raise ExecutionError(f"Unknown thread id: {stmt.conn_id}")
            if self.user != "root" and victim.user != self.user:
                self._priv("super")  # only SUPER kills others
            if stmt.query_only:
                victim._kill_query = True
            else:
                victim._killed = True
            return None
        if isinstance(stmt, A.CreateViewStmt):
            self._priv("create", stmt.schema or self.db)
            self._commit()  # DDL semantics
            self.catalog.create_view(
                stmt.schema or self.db, stmt.name, stmt.columns,
                stmt.select, stmt.select_sql, stmt.or_replace)
            return None
        if isinstance(stmt, A.DropViewStmt):
            for t in stmt.names:
                self._priv("drop", t.schema or self.db, t.name)
            self._commit()
            # MySQL 8: all-or-nothing — validate every name first
            if not stmt.if_exists:
                for t in stmt.names:
                    if self.catalog.view(t.schema or self.db, t.name) is None:
                        raise SchemaError(f"no view {t.schema or self.db}.{t.name}")
            for t in stmt.names:
                self.catalog.drop_view(t.schema or self.db, t.name, if_exists=True)
            return None
        if isinstance(stmt, A.InstallPluginStmt):
            self._priv("super")  # SQL-reachable module import is admin-only
            self.catalog.plugins.load_module(stmt.name, stmt.module)
            return None
        if isinstance(stmt, A.UninstallPluginStmt):
            self._priv("super")
            self.catalog.plugins.uninstall(stmt.name)
            return None
        if isinstance(stmt, A.BeginStmt):
            self._begin()
            return None
        if isinstance(stmt, A.CommitStmt):
            self._commit()
            return None
        if isinstance(stmt, A.RollbackStmt):
            self._rollback()
            return None
        if isinstance(stmt, A.SavepointStmt):
            if self.txn is None and not self.sysvars.get("autocommit"):
                self._begin()  # MySQL: SAVEPOINT joins/starts the txn
            if self.txn is not None:  # no-op in autocommit (MySQL)
                with self.catalog.lock:
                    self.txn.set_savepoint(stmt.name)
            return None
        if isinstance(stmt, A.RollbackToStmt):
            ok = False
            if self.txn is not None:
                with self.catalog.lock:
                    ok = self.txn.rollback_to(stmt.name)
            if not ok:
                raise ExecutionError(
                    f"SAVEPOINT {stmt.name} does not exist")
            return None
        if isinstance(stmt, A.ReleaseSavepointStmt):
            if self.txn is None or not self.txn.release_savepoint(stmt.name):
                raise ExecutionError(
                    f"SAVEPOINT {stmt.name} does not exist")
            return None
        if isinstance(stmt, A.AnalyzeStmt):
            from tidb_tpu.statistics import analyze_table

            for tn in stmt.tables:
                t = self.catalog.table(tn.schema or self.db, tn.name)
                analyze_table(t)  # also invalidates plan feedback —
                t.modify_count = 0  # see statistics.analyze_table
            return None
        if isinstance(stmt, A.CreateIndexStmt):
            t = self.catalog.table(stmt.table.schema or self.db, stmt.table.name)
            t.create_index(stmt.name, stmt.columns, unique=stmt.unique)
            # index DDL changes access-path choices: cached plans built
            # without (or with) this index must not survive it
            self.catalog.schema_version += 1
            return None
        if isinstance(stmt, A.DropIndexStmt):
            t = self.catalog.table(stmt.table.schema or self.db, stmt.table.name)
            t.drop_index(stmt.name)
            self.catalog.schema_version += 1
            return None
        if isinstance(stmt, A.AlterTableStmt):
            return self._run_alter_table(stmt)
        if isinstance(stmt, A.CreateUserStmt):
            self._priv("super")
            self.catalog.create_user(stmt.user, stmt.password, stmt.if_not_exists)
            return None
        if isinstance(stmt, A.DropUserStmt):
            self._priv("super")
            self.catalog.drop_user(stmt.user, stmt.if_exists)
            self.catalog.privileges.drop_user(stmt.user)
            return None
        if isinstance(stmt, A.GrantStmt):
            self._priv("super")
            if stmt.user not in self.catalog.users:
                raise ExecutionError(f"no user {stmt.user!r}")
            db = stmt.db if stmt.db is not None else self.db
            self.catalog.privileges.grant(stmt.user, stmt.privs, db, stmt.table)
            return None
        if isinstance(stmt, A.RevokeStmt):
            self._priv("super")
            if stmt.user not in self.catalog.users:
                raise ExecutionError(f"no user {stmt.user!r}")
            db = stmt.db if stmt.db is not None else self.db
            self.catalog.privileges.revoke(stmt.user, stmt.privs, db, stmt.table)
            return None
        raise UnsupportedError(f"statement {type(stmt).__name__}")

    _DDL_PRIV = {
        A.CreateTableStmt: "create", A.CreateDatabaseStmt: "create",
        A.CreateIndexStmt: "index", A.DropIndexStmt: "index",
        A.DropTableStmt: "drop", A.DropDatabaseStmt: "drop",
        A.TruncateStmt: "drop", A.AlterTableStmt: "alter",
    }

    def _check_ddl_privs(self, stmt) -> None:
        priv = self._DDL_PRIV[type(stmt)]
        if isinstance(stmt, A.DropTableStmt):
            for tn in stmt.tables:
                self._priv_table(priv, tn)
            return
        if isinstance(stmt, (A.CreateDatabaseStmt, A.DropDatabaseStmt)):
            self._priv(priv, stmt.name)
            return
        self._priv_table(priv, stmt.table)

    # -- prepared statements (ref: server/conn_stmt.go + planner plan
    # cache; the binary protocol's COM_STMT_* commands drive these) -------

    def prepare(self, sql: str) -> tuple:
        """Parse once, count placeholders. Returns (stmt_id, n_params)."""
        import time as _time

        from tidb_tpu.utils import metrics as M

        t0 = _time.perf_counter()
        stmts = parse(sql)
        M.PARSE_SECONDS.observe(_time.perf_counter() - t0)
        if len(stmts) != 1:
            raise UnsupportedError("PREPARE requires exactly one statement")
        stmt = stmts[0]
        n_params = _count_params(stmt)
        # prepare-time plan-cache context: the normalized digest and the
        # template's literal-slot analysis are value-independent, so the
        # per-execution hot path never re-lexes or re-walks the AST
        from tidb_tpu.bindinfo import normalize_sql, sql_digest
        from tidb_tpu.planner import plancache as _pc

        try:
            norm = normalize_sql(sql) if len(sql) <= 16384 else None
            digest = sql_digest(norm) if norm is not None else None
            tinfo = _pc.analyze_template(stmt)
        except Exception:  # noqa: BLE001 — fall back to per-exec analysis
            norm = digest = tinfo = None
        self._stmt_id += 1
        self._prepared[self._stmt_id] = (stmt, n_params, sql, norm, digest,
                                         tinfo)
        return self._stmt_id, n_params

    def execute_prepared(self, stmt_id: int, params: list) -> Optional[ResultSet]:
        ent = self._prepared.get(stmt_id)
        if ent is None:
            raise ExecutionError(f"unknown prepared statement {stmt_id}")
        stmt, n_params, sql, norm, digest, tinfo = ent
        if len(params) != n_params:
            raise ExecutionError(
                f"prepared statement takes {n_params} params, got {len(params)}")
        info = None
        if tinfo is not None and digest is not None:
            from tidb_tpu.planner import plancache as _pc

            info = _pc.bind_template_params(tinfo, params)
        # defer parameter substitution for plain SELECT/UNION templates
        # when the fast probe context is available: a plan-cache hit
        # executes without ever needing the bound AST, and every
        # planning path materializes it via _materialize_stmt. Locking
        # reads and DML consume literals outside the planner, so they
        # always bind eagerly.
        defer = (info is not None and n_params
                 and isinstance(stmt, (A.SelectStmt, A.UnionStmt))
                 and getattr(stmt, "lock_mode", None) is None
                 and getattr(stmt, "into_outfile", None) is None
                 and not (isinstance(stmt, A.UnionStmt) and any(
                     getattr(arm, "lock_mode", None)
                     for arm in _union_arms(stmt))))
        if n_params and not defer:
            stmt = _sub_params(stmt, params)
            # the rebuilt AST loses the parser's _source attr; restore
            # it — the plan cache and statements summary digest it (the
            # '?' markers normalize exactly like substituted literals)
            stmt._source = sql
        # through the timed path: prepared executions must hit the same
        # metrics / slow-query log / profiler hooks as text queries
        self._exec_prepared = True
        if info is not None:
            self._ps_ctx = (sql, norm, digest, info)
        if defer:
            self._ps_params = params
        try:
            return self._execute_timed(stmt, sql)
        finally:
            self._exec_prepared = False
            self._ps_ctx = None
            self._ps_params = None
            self._ps_materialized = None

    def close_prepared(self, stmt_id: int) -> None:
        self._prepared.pop(stmt_id, None)

    # ------------------------------------------------------------------

    def _column_info(self, c: A.ColumnDef) -> ColumnInfo:
        t = parse_type_name(c.type_name, c.type_args)
        default = None
        if c.default is not None:
            from tidb_tpu.planner.binder import Binder

            lit = Binder().bind_literal(c.default)
            default = lit.value
            if default is not None and lit.type_.kind == TypeKind.DECIMAL:
                import decimal as _dec

                # literals carry scaled-int decimals; defaults are stored
                # in logical form (DEFAULT 1.5 is 1.5, not 15), exactly
                default = _dec.Decimal(default).scaleb(-lit.type_.scale)
        text = c.type_name.lower()
        if c.type_args:
            text += "(" + ",".join(str(a) for a in c.type_args) + ")"
        return ColumnInfo(
            c.name, t,
            not_null=c.not_null or c.primary_key,
            default=default,
            auto_increment=c.auto_increment,
            type_text=text,
            collation=c.collation,
        )

    def apply_ddl_stage(self, sql: str, stage: str) -> None:
        """One step of an ONLINE schema change (ref: the multi-version
        none→write-only→public state machine with schema-version leases,
        SURVEY.md:180-185). The DCN coordinator drives every instance
        through the same stage before advancing, so at most two adjacent
        states coexist cluster-wide:

        ADD COLUMN:  write_only -> public
          write_only: the column exists in storage (default-backfilled)
          and is written by new DML, but is invisible to reads — an
          instance still at the previous version keeps inserting the
          old positional shape correctly.
        ADD INDEX:   write_only -> backfill -> public
          write_only: enforced on every new write, invisible to access
          paths; backfill: validate all existing rows (abort drops the
          staged index); public: readable.
        abort: undo a staged ADD (crash/validation-failure path)."""
        stmt = parse(sql)[0]
        if not isinstance(stmt, A.AlterTableStmt) or stmt.action not in (
                "add_column", "add_index"):
            raise UnsupportedError(
                "online DDL stages cover ADD COLUMN / ADD INDEX only")
        db = stmt.table.schema or self.db
        t = self.catalog.table(db, stmt.table.name)
        with self.catalog.lock:
            if stmt.action == "add_column":
                info = self._column_info(stmt.column)
                if info.collation is None and t.schema.collation:
                    info.collation = t.schema.collation
                if stage == "write_only":
                    if info.not_null and info.default is None:
                        raise ExecutionError(
                            "online ADD COLUMN requires a DEFAULT for a "
                            "NOT NULL column (writers one schema version "
                            "behind cannot supply it)")
                    info.state = "write_only"
                    t.add_column(info)
                elif stage == "public":
                    t.schema.col(info.name).state = "public"
                    t.version += 1
                elif stage == "abort":
                    # only a STAGED column may be dropped: a duplicate-
                    # name failure must never destroy the user's column
                    if any(c.name == info.name and c.state == "write_only"
                           for c in t.schema.columns):
                        t.schema.col(info.name).state = "public"
                        t.drop_column(info.name)
                else:
                    raise UnsupportedError(f"bad ddl stage {stage!r}")
            else:
                name, columns = stmt.index
                iname = name or f"idx_{'_'.join(columns)}"
                if stage == "write_only":
                    t.create_index(iname, columns, unique=stmt.unique,
                                   state="write_only")
                elif stage == "backfill":
                    idx = t.indexes[iname]
                    if idx.unique:
                        try:
                            t._check_unique(idx)
                        except Exception:
                            t.drop_index(iname)
                            raise
                elif stage == "public":
                    t.indexes[iname].state = "public"
                    t.version += 1
                elif stage == "abort":
                    staged = t.indexes.get(iname)
                    if staged is not None and staged.state == "write_only":
                        t.drop_index(iname)
                else:
                    raise UnsupportedError(f"bad ddl stage {stage!r}")
            self.catalog.schema_version += 1

    def _run_alter_table(self, stmt: A.AlterTableStmt):
        db = stmt.table.schema or self.db
        t = self.catalog.table(db, stmt.table.name)
        def with_table_coll(info):
            if info.collation is None and t.schema.collation:
                info.collation = t.schema.collation
            return info

        if stmt.action == "add_column":
            t.add_column(with_table_coll(self._column_info(stmt.column)))
        elif stmt.action == "drop_column":
            t.drop_column(stmt.old_name)
        elif stmt.action == "modify_column":
            t.modify_column(with_table_coll(self._column_info(stmt.column)))
        elif stmt.action == "rename":
            self.catalog.rename_table(db, stmt.table.name, stmt.new_name)
        elif stmt.action == "add_index":
            name, columns = stmt.index
            t.create_index(name or f"idx_{'_'.join(columns)}", columns,
                           unique=stmt.unique)
        elif stmt.action == "add_foreign_key":
            parent, fk = self.catalog._resolve_foreign_key(db, t, stmt.fk)
            if stmt.new_name:
                fk.name = stmt.new_name
            if any(f.name == fk.name for f in t.foreign_keys):
                raise SchemaError(
                    f"duplicate foreign key constraint name {fk.name!r}")
            # existing rows must already satisfy the constraint (same
            # probe as every write path, live versions only)
            if t.n:
                t._check_fk_parents(0, t.n, fks=[fk], live_only=True)
            t.foreign_keys.append(fk)
            parent.referencing.append((t, fk))
        elif stmt.action == "drop_foreign_key":
            fk = next((f for f in t.foreign_keys
                       if f.name == stmt.old_name), None)
            if fk is None:
                raise SchemaError(f"no foreign key {stmt.old_name!r}")
            t.foreign_keys.remove(fk)
            fk.parent.referencing = [
                (c, f) for c, f in fk.parent.referencing if f is not fk]
        elif stmt.action == "add_check":
            cname, e_ast, txt = stmt.check
            name = cname
            if not name:  # first free generated slot
                i = 1
                while any(c.name == f"{t.schema.name}_chk_{i}"
                          for c in t.checks):
                    i += 1
                name = f"{t.schema.name}_chk_{i}"
            self._wire_check(t, name, e_ast, txt)
            # existing rows must satisfy THE NEW CHECK specifically (no
            # column filter: a constant predicate has no columns at all)
            chk = t.checks[-1]
            try:
                if t.n:
                    t._check_row_constraints(0, t.n, live_only=True,
                                             checks=[chk])
            except ExecutionError:
                t.checks.pop()
                raise
        elif stmt.action == "drop_check":
            before = len(t.checks)
            t.checks = [c for c in t.checks if c.name != stmt.old_name]
            if len(t.checks) == before:
                raise SchemaError(f"no CHECK constraint {stmt.old_name!r}")
        elif stmt.action == "cluster":
            # ordered-compaction hint (ISSUE 18): persisted on the
            # schema; the NEXT delta->segment fold physically re-sorts
            # the table (Table.recluster), so the statement itself stays
            # metadata-only like reshard
            t.schema.cluster_by = self._cluster_by_col(
                stmt.cluster, t.schema.columns)
            base = getattr(t, "_base", t)
            base.clustered_rows = 0  # force the re-sort at the next fold
        elif stmt.action == "reshard":
            # new placement metadata; version bump invalidates placement
            # snapshots, schema_version bump (below) invalidates cached
            # plans — an in-flight statement demotes via the existing
            # catalog-lock revalidation instead of serving a stale map
            old = t.schema.shard_by
            info = self._shard_by_info(stmt.shard, t.schema.columns)
            info.version = (old.version + 1) if old is not None else 1
            t.schema.shard_by = info
        else:
            raise UnsupportedError(f"ALTER TABLE {stmt.action}")
        # every completed ALTER advances the schema version (ref: one
        # version per DDL job) — plan-cache invalidation hangs off it
        self.catalog.schema_version += 1
        return None

    @staticmethod
    def _cluster_by_col(name, cols):
        """Validate a CLUSTER BY column name (None = clear the hint).
        Any orderable type works — dictionary codes order
        lexicographically by construction — except JSON, whose code
        order carries no meaning worth sorting a table by."""
        if name is None:
            return None
        info = next((c for c in cols if c.name == name), None)
        if info is None:
            raise SchemaError(f"unknown cluster column {name!r}")
        if info.type_.kind == TypeKind.JSON:
            raise SchemaError(
                f"cluster column {name!r} must not be JSON-typed")
        return name

    @staticmethod
    def _shard_by_info(spec, cols):
        """Validate a parsed SHARD BY spec against the column list and
        build the persisted ShardByInfo (None passes through)."""
        if spec is None:
            return None
        from tidb_tpu.storage.table import ShardByInfo

        kind, scol, arg = spec
        info = next((c for c in cols if c.name == scol), None)
        if info is None:
            raise SchemaError(f"unknown shard column {scol!r}")
        if info.type_.kind != TypeKind.INT:
            raise SchemaError(
                f"shard column {scol!r} must be integer-typed")
        if kind == "range":
            return ShardByInfo(kind="range", column=scol,
                               shards=len(arg) + 1, bounds=list(arg))
        return ShardByInfo(kind="hash", column=scol, shards=int(arg))

    def _run_create_table(self, stmt: A.CreateTableStmt):
        if stmt.like is not None:
            return self._run_create_like(stmt)
        if stmt.as_select is not None:
            return self._run_ctas(stmt)
        cols = []
        pk = list(stmt.primary_key) if stmt.primary_key else None
        for c in stmt.columns:
            if c.primary_key:
                pk = [c.name]
            info = self._column_info(c)
            if info.collation is None and stmt.collation:
                info.collation = stmt.collation  # table default COLLATE
            cols.append(info)
        part = None
        if stmt.partition is not None:
            from tidb_tpu.storage.table import PartitionInfo

            kind, pcol, spec = stmt.partition
            pinfo = next((c for c in cols if c.name == pcol), None)
            if pinfo is None:
                raise SchemaError(f"unknown partition column {pcol!r}")
            if pinfo.type_.kind != TypeKind.INT:
                # MySQL likewise rejects non-integer partition functions
                raise SchemaError(
                    f"partition column {pcol!r} must be integer-typed")
            if kind == "range":
                uppers = [u for _n, u in spec]
                finite = [u for u in uppers if u is not None]
                strictly_inc = all(a < b for a, b in zip(finite, finite[1:]))
                maxvalue_ok = all(u is not None for u in uppers[:-1])
                if not strictly_inc or not maxvalue_ok:
                    raise SchemaError(
                        "RANGE partition bounds must be strictly "
                        "increasing with MAXVALUE last")
                part = PartitionInfo(kind="range", column=pcol,
                                     names=[n for n, _u in spec],
                                     uppers=uppers)
            else:
                part = PartitionInfo(kind="hash", column=pcol,
                                     n_parts=int(spec))
        schema = TableSchema(stmt.table.name, cols, primary_key=pk,
                             collation=stmt.collation, partition=part,
                             shard_by=self._shard_by_info(stmt.shard, cols),
                             cluster_by=self._cluster_by_col(
                                 stmt.cluster, cols))
        if stmt.temporary:
            if stmt.foreign_keys:
                raise UnsupportedError(
                    "TEMPORARY tables cannot have foreign keys (MySQL)")
            t = self.catalog.create_temp_table(
                stmt.table.schema or self.db, schema, stmt.if_not_exists,
                engine=stmt.engine)
        else:
            t = self.catalog.create_table(
                stmt.table.schema or self.db, schema,
                stmt.if_not_exists, engine=stmt.engine,
                foreign_keys=stmt.foreign_keys)
        if t is not None and t.schema is schema:
            # inline constraint wiring happens only on a table this
            # statement actually created — and a failure must UNDO the
            # creation, or the catalog keeps a half-constrained table
            try:
                for kname, kcols in stmt.unique_keys:
                    t.create_index(kname or f"uk_{'_'.join(kcols)}", kcols,
                                   unique=True)
                for c in stmt.columns:
                    # column-level UNIQUE attribute == a unique key
                    if c.unique and not any(
                            ix.columns == [c.name] and ix.unique
                            for ix in t.indexes.values()):
                        t.create_index(f"uk_{c.name}", [c.name], unique=True)
                for kname, kcols in stmt.indexes:
                    t.create_index(kname or f"idx_{'_'.join(kcols)}", kcols)
                specs = [("", e, txt) for c in stmt.columns
                         for e, txt in c.checks] + list(stmt.checks)
                for i, (cname, e_ast, txt) in enumerate(specs):
                    self._wire_check(
                        t, cname or f"{schema.name}_chk_{i + 1}", e_ast, txt)
                for c in stmt.columns:
                    if c.generated is not None:
                        e_ast, txt, stored = c.generated
                        self._wire_generated(t, c.name, e_ast, txt, stored)
            except Exception:
                self.catalog.drop_table(stmt.table.schema or self.db,
                                        schema.name, if_exists=True)
                raise
            for item in stmt.ignored + [i for c in stmt.columns
                                        for i in c.ignored]:
                # accepted-but-ignored clauses surface as warnings
                # instead of vanishing (SHOW WARNINGS; MySQL code 1235)
                self._warnings.append(
                    ("Warning", 1235, f"{item} is parsed but ignored"))
        return None

    def _run_create_like(self, stmt: A.CreateTableStmt):
        """CREATE TABLE t LIKE src: clone columns (incl. declared type
        text, defaults, auto-increment), primary key, and secondary
        indexes — NOT data, foreign keys, or the source's rows (MySQL
        semantics; FKs are deliberately not copied, like MySQL)."""
        import copy

        src_tn = stmt.like
        self._priv("select", src_tn.schema or self.db, src_tn.name)
        # (FKs are deliberately not copied — MySQL LIKE semantics)
        src = self.catalog.table(src_tn.schema or self.db, src_tn.name)
        schema = copy.deepcopy(src.schema)
        schema.name = stmt.table.name
        for c in schema.columns:
            c.state = "public"
        if stmt.temporary:
            t = self.catalog.create_temp_table(
                stmt.table.schema or self.db, schema, stmt.if_not_exists,
                engine=src.engine)
        else:
            t = self.catalog.create_table(
                stmt.table.schema or self.db, schema,
                stmt.if_not_exists, engine=src.engine)
        if t is not None and t.schema is schema:
            for name, ix in src.indexes.items():
                if name != "PRIMARY" and name not in t.indexes:
                    t.create_index(name, list(ix.columns), unique=ix.unique)
            # MySQL 8 clones CHECK constraints too (preds bind by column
            # name against an identical schema, so sharing is sound)
            t.checks = list(src.checks)
        return None

    def _run_ctas(self, stmt: A.CreateTableStmt):
        """CREATE TABLE t AS SELECT ...: infer the schema from the
        select's output columns (engine types; strings land as varchar)
        and bulk-insert the result (ref: the reference's CTAS path)."""
        # refuse BEFORE running the (possibly expensive) select
        db = stmt.table.schema or self.db
        if self.catalog.has_table(db, stmt.table.name):
            if stmt.if_not_exists:
                return None
            from tidb_tpu.errors import DuplicateTableError

            raise DuplicateTableError(f"table {stmt.table.name!r} exists")
        rs = self._run_select(stmt.as_select)
        from tidb_tpu.types import (DATE, DATETIME, FLOAT64, INT64, STRING,
                                    TIME, TypeKind)

        kind_to_type = {
            TypeKind.INT: INT64, TypeKind.FLOAT: FLOAT64,
            TypeKind.BOOL: parse_type_name("boolean", ()),
            TypeKind.DATE: DATE, TypeKind.DATETIME: DATETIME,
            TypeKind.TIME: TIME,
        }
        cols = []
        seen = set()
        fulls = rs.sql_types or [None] * len(rs.names)
        colls = rs.collations or [None] * len(rs.names)
        for name, kind, full, coll in zip(rs.names, rs.types, fulls, colls):
            cname = name
            i = 2
            while cname in seen:  # duplicate output names disambiguate
                cname = f"{name}_{i}"
                i += 1
            seen.add(cname)
            if kind == TypeKind.DECIMAL:
                # the select's exact precision/scale carries over
                t_ = full if full is not None else parse_type_name(
                    "decimal", (18, 4))
            elif kind in (TypeKind.STRING, TypeKind.JSON):
                t_ = STRING
            elif full is not None and kind in (TypeKind.ENUM, TypeKind.SET):
                t_ = full
            else:
                t_ = kind_to_type.get(kind, STRING)
            # the source column's collation carries over (MySQL CTAS)
            cols.append(ColumnInfo(cname, t_, collation=coll))
        schema = TableSchema(stmt.table.name, cols)
        if stmt.temporary:
            t = self.catalog.create_temp_table(
                stmt.table.schema or self.db, schema, stmt.if_not_exists)
        else:
            t = self.catalog.create_table(
                stmt.table.schema or self.db, schema, stmt.if_not_exists)
        if t is not None and t.schema is schema and rs.rows:
            def do(txn):
                for start in range(0, len(rs.rows), 4096):
                    t.insert_rows(rs.rows[start:start + 4096],
                                  begin_ts=txn.marker, log=txn.log_for(t))

            self._run_dml(do)
        # CTAS is DDL: implicit commit even under autocommit=0 (MySQL) —
        # _run_select may have opened a snapshot txn that would otherwise
        # hold the inserted rows provisional forever
        if self.txn is not None:
            self._commit()
        return None

    def _wire_check(self, t, name: str, e_ast, sql_text: str) -> None:
        """Bind + compile one CHECK constraint at DDL time (ref: the
        reference's CHECK enforcement in MySQL-8 mode). Uids are column
        names, so the stored evaluator is schema-stable. Dict-encoded
        string columns are refused: a plan-time LUT would bake in codes
        of the CREATE-time (empty) dictionary and go stale as it
        grows."""
        from tidb_tpu.expression.compiler import compile_expr
        from tidb_tpu.planner.binder import Binder, PlanCol, Scope
        from tidb_tpu.planner.rules import _refs
        from tidb_tpu.storage.table import CheckInfo

        dict_cols = {c.name for c in t.schema.columns
                     if c.type_.is_dict_encoded}
        # refuse string-column checks BEFORE binding: the binder's own
        # dictionary-context errors would otherwise mask this message
        named = {n.name.lower() for n in _ast_names(e_ast)}
        if named & {c.lower() for c in dict_cols}:
            raise UnsupportedError(
                "CHECK constraints over string columns are not supported "
                "(dictionary codes are not stable across inserts)")
        cols = [PlanCol(uid=c.name, name=c.name, type_=c.type_)
                for c in t.schema.columns]
        binder = Binder()
        bound = binder.to_bool(binder.bind_expr(e_ast, Scope(cols, None)))
        refs = sorted(_refs(bound))
        if any(c.name == name for c in t.checks):
            raise SchemaError(
                f"duplicate check constraint name {name!r}")
        t.checks.append(CheckInfo(name=name, pred=compile_expr(bound),
                                  cols=refs, sql=sql_text))

    def _wire_generated(self, t, colname: str, e_ast, sql_text: str,
                        stored: bool) -> None:
        """Bind + compile one generated column at DDL time (ref: MySQL
        GENERATED ALWAYS AS). Same machinery and restrictions as CHECK
        constraints: uids are column names; string source columns are
        refused (plan-time dictionary LUTs go stale); self-reference and
        reference to other generated columns are refused like MySQL's
        ordering rule (only columns earlier in the row)."""
        from tidb_tpu.expression.compiler import compile_expr
        from tidb_tpu.planner.binder import Binder, PlanCol, Scope
        from tidb_tpu.planner.rules import _refs
        from tidb_tpu.storage.table import GeneratedInfo

        dict_cols = {c.name for c in t.schema.columns
                     if c.type_.is_dict_encoded}
        named = {n.name.lower() for n in _ast_names(e_ast)}
        if named & {c.lower() for c in dict_cols}:
            raise UnsupportedError(
                "generated columns over string columns are not supported "
                "(dictionary codes are not stable across inserts)")
        gen_cols = {g.col.lower() for g in t.generated} | {colname.lower()}
        if named & gen_cols:
            raise UnsupportedError(
                "a generated column cannot reference itself or another "
                "generated column")
        if t.schema.col(colname).type_.is_dict_encoded:
            raise UnsupportedError(
                "string-typed generated columns are not supported "
                "(computed values cannot be dictionary-encoded at "
                "write time)")
        cols = [PlanCol(uid=c.name, name=c.name, type_=c.type_)
                for c in t.schema.columns]
        bound = Binder().bind_expr(e_ast, Scope(cols, None))
        t.generated.append(GeneratedInfo(
            col=colname, fn=compile_expr(bound), cols=sorted(_refs(bound)),
            sql=sql_text, stored=stored))

    def _run_insert(self, stmt: A.InsertStmt):
        table = self.catalog.table(stmt.table.schema or self.db, stmt.table.name)
        gen_cols = {g.col for g in table.generated}
        if stmt.columns and gen_cols & set(stmt.columns):
            bad = sorted(gen_cols & set(stmt.columns))[0]
            raise ExecutionError(
                f"column {bad!r} is a generated column: "
                "its value cannot be inserted")
        if stmt.select is not None:
            def do(txn):
                rs = self._run_select(stmt.select)
                rows = [list(r) for r in rs.rows]
                if stmt.replace:
                    self._replace_rows(table, rows, stmt.columns, txn)
                elif stmt.on_dup:
                    row_asts = [[_value_to_ast(v) for v in r] for r in rows]
                    self._upsert_rows(table, stmt.table.name, rows, row_asts,
                                      stmt.columns, stmt.on_dup, txn)
                else:
                    table.insert_rows(rows, columns=stmt.columns,
                                      begin_ts=txn.marker,
                                      log=txn.log_for(table))

            return self._run_dml(do)
        from tidb_tpu.planner.binder import Binder

        binder = Binder()
        rows = []
        names = stmt.columns or table.insertable_names()
        for r_ast in stmt.rows:
            if len(r_ast) != len(names):
                raise ExecutionError(
                    f"column count mismatch: {len(r_ast)} values for {len(names)} columns"
                )
            row = []
            for cell, cname in zip(r_ast, names):
                col = table.schema.col(cname)
                bound = self._bind_const(binder, cell, col)
                row.append(bound)
            rows.append(row)

        tname = stmt.table.name

        if stmt.replace:
            def do(txn):
                self._replace_rows(table, rows, stmt.columns, txn)

            return self._run_dml(do)

        if stmt.on_dup:
            def do(txn):
                self._upsert_rows(table, tname, rows, stmt.rows,
                                  stmt.columns, stmt.on_dup, txn)

            return self._run_dml(do)

        def do(txn):
            table.insert_rows(rows, columns=stmt.columns, begin_ts=txn.marker,
                              log=txn.log_for(table))

        return self._run_dml(do)

    # -- upsert machinery (ref: InsertExec's dup-key flows) ------------

    @staticmethod
    def _conflict_maps(table, marker):
        """One conflict map per enforced unique index (O(n) pass each);
        maintained incrementally across the statement's own mutations."""
        return {idx.name: (idx, table.conflict_map(idx, marker))
                for idx in table.indexes.values() if idx.unique}

    def _replace_rows(self, table, rows, columns, txn) -> None:
        """REPLACE: delete every live row any unique key collides with;
        a later VALUES row colliding with an earlier one of the same
        statement supersedes it (last row wins). One delete + one
        insert call per statement."""
        names = columns or table.schema.public_names()
        maps = self._conflict_maps(table, txn.marker)
        log = txn.log_for(table)
        pending: list = []
        dead: list = []
        for row in rows:
            vals = table.row_value_map(names, row)
            keys = [(idx, m, table.encode_index_key(idx, vals))
                    for idx, m in maps.values()]
            for _idx, m, key in keys:
                if key is None:
                    continue
                hit = m.pop(key, None)
                if hit is None:
                    continue
                if isinstance(hit, tuple):  # pending row of this statement
                    pending[hit[1]] = None
                elif hit not in dead:
                    dead.append(hit)
            pi = len(pending)
            pending.append(list(row))
            for _idx, m, key in keys:
                if key is not None:
                    m[key] = ("p", pi)
        if dead:
            table.delete_rows(np.array(dead, dtype=np.int64),
                              end_ts=txn.marker, marker=txn.marker, log=log,
                              log_for=txn.log_for)
        live = [r for r in pending if r is not None]
        if live:
            table.insert_rows(live, columns=columns, begin_ts=txn.marker,
                              log=log)

    def _upsert_rows(self, table, tname, rows, row_asts, columns,
                     assignments, txn) -> None:
        """INSERT ... ON DUPLICATE KEY UPDATE: conflicting rows are
        updated (VALUES(col) refers to the would-be-inserted value),
        fresh rows insert."""
        from tidb_tpu.planner.binder import Binder

        binder = Binder()
        names = columns or table.schema.public_names()
        maps = self._conflict_maps(table, txn.marker)
        log = txn.log_for(table)
        for row, r_ast in zip(rows, row_asts):
            vals = table.row_value_map(names, row)
            hit = None
            for idx, m in maps.values():
                key = table.encode_index_key(idx, vals)
                if key is not None and key in m:
                    hit = m[key]
                    break
            if hit is None:
                table.insert_rows([row], columns=columns,
                                  begin_ts=txn.marker, log=log)
                new_id = table.n - 1
                for idx, m in maps.values():
                    key = table.encode_index_key(idx, vals)
                    if key is not None:
                        m[key] = new_id
                continue
            ids = np.array([hit], dtype=np.int64)
            cellmap = dict(zip(names, r_ast))
            # VALUES(col) over an omitted column yields its default
            # (consistent with row_value_map's conflict detection)
            for c in table.schema.columns:
                if c.name not in cellmap and c.default is not None \
                        and not c.auto_increment:
                    cellmap[c.name] = _value_to_ast(c.default)
            updates = {}
            for name_ast, val_ast in assignments:
                col = table.schema.col(name_ast.name)
                val_ast2 = _sub_values_refs(val_ast, cellmap)
                if not _ast_has_name(val_ast2):
                    v = self._bind_const(binder, val_ast2, col)
                    updates[col.name] = [v]
                else:
                    updates[col.name] = self._eval_update_expr(
                        table, tname, val_ast2, ids, col)
            table.update_rows(ids, updates, begin_ts=txn.marker,
                              end_ts=txn.marker, marker=txn.marker, log=log,
                              log_for=txn.log_for)
            # the update superseded `hit` with a new version: refresh
            # EVERY index's mapping (assignments may change key columns;
            # a later VALUES row hitting the stale id would silently
            # no-op against the dead version)
            new_id = table.n - 1
            for idx, m in maps.values():
                old_key = table.index_key_at(idx, hit)
                if old_key is not None and m.get(old_key) == hit:
                    del m[old_key]
                nk = table.index_key_at(idx, new_id)
                if nk is not None:
                    m[nk] = new_id

    def _bind_const(self, binder, cell_ast, col: ColumnInfo):
        """Evaluate a constant INSERT/UPDATE value to a python value in the
        table's logical form."""
        from tidb_tpu.planner.binder import Scope
        from tidb_tpu.planner.rules import fold_constants
        from tidb_tpu.types import TypeKind, days_to_date, micros_to_datetime

        bound = binder.bind_expr(cell_ast, Scope([], None))
        bound = binder.coerce_untyped_literal(bound, col.type_)
        bound = fold_constants(bound)
        from tidb_tpu.expression.expr import Literal

        if not isinstance(bound, Literal):
            raise UnsupportedError("non-constant INSERT value")
        if bound.value is None:
            return None
        k = col.type_.kind
        v = bound.value
        if k == TypeKind.DATE:
            if bound.type_.kind == TypeKind.DATE:
                return days_to_date(v)
            return v
        if k == TypeKind.DATETIME:
            if bound.type_.kind == TypeKind.DATETIME:
                return micros_to_datetime(v)
            return v
        if k == TypeKind.DECIMAL:
            if bound.type_.kind == TypeKind.DECIMAL:
                import decimal as _dec

                # exact descale: float division corrupts 16+-digit decimals
                return _dec.Decimal(v).scaleb(-bound.type_.scale)
            return v
        if k == TypeKind.TIME:
            if bound.type_.kind == TypeKind.TIME:
                # timedelta is TIME's logical form (as date is DATE's);
                # to_device_value reads a bare int as HHMMSS, not micros
                import datetime as _dt

                return _dt.timedelta(microseconds=v)
            return v
        if k == TypeKind.ENUM:
            if bound.type_.kind == TypeKind.ENUM:
                if v == 0:  # coercion's no-match sentinel: invalid on insert
                    raise ExecutionError(
                        f"invalid ENUM value for column {col.name!r}")
                return int(v)  # 1-based index
            return v
        if k == TypeKind.SET:
            if bound.type_.kind == TypeKind.SET:
                if v < 0:
                    raise ExecutionError(
                        f"invalid SET value for column {col.name!r}")
                return int(v)  # bitmask
            return v
        if bound.type_.kind == TypeKind.DECIMAL:
            # decimal literal into a non-decimal column: leave the
            # scaled-int representation (1.5 is Literal(15, scale=1))
            if k == TypeKind.STRING:
                from tidb_tpu.types import scaled_to_decimal_str

                return scaled_to_decimal_str(v, bound.type_.scale)
            return v / (10 ** bound.type_.scale)
        if k == TypeKind.STRING:
            return str(v)
        return v

    def _rows_matching(self, table, where, table_name: str) -> np.ndarray:
        """Row ids (physical) matching a WHERE clause — shared by
        UPDATE/DELETE. Runs a scan plan over the table with a hidden row id."""
        sel = A.SelectStmt(
            items=[A.SelectItem(A.EFunc("__row_id__", []))],
            from_=A.TableName(table_name),
            where=where,
        )
        # plan manually: scan + filter, materialize row ids
        from tidb_tpu.planner.binder import Binder, PlanCol, Scope
        from tidb_tpu.planner.logical import BuildContext, build_select
        from tidb_tpu.types import INT64

        # simpler: evaluate the predicate via a SELECT of the pk/rowid using
        # a dedicated scan executor
        from tidb_tpu.executor.scan import TableScanExec
        from tidb_tpu.expression.compiler import compile_predicate

        binder = Binder()
        cols = [
            PlanCol(
                uid=binder.new_uid(f"{table_name}.{c.name}"),
                name=c.name, type_=c.type_, qualifier=table_name,
                dict_=table.dicts.get(c.name),
            )
            for c in table.schema.columns
        ]
        scope = Scope(cols, None)
        stages = []
        if where is not None:
            cond = binder.bind_expr(where, scope)
            from tidb_tpu.planner.rules import fold_constants

            stages.append(("filter", fold_constants(cond)))
        # the scan's __rowid__ pseudo-column carries each row's TRUE
        # physical id. Reconstructing ids from chunk position (live +
        # running chunk_capacity) is wrong under the columnar store:
        # segment chunks size to the segment (not chunk_capacity) and
        # zone pruning skips ranges, so positional math deletes/updates
        # the wrong rows or misses delta rows entirely.
        rid = PlanCol(uid=binder.new_uid(f"{table_name}.__rowid__"),
                      name="__rowid__", type_=INT64, qualifier=table_name)
        scan = TableScanExec(schema=cols + [rid], table=table, stages=stages)
        ctx = self._exec_ctx()
        scan.open(ctx)
        ids = []
        try:
            while True:
                ch = scan.next()
                if ch is None:
                    break
                live = np.nonzero(np.asarray(ch.sel))[0]
                ids.append(np.asarray(ch.col(rid.uid).data)[live])
        finally:
            scan.close()
        return (np.concatenate(ids).astype(np.int64)
                if ids else np.zeros(0, dtype=np.int64))

    def _multi_table_targets(self, stmt) -> List[A.TableName]:
        """All base tables in a multi-table DML's table-refs tree."""
        out = []

        def visit(src):
            if isinstance(src, A.TableName):
                out.append(src)
            elif isinstance(src, A.Join):
                visit(src.left)
                visit(src.right)

        visit(stmt.from_)
        return out

    def _multi_dml_rowids(self, stmt, target: A.TableName,
                          val_asts=()) -> tuple:
        """Run the multi-table DML's join as a real SELECT of the
        target's hidden __rowid__ (+ SET value expressions), dedup by
        rowid keeping the first match (MySQL: a row matching multiple
        times is updated once)."""
        alias = target.alias or target.name
        items = [A.SelectItem(A.EName("__rowid__", qualifier=alias),
                              alias="__rid")]
        for i, v in enumerate(val_asts):
            items.append(A.SelectItem(v, alias=f"__v{i}"))
        sel = A.SelectStmt(items=items, from_=stmt.from_, where=stmt.where)
        rs = self._run_select(sel)
        seen = set()
        ids, vals = [], []
        for row in rs.rows:
            rid = row[0]
            # outer joins NULL-pad the target side; those rows have no
            # target row to touch (MySQL: unmatched rows are untouched)
            if rid is None or rid in seen:
                continue
            seen.add(rid)
            ids.append(rid)
            vals.append(row[1:])
        return np.array(ids, dtype=np.int64), vals

    def _precheck_outfile(self, into) -> None:
        """OUTFILE refusals run BEFORE the query: a non-SUPER user or a
        pre-existing target must not pay for the whole scan first."""
        import os

        self._priv("super")  # server-side file write (FILE analogue)
        if len(into.fields_term) != 1 or (
                into.enclosed is not None and len(into.enclosed) != 1):
            raise UnsupportedError(
                "FIELDS TERMINATED/ENCLOSED BY must be one character")
        if os.path.exists(into.path):
            raise ExecutionError(f"file {into.path!r} already exists")

    def _write_outfile(self, rs: ResultSet, into) -> ResultSet:
        """SELECT ... INTO OUTFILE: the LOAD DATA-compatible export pair
        (round-trips through _split_load_fields). mode='x' keeps the
        no-overwrite guarantee atomic under concurrent exporters."""
        delim, quote = into.fields_term, into.enclosed

        def field_text(v):
            if v is None:
                return "\\N"
            # control chars escape FIRST (line framing is \n; a tab
            # delim is covered by the \t mapping), then the delimiter
            s = (str(v).replace("\\", "\\\\").replace("\n", "\\n")
                 .replace("\t", "\\t").replace("\r", "\\r"))
            if quote:
                return quote + s.replace(quote, quote + quote) + quote
            if delim not in ("\t", "\n", "\r"):
                s = s.replace(delim, "\\" + delim)
            return s

        with open(into.path, "x", newline="") as f:
            for row in rs.rows:
                f.write(delim.join(field_text(v) for v in row))
                f.write(into.lines_term)
        return ResultSet(names=["rows"], rows=[(len(rs.rows),)],
                         types=[TypeKind.INT])

    def _run_load_data(self, stmt: A.LoadDataStmt):
        """LOAD DATA INFILE: streamed ingest in txn'd batches (ref:
        executor/load_data). Server-side reads gate on SUPER — the FILE
        privilege analogue; LOCAL (the caller supplies its own file, as
        in MySQL) needs only INSERT. MySQL field semantics via
        _split_load_fields: backslash escapes (\\t \\n \\\\ and escaped
        delimiters), the \\N NULL sentinel, optional enclosure with
        doubled or escaped quotes; empty fields are NULL for non-string
        columns and '' for strings; IGNORE n LINES skips headers."""
        db = stmt.table.schema or self.db
        self._priv("insert", db, stmt.table.name)
        if not stmt.local:
            self._priv("super")  # server-side file access (FILE analogue)
        table = self.catalog.table(db, stmt.table.name)
        if stmt.lines_term not in ("\n", "\r\n"):
            raise UnsupportedError("LINES TERMINATED BY must be \\n or \\r\\n")
        if len(stmt.fields_term) != 1 or (
                stmt.enclosed is not None and len(stmt.enclosed) != 1):
            raise UnsupportedError(
                "FIELDS TERMINATED/ENCLOSED BY must be one character")
        names = stmt.columns or table.schema.public_names()
        cols = [table.schema.col(n) for n in names]
        str_col = [c.type_.kind in (TypeKind.STRING, TypeKind.JSON)
                   for c in cols]
        bool_col = [c.type_.kind == TypeKind.BOOL for c in cols]

        def convert(row):
            out = []
            for j in range(len(cols)):
                raw = row[j] if j < len(row) else None
                if raw is None or (raw == "" and not str_col[j]):
                    out.append(None)
                elif bool_col[j]:
                    # raw text reaches to_device_value, whose bool(v)
                    # would make the STRING "0" truthy
                    out.append(raw.strip().lower() not in ("0", "false", ""))
                else:
                    out.append(raw)
            return out

        total = [0]
        resume_pos = [None]  # retry resumes AFTER already-staged batches

        def do(txn):
            with open(stmt.path, newline="") as f:
                if resume_pos[0] is not None:
                    # a WriteConflict retry re-enters with the earlier
                    # batches already provisionally inserted under this
                    # txn marker (a failing insert leaves the table
                    # untouched) — continue from the saved offset
                    f.seek(resume_pos[0])
                else:
                    for _ in range(stmt.ignore_lines):
                        f.readline()
                batch = []
                for line in f:
                    line = line.rstrip("\r\n")
                    batch.append(convert(_split_load_fields(
                        line, stmt.fields_term, stmt.enclosed)))
                    if len(batch) >= 4096:
                        total[0] += table.insert_rows(
                            batch, columns=names, begin_ts=txn.marker,
                            log=txn.log_for(table))
                        resume_pos[0] = f.tell()
                        batch = []
                if batch:
                    total[0] += table.insert_rows(
                        batch, columns=names, begin_ts=txn.marker,
                        log=txn.log_for(table))
                    resume_pos[0] = f.tell()

        self._run_dml(do)
        return ResultSet(names=["rows"], rows=[(total[0],)],
                         types=[TypeKind.INT])

    def _run_update(self, stmt: A.UpdateStmt):
        if stmt.from_ is not None:
            return self._run_update_multi(stmt)
        table = self.catalog.table(stmt.table.schema or self.db, stmt.table.name)

        def do(txn):
            ids = self._rows_matching(table, stmt.where, stmt.table.name)
            if len(ids) == 0:
                return
            from tidb_tpu.planner.binder import Binder

            binder = Binder()
            updates = {}
            gen_cols = {g.col for g in table.generated}
            for name_ast, val_ast in stmt.sets:
                col = table.schema.col(name_ast.name)
                if col.name in gen_cols:
                    raise ExecutionError(
                        f"column {col.name!r} is a generated column: "
                        "its value cannot be set")
                has_refs = _ast_has_name(val_ast)
                if not has_refs:
                    v = self._bind_const(binder, val_ast, col)
                    updates[col.name] = [v] * len(ids)
                else:
                    # expression over current row values: evaluate via scan
                    vals = self._eval_update_expr(table, stmt.table.name, val_ast, ids, col)
                    updates[col.name] = vals
            table.update_rows(ids, updates, begin_ts=txn.marker,
                              end_ts=txn.marker, marker=txn.marker,
                              log=txn.log_for(table), log_for=txn.log_for)

        return self._run_dml(do)

    def _run_update_multi(self, stmt: A.UpdateStmt):
        """UPDATE t1 JOIN t2 ... SET t1.c = expr [WHERE ...]: the join
        runs as a real SELECT of t1's hidden rowid + the SET values
        (evaluated in full join context — expressions may reference any
        joined table), then the target applies a plain MVCC update."""
        refs = self._multi_table_targets(stmt)
        by_alias = {(t.alias or t.name).lower(): t for t in refs}
        quals = {q.lower() for q, _ in
                 ((n.qualifier, n) for n, _ in stmt.sets) if q}
        if len(quals) > 1:
            raise UnsupportedError(
                "multi-table UPDATE touching several target tables")
        if quals:
            target = by_alias.get(next(iter(quals)))
            if target is None:
                raise PlanError(f"unknown table {next(iter(quals))!r} in SET")
        else:
            # unqualified SET columns: the owning table must be unique
            owners = set()
            for name_ast, _ in stmt.sets:
                for t in refs:
                    tab = self.catalog.table(t.schema or self.db, t.name)
                    if any(c.name == name_ast.name
                           for c in tab.schema.columns):
                        owners.add((t.alias or t.name).lower())
            if len(owners) != 1:
                raise PlanError(
                    "SET columns must name their table in a multi-table "
                    "UPDATE")
            target = by_alias[next(iter(owners))]
        table = self.catalog.table(target.schema or self.db, target.name)
        self._priv("update", target.schema or self.db, target.name)

        def do(txn):
            ids, vals = self._multi_dml_rowids(
                stmt, target, [v for _, v in stmt.sets])
            if len(ids) == 0:
                return
            updates = {}
            for j, (name_ast, _) in enumerate(stmt.sets):
                col = table.schema.col(name_ast.name)
                updates[col.name] = [v[j] for v in vals]
            table.update_rows(ids, updates, begin_ts=txn.marker,
                              end_ts=txn.marker, marker=txn.marker,
                              log=txn.log_for(table), log_for=txn.log_for)

        return self._run_dml(do)

    def _eval_update_expr(self, table, table_name, val_ast, ids, col: ColumnInfo):
        from tidb_tpu.executor.scan import TableScanExec
        from tidb_tpu.planner.binder import Binder, PlanCol, Scope
        from tidb_tpu.types import TypeKind, days_to_date, micros_to_datetime

        binder = Binder()
        cols = [
            PlanCol(
                uid=binder.new_uid(f"{table_name}.{c.name}"),
                name=c.name, type_=c.type_, qualifier=table_name,
                dict_=table.dicts.get(c.name),
            )
            for c in table.schema.columns
        ]
        scope = Scope(cols, None)
        bound = binder.bind_expr(val_ast, scope)
        out_uid = "__upd__"
        scan = TableScanExec(
            schema=cols, table=table,
            stages=[("project", [(out_uid, bound)])],
        )
        ctx = self._exec_ctx()
        scan.open(ctx)
        datas, valids = [], []
        try:
            while True:
                ch = scan.next()
                if ch is None:
                    break
                c = ch.columns[out_uid]
                datas.append(np.asarray(c.data))
                valids.append(np.asarray(c.valid))
        finally:
            scan.close()
        data = np.concatenate(datas)[ids]
        valid = np.concatenate(valids)[ids]
        k = col.type_.kind
        if k == TypeKind.STRING:
            # string exprs evaluate to dictionary codes; decode host-side
            # (update_rows re-encodes into the column's own dictionary)
            d = getattr(bound, "_dict", None)
            if d is None:
                raise UnsupportedError(
                    "UPDATE string expression without a dictionary context")
            return d.decode(data, valid)
        out = []
        for d, v in zip(data, valid):
            if not v:
                out.append(None)
            elif k == TypeKind.DATE:
                out.append(days_to_date(int(d)))
            elif k == TypeKind.DATETIME:
                out.append(micros_to_datetime(int(d)))
            elif k == TypeKind.DECIMAL:
                src_scale = bound.type_.scale if bound.type_.kind == TypeKind.DECIMAL else 0
                out.append(int(d) / (10 ** src_scale) if src_scale else int(d))
            else:
                out.append(d.item())
        return out

    def _run_delete(self, stmt: A.DeleteStmt):
        if stmt.from_ is not None:
            # DELETE t FROM <refs> / DELETE FROM t USING <refs>: rows to
            # delete come from the join (dedup'd target rowids). The
            # DELETE target names a table OR its alias in the refs.
            refs = self._multi_table_targets(stmt)
            want = (stmt.table.alias or stmt.table.name).lower()
            target = next(
                (t for t in refs
                 if (t.alias or t.name).lower() == want
                 or t.name.lower() == want), None)
            if target is None:
                raise PlanError(
                    f"DELETE target {stmt.table.name!r} is not in the "
                    "table references")
            table = self.catalog.table(target.schema or self.db, target.name)
            self._priv("delete", target.schema or self.db, target.name)

            def do(txn):
                ids, _ = self._multi_dml_rowids(stmt, target)
                if len(ids):
                    table.delete_rows(ids, end_ts=txn.marker,
                                      marker=txn.marker,
                                      log=txn.log_for(table), log_for=txn.log_for)

            return self._run_dml(do)

        table = self.catalog.table(stmt.table.schema or self.db, stmt.table.name)

        def do(txn):
            ids = self._rows_matching(table, stmt.where, stmt.table.name)
            table.delete_rows(ids, end_ts=txn.marker, marker=txn.marker,
                              log=txn.log_for(table), log_for=txn.log_for)

        return self._run_dml(do)

    # ------------------------------------------------------------------

    def _run_explain(self, stmt: A.ExplainStmt):
        target = stmt.stmt
        if not isinstance(target, (A.SelectStmt, A.UnionStmt)):
            raise UnsupportedError("EXPLAIN only supports SELECT")
        target = self._apply_binding(target)  # EXPLAIN shows the bound plan
        phys = self._plan_select(target)
        # MySQL requires the same privileges for EXPLAIN as for the
        # statement itself; ANALYZE even executes it
        self._check_plan_privs(phys)
        if stmt.analyze:
            from tidb_tpu.utils import dispatch as _dsp
            from tidb_tpu.utils.execdetails import analyze_text, instrument

            root = self._build_root(phys)
            instrument(root)
            # resource profile (ISSUE 16): deltas of the thread-local
            # host-side counters around the execution — no new syncs
            from tidb_tpu.columnar.store import compact_counts as _cmp

            p0 = (_dsp.xfer_bytes(), _dsp.compile_seconds(),
                  _dsp.spill_bytes())
            cw0 = _cmp()
            run_plan(root, self._exec_ctx(plan=phys))  # execute; rows discarded
            text = analyze_text(root)
            mem_max = max((t.max_consumed for t in self._stmt_trackers),
                          default=0)
            text += ("\nprofile: mem_max=%d xfer_bytes=%d compile_ms=%.1f"
                     " spill_bytes=%d compaction_wait_ms=%.1f"
                     % (mem_max, _dsp.xfer_bytes() - p0[0],
                        (_dsp.compile_seconds() - p0[1]) * 1e3,
                        _dsp.spill_bytes() - p0[2],
                        (_cmp()[0] - cw0[0]) * 1e3))
            return ResultSet(names=["EXPLAIN ANALYZE"],
                             rows=[(line,) for line in text.split("\n")])
        text = explain_text(phys)
        return ResultSet(names=["EXPLAIN"], rows=[(line,) for line in text.split("\n")])

    def _run_trace(self, stmt: A.TraceStmt):
        """TRACE <select>: execute under the statement's (always-on)
        trace and render ITS span tree — one tracer serves TRACE, the
        slow log, /trace, and information_schema.cluster_trace (ref:
        util/tracing; the bespoke TRACE-only span code died with the
        tail-sampling tentpole). Fragment dispatches, DCN worker spans,
        and recompile annotations all appear because they record into
        the same trace the statement already carries."""
        target = stmt.stmt
        if not isinstance(target, (A.SelectStmt, A.UnionStmt)):
            raise UnsupportedError("TRACE only supports SELECT")
        from tidb_tpu.utils import tracing
        from tidb_tpu.utils.execdetails import instrument

        if self.txn is None and not self.sysvars.get("autocommit"):
            self._begin()  # same consistent-snapshot rule as _run_select
        tracing.keep("trace")  # the trace IS the output: always retain
        tr = tracing.current()
        with tracing.span("session.plan"):
            phys = self._plan_select(target)
            self._check_plan_privs(phys)  # TRACE executes the statement
        with tracing.span("session.build_executor"):
            root = self._build_root(phys)
            instrument(root)
        with tracing.span("session.execute") as exec_span:
            run_plan(root, self._exec_ctx(plan=phys))
        if tr is not None and exec_span is not None:
            self._graft_operator_spans(tr, exec_span, root)
        return ResultSet(names=["span", "start_ms", "duration_ms"],
                         rows=self._trace_rows(tr))

    @staticmethod
    def _graft_operator_spans(tr, exec_span, root) -> None:
        """Per-operator spans from the EXPLAIN ANALYZE instrumentation:
        start = the operator's first open/next activity, duration = its
        cumulative open+next wall (operators interleave per chunk, so
        the span is a coverage envelope, not one contiguous interval)."""
        def visit(e, parent_id):
            st = e.stats
            t0 = (st.first_ts if st.first_ts is not None
                  else tr.t0_perf + exec_span.start_us / 1e6)
            notes = [f"rows={st.rows}", f"loops={st.chunks}",
                     f"dispatches={st.dispatches}"]
            if st.recompiles:
                notes.append(f"recompiles={st.recompiles}")
            s = tr.add_complete("executor." + type(e).__name__, t0,
                                st.open_wall + st.next_wall,
                                parent_id=parent_id, notes=notes)
            pid = s.span_id if s.span_id > 0 else parent_id
            for c in e.children:
                visit(c, pid)

        visit(root, exec_span.span_id)

    @staticmethod
    def _trace_rows(tr) -> list:
        """Render the current statement span's subtree as the TRACE
        result rows: (indented name, start_ms offset, duration_ms)."""
        from tidb_tpu.utils import tracing

        if tr is None:
            return []
        base_id = tracing.current_span_id()
        spans = list(tr.spans)
        base = next((s for s in spans if s.span_id == base_id), None)
        base_start = base.start_us if base is not None else 0
        children: dict = {}
        for s in spans:
            children.setdefault(s.parent_id, []).append(s)
        rows: list = []

        def visit(s, depth):
            rows.append(("  " * depth + s.name,
                         round((s.start_us - base_start) / 1e3, 3),
                         round(max(s.dur_us, 0) / 1e3, 3)))
            for c in sorted(children.get(s.span_id, ()),
                            key=lambda x: x.start_us):
                visit(c, depth + 1)

        for c in sorted(children.get(base_id, ()), key=lambda x: x.start_us):
            visit(c, 0)
        return rows

    @staticmethod
    def _like_filter(rows, like: Optional[str], col: int = 0):
        if like is None:
            return rows
        import re

        pat = re.escape(like).replace("%", ".*").replace("_", ".")
        rx = re.compile(f"^{pat}$", re.IGNORECASE)
        return [r for r in rows if rx.match(str(r[col]))]

    def _run_show(self, stmt: A.ShowStmt):
        if stmt.kind == "grants":
            user = stmt.target or self.user
            if user != self.user:
                self._priv("super")
            if user not in self.catalog.users:
                raise ExecutionError(f"no user {user!r}")
            rows = [(g,) for g in self.catalog.privileges.grants_for(user)]
            return ResultSet(names=[f"Grants for {user}"], rows=rows)
        if stmt.kind == "processlist":
            # shared builder: privilege filtering (non-SUPER users see
            # their own threads only) lives in ONE place with the
            # information_schema.processlist path
            rows = self.catalog.processlist_rows(
                viewer_user=self.user, with_state=True)
            return ResultSet(
                names=["Id", "User", "Host", "db", "Command", "Time",
                       "State", "Info"], rows=rows)
        if stmt.kind == "warnings":
            return ResultSet(names=["Level", "Code", "Message"],
                             rows=list(self._warnings))
        if stmt.kind == "databases":
            rows = [(n,) for n in sorted(self.catalog.databases)]
            return ResultSet(names=["Database"], rows=self._like_filter(rows, stmt.like))
        if stmt.kind == "tables":
            names = set(self.catalog.tables(self.db))
            names.update(self.catalog.database(self.db).views)
            rows = [(n,) for n in sorted(names)]  # MySQL lists views too
            return ResultSet(names=[f"Tables_in_{self.db}"], rows=self._like_filter(rows, stmt.like))
        if stmt.kind == "columns":
            t = self.catalog.table(self.db, stmt.target)
            rows = [
                (c.name, str(c.type_), "NO" if c.not_null else "YES")
                for c in t.schema.public_columns()
            ]
            return ResultSet(names=["Field", "Type", "Null"], rows=rows)
        if stmt.kind == "index":
            t = self.catalog.table(self.db, stmt.target)
            rows = []
            for idx in t.indexes.values():
                if idx.state != "public":
                    continue  # staged online-DDL index: not visible yet
                for seq, col in enumerate(idx.columns, 1):
                    rows.append((stmt.target, 0 if idx.unique else 1,
                                 idx.name, seq, col))
            return ResultSet(
                names=["Table", "Non_unique", "Key_name", "Seq_in_index",
                       "Column_name"],
                rows=rows)
        if stmt.kind == "create_table":
            # privilege BEFORE the lookup: an unprivileged probe must not
            # learn which table names exist
            self._priv("select", self.db, stmt.target)
            t = self.catalog.table(self.db, stmt.target)
            kindmap = {"int": "bigint", "float": "double",
                       "string": "varchar(255)", "bool": "tinyint(1)"}
            lines = []
            for c in t.schema.public_columns():
                ty = c.type_text or kindmap.get(str(c.type_), str(c.type_))
                parts = [f"  `{c.name}` {ty}"]
                if c.type_.is_dict_encoded and c.collation is not None:
                    # a non-default collation round-trips (the default,
                    # utf8mb4_general_ci, is implied like MySQL's)
                    parts.append(f"COLLATE {c.collation}")
                if c.not_null:
                    parts.append("NOT NULL")
                if c.auto_increment:
                    parts.append("AUTO_INCREMENT")
                if c.default is not None:
                    dv = str(c.default).replace("\\", "\\\\")
                    dv = dv.replace("'", "''")
                    parts.append(f"DEFAULT '{dv}'")
                lines.append(" ".join(parts))
            if t.schema.primary_key:
                keys = ", ".join(f"`{k}`" for k in t.schema.primary_key)
                lines.append(f"  PRIMARY KEY ({keys})")
            for name, ix in t.indexes.items():
                if name == "PRIMARY" or ix.state != "public":
                    continue
                keys = ", ".join(f"`{k}`" for k in ix.columns)
                kw = "UNIQUE KEY" if ix.unique else "KEY"
                lines.append(f"  {kw} `{name}` ({keys})")
            for fk in t.foreign_keys:
                cols = ", ".join(f"`{c}`" for c in fk.columns)
                pcols = ", ".join(f"`{c}`" for c in fk.parent_cols)
                line = (f"  FOREIGN KEY ({cols}) REFERENCES "
                        f"`{fk.parent.schema.name}` ({pcols})")
                for clause, act in (("ON DELETE", fk.on_delete),
                                    ("ON UPDATE", fk.on_update)):
                    if act != "restrict":
                        line += f" {clause} {act.replace('_', ' ').upper()}"
                lines.append(line)
            for chk in getattr(t, "checks", ()):
                lines.append(
                    f"  CONSTRAINT `{chk.name}` CHECK ({chk.sql})")
            ddl = (f"CREATE TABLE `{stmt.target}` (\n"
                   + ",\n".join(lines)
                   + f"\n) ENGINE={t.engine}")
            pi = t.schema.partition
            if pi is not None:
                if pi.kind == "hash":
                    ddl += (f"\nPARTITION BY HASH (`{pi.column}`) "
                            f"PARTITIONS {pi.n_parts}")
                else:
                    parts = ", ".join(
                        f"PARTITION `{n}` VALUES LESS THAN "
                        + ("MAXVALUE" if u is None else f"({u})")
                        for n, u in zip(pi.names, pi.uppers))
                    ddl += (f"\nPARTITION BY RANGE (`{pi.column}`) "
                            f"({parts})")
            if t.schema.cluster_by:
                ddl += f"\nCLUSTER BY (`{t.schema.cluster_by}`)"
            return ResultSet(names=["Table", "Create Table"],
                             rows=[(stmt.target, ddl)])
        if stmt.kind == "create_view":
            v = self.catalog.view(self.db, stmt.target)
            if v is None:
                raise SchemaError(f"no view {self.db}.{stmt.target}")
            vcols, _ast, sql = v
            collist = f" ({', '.join(vcols)})" if vcols else ""
            return ResultSet(
                names=["View", "Create View"],
                rows=[(stmt.target,
                       f"CREATE VIEW `{stmt.target}`{collist} AS {sql}")])
        if stmt.kind == "bindings":
            rows = self._bindings.rows() + self.catalog.bind_handle.rows()
            return ResultSet(
                names=["Original_sql", "Bind_sql", "Scope", "Status"], rows=rows)
        if stmt.kind == "plugins":
            return ResultSet(
                names=["Name", "Status", "Type", "Library", "Version"],
                rows=self.catalog.plugins.rows())
        if stmt.kind == "variables":
            from tidb_tpu.session.sysvars import display

            rows = sorted((k, display(v)) for k, v in self.sysvars.all_effective().items())
            return ResultSet(names=["Variable_name", "Value"],
                             rows=self._like_filter(rows, stmt.like))
        raise UnsupportedError(f"SHOW {stmt.kind}")


def _ast_contains(e, cls) -> bool:
    """Whether any node of type `cls` occurs in an AST (tuples in lists
    included — e.g. SET assignments, CASE whens)."""
    if isinstance(e, cls):
        return True
    if not hasattr(e, "__dataclass_fields__"):
        return False
    for f in e.__dataclass_fields__:
        v = getattr(e, f)
        if isinstance(v, list):
            for x in v:
                if isinstance(x, tuple):
                    if any(_ast_contains(y, cls) for y in x if hasattr(y, "__dataclass_fields__")):
                        return True
                elif hasattr(x, "__dataclass_fields__") and _ast_contains(x, cls):
                    return True
        elif hasattr(v, "__dataclass_fields__") and _ast_contains(v, cls):
            return True
    return False


def _value_to_ast(v):
    """Python logical value -> literal AST (SELECT-sourced upserts,
    VALUES() over defaulted columns)."""
    import datetime
    import decimal

    if v is None:
        return A.ENull()
    if isinstance(v, bool):
        return A.EBool(v)
    if isinstance(v, (int, float, decimal.Decimal)):
        return A.ENum(str(v))
    return A.EStr(str(v))


def _sub_values_refs(e, cellmap):
    """ON DUPLICATE KEY UPDATE: VALUES(col) -> that row's insert value."""
    def fn(x):
        if (isinstance(x, A.EFunc) and x.name == "values"
                and len(x.args) == 1 and isinstance(x.args[0], A.EName)):
            n = x.args[0].name
            if n not in cellmap:
                raise PlanError(f"VALUES({n}) refers to a column not inserted")
            return cellmap[n]
        return x

    return _ast_transform(e, fn)


def _parse_quota(arg: str):
    """MEMORY_QUOTA hint argument: plain bytes, or 'N MB' / 'N GB'
    (TiDB's documented unit forms). None = unparseable, ignore."""
    parts = str(arg).strip().split()
    try:
        n = int(parts[0])
    except (ValueError, IndexError):
        return None
    unit = parts[1].upper() if len(parts) > 1 else ""
    mult = {"": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30}.get(unit)
    return n * mult if mult is not None else None


def _ast_has_name(e) -> bool:
    return _ast_contains(e, A.EName)


def _ast_transform(e, fn):
    """Rebuild an AST applying fn to every dataclass node (pre-order);
    fn returning a new node stops recursion into it. Containers (lists,
    tuples, nested lists — e.g. InsertStmt.rows) recurse structurally."""
    def walk(v):
        if hasattr(v, "__dataclass_fields__"):
            return _ast_transform(v, fn)
        if isinstance(v, list):
            return [walk(x) for x in v]
        if isinstance(v, tuple):
            return tuple(walk(x) for x in v)
        return v

    r = fn(e)
    if r is not e:
        return r
    if not hasattr(e, "__dataclass_fields__"):
        return e
    return type(e)(**{f: walk(getattr(e, f)) for f in e.__dataclass_fields__})


def _count_params(stmt) -> int:
    n = 0
    stack = [stmt]
    while stack:
        e = stack.pop()
        if isinstance(e, A.EParam):
            n = max(n, e.index + 1)
        elif isinstance(e, (list, tuple)):
            stack.extend(e)
        elif hasattr(e, "__dataclass_fields__"):
            stack.extend(getattr(e, f) for f in e.__dataclass_fields__)
    return n


def _param_literal(v):
    """Bound parameter value -> literal AST node (typed contexts coerce
    strings the same way quoted literals coerce)."""
    import datetime

    if v is None:
        return A.ENull()
    if isinstance(v, bool):
        return A.ENum("1" if v else "0")
    if isinstance(v, int):
        return A.ENum(str(v))
    if isinstance(v, float):
        return A.ENum(repr(v))
    if isinstance(v, bytes):
        return A.EStr(v.decode("utf-8", "replace"))
    if isinstance(v, datetime.datetime):
        return A.EStr(v.isoformat(sep=" "))
    if isinstance(v, datetime.date):
        return A.EStr(v.isoformat())
    return A.EStr(str(v))


def _sub_params(stmt, params):
    return _ast_transform(
        stmt, lambda e: _param_literal(params[e.index]) if isinstance(e, A.EParam) else e
    )
