"""Plan feedback: per-digest est-vs-actual capture and the first
runtime-truth planner decisions (ref: TiDB's statement summary + SQL
plan management loop — record what a plan actually did, use it the next
time the same statement is planned).

The engine produces accurate runtime facts everywhere (exact NDV zone
maps, per-operator EXPLAIN ANALYZE actuals, per-probe-chunk match
totals); until this module the planner consumed only heuristics
(``planner/physical.py``'s 1/NDV selectivities, ``est_rows`` never
compared against reality). The store closes that loop:

  * ``Session._execute_timed`` harvests, at statement end, the
    per-operator est-vs-actual row counts from ``RuntimeStats``
    (``executor/base.py``): actuals come free where the engine already
    knows them host-side (join match totals, aggregate group counts,
    the materialized root) and exactly under EXPLAIN ANALYZE / TRACE
    instrumentation — never from a new per-chunk device sync.
  * Observations fold into a process-global, capacity-bounded store
    keyed by (statement digest, plan identity), invalidated on
    DDL/ANALYZE through the same ``catalog.schema_version`` hook the
    plan cache uses.
  * Consumers, behind ``tidb_tpu_plan_feedback`` (default on):
      (a) recorded scan selectivities and join output cardinalities
          override the heuristic estimates on the NEXT planning of the
          same shapes (join ordering; dcn ``_plan_shuffle`` reads the
          observed per-side exchange bytes for broadcast-vs-shuffle);
      (b) the eager-agg push-down decision becomes measured: when a
          digest's default plan carries an eager partial, the
          alternative (no-push, fusible) plan is explored once and the
          warm-measured faster variant wins — the Q18 bench no longer
          pins ``tidb_opt_agg_push_down=0``;
      (c) fused-probe tile sizing: observed overflow rates raise the
          statement's ``join_tiles`` so dup-heavy probes expand in
          fewer dispatches.
  * Surfaces: ``information_schema.plan_feedback``, est/drift columns
    on EXPLAIN ANALYZE, the ``PLAN_EST_DRIFT`` histogram (with trace
    exemplars), worst-drift annotations on kept traces, and the
    ``/plan_feedback`` status endpoint.

Correctness contract: feedback may change PLANS, never RESULTS. Every
consumer picks among independently-correct alternatives (join order,
exchange mode, push-down variant, tile count), so a bad feedback entry
can degrade performance but never correctness — the tests re-validate
feedback-driven plans against the sqlite oracle.

Concurrency: one leaf lock guards the store; nothing blocking (no
planning, no device work, no I/O) ever runs under it — the
lock-discipline and blocking-under-lock passes check this module.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

__all__ = ["PlanFeedbackStore", "STORE", "Observation", "OpObservation",
           "planning_hints", "current_hints", "cond_fingerprint",
           "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 512

# a recorded actual only overrides the heuristic when the misestimate
# is material: small drift is within the noise the estimators already
# carry, and overriding it would churn plans for nothing
SIGNIFICANT_DRIFT = 4.0

# exploration budget per plan variant: runs allowed before giving up on
# ever seeing a warm (cache-hit, no-recompile) measurement and scoring
# the variant by its best cold run instead
EXPLORE_BUDGET = 8

# a variant must beat the incumbent's warm best by this margin to take
# over — hysteresis against latency jitter flip-flopping near-ties
WIN_MARGIN = 0.9


# ---------------------------------------------------------------------------
# observations
# ---------------------------------------------------------------------------

class OpObservation:
    """One operator's est-vs-actual fold across executions."""

    __slots__ = ("op", "est_rows", "actual_rows", "execs")

    def __init__(self, op: str, est_rows: float, actual_rows: float):
        self.op = op
        self.est_rows = float(est_rows)
        self.actual_rows = float(actual_rows)
        self.execs = 1

    def fold(self, est_rows: float, actual_rows: float) -> None:
        self.est_rows = float(est_rows)
        self.actual_rows = float(actual_rows)  # latest wins: the most
        self.execs += 1                        # recent truth is freshest

    def drift(self) -> float:
        """actual/est ratio; 0.0 when the estimate was zero."""
        return self.actual_rows / self.est_rows if self.est_rows > 0 else 0.0


class Observation:
    """What one execution of one (digest, plan) taught us. Built by
    ``harvest`` outside any lock; folded into the store under it."""

    def __init__(self):
        self.ops: List[Tuple[str, float, float]] = []  # (op, est, actual)
        self.scan_rows: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self.join_rows: Dict[frozenset, float] = {}
        self.eager_partial = False
        self.fused_probe = False
        self.latency_s = 0.0
        self.warm = False
        self.tile_chunks = 0
        self.tile_overflows = 0
        self.tile_max_need = 0
        # fused top-k (ISSUE 18): largest LIMIT+offset k a FusedScanTopNExec
        # observed past its device capacity gate (0 = never overflowed)
        self.topn_overflow = 0
        self.worst_drift = 0.0       # max(ratio, 1/ratio) over known ops
        self.worst_drift_op = ""
        self.worst_drift_ratio = 1.0  # signed actual/est of the worst op


class _Variant:
    """Per-(digest, plan_digest) aggregate entry."""

    __slots__ = ("digest", "plan_digest", "apd", "execs", "warm_execs",
                 "best_warm_s", "best_any_s", "eager_partial",
                 "fused_probe", "ops", "tile_chunks", "tile_overflows",
                 "tile_max_need", "topn_overflow", "worst_drift",
                 "worst_drift_op")

    def __init__(self, digest: str, plan_digest: str, apd: bool):
        self.digest = digest
        self.plan_digest = plan_digest
        self.apd = apd
        self.execs = 0
        self.warm_execs = 0
        self.best_warm_s: Optional[float] = None
        self.best_any_s: Optional[float] = None
        self.eager_partial = False
        self.fused_probe = False
        self.ops: "OrderedDict[str, OpObservation]" = OrderedDict()
        self.tile_chunks = 0
        self.tile_overflows = 0
        self.tile_max_need = 0
        self.topn_overflow = 0
        self.worst_drift = 0.0
        self.worst_drift_op = ""

    def score(self) -> Optional[float]:
        """Latency this variant competes with: warm best when measured,
        else (exploration budget exhausted) the best cold run."""
        if self.best_warm_s is not None:
            return self.best_warm_s
        if self.execs >= EXPLORE_BUDGET:
            return self.best_any_s
        return None


# ---------------------------------------------------------------------------
# expression fingerprints (stable across re-plannings)
# ---------------------------------------------------------------------------

def cond_fingerprint(cond, uid_to_name: Dict[str, str]) -> str:
    """Stable fingerprint of a pushed filter with ColumnRef uids mapped
    to base column NAMES — binder uids can differ between plannings of
    the same SQL, so a raw repr() would never match across executions."""
    from tidb_tpu.expression.expr import Call, ColumnRef, Literal, Lookup

    parts: List[str] = []

    def visit(e):
        if e is None:
            parts.append("~")
            return
        if isinstance(e, ColumnRef):
            parts.append("c:" + uid_to_name.get(e.name, e.name))
            return
        if isinstance(e, Literal):
            parts.append("l:" + repr(e.value))
            return
        if isinstance(e, Lookup):
            parts.append("lk(")
            visit(e.arg)
            parts.append(")")
            return
        if isinstance(e, Call):
            parts.append(e.op + "(")
            for a in e.args:
                visit(a)
                parts.append(",")
            parts.append(")")
            return
        parts.append(type(e).__name__)

    visit(cond)
    return "".join(parts)


def _base_relation(plan) -> bool:
    """True when a physical subtree is one base table reached through
    row-shaping operators only (selections/projections over a scan) —
    the shapes whose observed join cardinality is a clean PAIRWISE
    truth the join orderer can reuse."""
    from tidb_tpu.planner.physical import PProjection, PScan, PSelection

    p = plan
    while isinstance(p, (PProjection, PSelection)):
        p = p.child
    return isinstance(p, PScan) and p.table is not None


def _resolve_scan_col_phys(plan, uid: str):
    """Physical-tree twin of planner.physical.resolve_scan_col: trace a
    column uid to its defining base-table (table_name, column_name)
    through pass-through projections."""
    from tidb_tpu.expression.expr import ColumnRef
    from tidb_tpu.planner.physical import PProjection, PScan

    if isinstance(plan, PScan):
        for c in plan.schema:
            if c.uid == uid:
                return (plan.table_name, c.name) if plan.table is not None \
                    else None
        return None
    if isinstance(plan, PProjection):
        for c, e in zip(plan.schema, plan.exprs):
            if c.uid == uid:
                if isinstance(e, ColumnRef):
                    return _resolve_scan_col_phys(plan.child, e.name)
                return None
    for ch in plan.children:
        r = _resolve_scan_col_phys(ch, uid)
        if r is not None:
            return r
    return None


def _side_fingerprint(plan) -> Optional[Tuple[str, str]]:
    """(table_name, combined filter fingerprint) of a join side that is
    one base table reached through row-shaping operators only, else
    None. Duck-typed over BOTH trees (logical and physical share the
    projection/selection/scan attribute shapes): selections above the
    scan contribute their conditions to the fingerprint alongside the
    scan's pushed filter, so a filtered and an unfiltered join of the
    same tables never share an observation."""
    p = plan
    fps: List[str] = []
    while True:
        if hasattr(p, "pushed_cond"):  # the base scan (LScan / PScan)
            if getattr(p, "table", None) is None:
                return None
            if p.pushed_cond is not None:
                fps.append(cond_fingerprint(
                    p.pushed_cond, {c.uid: c.name for c in p.schema}))
            return (p.table_name, "&".join(sorted(fps)))
        if hasattr(p, "exprs"):        # projection: row-preserving
            p = p.children[0]
            continue
        if hasattr(p, "cond") and not hasattr(p, "eq_left") \
                and not hasattr(p, "eq_conds"):  # selection
            fps.append(cond_fingerprint(
                p.cond, {c.uid: c.name for c in p.schema}))
            p = p.children[0]
            continue
        return None  # joins, aggregates, anything else: not pairwise


def _join_key(left, right, eq_pairs, resolve) -> Optional[tuple]:
    """Feedback key of one pairwise join: the (table, column) pairs its
    equalities resolve to, plus each side's (table, filter fingerprint).
    None when either side is not a base relation or a key fails to
    resolve — the recorded truth is PAIRWISE and filter-specific, so
    only the same shape may record or consume it."""
    from tidb_tpu.expression.expr import ColumnRef, Lookup

    fl, fr = _side_fingerprint(left), _side_fingerprint(right)
    if fl is None or fr is None:
        return None
    pairs = set()
    for side, e in eq_pairs:
        while isinstance(e, Lookup):
            e = e.arg
        if not isinstance(e, ColumnRef):
            return None
        r = resolve(side, e.name)
        if r is None:
            return None
        pairs.add(r)
    if not pairs:
        return None
    return (frozenset(pairs), frozenset({fl, fr}))


def join_key_logical(left, right, eq_conds) -> Optional[tuple]:
    from tidb_tpu.planner.physical import resolve_scan_col

    def resolve(side, uid):
        r = resolve_scan_col(side, uid)
        return None if r is None else (getattr(r[0].schema, "name", ""),
                                       r[1])

    eq_pairs = [(s, e) for le, re_ in eq_conds
                for s, e in ((left, le), (right, re_))]
    return _join_key(left, right, eq_pairs, resolve)


def _join_key_physical(plan) -> Optional[tuple]:
    left, right = plan.children
    eq_pairs = ([(left, e) for e in plan.eq_left]
                + [(right, e) for e in plan.eq_right])
    return _join_key(left, right, eq_pairs, _resolve_scan_col_phys)


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

class PlanFeedbackStore:
    """Process-global, capacity-bounded (LRU on digest) plan-feedback
    store. The lock is a LEAF: fold/read only — callers do planning and
    harvesting outside it."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        from tidb_tpu.analysis import sanitizer as _san

        # tracked: the runtime sanitizer witnesses acquisition order,
        # so a future harvest/consumer that nests this under another
        # registered lock shows up as a cycle finding, not a hang
        self.lock = _san.tracked_lock("PlanFeedbackStore.lock")
        self.capacity = capacity
        self._by_digest: "OrderedDict[str, Dict[str, _Variant]]" = \
            OrderedDict()
        # digest-independent cardinality truth (the production QFB
        # shape): observed scan selectivities and join output rows,
        # keyed by base-table fingerprints so any statement touching
        # the same shapes benefits. Bounded alongside the digest LRU.
        self._scan_rows: "OrderedDict[Tuple[str, str], Tuple[float, float]]"\
            = OrderedDict()
        self._join_rows: "OrderedDict[frozenset, float]" = OrderedDict()
        # dcn exchange observations: digest -> (side->bytes, side->
        # shard-map version). Survives schema_version invalidation by
        # design (see record_shuffle); bounded by the same capacity.
        self._shuffle: "OrderedDict[str, tuple]" = OrderedDict()
        self.evicted = 0
        self.invalidations = 0
        self.recorded = 0

    # -- recording ----------------------------------------------------------

    def record(self, digest: str, plan_digest: str, apd: bool,
               obs: Observation, capacity: Optional[int] = None) -> bool:
        """Fold one execution's observation. Returns True when a NEW
        significant cardinality hint appeared (the caller then evicts
        the digest's plan-cache entries so the next planning actually
        consults it)."""
        if not digest:
            return False
        new_hint = False
        with self.lock:
            if capacity is not None:
                self.capacity = max(1, int(capacity))
            variants = self._by_digest.get(digest)
            if variants is None:
                variants = self._by_digest[digest] = {}
            self._by_digest.move_to_end(digest)
            v = variants.get(plan_digest)
            if v is None:
                v = variants[plan_digest] = _Variant(
                    digest, plan_digest, apd)
            v.execs += 1
            v.eager_partial = obs.eager_partial
            v.fused_probe = v.fused_probe or obs.fused_probe
            v.best_any_s = (obs.latency_s if v.best_any_s is None
                            else min(v.best_any_s, obs.latency_s))
            if obs.warm:
                v.warm_execs += 1
                v.best_warm_s = (obs.latency_s if v.best_warm_s is None
                                 else min(v.best_warm_s, obs.latency_s))
            for op, est, actual in obs.ops:
                cur = v.ops.get(op)
                if cur is None:
                    if len(v.ops) >= 64:  # bound pathological plans
                        continue
                    v.ops[op] = OpObservation(op, est, actual)
                else:
                    cur.fold(est, actual)
            v.tile_chunks += obs.tile_chunks
            v.tile_overflows += obs.tile_overflows
            v.tile_max_need = max(v.tile_max_need, obs.tile_max_need)
            v.topn_overflow = max(v.topn_overflow, obs.topn_overflow)
            if obs.worst_drift > v.worst_drift:
                v.worst_drift = obs.worst_drift
                v.worst_drift_op = obs.worst_drift_op
            for key, (actual, base) in obs.scan_rows.items():
                # scan hints never force a plan-cache eviction: they
                # refine estimates at the NEXT natural replan (a lone
                # misestimated filter rarely changes the plan, and
                # evicting would break the hit-on-second-execution
                # contract for every drifting point lookup)
                self._scan_rows[key] = (actual, base)
                self._scan_rows.move_to_end(key)
            for key, actual in obs.join_rows.items():
                prev = self._join_rows.get(key)
                self._join_rows[key] = actual
                self._join_rows.move_to_end(key)
                if prev is None or abs(prev - actual) > 0.5 * max(
                        actual, 1.0):
                    new_hint = True
            self.recorded += 1
            while len(self._by_digest) > self.capacity:
                self._by_digest.popitem(last=False)
                self.evicted += 1
            cap8 = self.capacity * 8  # a few shapes per digest
            while len(self._scan_rows) > cap8:
                self._scan_rows.popitem(last=False)
            while len(self._join_rows) > cap8:
                self._join_rows.popitem(last=False)
        return new_hint

    def record_shuffle(self, digest: str, side_bytes: Dict[str, int],
                       versions: Optional[Dict[str, int]] = None) -> None:
        """Observed per-side wire bytes of a dcn shuffle join (the
        coordinator's scatter acks), with the shard-map versions they
        were measured under. Kept in a SEPARATE map that schema_version
        bumps do NOT clear: every dcn query creates a local staging
        table (DDL), which would erase the observation before the next
        planning could use it. The honest invalidation signal for
        exchange sizing is the PLACEMENT version — reshard/reload bumps
        it, and shuffle_hint() refuses stale versions."""
        if not digest or not side_bytes:
            return
        with self.lock:
            cur = self._shuffle.get(digest)
            merged = dict(cur[0]) if cur is not None else {}
            for side, nbytes in side_bytes.items():
                merged[side] = int(nbytes)
            self._shuffle[digest] = (merged, dict(versions or {}))
            self._shuffle.move_to_end(digest)
            while len(self._shuffle) > self.capacity:
                self._shuffle.popitem(last=False)

    # -- invalidation -------------------------------------------------------

    def on_schema_change(self) -> None:
        """DDL/ANALYZE: recorded truth was measured against data and
        stats that no longer exist — drop everything (the plan cache's
        rule, applied to the feedback that would re-shape its plans).
        Exchange observations are exempt: they invalidate by PLACEMENT
        version instead (see record_shuffle) — every dcn query's local
        staging DDL would otherwise erase them immediately."""
        with self.lock:
            self._by_digest.clear()
            self._scan_rows.clear()
            self._join_rows.clear()
            self.invalidations += 1

    # -- consumers ----------------------------------------------------------

    def scan_hint(self, table_name: str, cond_fp: str
                  ) -> Optional[Tuple[float, float]]:
        with self.lock:
            return self._scan_rows.get((table_name, cond_fp))

    def join_hint(self, key: frozenset) -> Optional[float]:
        with self.lock:
            return self._join_rows.get(key)

    def apd_decision(self, digest: str) -> Optional[bool]:
        """Measured eager-agg push-down choice for this digest, or None
        to keep the heuristic default. Only consulted when the session
        default WOULD push (a user pin of 0 is authoritative).

        Protocol: the default (push) plan executes first; if it carried
        an eager partial, the no-push alternative is explored, then the
        warm-measured faster variant wins (cold runs — plan-cache miss
        or kernel recompile — never count as measurements; after
        EXPLORE_BUDGET runs a variant scores by its best cold run so a
        never-warm variant cannot block convergence)."""
        with self.lock:
            variants = self._by_digest.get(digest)
            if not variants:
                return None
            on = next((v for v in variants.values() if v.apd), None)
            off = next((v for v in variants.values() if not v.apd), None)
            if on is None or not on.eager_partial:
                # push-down never fired (or the default variant hasn't
                # run yet): the decision changes nothing — stay default
                return None
            if off is None:
                return False  # explore the no-push alternative once
            s_off, s_on = off.score(), on.score()
            if s_off is None:
                return False   # keep exploring until warm (budgeted)
            if s_on is None:
                return None    # re-measure the default until warm
            return False if s_off < s_on * WIN_MARGIN else None

    def tile_hint(self, digest: str) -> int:
        """Learned join_tiles floor for this digest from observed fused
        tile overflow (0 = no opinion). Dup-heavy probes that overflowed
        their in-program tile expand the remainder in ceil(need/tiles)
        dispatches — size the tile batch to the observed worst need."""
        with self.lock:
            variants = self._by_digest.get(digest)
            if not variants:
                return 0
            need = 0
            for v in variants.values():
                if v.tile_overflows > 0:
                    need = max(need, v.tile_max_need)
            return min(need, 64)

    def topn_overflow(self, digest: str) -> int:
        """Largest ORDER BY+LIMIT k this digest was observed to need
        PAST the fused top-k capacity gate (0 = never overflowed). The
        session consumes it per statement: an overflowing digest's
        SECOND execution starts on the classic materializing sort
        instead of re-failing the fused gate at every open()."""
        with self.lock:
            variants = self._by_digest.get(digest)
            if not variants:
                return 0
            return max((v.topn_overflow for v in variants.values()),
                       default=0)

    def shuffle_hint(self, digest: str,
                     versions: Optional[Dict[str, int]] = None
                     ) -> Dict[str, int]:
        """Observed per-side exchange bytes for this digest, or {} when
        the placement moved since they were measured (any recorded
        table whose current shard-map version differs)."""
        with self.lock:
            hit = self._shuffle.get(digest)
            if hit is None:
                return {}
            side_bytes, recorded_v = hit
            if versions is not None:
                for t, v in recorded_v.items():
                    if versions.get(t, v) != v:
                        del self._shuffle[digest]  # stale: placement
                        return {}                  # moved underneath
            return dict(side_bytes)

    # -- surfaces -----------------------------------------------------------

    def rows(self) -> List[tuple]:
        """information_schema.plan_feedback: one row per recorded
        operator per (digest, plan)."""
        with self.lock:
            out = []
            for digest, variants in self._by_digest.items():
                for v in variants.values():
                    base = (digest, v.plan_digest,
                            "push" if v.apd else "no_push", v.execs,
                            v.warm_execs,
                            round((v.best_warm_s or 0.0) * 1e3, 3),
                            1 if v.eager_partial else 0,
                            1 if v.fused_probe else 0)
                    if not v.ops:
                        out.append(base + ("", -1.0, -1.0, 0.0, 0))
                    for op, o in v.ops.items():
                        out.append(base + (
                            op, round(o.est_rows, 2),
                            round(o.actual_rows, 2),
                            round(o.drift(), 4), o.execs))
            for digest, (side_bytes, _vers) in self._shuffle.items():
                for side, nb in sorted(side_bytes.items()):
                    out.append((digest, "", "shuffle", 0, 0, 0.0, 0, 0,
                                f"shuffle:{side}", -1.0, float(nb),
                                0.0, 0))
            return out

    def stats_dict(self, top: int = 50) -> dict:
        """/plan_feedback endpoint payload."""
        with self.lock:
            digests = []
            for digest, variants in list(self._by_digest.items())[-top:]:
                vs = []
                for v in variants.values():
                    vs.append({
                        "plan_digest": v.plan_digest,
                        "agg_push_down": v.apd,
                        "execs": v.execs,
                        "warm_execs": v.warm_execs,
                        "best_warm_ms": round((v.best_warm_s or 0) * 1e3, 3),
                        "best_any_ms": round((v.best_any_s or 0) * 1e3, 3),
                        "eager_partial": v.eager_partial,
                        "fused_probe": v.fused_probe,
                        "worst_drift": round(v.worst_drift, 3),
                        "worst_drift_op": v.worst_drift_op,
                        "tile_overflow": [v.tile_overflows, v.tile_chunks],
                        "topn_overflow": v.topn_overflow,
                        "ops": {op: [round(o.est_rows, 2),
                                     round(o.actual_rows, 2)]
                                for op, o in v.ops.items()},
                    })
                digests.append({"digest": digest, "variants": vs})
            return {
                "digests": digests,
                "capacity": self.capacity,
                "recorded": self.recorded,
                "evicted": self.evicted,
                "invalidations": self.invalidations,
                "scan_hints": len(self._scan_rows),
                "join_hints": len(self._join_rows),
                "shuffle": {d: dict(sb) for d, (sb, _v)
                            in self._shuffle.items()},
            }

    def clear(self) -> None:
        with self.lock:
            self._by_digest.clear()
            self._scan_rows.clear()
            self._join_rows.clear()
            self._shuffle.clear()
            self.evicted = 0
            self.recorded = 0


STORE = PlanFeedbackStore()


# ---------------------------------------------------------------------------
# planning hints (thread-local: installed by the session around one
# plan_statement call; planner/physical.py estimators consult them)
# ---------------------------------------------------------------------------

class _Hints:
    __slots__ = ("store",)

    def __init__(self, store: PlanFeedbackStore):
        self.store = store

    def scan_rows(self, table, table_name: str, cond, uid_to_name,
                  current_n: float) -> Optional[float]:
        """Observed-selectivity estimate for a filtered scan, or None.
        The stored actual is rescaled by the table's CURRENT cardinality
        so DML between executions ages the hint gracefully."""
        hit = self.store.scan_hint(
            table_name, cond_fingerprint(cond, uid_to_name))
        if hit is None:
            return None
        actual, base = hit
        est = actual if base <= 0 else actual / base * max(current_n, 1.0)
        return max(est, 1.0)

    def join_rows(self, left, right, eq_conds) -> Optional[float]:
        key = join_key_logical(left, right, eq_conds)
        if key is None:
            return None
        return self.store.join_hint(key)


_TLS = threading.local()


class planning_hints:
    """Context manager installing feedback hints for one planning call.
    Reentrant-safe: an inner install (subplan planning) shadows and
    restores."""

    def __init__(self, enabled: bool, store: Optional[PlanFeedbackStore]
                 = None):
        self._hints = _Hints(store or STORE) if enabled else None
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_TLS, "hints", None)
        _TLS.hints = self._hints
        return self._hints

    def __exit__(self, *exc):
        _TLS.hints = self._prev
        return False


def current_hints() -> Optional[_Hints]:
    return getattr(_TLS, "hints", None)


# ---------------------------------------------------------------------------
# harvest (statement end, outside the store lock)
# ---------------------------------------------------------------------------

def harvest(phys, root, result_rows: int, latency_s: float,
            warm: bool) -> Observation:
    """Walk the executed tree and collect est-vs-actual truth. Actuals
    come from RuntimeStats only: ``rows`` when the operator was
    instrumented (EXPLAIN ANALYZE / TRACE), else ``out_rows`` — the
    counts operators learn host-side for free (join match totals,
    aggregate group counts). The plan node each executor answers for
    rides the builder's ``_feedback_plan`` annotation."""
    from tidb_tpu.planner.physical import (PHashAgg, PHashJoin,
                                           PProjection, PScan,
                                           PSelection)

    obs = Observation()
    obs.latency_s = float(latency_s)
    obs.warm = bool(warm)
    # eager-partial detection walks the PLAN (always complete); the
    # exec tree may have absorbed the partial into a fused/transient
    # subtree
    pstack = [phys]
    while pstack:
        p = pstack.pop()
        if isinstance(p, PHashAgg) and any(
                a.uid.startswith("eagg.") for a in p.aggs):
            obs.eager_partial = True
            break
        pstack.extend(p.children)
    seen_plans = set()
    pairs: List[Tuple[object, float]] = []  # (plan node, actual rows)
    stack = [root]
    while stack:
        e = stack.pop()
        stack.extend(c for c in e.children if c is not None)
        p = getattr(e, "_feedback_plan", None)
        st = getattr(e, "stats", None)
        if type(e).__name__ == "FusedScanProbeExec" \
                and getattr(e, "_ran_fused", False):
            obs.fused_probe = True
            if st is not None:
                obs.tile_chunks += st.tile_chunks
                obs.tile_overflows += st.tile_overflows
                obs.tile_max_need = max(obs.tile_max_need,
                                        st.tile_max_need)
        if type(e).__name__ == "FusedScanTopNExec" \
                and getattr(e, "_topn_overflow", 0):
            # the k this root WANTED but couldn't fuse — the store's
            # topn_overflow() consumer routes the digest classic
            obs.topn_overflow = max(obs.topn_overflow,
                                    int(e._topn_overflow))
        # actuals a transient subtree learned before it was dropped —
        # a fused probe's drained build child, or EITHER fused exec's
        # open()-time fallback delegate tree (_close_delegate parks
        # them on the OUTER exec for exactly this walk)
        pairs.extend((bp, float(rows)) for bp, rows
                     in getattr(e, "_fb_build_pairs", ()))
        if p is None or st is None:
            continue
        if st.measured:
            pairs.append((p, float(st.rows)))
        elif st.out_rows >= 0:
            pairs.append((p, float(st.out_rows)))
        elif e is root and result_rows >= 0:
            pairs.append((p, float(result_rows)))

    def peel_projections(p):
        """Physical node -> base PScan through row-preserving
        projections (None when a Selection intervenes: its output count
        is not the scan's)."""
        while isinstance(p, PProjection):
            p = p.child
        if isinstance(p, PSelection):
            return None
        return p if isinstance(p, PScan) and p.table is not None else None

    for p, actual in pairs:
        if id(p) in seen_plans:
            continue
        seen_plans.add(id(p))
        est = float(getattr(p, "est_rows", 0.0))
        # disambiguate same-named operators (a bushy plan has several
        # HashJoins): suffix the occurrence index
        name = p.op_name()
        k = sum(1 for n, _e, _a in obs.ops
                if n == name or n.startswith(name + "#"))
        if k:
            name = f"{name}#{k + 1}"
        obs.ops.append((name, est, actual))
        ratio = actual / est if est > 0 else 0.0
        if ratio > 0:
            sym = max(ratio, 1.0 / ratio)
            if sym > obs.worst_drift:
                obs.worst_drift = sym
                obs.worst_drift_op = p.op_name()
                obs.worst_drift_ratio = ratio
        significant = (est <= 0 or ratio <= 0
                       or ratio >= SIGNIFICANT_DRIFT
                       or ratio <= 1.0 / SIGNIFICANT_DRIFT)
        if not significant:
            continue
        if isinstance(p, PHashJoin) and p.kind == "inner" \
                and all(_base_relation(c) for c in p.children):
            # only joins over BASE relations record a cardinality hint:
            # a join above another join observes its whole subtree's
            # fan-out, which would poison the pairwise estimate the
            # join orderer asks for
            key = _join_key_physical(p)
            if key is not None:
                obs.join_rows[key] = actual
        base = peel_projections(p)
        if base is not None and base.pushed_cond is not None:
            from tidb_tpu.statistics import table_stats

            s = table_stats(base.table)
            n = float(s.n_rows) if s is not None \
                else float(base.table.live_rows)
            uid_to_name = {c.uid: c.name for c in base.schema}
            fp = cond_fingerprint(base.pushed_cond, uid_to_name)
            obs.scan_rows[(base.table_name, fp)] = (actual, n)
    return obs


def drift_factor(obs: Observation) -> float:
    """The symmetric drift of the worst-estimated operator (>= 1.0; 1.0
    = every known estimate was exact). Observed on PLAN_EST_DRIFT."""
    return max(obs.worst_drift, 1.0)
