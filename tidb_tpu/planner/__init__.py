"""Planner (ref: planner/core — PlanBuilder, logicalOptimize,
physicalOptimize).

    binder.py    -- name resolution + AST->typed-IR lowering, including the
                    string-predicate rewrite onto dictionary codes
    logical.py   -- logical plan nodes + build from parsed statements
    rules.py     -- rule-based logical optimization (constant folding,
                    predicate pushdown, column pruning, subquery-to-join)
    physical.py  -- physical operators + lowering + EXPLAIN text
    optimizer.py -- the Optimize() entry: AST -> optimized physical plan

The reference runs a cost-based search over storage paths; this engine has
one storage tier (host columnar -> device), so physical choice reduces to
algorithm selection (agg strategy, join order/build side) driven by simple
stats — the cascades-style search can arrive later without changing the
plan interfaces.
"""

from tidb_tpu.planner.binder import Binder, PlanCol, Scope
from tidb_tpu.planner.optimizer import plan_statement
from tidb_tpu.planner.physical import explain_text

__all__ = ["Binder", "PlanCol", "Scope", "plan_statement", "explain_text"]
