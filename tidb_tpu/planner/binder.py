"""Name resolution and AST -> typed-IR lowering.

This is where SQL semantics meet the TPU data layout:

  * every column gets a plan-unique uid; chunks key columns by uid, so
    operators never collide on names
  * string predicates are rewritten onto sorted-dictionary codes at bind
    time (equality -> code compare, ranges -> code bounds, LIKE -> host
    LUT + device gather, cross-dictionary compares -> union-dict
    translation) — the device never sees a string
  * temporal literals and INTERVAL arithmetic over literals fold to day
    counts host-side
  * decimal types carry scales; binding computes result scales (mul adds
    scales, div leaves fixed point for float)

ref: planner/core expression rewriting + expression/ type inference.
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tidb_tpu.chunk.dictionary import Dictionary
from tidb_tpu.errors import (
    AmbiguousColumnError,
    PlanError,
    UnknownColumnError,
    UnsupportedError,
)
from tidb_tpu.expression.expr import (
    Call,
    Case,
    Cast,
    ColumnRef,
    Expr,
    InList,
    Literal,
    Lookup,
)
from tidb_tpu.parser import ast as A
from tidb_tpu.types import (
    BOOL,
    DATE,
    DATETIME,
    JSONTYPE,
    TIME,
    FLOAT64,
    INT64,
    NULLTYPE,
    STRING,
    SQLType,
    TypeKind,
    common_type,
    date_to_days,
    datetime_to_micros,
    decimal_to_scaled,
    decimal_type,
)

__all__ = ["PlanCol", "Scope", "Binder", "AGG_FUNCS", "ast_key"]

AGG_FUNCS = {"sum", "count", "avg", "min", "max",
             "bit_and", "bit_or", "bit_xor", "group_concat",
             "var_pop", "var_samp", "stddev_pop", "stddev_samp"}


@dataclass
class PlanCol:
    uid: str
    name: str                      # display / alias name
    type_: SQLType
    qualifier: Optional[str] = None  # table alias for qualified resolution
    dict_: Optional[Dictionary] = None  # for STRING columns
    hidden: bool = False  # pseudo-columns (__rowid__): resolvable, not in *

    def ref(self) -> ColumnRef:
        return ColumnRef(type_=self.type_, name=self.uid)


class Scope:
    """Visible columns during binding; supports qualified/unqualified lookup."""

    def __init__(self, cols: List[PlanCol], parent: Optional["Scope"] = None):
        self.cols = cols
        self.parent = parent

    def resolve(self, name: str, qualifier: Optional[str]) -> PlanCol:
        # exact-uid references come from the planner's own agg/group
        # substitution (uids contain '#', so they never collide with SQL names)
        if "#" in name:
            for c in self.cols:
                if c.uid == name:
                    return c
        matches = [
            c
            for c in self.cols
            if c.name.lower() == name.lower()
            and (qualifier is None or (c.qualifier or "").lower() == qualifier.lower())
        ]
        if len(matches) > 1:
            # identical uid through different paths is fine
            if len({m.uid for m in matches}) > 1:
                raise AmbiguousColumnError(f"ambiguous column {name!r}")
        if matches:
            return matches[0]
        if self.parent is not None:
            # correlated reference — recognized so we can error clearly
            found = self.parent.try_resolve(name, qualifier)
            if found:
                raise UnsupportedError(
                    f"correlated subquery reference {qualifier + '.' if qualifier else ''}{name} not supported yet"
                )
        raise UnknownColumnError(f"unknown column {qualifier + '.' if qualifier else ''}{name}")

    def try_resolve(self, name: str, qualifier: Optional[str]) -> Optional[PlanCol]:
        try:
            return self.resolve(name, qualifier)
        except UnknownColumnError:
            return None
        except UnsupportedError:
            return None


def ast_key(e) -> str:
    """Stable structural key for AST dedup (same agg/group expr -> one slot)."""
    if isinstance(e, list):
        return "[" + ",".join(ast_key(x) for x in e) + "]"
    if isinstance(e, tuple):
        return "(" + ",".join(ast_key(x) for x in e) + ")"
    if hasattr(e, "__dataclass_fields__"):
        parts = [type(e).__name__]
        for f in e.__dataclass_fields__:
            parts.append(f + "=" + ast_key(getattr(e, f)))
        return "{" + ";".join(parts) + "}"
    return repr(e)


class Binder:
    def __init__(self):
        self._uid = 0
        # session context for DATABASE()/USER()/CONNECTION_ID() etc.;
        # populated by plan_statement from the owning Session
        self.session_info: Dict[str, object] = {}
        # NOW() is statement-start time: every NOW()/CURRENT_TIMESTAMP in
        # one statement sees the same instant (MySQL semantics). The
        # engine session timezone is fixed to UTC — stored DATETIMEs are
        # naive UTC wall time, so UNIX_TIMESTAMP(col) == epoch seconds on
        # any host timezone (documented deviation: @@time_zone = UTC)
        self._now: Optional[datetime.datetime] = None

    def _stmt_now(self) -> datetime.datetime:
        if self._now is None:
            self._now = datetime.datetime.utcnow()
        return self._now

    def new_uid(self, base: str) -> str:
        self._uid += 1
        return f"{base}#{self._uid}"

    # ------------------------------------------------------------------
    # literals
    # ------------------------------------------------------------------

    def bind_literal(self, e) -> Expr:
        if isinstance(e, A.ENum):
            t = e.text
            if re.search(r"[eE]", t):
                return Literal(type_=FLOAT64, value=float(t))
            if "." in t:
                scale = len(t.split(".", 1)[1])
                if scale > 12:
                    # decimal compares rescale both sides; huge literal scales
                    # would overflow int64 — treat as float like MySQL double
                    return Literal(type_=FLOAT64, value=float(t))
                return Literal(
                    type_=decimal_type(18, scale), value=decimal_to_scaled(t, scale)
                )
            if t.lower().startswith("0x"):
                return Literal(type_=INT64, value=int(t, 16))
            return Literal(type_=INT64, value=int(t))
        if isinstance(e, A.EStr):
            # bare string literal: kept as python str until context decides
            # (string compare -> code; numeric context -> parsed number)
            return Literal(type_=STRING, value=e.value)
        if isinstance(e, A.ENull):
            return Literal(type_=NULLTYPE, value=None)
        if isinstance(e, A.EBool):
            return Literal(type_=BOOL, value=e.value)
        raise PlanError(f"not a literal: {e}")

    @staticmethod
    def parse_date_literal(s: str) -> int:
        return date_to_days(datetime.date.fromisoformat(s.strip()))

    @staticmethod
    def parse_datetime_literal(s: str) -> int:
        s = s.strip()
        try:
            return datetime_to_micros(datetime.datetime.fromisoformat(s))
        except ValueError:
            return datetime_to_micros(
                datetime.datetime.combine(datetime.date.fromisoformat(s), datetime.time())
            )

    # ------------------------------------------------------------------
    # main expression binding
    # ------------------------------------------------------------------

    def bind_expr(self, e, scope: Scope) -> Expr:
        if isinstance(e, (A.ENum, A.EStr, A.ENull, A.EBool)):
            return self.bind_literal(e)

        if isinstance(e, A.EName):
            try:
                pc = scope.resolve(e.name, e.qualifier)
            except UnknownColumnError:
                # parens-less builtins (CURRENT_DATE, CURRENT_TIMESTAMP,
                # CURRENT_USER...) parse as names; a real column wins
                if e.qualifier is None:
                    lit = self._no_paren_builtin(e.name.lower())
                    if lit is not None:
                        return lit
                raise
            return self.attach_dict(pc.ref(), pc.dict_)

        if isinstance(e, A.EUnary):
            return self.bind_unary(e, scope)

        if isinstance(e, A.EBinary):
            return self.bind_binary(e.op, e.left, e.right, scope)

        if isinstance(e, A.EIsNull):
            arg = self.bind_expr(e.arg, scope)
            op = "is_not_null" if e.negated else "is_null"
            return Call(type_=BOOL, op=op, args=(arg,))

        if isinstance(e, A.EBetween):
            lo = A.EBinary(">=", e.arg, e.low)
            hi = A.EBinary("<=", e.arg, e.high)
            both = A.EBinary("and", lo, hi)
            return self.bind_expr(
                A.EUnary("not", both) if e.negated else both, scope
            )

        if isinstance(e, A.EIn):
            if e.subquery is not None:
                raise UnsupportedError(
                    "IN (SELECT ...) outside a WHERE conjunct is not supported yet"
                )
            return self.bind_in_values(e, scope)

        if isinstance(e, A.ELike):
            return self.bind_like(e, scope)

        if isinstance(e, A.ERegexp):
            return self.bind_regexp(e, scope)

        if isinstance(e, A.ECase):
            return self.bind_case(e, scope)

        if isinstance(e, A.ECast):
            from tidb_tpu.types import parse_type_name

            arg = self.bind_expr(e.arg, scope)
            to = parse_type_name(e.type_name, e.type_args)
            if to.kind == TypeKind.STRING:
                n = e.type_args[0] if e.type_args else None
                if arg.type_.kind == TypeKind.STRING:
                    if n is None:
                        return arg  # dict codes pass through unchanged
                    # CHAR(n) truncates: same dictionary-LUT path as LEFT
                    return self.bind_string_func(
                        "left", A.EFunc("left", []),
                        [arg, Literal(type_=INT64, value=int(n))])
                if isinstance(arg, Literal) and arg.value is not None:
                    k = arg.type_.kind
                    if k == TypeKind.DATE:
                        days = int(arg.value)
                        v = str(datetime.date(1970, 1, 1) + datetime.timedelta(days=days))
                    elif k == TypeKind.DATETIME:
                        micros = int(arg.value)
                        v = str(datetime.datetime(1970, 1, 1)
                                + datetime.timedelta(microseconds=micros))
                    elif k == TypeKind.DECIMAL:
                        sc = arg.type_.scale
                        v = f"{int(arg.value) / 10**sc:.{sc}f}" if sc else str(int(arg.value))
                    elif k == TypeKind.INT:
                        v = str(int(arg.value))
                    else:
                        v = str(arg.value)
                    return Literal(type_=STRING, value=v if n is None else v[: int(n)])
                raise UnsupportedError(
                    "CAST of a non-string column to CHAR (unbounded value "
                    "set has no plan-time dictionary)")
            arg = self.coerce_untyped_literal(arg, to)
            return Cast(type_=to, arg=arg)

        if isinstance(e, A.EFunc):
            return self.bind_func(e, scope)

        if isinstance(e, A.EInterval):
            raise PlanError("INTERVAL only valid next to +/- on a date")

        if isinstance(e, (A.EExists, A.ESubquery)):
            raise UnsupportedError(
                "subquery in this position not supported yet (use WHERE conjuncts)"
            )

        if isinstance(e, A.EVar):
            raise UnsupportedError("variable reference must be bound by session layer")

        if isinstance(e, A.EStar):
            raise PlanError("* not valid in this context")

        if isinstance(e, A.EWindow):
            raise PlanError(
                "window functions are only allowed in SELECT items / ORDER BY")

        raise PlanError(f"cannot bind expression {type(e).__name__}")

    # ------------------------------------------------------------------

    def bind_unary(self, e: A.EUnary, scope: Scope) -> Expr:
        if e.op == "not":
            arg = self.bind_expr(e.arg, scope)
            return Call(type_=BOOL, op="not", args=(self.to_bool(arg),))
        if e.op == "-":
            arg = self.bind_expr(e.arg, scope)
            if isinstance(arg, Literal) and arg.value is not None:
                return Literal(type_=arg.type_, value=-arg.value)
            return Call(type_=arg.type_, op="neg", args=(arg,))
        if e.op == "~":
            arg = self.bind_expr(e.arg, scope)
            return Call(type_=INT64, op="bitnot", args=(self._to_int64(arg, "~"),))
        raise PlanError(f"unknown unary op {e.op}")

    def to_bool(self, arg: Expr) -> Expr:
        if arg.type_.kind == TypeKind.BOOL or arg.type_.kind == TypeKind.NULL:
            return arg
        return Call(type_=BOOL, op="ne", args=(arg, Literal(type_=arg.type_, value=0)))

    # ------------------------------------------------------------------

    _CMP = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}

    def bind_binary(self, op: str, left_ast, right_ast, scope: Scope) -> Expr:
        # date +/- INTERVAL
        if op in ("+", "-") and isinstance(right_ast, A.EInterval):
            return self.bind_interval_arith(op, left_ast, right_ast, scope)
        if op == "+" and isinstance(left_ast, A.EInterval):
            return self.bind_interval_arith(op, right_ast, left_ast, scope)

        if op in ("and", "or", "xor"):
            l = self.to_bool(self.bind_expr(left_ast, scope))
            r = self.to_bool(self.bind_expr(right_ast, scope))
            if op == "xor":
                return Call(type_=BOOL, op="ne", args=(l, r))
            return Call(type_=BOOL, op=op, args=(l, r))

        l = self.bind_expr(left_ast, scope)
        r = self.bind_expr(right_ast, scope)

        if op in self._CMP or op == "<=>":
            return self.bind_comparison(op, l, r)

        if op in ("+", "-", "*", "/", "div", "mod", "%"):
            return self.bind_arith(op, l, r)

        if op in ("|", "&", "^", "<<", ">>"):
            bop = {"|": "bitor", "&": "bitand", "^": "bitxor",
                   "<<": "shl", ">>": "shr"}[op]
            return Call(type_=INT64, op=bop,
                        args=(self._to_int64(l, op), self._to_int64(r, op)))
        raise PlanError(f"unknown binary op {op}")

    def _to_int64(self, e: Expr, op: str) -> Expr:
        """Bitwise operands: MySQL converts to BIGINT by rounding."""
        k = e.type_.kind
        if k in (TypeKind.INT, TypeKind.BOOL):
            return e
        if k in (TypeKind.DECIMAL, TypeKind.FLOAT):
            # Cast's kind conversion rounds half-away-from-zero (MySQL)
            return Cast(type_=INT64, arg=e)
        raise PlanError(f"bitwise {op} needs numeric operands")

    def bind_interval_arith(self, op: str, date_ast, interval: A.EInterval, scope: Scope) -> Expr:
        base = self.bind_expr(date_ast, scope)
        base = self.coerce_untyped_literal(base, DATE)
        iv = self.bind_expr(interval.value, scope)
        if not isinstance(iv, Literal):
            raise UnsupportedError("non-constant INTERVAL")
        amount = int(iv.value) if iv.type_.kind != TypeKind.STRING else int(str(iv.value))
        if op == "-":
            amount = -amount
        unit = interval.unit
        months = {"month": 1, "quarter": 3, "year": 12}
        if base.type_.kind == TypeKind.DATE:
            if isinstance(base, Literal):
                d = datetime.date.fromordinal(
                    datetime.date(1970, 1, 1).toordinal() + int(base.value)
                )
                return Literal(type_=DATE, value=date_to_days(_add_interval(d, amount, unit)))
            if unit == "day":
                return Call(type_=DATE, op="add", args=(base, Literal(type_=DATE, value=amount)))
            if unit == "week":
                return Call(type_=DATE, op="add", args=(base, Literal(type_=DATE, value=amount * 7)))
            if unit in months:
                return Call(type_=DATE, op="add_months",
                            args=(base, Literal(type_=INT64, value=amount * months[unit])))
            raise UnsupportedError(f"INTERVAL {unit} on non-constant date")
        if base.type_.kind == TypeKind.DATETIME:
            micros = {"day": 86_400_000_000, "week": 7 * 86_400_000_000,
                      "hour": 3_600_000_000, "minute": 60_000_000,
                      "second": 1_000_000, "microsecond": 1}
            if unit in micros:
                return Call(type_=DATETIME, op="add",
                            args=(base, Literal(type_=DATETIME, value=amount * micros[unit])))
            if unit in months:
                return Call(type_=DATETIME, op="add_months",
                            args=(base, Literal(type_=INT64, value=amount * months[unit])))
            raise UnsupportedError(f"INTERVAL {unit} on datetime expressions")
        raise UnsupportedError("INTERVAL arithmetic needs a date/datetime operand")

    # -- comparisons ----------------------------------------------------

    def bind_comparison(self, op: str, l: Expr, r: Expr) -> Expr:
        lk, rk = l.type_.kind, r.type_.kind

        # untyped string literal meets typed column: coerce literal
        if lk == TypeKind.STRING and isinstance(l, Literal) and rk != TypeKind.STRING:
            l = self.coerce_untyped_literal(l, r.type_)
            lk = l.type_.kind
        if rk == TypeKind.STRING and isinstance(r, Literal) and lk != TypeKind.STRING:
            r = self.coerce_untyped_literal(r, l.type_)
            rk = r.type_.kind

        if lk == TypeKind.STRING or rk == TypeKind.STRING:
            return self.bind_string_comparison(op, l, r)

        ir_op = {"<=>": "nseq"}.get(op) or self._CMP[op]
        return Call(type_=BOOL, op=ir_op, args=(l, r))

    def coerce_untyped_literal(self, e: Expr, target: SQLType) -> Expr:
        """A string Literal meeting a typed context parses into that type."""
        if not (isinstance(e, Literal) and e.type_.kind == TypeKind.STRING):
            return e
        s = str(e.value)
        k = target.kind
        if k == TypeKind.DATE:
            return Literal(type_=DATE, value=self.parse_date_literal(s))
        if k == TypeKind.DATETIME:
            return Literal(type_=DATETIME, value=self.parse_datetime_literal(s))
        if k == TypeKind.DECIMAL:
            return Literal(type_=target, value=decimal_to_scaled(s, target.scale))
        if k == TypeKind.INT:
            return Literal(type_=INT64, value=int(float(s)))
        if k == TypeKind.FLOAT:
            return Literal(type_=FLOAT64, value=float(s))
        if k == TypeKind.BOOL:
            return Literal(type_=BOOL, value=bool(float(s)))
        if k == TypeKind.TIME:
            from tidb_tpu.types import time_to_micros

            try:
                return Literal(type_=TIME, value=time_to_micros(s))
            except ValueError as ex:
                raise PlanError(f"bad TIME literal {s!r}: {ex}")
        if k == TypeKind.ENUM:
            # unknown member compares equal to nothing: index 0 is unused
            idx = target.members.index(s) + 1 if s in target.members else 0
            return Literal(type_=target, value=idx)
        if k == TypeKind.SET:
            from tidb_tpu.types import set_to_mask

            try:
                return Literal(type_=target, value=set_to_mask(s, list(target.members)))
            except ValueError:
                return Literal(type_=target, value=-1)  # matches no mask
        return e

    def _dict_of(self, e: Expr) -> Optional[Dictionary]:
        return getattr(e, "_dict", None)

    def attach_dict(self, e: Expr, d: Optional[Dictionary]) -> Expr:
        if d is not None:
            object.__setattr__(e, "_dict", d)
        return e

    def codify_output_literal(self, e: Expr) -> Expr:
        """A bare string literal reaching output position becomes code 0 of
        a one-entry dictionary (strings only exist as dict codes on device)."""
        import dataclasses as _dc

        if isinstance(e, Literal) and e.type_.kind == TypeKind.STRING and isinstance(e.value, str):
            return self.attach_dict(_dc.replace(e, value=0), Dictionary([e.value]))
        return e

    def bind_string_comparison(self, op: str, l: Expr, r: Expr) -> Expr:
        # NULL literal: = / <> yield NULL; <=> is IS NULL — no dictionary
        # context needed (codes are irrelevant against NULL)
        for side in (l, r):
            if isinstance(side, Literal) and side.value is None:
                other = r if side is l else l
                if op == "<=>":
                    return Call(type_=BOOL, op="nseq",
                                args=(other, Literal(type_=other.type_, value=None)))
                return Literal(type_=BOOL, value=None)
        ld, rd = self._dict_of(l), self._dict_of(r)

        # literal vs column: host-side code lookup
        if isinstance(r, Literal) and r.type_.kind == TypeKind.STRING and ld is not None:
            return self._string_col_vs_literal(op, l, ld, str(r.value))
        if isinstance(l, Literal) and l.type_.kind == TypeKind.STRING and rd is not None:
            flipped = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
            base = self._CMP.get(op, "nseq" if op == "<=>" else None)
            base = flipped.get(base, base)
            return self._string_col_vs_literal_op(base, r, rd, str(l.value))

        # column vs column: compare CANONICAL codes so a _ci collation's
        # fold-equal values ('abc' = 'ABC') compare equal; canon is
        # monotone, so order comparisons stay correct too
        if ld is not None and rd is not None:
            ir_op = {"<=>": "nseq"}.get(op) or self._CMP[op]
            if ld == rd:
                if ld.is_ci:
                    lut = ld.canon_lut()
                    l = Lookup.build(l, lut, STRING)
                    r = Lookup.build(r, lut, STRING)
                return Call(type_=BOOL, op=ir_op, args=(l, r))
            union = Dictionary.union(ld, rd)
            lt = Lookup.build(l, ld.translate_canon_to(union).astype(np.int32), STRING)
            rt = Lookup.build(r, rd.translate_canon_to(union).astype(np.int32), STRING)
            return Call(type_=BOOL, op=ir_op, args=(lt, rt))

        # literal vs literal
        if isinstance(l, Literal) and isinstance(r, Literal):
            a, b = str(l.value), str(r.value)
            res = {
                "=": a == b, "<>": a != b, "<": a < b, "<=": a <= b,
                ">": a > b, ">=": a >= b, "<=>": a == b,
            }[op]
            return Literal(type_=BOOL, value=res)

        raise UnsupportedError("string comparison without dictionary context")

    def _string_col_vs_literal(self, op: str, col: Expr, d: Dictionary, s: str) -> Expr:
        return self._string_col_vs_literal_op(
            {"<=>": "nseq"}.get(op) or self._CMP[op], col, d, s
        )

    def _string_col_vs_literal_op(self, ir_op: str, col: Expr, d: Dictionary, s: str) -> Expr:
        i32 = STRING  # codes are int32; compare as ints
        if ir_op in ("eq", "nseq"):
            lo, hi = d.eq_range(s)  # collation class: a code RANGE for _ci
            if lo >= hi:
                if ir_op == "nseq":
                    return Literal(type_=BOOL, value=False)
                # col = 'absent': FALSE for non-null, NULL for null
                return Call(type_=BOOL, op="ne", args=(col, col))
            if hi - lo == 1:
                return Call(type_=BOOL, op=ir_op, args=(col, Literal(type_=i32, value=lo)))
            if ir_op == "nseq":
                # null-safe over a class: canon-code compare (NULL -> FALSE)
                ccol = Lookup.build(col, d.canon_lut(), STRING)
                return Call(type_=BOOL, op="nseq", args=(ccol, Literal(type_=i32, value=lo)))
            return Call(type_=BOOL, op="and", args=(
                Call(type_=BOOL, op="ge", args=(col, Literal(type_=i32, value=lo))),
                Call(type_=BOOL, op="lt", args=(col, Literal(type_=i32, value=hi)))))
        if ir_op == "ne":
            lo, hi = d.eq_range(s)
            if lo >= hi:
                return Call(type_=BOOL, op="eq", args=(col, col))  # TRUE/NULL
            if hi - lo == 1:
                return Call(type_=BOOL, op="ne", args=(col, Literal(type_=i32, value=lo)))
            return Call(type_=BOOL, op="or", args=(
                Call(type_=BOOL, op="lt", args=(col, Literal(type_=i32, value=lo))),
                Call(type_=BOOL, op="ge", args=(col, Literal(type_=i32, value=hi)))))
        if ir_op == "lt":
            return Call(type_=BOOL, op="lt", args=(col, Literal(type_=i32, value=d.lower_bound(s))))
        if ir_op == "le":
            return Call(type_=BOOL, op="lt", args=(col, Literal(type_=i32, value=d.upper_bound(s))))
        if ir_op == "ge":
            return Call(type_=BOOL, op="ge", args=(col, Literal(type_=i32, value=d.lower_bound(s))))
        if ir_op == "gt":
            return Call(type_=BOOL, op="ge", args=(col, Literal(type_=i32, value=d.upper_bound(s))))
        raise PlanError(f"bad string op {ir_op}")

    # -- arithmetic -----------------------------------------------------

    def bind_arith(self, op: str, l: Expr, r: Expr) -> Expr:
        # untyped string literals in numeric context parse as numbers
        if isinstance(l, Literal) and l.type_.kind == TypeKind.STRING:
            l = self.coerce_untyped_literal(l, FLOAT64)
        if isinstance(r, Literal) and r.type_.kind == TypeKind.STRING:
            r = self.coerce_untyped_literal(r, FLOAT64)

        lt, rt = l.type_, r.type_

        # date arithmetic: date - date -> int days; date + int -> date
        if lt.kind == TypeKind.DATE and rt.kind == TypeKind.DATE:
            if op != "-":
                raise PlanError("only subtraction is defined between dates")
            return Call(type_=INT64, op="sub", args=(l, r))
        if lt.kind == TypeKind.DATE and rt.kind == TypeKind.INT:
            return Call(type_=DATE, op={"+": "add", "-": "sub"}[op], args=(l, r))

        if op == "/":
            return Call(type_=FLOAT64, op="div", args=(l, r))
        if op == "div":
            t = INT64 if lt.kind != TypeKind.FLOAT and rt.kind != TypeKind.FLOAT else FLOAT64
            return Call(type_=t, op="intdiv", args=(l, r))
        if op in ("mod", "%"):
            return Call(type_=common_type(lt, rt), op="mod", args=(l, r))

        ir = {"+": "add", "-": "sub", "*": "mul"}[op]
        if ir == "mul" and TypeKind.DECIMAL in (lt.kind, rt.kind) and TypeKind.FLOAT not in (lt.kind, rt.kind):
            s = (lt.scale if lt.kind == TypeKind.DECIMAL else 0) + (
                rt.scale if rt.kind == TypeKind.DECIMAL else 0
            )
            if s > 12:
                return Call(type_=FLOAT64, op="mul", args=(l, r))
            return Call(type_=decimal_type(18, s), op="mul", args=(l, r))
        return Call(type_=common_type(lt, rt), op=ir, args=(l, r))

    # -- IN / LIKE ------------------------------------------------------

    def bind_in_values(self, e: A.EIn, scope: Scope) -> Expr:
        arg = self.bind_expr(e.arg, scope)
        d = self._dict_of(arg)
        vals = []
        has_null = False
        for v_ast in e.values:
            v = self.bind_expr(v_ast, scope)
            if not isinstance(v, Literal):
                raise UnsupportedError("non-constant IN list")
            if v.value is None:
                has_null = True
                continue
            if arg.type_.kind == TypeKind.STRING:
                if d is None:
                    raise UnsupportedError("IN on string without dictionary")
                lo, hi = d.eq_range(str(v.value))
                vals.extend(range(lo, hi))  # every collation-equal code
            else:
                v = self.coerce_untyped_literal(v, arg.type_)
                val = v.value
                if arg.type_.kind == TypeKind.DECIMAL and v.type_.kind == TypeKind.DECIMAL and v.type_.scale != arg.type_.scale:
                    val = decimal_to_scaled(
                        str(val / 10**v.type_.scale), arg.type_.scale
                    )
                vals.append(val)
        base = InList(type_=BOOL, arg=arg, values=tuple(vals), negated=e.negated)
        if has_null:
            # x IN (..., NULL) is never FALSE (TRUE or NULL); x NOT IN with a
            # NULL member is never TRUE — Kleene OR/AND with NULL encodes both.
            null_lit = Literal(type_=BOOL, value=None)
            op = "and" if e.negated else "or"
            return Call(type_=BOOL, op=op, args=(base, null_lit))
        return base

    def bind_like(self, e: A.ELike, scope: Scope) -> Expr:
        arg = self.bind_expr(e.arg, scope)
        d = self._dict_of(arg)
        pat = self.bind_expr(e.pattern, scope)
        if not isinstance(pat, Literal):
            raise UnsupportedError("non-constant LIKE pattern")
        if d is None:
            raise UnsupportedError("LIKE on non-string or dictionary-less value")
        rx = _like_to_regex(str(pat.value), e.escape)
        if d.is_ci:
            # MySQL LIKE honors the column collation: case-insensitive
            # under the default _ci collations. ASCII keeps the fold
            # identical to the dictionary's (and sqlite NOCASE's) —
            # full-Unicode IGNORECASE would make LIKE disagree with =
            import re as _re

            rx = _re.compile(
                rx.pattern,
                (rx.flags | _re.IGNORECASE | _re.ASCII) & ~_re.UNICODE)
        lut = d.match_table(lambda s: rx.fullmatch(s) is not None)
        if e.negated:
            lut = ~lut
        return Lookup.build(arg, lut, BOOL)

    def bind_regexp(self, e: A.ERegexp, scope: Scope) -> Expr:
        """col REGEXP/RLIKE 'pat' — same plan-time-LUT design as LIKE.
        MySQL semantics: partial match (re.search), case-insensitive by
        default (the _ci collation default; python `re` dialect stands
        in for ICU — the shared subset covers common patterns)."""
        arg = self.bind_expr(e.arg, scope)
        pat = self.bind_expr(e.pattern, scope)
        if not isinstance(pat, Literal):
            raise UnsupportedError("non-constant REGEXP pattern")
        neg = e.negated
        if isinstance(arg, Literal) and arg.type_.kind == TypeKind.STRING:
            hit = re.search(str(pat.value), str(arg.value),
                            re.IGNORECASE) is not None
            return Literal(type_=BOOL, value=hit != neg)
        d = self._dict_of(arg)
        if d is None:
            raise UnsupportedError("REGEXP on non-string or dictionary-less value")
        rx = re.compile(str(pat.value), re.IGNORECASE)
        lut = d.match_table(lambda s: rx.search(s) is not None)
        if neg:
            lut = ~lut
        return Lookup.build(arg, lut, BOOL)

    # -- CASE -----------------------------------------------------------

    def bind_case(self, e: A.ECase, scope: Scope) -> Expr:
        whens = []
        for cond_ast, res_ast in e.whens:
            if e.operand is not None:
                cond = self.bind_binary("=", e.operand, cond_ast, scope)
            else:
                cond = self.to_bool(self.bind_expr(cond_ast, scope))
            whens.append((cond, self.bind_expr(res_ast, scope)))
        else_ = self.bind_expr(e.else_, scope) if e.else_ is not None else None
        # result type: common type over branches
        branch_types = [r.type_ for _, r in whens] + ([else_.type_] if else_ else [])
        rt = branch_types[0]
        for bt in branch_types[1:]:
            rt = common_type(rt, bt)
        if rt.kind == TypeKind.STRING:
            return self._string_case(whens, else_, rt)
        out = Case(type_=rt, whens=tuple(whens), else_=else_)
        return out

    def _string_case(self, whens, else_, rt) -> Expr:
        """String-valued CASE: unify branch dictionaries/literals into one
        result Dictionary and rewrite branches to codes in it."""
        branches = [r for _, r in whens] + ([else_] if else_ is not None else [])
        values: list = []
        dicts: list = []
        for b in branches:
            d = self._dict_of(b)
            if d is not None:
                dicts.append(d)
            elif isinstance(b, Literal):
                if b.value is not None:
                    values.append(str(b.value))
            else:
                raise UnsupportedError("string CASE branch without dictionary")
        union = Dictionary(values)
        for d in dicts:
            union = Dictionary.union(union, d)

        def rewrite(b: Expr) -> Expr:
            d = self._dict_of(b)
            if d is not None:
                if d == union:
                    return b
                return Lookup.build(b, d.translate_to(union).astype(np.int32), STRING)
            assert isinstance(b, Literal)
            if b.value is None:
                return Literal(type_=STRING, value=None)
            return Literal(type_=STRING, value=union.code_of(str(b.value)))

        new_whens = tuple((c, rewrite(r)) for c, r in whens)
        new_else = rewrite(else_) if else_ is not None else None
        out = Case(type_=rt, whens=new_whens, else_=new_else)
        return self.attach_dict(out, union)

    # -- scalar functions ----------------------------------------------

    # parens-less keywords usable as 0-arg builtins
    _NO_PAREN = {
        "current_date", "current_timestamp", "current_time", "localtime",
        "localtimestamp", "current_user", "session_user", "utc_date",
        "utc_time", "utc_timestamp",
    }

    def _no_paren_builtin(self, name: str) -> Optional[Expr]:
        if name not in self._NO_PAREN:
            return None
        return self._session_builtin(name)

    def _session_builtin(self, name: str) -> Optional[Expr]:
        """Session/clock builtins folded to literals at bind time (the
        MySQL statement-start snapshot; ref: expression builtin_time /
        builtin_info evaluators)."""
        now = self._stmt_now
        if name in ("now", "current_timestamp", "localtime", "localtimestamp",
                    "sysdate"):
            return Literal(type_=DATETIME, value=datetime_to_micros(now()))
        if name in ("curdate", "current_date"):
            return Literal(type_=DATE, value=date_to_days(now().date()))
        if name == "utc_date":
            return Literal(
                type_=DATE,
                value=date_to_days(datetime.datetime.utcnow().date()))
        if name == "utc_timestamp":
            return Literal(
                type_=DATETIME,
                value=datetime_to_micros(datetime.datetime.utcnow()))
        if name in ("curtime", "current_time", "utc_time"):
            from tidb_tpu.types import time_to_micros

            t = (datetime.datetime.utcnow() if name == "utc_time"
                 else now()).time()
            return Literal(type_=TIME, value=time_to_micros(t))
        if name in ("database", "schema"):
            db = self.session_info.get("db")
            return Literal(type_=STRING,
                           value=None if db is None else str(db))
        if name in ("user", "current_user", "session_user", "system_user"):
            return Literal(
                type_=STRING,
                value=f"{self.session_info.get('user', 'root')}@%")
        if name == "version":
            from tidb_tpu import __version__

            return Literal(type_=STRING, value=f"8.0.11-tidb-tpu-{__version__}")
        if name == "connection_id":
            return Literal(type_=INT64,
                           value=int(self.session_info.get("conn_id", 0)))
        if name == "unix_timestamp":
            # derive from the same statement-start instant NOW() folds
            # to, so UNIX_TIMESTAMP() == UNIX_TIMESTAMP(NOW()) on any
            # host timezone (the engine clock is naive wall time)
            return Literal(type_=INT64,
                           value=datetime_to_micros(now()) // 1_000_000)
        return None

    _MICRO_UNITS = {
        "microsecond": 1, "second": 1_000_000, "minute": 60_000_000,
        "hour": 3_600_000_000, "day": 86_400_000_000,
        "week": 7 * 86_400_000_000,
    }

    def bind_func(self, e: A.EFunc, scope: Scope) -> Expr:
        name = e.name
        if name in AGG_FUNCS:
            raise PlanError(
                f"aggregate function {name.upper()} not allowed in this context"
            )

        if not e.args:
            lit = self._session_builtin(name)
            if lit is not None:
                return lit

        if name == "timestampadd" and len(e.args) == 3 and \
                isinstance(e.args[0], A.EName):
            return self.bind_interval_arith(
                "+", e.args[2], A.EInterval(e.args[1], e.args[0].name.lower()),
                scope)

        if name == "timestampdiff" and len(e.args) == 3 and \
                isinstance(e.args[0], A.EName):
            unit = e.args[0].name.lower()
            a = self.coerce_untyped_literal(self.bind_expr(e.args[1], scope), DATE)
            b = self.coerce_untyped_literal(self.bind_expr(e.args[2], scope), DATE)
            if not (a.type_.is_temporal and b.type_.is_temporal):
                raise PlanError("TIMESTAMPDIFF needs date/datetime arguments")
            if unit in self._MICRO_UNITS:
                am = a if a.type_.kind == TypeKind.DATETIME else Cast(
                    type_=DATETIME, arg=a)
                bm = b if b.type_.kind == TypeKind.DATETIME else Cast(
                    type_=DATETIME, arg=b)
                diff = Call(type_=INT64, op="sub", args=(bm, am))
                return Call(type_=INT64, op="intdiv", args=(
                    diff, Literal(type_=INT64, value=self._MICRO_UNITS[unit])))
            if unit in ("month", "quarter", "year"):
                months = Call(type_=INT64, op="tsdiff_months", args=(a, b))
                div = {"month": 1, "quarter": 3, "year": 12}[unit]
                if div == 1:
                    return months
                return Call(type_=INT64, op="intdiv", args=(
                    months, Literal(type_=INT64, value=div)))
            raise UnsupportedError(f"TIMESTAMPDIFF unit {unit}")

        if name in ("date_add", "adddate", "date_sub", "subdate") and len(e.args) == 2:
            iv = e.args[1]
            if not isinstance(iv, A.EInterval):
                iv = A.EInterval(iv, "day")  # ADDDATE(d, n) = n days
            op = "-" if name in ("date_sub", "subdate") else "+"
            return self.bind_interval_arith(op, e.args[0], iv, scope)

        if name in ("date",) and len(e.args) == 1 and isinstance(e.args[0], A.EStr):
            return Literal(type_=DATE, value=self.parse_date_literal(e.args[0].value))
        if name in ("timestamp", "datetime") and len(e.args) == 1 and isinstance(e.args[0], A.EStr):
            return Literal(
                type_=DATETIME, value=self.parse_datetime_literal(e.args[0].value)
            )
        if name == "time" and len(e.args) == 1 and isinstance(e.args[0], A.EStr):
            from tidb_tpu.types import time_to_micros

            return Literal(type_=TIME, value=time_to_micros(e.args[0].value))

        args = [self.bind_expr(a, scope) for a in e.args]

        if name in ("if",):
            if len(args) != 3:
                raise PlanError("IF takes 3 arguments")
            rt = common_type(args[1].type_, args[2].type_)
            return Call(type_=rt, op="if", args=(self.to_bool(args[0]), args[1], args[2]))
        if name == "ifnull":
            rt = common_type(args[0].type_, args[1].type_)
            return Call(type_=rt, op="ifnull", args=tuple(args))
        if name == "nullif":
            return Call(type_=args[0].type_, op="nullif", args=tuple(args))
        if name == "coalesce":
            rt = args[0].type_
            for a in args[1:]:
                rt = common_type(rt, a.type_)
            return Call(type_=rt, op="coalesce", args=tuple(args))

        if name in ("year", "month", "day", "dayofmonth", "quarter",
                    "dayofweek", "weekday", "dayofyear"):
            op = {"dayofmonth": "day"}.get(name, name)
            a = self.coerce_untyped_literal(args[0], DATE)
            if not a.type_.is_temporal:
                raise PlanError(f"{name.upper()} needs a date/datetime argument")
            if isinstance(a, Literal):
                days = int(a.value)
                if a.type_.kind == TypeKind.DATETIME:
                    days = days // 86_400_000_000  # micros -> days
                d = datetime.date(1970, 1, 1) + datetime.timedelta(days=days)
                iso = d.isoweekday()  # 1=Mon .. 7=Sun
                val = {
                    "year": d.year, "month": d.month, "day": d.day,
                    "quarter": (d.month - 1) // 3 + 1,
                    "dayofweek": iso % 7 + 1,  # MySQL: 1=Sun .. 7=Sat
                    "weekday": iso - 1,        # MySQL: 0=Mon .. 6=Sun
                    "dayofyear": d.timetuple().tm_yday,
                }[op]
                return Literal(type_=INT64, value=val)
            return Call(type_=INT64, op=op, args=(a,))
        if name in ("hour", "minute", "second", "microsecond"):
            a = args[0]
            if isinstance(a, Literal) and a.type_.kind == TypeKind.STRING:
                # '10:30:00' is a TIME; date dashes mean a datetime
                target = DATETIME if "-" in str(a.value).lstrip("-") else TIME
                a = self.coerce_untyped_literal(a, target)
            else:
                a = self.coerce_untyped_literal(a, DATETIME)
            if not a.type_.is_temporal and a.type_.kind != TypeKind.TIME:
                raise PlanError(f"{name.upper()} needs a date/time argument")
            if isinstance(a, Literal) and a.type_.kind == TypeKind.TIME:
                mag = abs(int(a.value))
                val = {
                    "hour": mag // 3_600_000_000,
                    "minute": mag // 60_000_000 % 60,
                    "second": mag // 1_000_000 % 60,
                    "microsecond": mag % 1_000_000,
                }[name]
                return Literal(type_=INT64, value=val)
            if isinstance(a, Literal):
                micros = int(a.value) if a.type_.kind == TypeKind.DATETIME else 0
                val = {
                    "hour": micros // 3_600_000_000 % 24,
                    "minute": micros // 60_000_000 % 60,
                    "second": micros // 1_000_000 % 60,
                    "microsecond": micros % 1_000_000,
                }[name]
                return Literal(type_=INT64, value=val)
            return Call(type_=INT64, op=name, args=(a,))
        if name in ("datediff",):
            a = self.coerce_untyped_literal(args[0], DATE)
            b = self.coerce_untyped_literal(args[1], DATE)
            return Call(type_=INT64, op="sub", args=(a, b))

        if name in ("week", "weekofyear", "to_days", "last_day", "dayname",
                    "monthname"):
            a = self.coerce_untyped_literal(args[0], DATE)
            if not a.type_.is_temporal:
                raise PlanError(f"{name.upper()} needs a date/datetime argument")
            if name == "week":
                mode = 0
                if len(args) > 1:
                    if not isinstance(args[1], Literal):
                        raise UnsupportedError("WEEK mode must be a constant")
                    mode = int(args[1].value)
                if mode == 0:
                    return Call(type_=INT64, op="week", args=(a,))
                if mode == 3:
                    return Call(type_=INT64, op="weekofyear", args=(a,))
                raise UnsupportedError(f"WEEK mode {mode} (0 and 3 supported)")
            if name == "weekofyear":
                return Call(type_=INT64, op="weekofyear", args=(a,))
            if name == "to_days":
                return Call(type_=INT64, op="to_days", args=(a,))
            if name == "last_day":
                return Call(type_=DATE, op="last_day", args=(a,))
            if name == "dayname":
                idx = Call(type_=INT64, op="weekday", args=(a,))
                return self._lut_strings(idx, [
                    "Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
                    "Saturday", "Sunday"])
            # monthname
            idx = Call(type_=INT64, op="sub", args=(
                Call(type_=INT64, op="month", args=(a,)),
                Literal(type_=INT64, value=1)))
            return self._lut_strings(idx, [
                "January", "February", "March", "April", "May", "June",
                "July", "August", "September", "October", "November",
                "December"])
        if name == "from_days":
            return Call(type_=DATE, op="from_days", args=(args[0],))
        if name == "time_to_sec" and len(args) == 1:
            a = self._coerce_time_str(args[0])
            if a.type_.kind not in (TypeKind.TIME, TypeKind.DATETIME):
                raise PlanError("TIME_TO_SEC needs a time/datetime argument")
            return Call(type_=INT64, op="time_to_sec", args=(a,))
        if name == "sec_to_time" and len(args) == 1:
            a = args[0]
            if a.type_.kind not in (TypeKind.INT, TypeKind.FLOAT,
                                    TypeKind.DECIMAL, TypeKind.BOOL):
                raise PlanError("SEC_TO_TIME needs a numeric argument")
            if a.type_.kind != TypeKind.INT:
                a = Cast(type_=INT64, arg=a)
            return Call(type_=TIME, op="sec_to_time", args=(a,))
        if name == "makedate" and len(args) == 2:
            return Call(type_=DATE, op="makedate", args=tuple(args))
        if name == "maketime" and len(args) == 3:
            if all(isinstance(a, Literal) for a in args):
                h, m, sec = (int(a.value) for a in args)
                sign = -1 if h < 0 else 1
                total = (abs(h) * 3600 + m * 60 + sec) * 1_000_000
                return Literal(type_=TIME, value=sign * total)
            # sign follows the HOUR for column arguments too:
            # h >= 0 -> h*3600 + m*60 + s; h < 0 -> h*3600 - m*60 - s
            h, m, sec = args

            def _c(op, x, y):
                return Call(type_=INT64, op=op, args=(x, y))

            h3600 = _c("mul", h, Literal(type_=INT64, value=3600))
            m60 = _c("mul", m, Literal(type_=INT64, value=60))
            pos = _c("add", _c("add", h3600, m60), sec)
            neg = _c("sub", _c("sub", h3600, m60), sec)
            secs = Call(type_=INT64, op="if", args=(
                Call(type_=BOOL, op="lt",
                     args=(h, Literal(type_=INT64, value=0))),
                neg, pos))
            return Call(type_=TIME, op="sec_to_time", args=(secs,))
        if name in ("addtime", "subtime") and len(args) == 2:
            a = self._coerce_time_str(args[0])
            b = self._coerce_time_str(args[1])
            if b.type_.kind != TypeKind.TIME or a.type_.kind not in (
                    TypeKind.TIME, TypeKind.DATETIME):
                raise PlanError(
                    f"{name.upper()} needs (time|datetime, time) arguments")
            return Call(type_=a.type_, op=name, args=(a, b))
        if name == "unix_timestamp" and len(args) == 1:
            a = self.coerce_untyped_literal(args[0], DATETIME)
            if not a.type_.is_temporal:
                raise PlanError("UNIX_TIMESTAMP needs a date/datetime argument")
            return Call(type_=INT64, op="unix_timestamp", args=(a,))
        if name == "from_unixtime" and len(args) >= 1:
            return Call(type_=DATETIME, op="from_unixtime", args=(args[0],))
        if name == "str_to_date" and len(args) == 2:
            return self._bind_str_to_date(args)
        if name == "date_format" and len(args) == 2:
            a = self.coerce_untyped_literal(args[0], DATE)
            if isinstance(a, Literal) and isinstance(args[1], Literal) \
                    and a.type_.is_temporal and a.value is not None:
                days = int(a.value)
                if a.type_.kind == TypeKind.DATETIME:
                    dt = (datetime.datetime(1970, 1, 1)
                          + datetime.timedelta(microseconds=days))
                else:
                    dt = (datetime.datetime(1970, 1, 1)
                          + datetime.timedelta(days=days))
                return Literal(type_=STRING,
                               value=_mysql_strftime(dt, str(args[1].value)))
            raise UnsupportedError(
                "DATE_FORMAT on columns not supported yet (constant fold only)")

        if name in ("abs",):
            return Call(type_=args[0].type_, op="abs", args=tuple(args))
        if name in ("ceil", "ceiling", "floor"):
            op = {"ceiling": "ceil"}.get(name, name)
            return Call(type_=FLOAT64, op=op, args=tuple(args))
        if name in ("sqrt", "exp", "ln", "log2", "log10", "sin", "cos"):
            return Call(type_=FLOAT64, op=name, args=tuple(args))
        if name in ("log",):
            if len(args) == 2:  # LOG(b, x) = LN(x) / LN(b)
                return Call(type_=FLOAT64, op="div", args=(
                    Call(type_=FLOAT64, op="ln", args=(args[1],)),
                    Call(type_=FLOAT64, op="ln", args=(args[0],))))
            return Call(type_=FLOAT64, op="ln", args=tuple(args))
        if name in ("power", "pow"):
            return Call(type_=FLOAT64, op="pow", args=tuple(args))
        if name in ("round", "truncate"):
            rt = args[0].type_
            if rt.kind == TypeKind.DECIMAL:
                nd = int(args[1].value) if len(args) > 1 else 0
                rt = decimal_type(rt.precision, max(0, min(rt.scale, nd)))
            op = "truncate" if name == "truncate" else "round"
            return Call(type_=rt if rt.kind != TypeKind.INT else INT64, op=op, args=tuple(args))
        if name in ("mod",):
            return Call(
                type_=common_type(args[0].type_, args[1].type_), op="mod", args=tuple(args)
            )
        if name in ("greatest", "least"):
            if len(args) < 2:
                raise PlanError(f"{name.upper()} needs at least 2 arguments")
            if any(a.type_.kind == TypeKind.STRING for a in args):
                return self._bind_extreme_strings(name, args)
            rt = args[0].type_
            for a in args[1:]:
                rt = common_type(rt, a.type_)
            return Call(type_=rt, op=name, args=tuple(args))
        if name == "pi" and not args:
            return Literal(type_=FLOAT64, value=3.141592653589793)
        if name in ("atan2",) and len(args) == 2:
            return Call(type_=FLOAT64, op="atan2", args=tuple(args))
        if name in ("sign",):
            return Call(type_=INT64, op="sign", args=tuple(args))
        if name in ("tan", "atan", "asin", "acos", "radians", "degrees"):
            return Call(type_=FLOAT64, op=name, args=tuple(args))

        if name in ("json_extract", "json_unquote", "json_valid", "json_type",
                    "json_length"):
            return self.bind_json_func(name, args)

        if name == "locate" and len(args) >= 2:
            # LOCATE(substr, str[, pos]) = INSTR(str, substr[, pos])
            return self.bind_string_func("instr", e, [args[1], args[0]] + args[2:])

        if name == "space" and len(args) == 1 and isinstance(args[0], Literal):
            return Literal(type_=STRING, value=" " * max(int(args[0].value), 0))
        if name == "strcmp" and len(args) == 2:
            return self._bind_strcmp(args)
        if name in ("field", "elt", "find_in_set"):
            return self._bind_string_list_func(name, args)
        if name == "char" and all(isinstance(a, Literal) for a in args):
            return Literal(type_=STRING,
                           value="".join(chr(int(a.value)) for a in args
                                         if a.value is not None))
        if name in ("cot", "sinh", "cosh", "tanh"):
            return Call(type_=FLOAT64, op=name, args=tuple(args))

        if name in ("regexp_like", "regexp_replace", "regexp_substr",
                    "regexp_instr"):
            return self._bind_regexp_func(name, args)

        # string functions via dictionary LUTs
        if name in _STRING_VALUE_FUNCS:
            return self.bind_string_func(name, e, args)

        raise UnsupportedError(f"function {name.upper()} not supported yet")

    def bind_string_func(self, name: str, e: A.EFunc, args: List[Expr]) -> Expr:
        if name == "concat":
            return self._bind_concat(args)
        arg = args[0]
        d = self._dict_of(arg)
        if d is None:
            if isinstance(arg, Literal) and arg.type_.kind == TypeKind.STRING:
                # fold over the literal host-side
                val = _apply_string_func(name, str(arg.value), e, args)
                t = INT64 if name in _STRING_INT_FUNCS else STRING
                return Literal(type_=t, value=val)
            raise UnsupportedError(f"{name} on dictionary-less string")
        if name in _STRING_INT_FUNCS:
            mapped = [_apply_string_func(name, s, e, args) for s in d.values]
            lut = np.array(mapped, dtype=np.int64)
            return Lookup.build(arg, lut, INT64)
        # string->string: build the target dictionary; None marks NULL
        mapped = [_apply_string_func(name, s, e, args) for s in d.values]
        return self._lut_strings(
            arg, ["" if m is None else m for m in mapped],
            valid=None if all(m is not None for m in mapped)
            else [m is not None for m in mapped])

    def bind_json_func(self, name: str, args: List[Expr]) -> Expr:
        """JSON functions as plan-time LUTs over the document dictionary
        (the LIKE design): O(|dict|) host json parsing, one device
        gather per chunk. Ref: the reference's types/json + expression
        builtin_json vectorized evaluators."""
        import json as _json

        arg = args[0]
        d = self._dict_of(arg)
        if d is None:
            if isinstance(arg, Literal) and arg.type_.kind in (TypeKind.STRING, TypeKind.JSON):
                d = Dictionary([str(arg.value)])
                arg = self.attach_dict(Literal(type_=arg.type_, value=0), d)
            else:
                raise UnsupportedError(f"{name} needs a JSON/string document column")

        def parsed(s):
            try:
                return _json.loads(s)
            except (ValueError, TypeError):
                return _JSON_BAD

        docs = [parsed(s) for s in d.values]

        if name == "json_valid":
            lut = np.array([v is not _JSON_BAD for v in docs], dtype=np.bool_)
            return Lookup.build(arg, lut, BOOL)
        if name == "json_type":
            names_ = [_json_type_name(v) for v in docs]
            return self._lut_strings(arg, names_)
        if name == "json_length":
            if len(args) > 1:
                if not isinstance(args[1], Literal):
                    raise UnsupportedError("JSON_LENGTH needs a constant path")
                path = str(args[1].value)
                docs = [_json_path_get(v, path) for v in docs]
            lut = np.array(
                [len(v) if isinstance(v, (list, dict)) else 1 for v in docs],
                dtype=np.int64)
            tv = np.array([v is not _JSON_BAD for v in docs], dtype=np.bool_)
            return Lookup.build(arg, lut, INT64, table_valid=tv)
        if name == "json_unquote":
            outs = []
            for s in d.values:
                v = parsed(s)
                outs.append(v if isinstance(v, str) else s)
            return self._lut_strings(arg, outs)
        # json_extract(doc, path [, path...]); multiple paths return a
        # JSON array of the values found (MySQL semantics)
        if len(args) < 2 or not all(isinstance(a, Literal) for a in args[1:]):
            raise UnsupportedError("JSON_EXTRACT needs constant paths")
        paths = [str(a.value) for a in args[1:]]
        outs, valid = [], []
        for v in docs:
            subs = [s for s in (_json_path_get(v, p) for p in paths)
                    if s is not _JSON_BAD]
            if not subs:
                outs.append("")
                valid.append(False)
            else:
                out = subs[0] if len(paths) == 1 else subs
                outs.append(_json.dumps(out, separators=(", ", ": ")))
                valid.append(True)
        return self._lut_strings(arg, outs, valid, type_=JSONTYPE)

    def _bind_regexp_func(self, name: str, args: List[Expr]) -> Expr:
        """REGEXP_LIKE / REGEXP_REPLACE / REGEXP_SUBSTR / REGEXP_INSTR
        as per-dictionary-value host evaluations (the LIKE design).
        Case-insensitive by default like the _ci collations; a trailing
        match_type literal of 'c' flips REGEXP_LIKE case-sensitive."""
        if len(args) < 2 or not isinstance(args[1], Literal):
            raise UnsupportedError(f"{name.upper()} needs a constant pattern")
        # MySQL's pos/occurrence/return_option/match_type extras are not
        # implemented — reject rather than silently answer for the
        # defaults (regexp_like accepts a match_type of 'c'/'i')
        max_args = {"regexp_like": 3, "regexp_replace": 3,
                    "regexp_substr": 2, "regexp_instr": 2}[name]
        if len(args) > max_args:
            raise UnsupportedError(
                f"{name.upper()} extra arguments (pos/occurrence/"
                "match_type) not supported yet")
        flags = re.IGNORECASE
        if name == "regexp_like" and len(args) > 2:
            if not isinstance(args[2], Literal):
                raise UnsupportedError("REGEXP_LIKE match_type must be constant")
            if "c" in str(args[2].value):
                flags = 0
        rx = re.compile(str(args[1].value), flags)
        repl = None
        if name == "regexp_replace":
            if len(args) < 3 or not isinstance(args[2], Literal):
                raise UnsupportedError(
                    "REGEXP_REPLACE needs a constant replacement")
            # MySQL backrefs are $1..$9; python's are \1..\9
            repl = re.sub(r"\$(\d)", r"\\\1", str(args[2].value))

        def apply(s: str):
            if name == "regexp_like":
                return rx.search(s) is not None
            if name == "regexp_replace":
                return rx.sub(repl, s)
            m = rx.search(s)
            if name == "regexp_substr":
                return m.group(0) if m else None
            return (m.start() + 1) if m else 0  # regexp_instr

        arg = args[0]
        if isinstance(arg, Literal) and arg.type_.kind == TypeKind.STRING:
            v = apply(str(arg.value))
            t = {"regexp_like": BOOL, "regexp_instr": INT64}.get(name, STRING)
            return Literal(type_=t, value=v)
        d = self._dict_of(arg)
        if d is None:
            raise UnsupportedError(f"{name.upper()} needs a string column")
        if name == "regexp_like":
            return Lookup.build(arg, d.match_table(apply), BOOL)
        if name == "regexp_instr":
            return Lookup.build(arg, d.apply_table(apply, np.int64), INT64)
        mapped = [apply(s) for s in d.values]
        return self._lut_strings(
            arg, ["" if m is None else m for m in mapped],
            valid=None if all(m is not None for m in mapped)
            else [m is not None for m in mapped])

    def _coerce_time_str(self, a: Expr) -> Expr:
        """A string literal in time position: date-dashes mean a
        DATETIME ('2024-01-01 23:30:00'), otherwise a TIME duration
        ('01:45:00') — the same heuristic HOUR()/MINUTE() use."""
        if isinstance(a, Literal) and a.type_.kind == TypeKind.STRING:
            from tidb_tpu.types import time_to_micros

            s = str(a.value)
            if "-" in s.lstrip("-"):
                return Literal(type_=DATETIME,
                               value=self.parse_datetime_literal(s))
            return Literal(type_=TIME, value=time_to_micros(s))
        return a

    def _bind_str_to_date(self, args: List[Expr]) -> Expr:
        """STR_TO_DATE(str, fmt): per-dictionary-value host parse -> a
        numeric date/datetime LUT (the LIKE design); unparseable values
        are NULL via table_valid."""
        fmt_lit = args[1]
        if not isinstance(fmt_lit, Literal):
            raise UnsupportedError("STR_TO_DATE needs a constant format")
        pyfmt, has_time = _mysql_fmt_translate(str(fmt_lit.value))
        t = DATETIME if has_time else DATE

        def parse_one(s):
            try:
                dt = datetime.datetime.strptime(s, pyfmt)
            except (ValueError, TypeError):
                return None
            return datetime_to_micros(dt) if has_time else date_to_days(dt.date())

        arg = args[0]
        if isinstance(arg, Literal) and arg.type_.kind == TypeKind.STRING:
            v = None if arg.value is None else parse_one(str(arg.value))
            return Literal(type_=t, value=v)
        d = self._dict_of(arg)
        if d is None or arg.type_.kind != TypeKind.STRING:
            raise UnsupportedError("STR_TO_DATE needs a string column or literal")
        vals = [parse_one(s) for s in d.values]
        lut = np.array([0 if v is None else v for v in vals],
                       dtype=np.int64 if has_time else np.int32)
        tv = np.array([v is not None for v in vals], dtype=np.bool_)
        return Lookup.build(arg, lut, t, table_valid=tv)

    def _lut_strings(self, arg: Expr, mapped: List[str], valid=None, type_=STRING) -> Expr:
        """Build a string-valued Lookup: mapped[i] is the output for dict
        code i; valid[i]=False marks NULL outputs."""
        nd = Dictionary([m for m in mapped])
        table = np.array([nd.code_of(m) for m in mapped], dtype=np.int32)
        tv = None if valid is None else np.asarray(valid, dtype=np.bool_)
        out = Lookup.build(arg, table, type_, table_valid=tv)
        return self.attach_dict(out, nd)

    def _union_strings(self, name: str, args: List[Expr]):
        """Translate string operands into one union dictionary (codes are
        sorted-order-preserving, so code comparisons are lexicographic).
        Returns (union, translated args)."""
        union = None
        for a in args:
            if isinstance(a, Literal) and a.type_.kind == TypeKind.STRING:
                d = Dictionary([str(a.value)])
            else:
                d = self._dict_of(a)
                if d is None or a.type_.kind != TypeKind.STRING:
                    raise UnsupportedError(
                        f"{name.upper()} mixes strings with non-strings")
            union = d if union is None else Dictionary.union(union, d)
        out_args = []
        for a in args:
            if isinstance(a, Literal):
                out_args.append(Literal(type_=STRING, value=union.code_of(str(a.value))))
            else:
                d = self._dict_of(a)
                if d == union:
                    out_args.append(a)
                else:
                    out_args.append(Lookup.build(
                        a, d.translate_to(union).astype(np.int32), STRING))
        return union, out_args

    def _bind_extreme_strings(self, name: str, args: List[Expr]) -> Expr:
        """GREATEST/LEAST over strings: max/min over union codes."""
        union, out_args = self._union_strings(name, args)
        out = Call(type_=STRING, op=name, args=tuple(out_args))
        return self.attach_dict(out, union)

    def _bind_strcmp(self, args: List[Expr]) -> Expr:
        """STRCMP(a, b) = sign(a - b) lexicographically, via union-dict
        code comparison."""
        _, (ca, cb) = self._union_strings("strcmp", args)
        diff = Call(type_=INT64, op="sub", args=(ca, cb))
        return Call(type_=INT64, op="sign", args=(diff,))

    def _bind_string_list_func(self, name: str, args: List[Expr]) -> Expr:
        """FIELD / ELT / FIND_IN_SET over dictionary LUTs."""
        if name == "elt":
            n, items = args[0], args[1:]
            if not all(isinstance(a, Literal) and a.type_.kind == TypeKind.STRING
                       for a in items):
                raise UnsupportedError("ELT items must be string constants")
            union = Dictionary([str(a.value) for a in items])
            whens = []
            for i, a in enumerate(items):
                cond = Call(type_=BOOL, op="eq",
                            args=(n, Literal(type_=INT64, value=i + 1)))
                whens.append((cond, Literal(
                    type_=STRING, value=union.code_of(str(a.value)))))
            out = Case(type_=STRING, whens=tuple(whens), else_=None)
            return self.attach_dict(out, union)

        def set_pos(needle: str, hay: str) -> int:
            if "," in needle:
                return 0  # MySQL: a needle containing ',' never matches
            parts = hay.split(",")
            return parts.index(needle) + 1 if needle in parts else 0

        if name == "field":
            arg, items = args[0], []
            for a in args[1:]:
                if not isinstance(a, Literal):
                    raise UnsupportedError("FIELD items must be constants")
                items.append(str(a.value))
            if isinstance(arg, Literal):
                s = str(arg.value)
                return Literal(type_=INT64,
                               value=items.index(s) + 1 if s in items else 0)
            d = self._dict_of(arg)
            if d is None:
                raise UnsupportedError("FIELD needs a string column or constant")
            lut = np.array([items.index(s) + 1 if s in items else 0
                            for s in d.values], dtype=np.int64)
            return Lookup.build(arg, lut, INT64)

        # find_in_set(needle, haystack): LUT over whichever side is a column
        needle, hay = args
        dn, dh = self._dict_of(needle), self._dict_of(hay)
        if isinstance(needle, Literal) and isinstance(hay, Literal):
            return Literal(type_=INT64,
                           value=set_pos(str(needle.value), str(hay.value)))
        if isinstance(hay, Literal) and dn is not None:
            lut = np.array([set_pos(s, str(hay.value)) for s in dn.values],
                           dtype=np.int64)
            return Lookup.build(needle, lut, INT64)
        if isinstance(needle, Literal) and dh is not None:
            lut = np.array([set_pos(str(needle.value), s) for s in dh.values],
                           dtype=np.int64)
            return Lookup.build(hay, lut, INT64)
        raise UnsupportedError("FIND_IN_SET needs a constant needle or list")

    def _bind_concat(self, args: List[Expr]) -> Expr:
        """CONCAT over any mix of dict-encoded string columns and
        constants: pack the per-column codes into one dense index
        (row-major over the dictionary sizes) and gather through a
        host-built product table. Strict NULL semantics fall out of the
        packing arithmetic. Bounded by the product of dictionary sizes —
        the same plan-time-LUT design as LIKE."""
        import itertools

        parts = []  # ("lit", str) | ("col", (expr, dict))
        dims = []
        for a in args:
            if isinstance(a, Literal):
                if a.type_.kind == TypeKind.STRING:
                    parts.append(("lit", str(a.value)))
                elif a.type_.kind == TypeKind.INT:
                    parts.append(("lit", str(int(a.value))))
                else:
                    raise UnsupportedError("CONCAT of non-string/int constant")
            else:
                d = self._dict_of(a)
                if d is None or a.type_.kind != TypeKind.STRING:
                    raise UnsupportedError("CONCAT argument without dictionary context")
                parts.append(("col", (a, d)))
                dims.append(len(d.values))
        if not dims:
            return Literal(type_=STRING, value="".join(v for _, v in parts))
        total = 1
        for s in dims:
            total *= s
        if total > (1 << 16):
            raise UnsupportedError(
                f"CONCAT dictionary product too large ({total} > 65536)")
        acc = None
        for kind, v in parts:
            if kind != "col":
                continue
            aexpr, d = v
            if acc is None:
                acc = aexpr
            else:
                acc = Call(type_=INT64, op="add", args=(
                    Call(type_=INT64, op="mul",
                         args=(acc, Literal(type_=INT64, value=len(d.values)))),
                    aexpr))
        col_dicts = [v[1] for kind, v in parts if kind == "col"]
        mapped = []
        for combo in itertools.product(*[dd.values for dd in col_dicts]):
            it = iter(combo)
            mapped.append("".join(v if kind == "lit" else next(it)
                                  for kind, v in parts))
        nd = Dictionary(mapped)
        table = np.array([nd.code_of(m) for m in mapped], dtype=np.int32)
        out = Lookup.build(acc, table, STRING)
        return self.attach_dict(out, nd)


class _JsonBad:
    """Sentinel: unparseable document / missing path."""


_JSON_BAD = _JsonBad()


def _json_type_name(v) -> str:
    if v is _JSON_BAD:
        return "INVALID"
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "DOUBLE"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "ARRAY"
    return "OBJECT"


def _json_path_get(doc, path: str):
    """Minimal MySQL JSON path: $, .key, [N]. Returns _JSON_BAD when the
    path is absent or the doc was invalid."""
    if doc is _JSON_BAD:
        return _JSON_BAD
    p = path.strip()
    if not p.startswith("$"):
        return _JSON_BAD
    cur = doc
    i = 1
    while i < len(p):
        if p[i] == ".":
            j = i + 1
            while j < len(p) and p[j] not in ".[":
                j += 1
            key = p[i + 1 : j]
            if not isinstance(cur, dict) or key not in cur:
                return _JSON_BAD
            cur = cur[key]
            i = j
        elif p[i] == "[":
            try:
                j = p.index("]", i)
                idx = int(p[i + 1 : j])
            except ValueError:  # unterminated bracket / non-integer index
                return _JSON_BAD
            if not isinstance(cur, list) or not -len(cur) <= idx < len(cur):
                return _JSON_BAD
            cur = cur[idx]
            i = j + 1
        else:
            return _JSON_BAD
    return cur


_STRING_VALUE_FUNCS = {
    "length", "char_length", "character_length", "upper", "ucase", "lower",
    "lcase", "trim", "ltrim", "rtrim", "substring", "substr", "mid", "left",
    "right", "reverse", "concat", "replace", "lpad", "rpad", "repeat",
    "ascii", "instr", "substring_index", "md5", "sha1", "sha", "sha2",
    "to_base64", "from_base64", "hex", "soundex", "quote", "insert",
    "bit_length", "octet_length", "crc32",
}

# per-value functions whose result is an integer, not a string
_STRING_INT_FUNCS = {
    "length", "char_length", "character_length", "ascii", "instr",
    "bit_length", "octet_length", "crc32",
}


# MySQL date-format specifier -> python strftime (shared by DATE_FORMAT
# constant folding and STR_TO_DATE parsing)
_MYSQL_FMT = {
    "Y": "%Y", "y": "%y", "m": "%m", "c": "%m", "d": "%d", "e": "%d",
    "H": "%H", "k": "%H", "h": "%I", "I": "%I", "i": "%M", "s": "%S",
    "S": "%S", "f": "%f", "p": "%p", "M": "%B", "b": "%b", "a": "%a",
    "W": "%A", "j": "%j", "w": "%w", "T": "%H:%M:%S", "r": "%I:%M:%S %p",
    "%": "%%",
}
_TIME_SPECS = set("HkhIisSfpTr")


def _mysql_fmt_translate(fmt: str) -> Tuple[str, bool]:
    """MySQL %-format -> (python strftime format, mentions-time)."""
    out: List[str] = []
    has_time = False
    i = 0
    while i < len(fmt):
        c = fmt[i]
        if c == "%" and i + 1 < len(fmt):
            spec = fmt[i + 1]
            if spec in _TIME_SPECS:
                has_time = True
            py = _MYSQL_FMT.get(spec)
            if py is None:
                raise UnsupportedError(f"date format specifier %{spec}")
            out.append(py)
            i += 2
        else:
            out.append("%%" if c == "%" else c)
            i += 1
    return "".join(out), has_time


def _mysql_strftime(dt: datetime.datetime, fmt: str) -> str:
    pyfmt, _ = _mysql_fmt_translate(fmt)
    return dt.strftime(pyfmt)


def _apply_string_func(name: str, s: str, e: A.EFunc, args: List[Expr]) -> str:
    if name in ("length", "char_length", "character_length"):
        return len(s)
    if name in ("upper", "ucase"):
        return s.upper()
    if name in ("lower", "lcase"):
        return s.lower()
    if name == "trim":
        return s.strip()
    if name == "ltrim":
        return s.lstrip()
    if name == "rtrim":
        return s.rstrip()
    if name == "reverse":
        return s[::-1]
    if name in ("substring", "substr"):
        if len(args) < 2 or not all(isinstance(a, Literal) for a in args[1:]):
            raise UnsupportedError("SUBSTRING needs constant positions")
        start = int(args[1].value)
        start = start - 1 if start > 0 else len(s) + start
        if len(args) > 2:
            return s[start : start + int(args[2].value)]
        return s[start:]
    if name == "left":
        return s[: int(args[1].value)]
    if name == "right":
        return s[-int(args[1].value):] if int(args[1].value) else ""
    if name == "concat":
        parts = [s]
        for a in args[1:]:
            if not (isinstance(a, Literal) and a.type_.kind == TypeKind.STRING):
                raise UnsupportedError("CONCAT of two columns not supported yet")
            parts.append(str(a.value))
        return "".join(parts)
    if name == "replace":
        if not all(isinstance(a, Literal) for a in args[1:]):
            raise UnsupportedError("REPLACE needs constant arguments")
        return s.replace(str(args[1].value), str(args[2].value))
    if name in ("lpad", "rpad"):
        if not all(isinstance(a, Literal) for a in args[1:]):
            raise UnsupportedError(f"{name.upper()} needs constant arguments")
        n = int(args[1].value)
        pad = str(args[2].value) if len(args) > 2 else " "
        if len(s) >= n:
            return s[:n]
        fill = (pad * n)[: n - len(s)] if pad else ""
        return fill + s if name == "lpad" else s + fill
    if name == "repeat":
        if not isinstance(args[1], Literal):
            raise UnsupportedError("REPEAT needs a constant count")
        return s * max(int(args[1].value), 0)
    if name == "ascii":
        return ord(s[0]) if s else 0
    if name == "instr":
        if len(args) < 2 or not all(isinstance(a, Literal) for a in args[1:]):
            raise UnsupportedError("INSTR needs constant arguments")
        if len(args) > 2 and int(args[2].value) < 1:
            return 0  # MySQL: pos <= 0 -> 0
        start = int(args[2].value) - 1 if len(args) > 2 else 0
        return s.find(str(args[1].value), start) + 1
    if name == "substring_index":
        if not all(isinstance(a, Literal) for a in args[1:]):
            raise UnsupportedError("SUBSTRING_INDEX needs constant arguments")
        delim, count = str(args[1].value), int(args[2].value)
        if not delim or count == 0:
            return ""
        parts = s.split(delim)
        if count > 0:
            return delim.join(parts[:count])
        return delim.join(parts[count:])
    if name == "md5":
        import hashlib

        return hashlib.md5(s.encode()).hexdigest()
    if name in ("sha1", "sha"):
        import hashlib

        return hashlib.sha1(s.encode()).hexdigest()
    if name == "sha2":
        import hashlib

        bits = int(args[1].value) if len(args) > 1 and isinstance(args[1], Literal) else 256
        algo = {0: "sha256", 224: "sha224", 256: "sha256",
                384: "sha384", 512: "sha512"}.get(bits)
        if algo is None:
            return None  # MySQL: invalid hash length -> NULL
        return getattr(hashlib, algo)(s.encode()).hexdigest()
    if name == "to_base64":
        import base64

        return base64.b64encode(s.encode()).decode()
    if name == "from_base64":
        import base64

        try:
            return base64.b64decode(s, validate=True).decode()
        except Exception:  # noqa: BLE001  (binascii or unicode errors)
            return None  # MySQL: invalid input -> NULL
    if name == "hex":
        return s.encode().hex().upper()
    if name == "soundex":
        if not s or not s[0].isalpha():
            return ""
        codes = {**{c: "1" for c in "BFPV"}, **{c: "2" for c in "CGJKQSXZ"},
                 **{c: "3" for c in "DT"}, "L": "4",
                 **{c: "5" for c in "MN"}, "R": "6"}
        up = [c for c in s.upper() if c.isalpha()]
        out, last = up[0], codes.get(up[0], "")
        for c in up[1:]:
            code = codes.get(c, "")
            if code and code != last:
                out += code
            last = code
        return (out + "000")[:4]
    if name == "quote":
        return "'" + s.replace("\\", "\\\\").replace("'", "\\'") + "'"
    if name == "insert":
        if not all(isinstance(a, Literal) for a in args[1:]):
            raise UnsupportedError("INSERT needs constant arguments")
        pos, ln, repl = int(args[1].value), int(args[2].value), str(args[3].value)
        if pos < 1 or pos > len(s):
            return s
        # MySQL: a length that is negative or runs past the end replaces
        # through the end of the string
        if ln < 0 or pos - 1 + ln > len(s):
            return s[: pos - 1] + repl
        return s[: pos - 1] + repl + s[pos - 1 + ln:]
    if name == "bit_length":
        return len(s.encode()) * 8
    if name == "octet_length":
        return len(s.encode())
    if name == "crc32":
        import zlib

        return zlib.crc32(s.encode())
    if name == "mid":
        return _apply_string_func("substring", s, e, args)
    raise UnsupportedError(f"string function {name}")


def _add_interval(d: datetime.date, amount: int, unit: str) -> datetime.date:
    if unit == "day":
        return d + datetime.timedelta(days=amount)
    if unit == "week":
        return d + datetime.timedelta(weeks=amount)
    if unit == "month":
        m = d.month - 1 + amount
        y = d.year + m // 12
        m = m % 12 + 1
        import calendar

        return datetime.date(y, m, min(d.day, calendar.monthrange(y, m)[1]))
    if unit == "year":
        import calendar

        y = d.year + amount
        return datetime.date(y, d.month, min(d.day, calendar.monthrange(y, d.month)[1]))
    raise UnsupportedError(f"INTERVAL unit {unit}")


def _like_to_regex(pattern: str, escape: Optional[str]) -> "re.Pattern":
    esc = escape or "\\"
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == esc and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out), re.DOTALL)
