"""Digest-keyed plan cache (ref: planner/core plan_cache* — the prepared
plan cache plus the instance-level cache behind
tidb_enable_non_prepared_plan_cache).

The cache maps a statement's *shape* — the bindinfo-normalized digest
plus everything else that legitimately feeds planning (current db,
parameter type fingerprint, plan-structural constants the digest blurs,
hints, planner sysvars, mesh width, binding versions) — to a lowered
physical plan. Parameter values are bound at execution time WITHOUT
re-planning by patching the recorded literal slots of the cached plan.

Soundness model (the part that differs from the reference, which plans
param-agnostically): this engine's binder consumes literal VALUES while
planning (dictionary-code rewrites, constant folding, point-get keys),
so a plan built for one parameter vector is only reusable if every
place a value leaked into the final plan is known and patchable. That
is established constructively on the first (miss) execution:

  1. plan the statement with its actual literals;
  2. plan it AGAIN with per-slot perturbed sentinel values;
  3. diff the two physical plans in lockstep. If they differ anywhere
     except at scalar leaves whose (value, sentinel) pair exactly
     matches one parameter's raw value, the statement is uncacheable.
     Every parameter must surface in at least one leaf (coverage) — a
     parameter folded away (``? > 0`` -> TRUE), rewritten to dictionary
     codes, rescaled into a decimal/date encoding, or hidden in a
     derived LUT produces either an unattributable diff or a coverage
     gap, and the statement is (soundly) refused.

On a hit the recorded (path, param-index) slots are patched into a
structurally-shared copy; untouched subtrees are shared and read-only.
Access-path values patched this way (point-get keys, index range
bounds) stay correct because every access node retains the full
``pushed_cond`` as a residual filter.

Invalidation: any ``catalog.schema_version`` bump clears the whole
cache (the reference's schema-change invalidation); per-entry stats
identity + freshness checks evict entries whose tables were ANALYZEd
(new stats object) or written (freshness flip) since planning.

Known-uncacheable shapes are cached negatively (entry with
``patches=None``) so they pay the sentinel verification once, not per
execution; the reason is surfaced on the ``/plan_cache`` endpoint.
"""

from __future__ import annotations

import copy
import dataclasses
import threading
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from tidb_tpu.chunk.dictionary import Dictionary, RuntimeDictionary
from tidb_tpu.parser import ast as A

__all__ = ["PlanCache", "PlanCacheEntry", "StmtInfo", "TemplateInfo",
           "analyze_statement", "analyze_template", "bind_template_params",
           "transform_literals", "make_sentinels", "build_entry",
           "instantiate", "batchable_plan", "batchable_dml",
           "classify_dml", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 256

# builtins the binder folds to bind-time literals that must never be
# frozen into a shared cached plan. Session identity (user/conn_id)
# matters because the cache is instance-wide; clocks matter everywhere.
# database()/version() are deliberately absent: db is a key component
# and version is process-constant.
_VOLATILE = frozenset({
    "now", "current_timestamp", "localtime", "localtimestamp", "sysdate",
    "curdate", "current_date", "curtime", "current_time", "utc_date",
    "utc_time", "utc_timestamp", "user", "current_user", "session_user",
    "system_user", "connection_id", "rand", "uuid", "sleep",
    "last_insert_id", "found_rows",
})

# plan fields legitimately value-dependent without being value-carrying:
# cost estimates, and the TopN pushdown descriptor (re-derived after
# patching via optimizer._annotate_topn, so it never aliases stale
# subtrees).
_IGNORE_FIELDS = frozenset({"est_rows", "pushdown"})


# ---------------------------------------------------------------------------
# statement analysis: literal slots, structural constants, volatility
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StmtInfo:
    params: List[object]          # literal values in deterministic order
    kinds: Tuple[str, ...]        # per-param type code: i | f | s
    struct: Tuple                 # digest-blurred plan-structural constants
    volatile: Optional[str]      # first volatile builtin found, else None
    unsafe: bool = False         # a literal sits in a foldable context


def _num_value(text: str):
    t = text.lower()
    if t.startswith("0x") or t.startswith("-0x"):
        return int(t, 16)
    try:
        return int(text)
    except ValueError:
        return float(text)


def _num_text(v) -> str:
    return repr(v) if isinstance(v, float) else str(v)


def _is_dc(x) -> bool:
    return dataclasses.is_dataclass(x) and not isinstance(x, type)


# binary operators whose DIRECT literal operands the binder consumes
# verbatim (comparisons and the boolean skeleton). A literal under any
# OTHER operator/function can be folded into a derived value that is
# coincidentally identity on the sampled points (abs(5) == 5) — such
# slots are flagged unsafe and the whole statement refuses to cache.
_SAFE_BINOPS = frozenset({"=", "<>", "!=", "<", "<=", ">", ">=", "<=>",
                          "and", "or", "xor"})


def _child_safety(v, safe: bool) -> bool:
    if isinstance(v, (A.SelectStmt, A.UnionStmt)):
        return True  # fresh clause context (subqueries included)
    if isinstance(v, A.EBinary):
        return safe and v.op in _SAFE_BINOPS
    if isinstance(v, (A.EFunc, A.ECase, A.ECast, A.EUnary, A.EInterval,
                      A.EWindow, A.ELike, A.ERegexp)):
        return False
    return safe


def _traverse(v, fn, rebuild: bool, safe: bool = True):
    """THE literal-slot traversal — the single definition of slot order
    shared by analysis (collect-only) and sentinel substitution
    (rebuild): one walker means the positional patch map can never
    desynchronize. ``fn(kind, value, safe)`` fires per slot with kind
    in {num, str, int, param, node}; its return value replaces the slot
    in rebuild mode. Slots are A.ENum (int/float), A.EStr (str), the
    plain-int limit/offset fields of SelectStmt/UnionStmt, and EParam
    markers — every NUM/STR/? token normalizes to ``?`` in the digest,
    so each must be a slot or two same-digest statements could share
    one cached plan."""
    if isinstance(v, A.ENum):
        r = fn("num", _num_value(v.text), safe)
        return A.ENum(_num_text(r)) if rebuild else v
    if isinstance(v, A.EStr):
        r = fn("str", v.value, safe)
        return A.EStr(r) if rebuild else v
    if isinstance(v, A.EParam):
        fn("param", v.index, safe)
        return v
    if isinstance(v, list):
        out = [_traverse(x, fn, rebuild, safe) for x in v]
        return out if rebuild else v
    if isinstance(v, tuple):
        out = tuple(_traverse(x, fn, rebuild, safe) for x in v)
        return out if rebuild else v
    if not _is_dc(v):
        return v
    fn("node", v, safe)
    child_safe = _child_safety(v, safe)
    is_su = isinstance(v, (A.SelectStmt, A.UnionStmt))
    if rebuild:
        kw = {}
        for f in dataclasses.fields(v):
            x = getattr(v, f.name)
            if (is_su and f.name in ("limit", "offset")
                    and isinstance(x, int) and not isinstance(x, bool)):
                kw[f.name] = int(fn("int", x, True))
            else:
                kw[f.name] = _traverse(x, fn, True, child_safe)
        return type(v)(**kw)
    for f in dataclasses.fields(v):
        x = getattr(v, f.name)
        if (is_su and f.name in ("limit", "offset")
                and isinstance(x, int) and not isinstance(x, bool)):
            fn("int", x, True)
        else:
            _traverse(x, fn, False, child_safe)
    return v


def transform_literals(stmt, fn):
    """Rebuild the statement AST passing every literal slot value
    through ``fn(value)`` in slot order (sentinel substitution)."""
    return _traverse(
        stmt,
        lambda kind, v, safe: v if kind in ("param", "node") else fn(v),
        rebuild=True)


class _Analysis:
    """Shared collector for analyze_statement / analyze_template."""

    def __init__(self):
        self.slots: List = []
        self.struct: List = []
        self.volatile: List[str] = []
        self.unsafe = False

    def __call__(self, kind, v, safe):
        if kind in ("num", "str", "int"):
            self.slots.append(("c", v))
            if not safe:
                self.unsafe = True
        elif kind == "param":
            self.slots.append(("p", v))
            if not safe:
                self.unsafe = True
        elif isinstance(v, A.EFunc):
            n = v.name
            if n in _VOLATILE and (n != "unix_timestamp" or not v.args):
                self.volatile.append(n)
        elif isinstance(v, A.ECast):
            self.struct.append(("cast", v.type_name, tuple(v.type_args)))
        elif isinstance(v, A.EWindow) and v.frame is not None:
            self.struct.append(("frame", repr(v.frame)))
        return v


def _kinds(vals) -> Tuple[str, ...]:
    return tuple("i" if isinstance(v, int) and not isinstance(v, bool)
                 else "f" if isinstance(v, float) else "s" for v in vals)


def analyze_statement(stmt) -> StmtInfo:
    """Collect-only pass over a literal-substituted statement (runs on
    EVERY cache probe — no AST rebuild)."""
    a = _Analysis()
    _traverse(stmt, a, rebuild=False)
    if any(k == "p" for k, _ in a.slots):
        a.unsafe = True  # unbound markers cannot be patched or planned
    params = [v for k, v in a.slots if k == "c"]
    a.struct.sort(key=repr)
    return StmtInfo(params=params, kinds=_kinds(params),
                    struct=tuple(a.struct),
                    volatile=(a.volatile[0] if a.volatile else None),
                    unsafe=a.unsafe)


@dataclasses.dataclass
class TemplateInfo:
    """Prepare-time analysis of a statement TEMPLATE (EParam markers in
    place): literal slots in walk order, each a constant value or a
    parameter reference, plus the value-independent struct/volatile
    findings. Lets execute_prepared skip the per-execution AST walk."""

    slots: Tuple                  # (("c", value) | ("p", param_index), ...)
    struct: Tuple
    volatile: Optional[str]
    unsafe: bool = False


def analyze_template(stmt) -> TemplateInfo:
    """analyze_statement over a prepared template: EParam nodes become
    parameter slots at exactly the position their substituted literal
    would occupy (the _param_literal substitution yields one ENum/EStr
    per marker, so slot order is preserved — same walker, same order)."""
    a = _Analysis()
    _traverse(stmt, a, rebuild=False)
    a.struct.sort(key=repr)
    return TemplateInfo(slots=tuple(a.slots), struct=tuple(a.struct),
                        volatile=(a.volatile[0] if a.volatile else None),
                        unsafe=a.unsafe)


_UNSUPPORTED = object()


def _coerce_param(v):
    """A bound parameter value as the literal the _param_literal
    substitution would produce — MUST track that function exactly, or
    the fast path and the substituted-AST analysis would disagree."""
    import datetime

    if isinstance(v, bool):
        return 1 if v else 0
    if isinstance(v, int):
        return v
    if isinstance(v, float):
        return v  # ENum(repr(v)) round-trips exactly
    if isinstance(v, str):
        return v
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, datetime.datetime):
        return v.isoformat(sep=" ")
    if isinstance(v, datetime.date):
        return v.isoformat()
    # None substitutes as ENull (not a literal slot) and anything else
    # is str()-ed by _param_literal — shapes the template walk cannot
    # predict, so the caller falls back to analyzing the substituted AST
    return _UNSUPPORTED


def bind_template_params(tinfo: TemplateInfo, params) -> Optional[StmtInfo]:
    """TemplateInfo + bound params -> the StmtInfo the substituted AST
    would analyze to, or None when a value needs the slow path."""
    vals: List[object] = []
    for kind, v in tinfo.slots:
        if kind == "c":
            vals.append(v)
        else:
            if v >= len(params):
                return None
            w = _coerce_param(params[v])
            if w is _UNSUPPORTED:
                return None
            vals.append(w)
    return StmtInfo(params=vals, kinds=_kinds(vals), struct=tinfo.struct,
                    volatile=tinfo.volatile, unsafe=tinfo.unsafe)


def make_sentinels(params) -> List[object]:
    """Per-slot perturbed values of the same Python type. Distinct
    (value, sentinel) pairs per index: equal values at two indices get
    different sentinels, so diff attribution is never ambiguous."""
    out = []
    for i, v in enumerate(params):
        if isinstance(v, bool):
            out.append(v)  # never produced by extraction; keep stable
        elif isinstance(v, int):
            out.append(v + 1 + i)
        elif isinstance(v, float):
            out.append(v + 1.5 + i)
        else:
            out.append(str(v) + "\x00~" + str(i))
    return out


# ---------------------------------------------------------------------------
# lockstep plan diff + patch-map attribution
# ---------------------------------------------------------------------------


def _scalar(x) -> bool:
    return (isinstance(x, (int, float, str, np.integer, np.floating))
            and not isinstance(x, bool))


def _int_like(x) -> bool:
    return isinstance(x, (int, np.integer)) and not isinstance(x, bool)


def _float_like(x) -> bool:
    return isinstance(x, (float, np.floating))


def _diff(a, b, path, out) -> bool:
    """Lockstep structural compare; scalar mismatches are recorded as
    candidate patch leaves, anything else incompatible returns False."""
    if a is b:
        return True
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if _scalar(a) and _scalar(b):
        same_class = (type(a) is type(b)
                      or (_int_like(a) and _int_like(b))
                      or (_float_like(a) and _float_like(b)))
        if not same_class:
            return bool(a == b)
        if a == b:
            return True
        out.append((path, a, b))
        return True
    if type(a) is not type(b):
        return False
    if _is_dc(a):
        for f in dataclasses.fields(a):
            if f.name in _IGNORE_FIELDS:
                continue
            if not _diff(getattr(a, f.name), getattr(b, f.name),
                         path + (f.name,), out):
                return False
        return True
    if isinstance(a, (list, tuple)):
        if len(a) != len(b):
            return False
        for i, (x, y) in enumerate(zip(a, b)):
            if not _diff(x, y, path + (i,), out):
                return False
        return True
    if isinstance(a, dict):
        if a.keys() != b.keys():
            return False
        for k in a:
            if not _diff(a[k], b[k], path + (("key", k),), out):
                return False
        return True
    if isinstance(a, np.ndarray):
        return (isinstance(b, np.ndarray) and a.dtype == b.dtype
                and np.array_equal(a, b))
    if isinstance(a, Dictionary):
        return a.values == b.values and a.collation == b.collation
    # other objects (tables, indexes, ...) must be the SAME object —
    # two plans over one catalog resolve identical instances
    return False


def _match(leaf, p) -> bool:
    """Does a plan leaf hold parameter value `p` under the identity
    transform (type-compatible exact equality)? Anything the binder
    transformed (dict codes, decimal scaling, date encoding) fails here
    and makes the statement uncacheable — by design."""
    if isinstance(p, bool) or isinstance(leaf, bool):
        return False
    if isinstance(p, int) and _int_like(leaf):
        return int(leaf) == p
    if isinstance(p, float) and _float_like(leaf):
        return float(leaf) == p
    if isinstance(p, str) and isinstance(leaf, str):
        return leaf == p
    return False


def _attribute(diffs, params, sentinels):
    """diff leaves -> ((path, param_index), ...) or None. Every leaf
    must map to exactly one parameter's (value, sentinel) pair and every
    parameter must be covered by at least one leaf."""
    patches, covered = [], set()
    for path, av, bv in diffs:
        hit = None
        for i, (p, sv) in enumerate(zip(params, sentinels)):
            if _match(av, p) and _match(bv, sv):
                hit = i
                break
        if hit is None:
            return None
        patches.append((path, hit))
        covered.add(hit)
    if covered != set(range(len(params))):
        return None
    return tuple(patches)


def _patch(node, path, value):
    """Persistent-structure rebuild of `node` with `value` at `path`;
    only nodes along the path are copied, everything else is shared
    with the cached plan (plans are read-only at execution)."""
    if not path:
        return value
    step, rest = path[0], path[1:]
    if isinstance(node, list):
        cp = list(node)
        cp[step] = _patch(node[step], rest, value)
        return cp
    if isinstance(node, tuple):
        cp = list(node)
        cp[step] = _patch(node[step], rest, value)
        return tuple(cp)
    if isinstance(node, dict):
        cp = dict(node)
        cp[step[1]] = _patch(node[step[1]], rest, value)
        return cp
    # dataclass, frozen (Expr) or not (plan nodes): copy.copy keeps
    # out-of-band attrs (_dict, segment_sizes); object.__setattr__
    # writes through frozen-ness
    cp = copy.copy(node)
    object.__setattr__(cp, step, _patch(getattr(node, step), rest, value))
    return cp


# ---------------------------------------------------------------------------
# entries
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PlanCacheEntry:
    digest: str
    db: str
    phys: object                       # cached physical plan; None if negative
    patches: Optional[Tuple]           # None => known-uncacheable
    n_params: int
    param_kinds: Tuple[str, ...]
    # per referenced table: (table, id(stats) or None, stats_fresh)
    table_states: Tuple
    schema_version: int
    reason: str = ""                   # why uncacheable (negative entries)
    hits: int = 0
    # shape digest of the cached plan (EXPLAIN text hash), computed on
    # the first hit and reused — hits identify the SAME plan, so
    # re-hashing per execution would be pure waste
    plan_digest: str = ""
    # memoized batchable_plan() verdict: None = not yet asked, "" =
    # batchable, else the blocking reason (the serving tier asks on
    # every coalescing probe; the plan never changes after publication)
    batch_reason: Optional[str] = None


def _plan_hazards(phys):
    """Walk the physical plan for referenced tables and disqualifying
    embedded state. Returns (tables, reason_or_None)."""
    tables, reason = [], None
    stack, seen = [phys], set()
    while stack:
        x = stack.pop()
        if x is None or id(x) in seen:
            continue
        seen.add(id(x))
        if isinstance(x, RuntimeDictionary):
            # filled/reset per execution (group_concat output state):
            # sharing it across cached executions would race
            reason = reason or "runtime dictionary state in plan"
            continue
        if _is_dc(x):
            for attr in ("table", "inner_table"):
                t = getattr(x, attr, None)
                if t is None:
                    continue
                tables.append(t)
                if getattr(t, "_anonymous", False):
                    reason = reason or "plan-time materialized table"
                if getattr(getattr(t, "schema", None), "partition",
                           None) is not None:
                    # partition pruning consumes values non-identically
                    # (v % n_parts, range bisects) — coincidental
                    # identity at fill time would patch wrong part ids
                    reason = reason or "partitioned table"
            if str(getattr(x, "db", "")).lower() == "information_schema":
                reason = reason or "information_schema source"
            stack.extend(getattr(x, f.name) for f in dataclasses.fields(x))
        elif isinstance(x, (list, tuple)):
            stack.extend(x)
        elif isinstance(x, dict):
            stack.extend(x.values())
    return tables, reason


def _table_states(tables) -> Tuple:
    out, seen = [], set()
    for t in tables:
        if id(t) in seen:
            continue
        seen.add(id(t))
        s = getattr(t, "stats", None)
        out.append((t, None if s is None else id(s),
                    s is not None and s.version == t.version))
    return tuple(out)


def build_entry(stmt, phys, info: StmtInfo, digest: str, db: str,
                schema_version: int, plan_sentinel, subplan_used):
    """Verify cacheability of `phys` for `stmt` and build the entry.
    `plan_sentinel(stmt2)` must run the exact planning pipeline the real
    plan used; `subplan_used()` reports whether planning executed a
    plan-time subquery (which bakes data, not just shape)."""
    tables, reason = _plan_hazards(phys)
    states = _table_states(tables)

    def entry(phys_, patches, why=""):
        return PlanCacheEntry(
            digest=digest, db=db, phys=phys_, patches=patches,
            n_params=len(info.params), param_kinds=info.kinds,
            table_states=states, schema_version=schema_version, reason=why)

    if subplan_used():
        return entry(None, None, "plan-time subquery/CTE execution")
    if reason:
        return entry(None, None, reason)
    if not info.params:
        return entry(phys, ())
    sentinels = make_sentinels(info.params)
    try:
        it = iter(sentinels)
        sstmt = transform_literals(stmt, lambda v: next(it))
        sphys = plan_sentinel(sstmt)
    except Exception:  # noqa: BLE001 — any sentinel failure just refuses
        return entry(None, None, "sentinel planning failed")
    if subplan_used():
        return entry(None, None, "plan-time subquery/CTE execution")
    diffs: List = []
    if not _diff(phys, sphys, (), diffs):
        return entry(None, None, "value-dependent plan shape")
    patches = _attribute(diffs, info.params, sentinels)
    if patches is None:
        return entry(None, None, "literal not traceable to a plan slot")
    return entry(phys, patches)


def instantiate(entry: PlanCacheEntry, params) -> object:
    """Cached plan with `params` bound into the verified slots."""
    plan = entry.phys
    for path, idx in entry.patches:
        plan = _patch(plan, path, params[idx])
    return plan


def batchable_plan(entry: PlanCacheEntry) -> str:
    """'' when `entry`'s plan can carry several sessions' parameter
    vectors in ONE gathered dispatch (the serving tier's cross-session
    micro-batching), else the blocking reason.

    Batchable shape: a ``cond_covered`` PPointGet, optionally under a
    fused Projection chain, whose verified patch slots ALL live in the
    access path (``key_values``, or the ``pushed_cond`` the unique-index
    probe subsumes). The projection pipeline is then parameter-free —
    identical for every member — so one pass over the gathered union of
    every member's fetched rows followed by a positional split yields
    exactly what N singleton executions would have produced."""
    r = entry.batch_reason
    if r is None:
        r = _batchable_reason(entry)
        entry.batch_reason = r
    return r


def _batchable_reason(entry: PlanCacheEntry) -> str:
    from tidb_tpu.planner.physical import PPointGet, PProjection

    if entry.patches is None or entry.phys is None:
        return "uncacheable"
    node = entry.phys
    while isinstance(node, PProjection):
        node = node.children[0]
    if not isinstance(node, PPointGet):
        return "not a covered point get"
    if not node.cond_covered:
        return "residual filter over fetched rows"
    for path, _idx in entry.patches:
        names = [p for p in path if isinstance(p, str)]
        anchor = next((n for n in names if n != "children"), "")
        if anchor not in ("key_values", "pushed_cond"):
            return f"param outside the access path ({anchor or '?'})"
    return ""


def _literal_expr(e) -> bool:
    """True when `e` evaluates to a constant from its text alone: a
    literal, or a sign applied to a numeric literal. Deliberately
    stricter than the binder's constant folding — functions (NOW()),
    casts and variables bind fine on the singleton path but are refused
    here so a group-committed member can never observe a different
    evaluation context than its singleton execution would have."""
    if isinstance(e, (A.ENum, A.EStr, A.ENull, A.EBool)):
        return True
    if isinstance(e, A.EUnary) and e.op in ("-", "+"):
        return isinstance(e.arg, A.ENum)
    return False


def _point_where(stmt) -> Optional[Tuple[str, object]]:
    """(column, literal value AST) for a WHERE of exactly `col = lit`
    (either operand order); None for any other shape."""
    w = getattr(stmt, "where", None)
    if not isinstance(w, A.EBinary) or w.op != "=":
        return None
    name, lit = w.left, w.right
    if _literal_expr(name) and isinstance(lit, A.EName):
        name, lit = lit, name
    if not isinstance(name, A.EName) or not _literal_expr(lit):
        return None
    tname = stmt.table.name.lower()
    alias = (stmt.table.alias or stmt.table.name).lower()
    if name.qualifier and name.qualifier.lower() not in (tname, alias):
        return None
    return name.name, lit


def classify_dml(stmt) -> Tuple[str, Optional[dict]]:
    """Structural half of the group-commit DML classifier (ISSUE 17):
    ('', parts) when `stmt` has a shape the write batcher can coalesce,
    else (reason, None). Schema-dependent gates (unique index on the
    WHERE column, SET columns outside every index, value binding) run
    in Session.dml_batch_probe, which owns the catalog.

    Coalescible shapes — chosen so N members applied as ONE engine pass
    inside one transaction are provably equal to N serial singletons:

      * INSERT ... VALUES with purely literal rows (no SELECT source,
        no REPLACE/ON DUPLICATE KEY — their conflict flows are
        per-row-stateful);
      * point UPDATE: single table, WHERE col = literal, every SET
        value a literal or one ``col ± literal`` step over this table's
        own columns (host-evaluable at the probed rows);
      * point DELETE: single table, WHERE col = literal.
    """
    if isinstance(stmt, A.InsertStmt):
        if stmt.select is not None:
            return "INSERT ... SELECT", None
        if stmt.replace or stmt.on_dup:
            return "REPLACE / ON DUPLICATE KEY UPDATE", None
        if not stmt.rows:
            return "no VALUES rows", None
        for row in stmt.rows:
            for cell in row:
                if not _literal_expr(cell):
                    return "non-literal INSERT value", None
        return "", {"kind": "insert"}
    if isinstance(stmt, A.UpdateStmt):
        if stmt.from_ is not None:
            return "multi-table UPDATE", None
        point = _point_where(stmt)
        if point is None:
            return "WHERE is not `col = literal`", None
        sets = []
        for name_ast, val_ast in stmt.sets:
            if name_ast.qualifier:
                return "qualified SET column", None
            if _literal_expr(val_ast):
                sets.append((name_ast.name, ("const", val_ast)))
                continue
            # one additive step over a column of this table:
            # col ± literal (or literal + col)
            if (isinstance(val_ast, A.EBinary) and val_ast.op in ("+", "-")):
                lhs, rhs = val_ast.left, val_ast.right
                if (isinstance(lhs, A.EName) and not lhs.qualifier
                        and _literal_expr(rhs)):
                    sets.append((name_ast.name,
                                 ("delta", lhs.name, val_ast.op, rhs, False)))
                    continue
                if (val_ast.op == "+" and isinstance(rhs, A.EName)
                        and not rhs.qualifier and _literal_expr(lhs)):
                    sets.append((name_ast.name,
                                 ("delta", rhs.name, "+", lhs, False)))
                    continue
            return "SET value beyond literal / col±literal", None
        return "", {"kind": "update", "where": point, "sets": sets}
    if isinstance(stmt, A.DeleteStmt):
        if stmt.from_ is not None:
            return "multi-table DELETE", None
        point = _point_where(stmt)
        if point is None:
            return "WHERE is not `col = literal`", None
        return "", {"kind": "delete", "where": point}
    return "not a DML statement", None


def batchable_dml(stmt) -> str:
    """'' when `stmt` passes the structural group-commit gate (the
    write-path sibling of batchable_plan), else the blocking reason."""
    return classify_dml(stmt)[0]


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------


class PlanCache:
    """Instance-wide LRU over verified plan entries (the catalog owns
    one, like the statements-summary store). Thread-safe; entries are
    immutable after publication."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        from tidb_tpu.analysis import sanitizer as _san

        self.lock = _san.tracked_lock("PlanCache.lock")
        self.capacity = capacity
        self._od: "OrderedDict" = OrderedDict()
        self._schema_version = -1
        self.hits = 0
        self.misses = 0
        self.bypasses = 0
        self.evictions = 0
        self.invalidations = 0
        self._bypass_reasons: dict = {}

    @staticmethod
    def _metric(event: str, n: int = 1) -> None:
        from tidb_tpu.utils.metrics import PLAN_CACHE_TOTAL

        PLAN_CACHE_TOTAL.inc(n, event=event)

    def _sync_schema_locked(self, schema_version: int) -> None:
        if schema_version != self._schema_version:
            if self._od:
                self.invalidations += len(self._od)
                self._metric("invalidate", len(self._od))
                self._od.clear()
            self._schema_version = schema_version

    @staticmethod
    def _valid(e: PlanCacheEntry) -> bool:
        for t, stats_id, fresh in e.table_states:
            s = getattr(t, "stats", None)
            if (None if s is None else id(s)) != stats_id:
                return False  # ANALYZE (or auto-analyze) since planning
            if (s is not None and s.version == t.version) != fresh:
                return False  # freshness flipped: DML since planning
        return True

    def on_schema_change(self, schema_version: int) -> None:
        """Eager invalidation hook (catalog.schema_version setter):
        release pinned plans/tables at the DDL, not at the next probe."""
        with self.lock:
            self._sync_schema_locked(schema_version)

    def lookup(self, key, schema_version: int,
               capacity: Optional[int] = None) -> Optional[PlanCacheEntry]:
        with self.lock:
            if capacity is not None:
                self.capacity = max(1, int(capacity))
            self._sync_schema_locked(schema_version)
            e = self._od.get(key)
            if e is None:
                return None
            if not self._valid(e):
                del self._od[key]
                self.invalidations += 1
                self._metric("invalidate")
                return None
            self._od.move_to_end(key)
            return e

    def store(self, key, entry: PlanCacheEntry, schema_version: int) -> None:
        with self.lock:
            self._sync_schema_locked(schema_version)
            if entry.schema_version != self._schema_version:
                return  # DDL raced the fill; the entry is already stale
            self._od[key] = entry
            self._od.move_to_end(key)
            while len(self._od) > self.capacity:
                self._od.popitem(last=False)
                self.evictions += 1
                self._metric("evict")

    def note_hit(self, entry: PlanCacheEntry) -> None:
        with self.lock:
            self.hits += 1
            entry.hits += 1
        self._metric("hit")

    def note_miss(self) -> None:
        with self.lock:
            self.misses += 1
        self._metric("miss")

    def note_bypass(self, reason: str) -> None:
        with self.lock:
            self.bypasses += 1
            self._bypass_reasons[reason] = \
                self._bypass_reasons.get(reason, 0) + 1
        self._metric("bypass")

    def invalidate_digest(self, digest: str) -> int:
        """Drop every entry of one statement digest (keys lead with the
        digest). Plan feedback (ISSUE 15) calls this when a NEW
        significant cardinality observation lands: a cached plan would
        otherwise keep serving the pre-feedback shape forever. O(size)
        over a small LRU; counted as invalidations."""
        with self.lock:
            doomed = [k for k in self._od
                      if isinstance(k, tuple) and k and k[0] == digest]
            for k in doomed:
                del self._od[k]
            if doomed:
                self.invalidations += len(doomed)
                self._metric("invalidate", len(doomed))
            return len(doomed)

    def clear(self) -> None:
        with self.lock:
            self._od.clear()

    def __len__(self) -> int:
        with self.lock:
            return len(self._od)

    def stats_dict(self, top: int = 50) -> dict:
        """JSON-ready snapshot (the /plan_cache endpoint payload)."""
        with self.lock:
            entries = [{
                "digest": e.digest, "db": e.db, "params": e.n_params,
                "cacheable": e.patches is not None, "hits": e.hits,
                "reason": e.reason,
            } for e in self._od.values()]
            snap = {
                "size": len(self._od), "capacity": self.capacity,
                "schema_version": self._schema_version,
                "hits": self.hits, "misses": self.misses,
                "bypasses": self.bypasses, "evictions": self.evictions,
                "invalidations": self.invalidations,
                "bypass_reasons": dict(self._bypass_reasons),
            }
        entries.sort(key=lambda d: d["hits"], reverse=True)
        snap["entries"] = entries[:max(0, top)]
        return snap
