"""Rule-based logical optimization (ref: planner/core logicalOptimize's
rule list: constant folding, predicate pushdown, column pruning, ...).

Rules here are functions LogicalPlan -> LogicalPlan, applied in a fixed
order. The set matters for the TPU backend: pushing predicates into the
scan means the filter mask is computed inside the same jitted fragment
that stages the columns (the coprocessor-pushdown analogue), and pruning
decides which columns get staged to HBM at all.
"""

from __future__ import annotations

from typing import List, Optional, Set

import numpy as np

from tidb_tpu.expression.compiler import eval_expr
from tidb_tpu.expression.expr import (
    AggRef,
    Call,
    Case,
    Cast,
    ColumnRef,
    Expr,
    InList,
    Literal,
    Lookup,
    walk,
)
from tidb_tpu.planner.logical import (
    AggSpec,
    LAggregate,
    LJoin,
    LLimit,
    LProjection,
    LScan,
    LSelection,
    LSort,
    LWindow,
    LUnion,
    LogicalPlan,
)
from tidb_tpu.types import BOOL, TypeKind

__all__ = ["optimize_logical", "fold_constants"]


# ---------------------------------------------------------------------------
# constant folding
# ---------------------------------------------------------------------------

def fold_constants(e: Expr) -> Expr:
    """Bottom-up folding of all-literal subtrees via the real compiler on a
    1-row chunk — semantics identical to runtime by construction."""
    if isinstance(e, Call):
        args = tuple(fold_constants(a) for a in e.args)
        e = Call(type_=e.type_, op=e.op, args=args)
        # Kleene shortcuts with literal TRUE/FALSE
        if e.op == "and":
            lits = [a for a in args if isinstance(a, Literal)]
            if any(a.value is False for a in lits):
                return Literal(type_=BOOL, value=False)
            non = [a for a in args if not (isinstance(a, Literal) and a.value is True)]
            if not non:
                return Literal(type_=BOOL, value=True)
            if len(non) == 1 and not any(isinstance(a, Literal) and a.value is None for a in args):
                return non[0]
        if e.op == "or":
            lits = [a for a in args if isinstance(a, Literal)]
            if any(a.value is True for a in lits):
                return Literal(type_=BOOL, value=True)
            non = [a for a in args if not (isinstance(a, Literal) and a.value is False)]
            if not non:
                return Literal(type_=BOOL, value=False)
            if len(non) == 1 and not any(isinstance(a, Literal) and a.value is None for a in args):
                return non[0]
        if all(isinstance(a, Literal) for a in args):
            return _eval_const(e)
        return e
    if isinstance(e, Cast):
        arg = fold_constants(e.arg)
        e = Cast(type_=e.type_, arg=arg)
        if isinstance(arg, Literal):
            return _eval_const(e)
        return e
    if isinstance(e, Case):
        whens = tuple((fold_constants(c), fold_constants(r)) for c, r in e.whens)
        else_ = fold_constants(e.else_) if e.else_ is not None else None
        return Case(type_=e.type_, whens=whens, else_=else_)
    if isinstance(e, Lookup):
        return Lookup(type_=e.type_, arg=fold_constants(e.arg), table=e.table,
                      table_valid=e.table_valid)
    if isinstance(e, InList):
        return InList(type_=e.type_, arg=fold_constants(e.arg), values=e.values,
                      negated=e.negated)
    return e


def _eval_const(e: Expr) -> Literal:
    from tidb_tpu.chunk.chunk import Chunk
    import jax.numpy as jnp

    dummy = Chunk({}, jnp.ones(1, dtype=jnp.bool_))
    data, valid = eval_expr(e, dummy)
    if not bool(np.asarray(valid)[0]):
        return Literal(type_=e.type_, value=None)
    v = np.asarray(data)[0]
    if e.type_.kind == TypeKind.BOOL:
        return Literal(type_=e.type_, value=bool(v))
    if e.type_.kind == TypeKind.FLOAT:
        return Literal(type_=e.type_, value=float(v))
    return Literal(type_=e.type_, value=int(v))


def _rule_fold(plan: LogicalPlan) -> LogicalPlan:
    for i, c in enumerate(plan.children):
        plan.children[i] = _rule_fold(c)
    if isinstance(plan, LSelection):
        plan.cond = fold_constants(plan.cond)
        if isinstance(plan.cond, Literal) and plan.cond.value is True:
            return plan.child
    elif isinstance(plan, LProjection):
        plan.exprs = [fold_constants(x) for x in plan.exprs]
    elif isinstance(plan, LAggregate):
        plan.group_exprs = [fold_constants(x) for x in plan.group_exprs]
        for a in plan.aggs:
            if a.arg is not None:
                a.arg = fold_constants(a.arg)
    elif isinstance(plan, LJoin):
        plan.eq_conds = [(fold_constants(l), fold_constants(r)) for l, r in plan.eq_conds]
        if plan.other_cond is not None:
            plan.other_cond = fold_constants(plan.other_cond)
    elif isinstance(plan, LSort):
        plan.items = [(fold_constants(x), d) for x, d in plan.items]
    return plan


# ---------------------------------------------------------------------------
# predicate pushdown
# ---------------------------------------------------------------------------

def _conj_split(e: Expr) -> List[Expr]:
    if isinstance(e, Call) and e.op == "and":
        return _conj_split(e.args[0]) + _conj_split(e.args[1])
    return [e]


def _conj_join(parts: List[Expr]) -> Optional[Expr]:
    out = None
    for p in parts:
        out = p if out is None else Call(type_=BOOL, op="and", args=(out, p))
    return out


def _refs(e: Expr) -> Set[str]:
    return {n.name for n in walk(e) if isinstance(n, (ColumnRef, AggRef))}


def _subst_proj(e: Expr, mapping) -> Expr:
    """Rewrite uids through a projection (uid -> defining expr)."""
    if isinstance(e, ColumnRef):
        return mapping.get(e.name, e)
    if isinstance(e, Call):
        return Call(type_=e.type_, op=e.op, args=tuple(_subst_proj(a, mapping) for a in e.args))
    if isinstance(e, Cast):
        return Cast(type_=e.type_, arg=_subst_proj(e.arg, mapping))
    if isinstance(e, Lookup):
        return Lookup(type_=e.type_, arg=_subst_proj(e.arg, mapping), table=e.table, table_valid=e.table_valid)
    if isinstance(e, InList):
        return InList(type_=e.type_, arg=_subst_proj(e.arg, mapping), values=e.values, negated=e.negated)
    if isinstance(e, Case):
        return Case(
            type_=e.type_,
            whens=tuple((_subst_proj(c, mapping), _subst_proj(r, mapping)) for c, r in e.whens),
            else_=_subst_proj(e.else_, mapping) if e.else_ is not None else None,
        )
    return e


def _push_cond(plan: LogicalPlan, conds: List[Expr]) -> LogicalPlan:
    """Push conjuncts as far down as they can go; returns new plan."""
    if not conds:
        return _rule_pushdown(plan)

    if isinstance(plan, LScan) and plan.table is not None:
        plan.pushed_cond = _conj_join(
            ([plan.pushed_cond] if plan.pushed_cond is not None else []) + conds
        )
        return plan

    if isinstance(plan, LSelection):
        return _push_cond(plan.child, conds + _conj_split(plan.cond))

    if isinstance(plan, LProjection):
        mapping = {c.uid: x for c, x in zip(plan.schema, plan.exprs)}
        # only push through simple (non-volatile) projections
        rewritten = [_subst_proj(c, mapping) for c in conds]
        plan.children[0] = _push_cond(plan.child, rewritten)
        return plan

    if isinstance(plan, LJoin):
        left_uids = {c.uid for c in plan.children[0].schema}
        right_uids = {c.uid for c in plan.children[1].schema}
        lconds, rconds, keep = [], [], []
        for c in conds:
            r = _refs(c)
            if r <= left_uids:
                lconds.append(c)
            elif r <= right_uids and plan.kind == "inner":
                rconds.append(c)
            elif r <= right_uids and plan.kind in ("semi", "anti"):
                rconds.append(c)
            elif plan.kind in ("inner", "cross"):
                # equi conjunct across the two sides becomes a join key
                # (this is what turns comma joins into hash joins)
                if isinstance(c, Call) and c.op == "eq":
                    a, b = c.args
                    ra, rb = _refs(a), _refs(b)
                    if ra <= left_uids and rb <= right_uids:
                        plan.eq_conds.append((a, b))
                        plan.kind = "inner"
                        continue
                    if ra <= right_uids and rb <= left_uids:
                        plan.eq_conds.append((b, a))
                        plan.kind = "inner"
                        continue
                keep.append(c)
            else:
                keep.append(c)
        plan.children[0] = _push_cond(plan.children[0], lconds)
        plan.children[1] = _push_cond(plan.children[1], rconds)
        plan.children[0] = _rule_pushdown(plan.children[0]) if not lconds else plan.children[0]
        if keep and plan.kind in ("inner", "cross"):
            # cross-side non-equi conjuncts: for an inner join a post-join
            # filter and a WHERE above are identical — fuse into the join
            plan.other_cond = _conj_join(
                ([plan.other_cond] if plan.other_cond is not None else []) + keep)
            keep = []
        if keep:
            return LSelection(schema=plan.schema, children=[plan], cond=_conj_join(keep))
        return plan

    if isinstance(plan, LAggregate):
        # conds referencing only group uids could push below; round 1: stop
        plan.children[0] = _rule_pushdown(plan.child)
        return LSelection(schema=plan.schema, children=[plan], cond=_conj_join(conds))

    # default: stop here
    plan.children = [_rule_pushdown(c) for c in plan.children]
    return LSelection(schema=plan.schema, children=[plan], cond=_conj_join(conds))


def _rule_pushdown(plan: LogicalPlan) -> LogicalPlan:
    if isinstance(plan, LSelection):
        child = plan.child
        return _push_cond(child, _conj_split(plan.cond))
    plan.children = [_rule_pushdown(c) for c in plan.children]
    return plan


# ---------------------------------------------------------------------------
# column pruning
# ---------------------------------------------------------------------------

def _rule_prune(plan: LogicalPlan, required: Optional[Set[str]]) -> LogicalPlan:
    """required=None means 'all visible outputs required' (root)."""
    if isinstance(plan, LScan):
        if required is not None and plan.table is not None:
            need = set(required)
            if plan.pushed_cond is not None:
                need |= _refs(plan.pushed_cond)
            keep = [c for c in plan.schema if c.uid in need]
            if not keep and plan.schema:
                keep = [plan.schema[0]]  # COUNT(*): one column for liveness
            plan.schema = keep
        return plan

    if isinstance(plan, LSelection):
        child_req = None
        if required is not None:
            child_req = set(required) | _refs(plan.cond)
        plan.children[0] = _rule_prune(plan.child, child_req)
        if required is not None:
            plan.schema = [c for c in plan.schema if c.uid in required or c.uid in {s.uid for s in plan.child.schema}]
        plan.schema = list(plan.child.schema)
        return plan

    if isinstance(plan, LProjection):
        if required is not None:
            keep = [
                (c, x)
                for c, x in zip(plan.schema, plan.exprs)
                if c.uid in required
            ]
            # keep at least one column so COUNT(*) style plans have a stream
            if not keep:
                keep = [(plan.schema[0], plan.exprs[0])]
            plan.schema = [c for c, _ in keep]
            plan.exprs = [x for _, x in keep]
            plan.n_visible = len(plan.schema)
        child_req = set()
        for x in plan.exprs:
            child_req |= _refs(x)
        plan.children[0] = _rule_prune(plan.child, child_req)
        return plan

    if isinstance(plan, LAggregate):
        if required is not None:
            keep_aggs = [a for a in plan.aggs if a.uid in required]
            plan.aggs = keep_aggs
            plan.schema = [
                c for c in plan.schema
                if c.uid in required or c.uid in plan.group_uids
            ]
        child_req = set()
        for g in plan.group_exprs:
            child_req |= _refs(g)
        for a in plan.aggs:
            if a.arg is not None:
                child_req |= _refs(a.arg)
        # an EMPTY set is meaningful ("only structural needs below" —
        # COUNT(*) over a join must still prune to the join keys);
        # widening it to None would disable pruning entirely
        plan.children[0] = _rule_prune(plan.child, child_req)
        return plan

    if isinstance(plan, LJoin):
        if required is None:
            # 'everything required' propagates as-is: a Selection above
            # this join may reference ANY child column — pruning down to
            # the eq keys here dropped columns the parent still reads
            plan.children[0] = _rule_prune(plan.children[0], None)
            plan.children[1] = _rule_prune(plan.children[1], None)
            if plan.kind in ("semi", "anti"):
                plan.schema = list(plan.children[0].schema)
            else:
                plan.schema = (list(plan.children[0].schema)
                               + list(plan.children[1].schema))
            return plan
        child_req_l, child_req_r = set(), set()
        left_uids = {c.uid for c in plan.children[0].schema}
        right_uids = {c.uid for c in plan.children[1].schema}
        for uid in required:
            if uid in left_uids:
                child_req_l.add(uid)
            elif uid in right_uids:
                child_req_r.add(uid)
        for l, r in plan.eq_conds:
            child_req_l |= _refs(l)
            child_req_r |= _refs(r)
        if plan.other_cond is not None:
            lu = {c.uid for c in plan.children[0].schema}
            for uid in _refs(plan.other_cond):
                (child_req_l if uid in lu else child_req_r).add(uid)
        plan.children[0] = _rule_prune(plan.children[0], child_req_l or None)
        plan.children[1] = _rule_prune(plan.children[1], child_req_r or None)
        if plan.kind in ("semi", "anti"):
            plan.schema = list(plan.children[0].schema)
        else:
            plan.schema = list(plan.children[0].schema) + list(plan.children[1].schema)
        if required is not None:
            plan.schema = [c for c in plan.schema if c.uid in required or c.uid in child_req_l | child_req_r]
        return plan

    if isinstance(plan, (LSort,)):
        child_req = None
        if required is not None:
            child_req = set(required)
            for x, _ in plan.items:
                child_req |= _refs(x)
        plan.children[0] = _rule_prune(plan.child, child_req)
        plan.schema = list(plan.child.schema)
        return plan

    if isinstance(plan, LWindow):
        child_req = None
        if required is not None:
            child_req = set(required) - {plan.out_uid}
            for x in plan.args:
                child_req |= _refs(x)
            for x in plan.partition_by:
                child_req |= _refs(x)
            for x, _ in plan.order_by:
                child_req |= _refs(x)
        plan.children[0] = _rule_prune(plan.child, child_req)
        out_col = plan.schema[-1]
        plan.schema = list(plan.child.schema) + [out_col]
        return plan

    if isinstance(plan, (LLimit,)):
        plan.children[0] = _rule_prune(plan.child, required)
        plan.schema = list(plan.child.schema)
        return plan

    if isinstance(plan, LUnion):
        # all sides share output uids; prune positionally
        plan.children = [_rule_prune(c, set(required) if required is not None else None) for c in plan.children]
        plan.schema = list(plan.children[0].schema)
        return plan

    plan.children = [_rule_prune(c, None) for c in plan.children]
    return plan


# ---------------------------------------------------------------------------
# join reordering (ref: planner/core's join-reorder rule — greedy over
# statistics-driven cardinality estimates; FROM-order joins are a 10-100x
# perf cliff at scale, and a cross join blocks the distributed tier)
# ---------------------------------------------------------------------------


def _flatten_inner(plan: LogicalPlan, leaves, eqs, others):
    """Collect the maximal contiguous inner/cross-join tree."""
    if isinstance(plan, LJoin) and plan.kind in ("inner", "cross"):
        _flatten_inner(plan.children[0], leaves, eqs, others)
        _flatten_inner(plan.children[1], leaves, eqs, others)
        eqs.extend(plan.eq_conds)
        if plan.other_cond is not None:
            others.append(plan.other_cond)
    else:
        leaves.append(plan)


def _classify_edges(leaves, eqs, others):
    """Split equi-conds into cross-leaf join edges vs leftovers that
    must re-apply as a post-join filter. Shared by the greedy and
    LEADING-forced orderers."""
    uidsets = [{c.uid for c in l.schema} for l in leaves]

    def owner(refs: Set[str]) -> Optional[int]:
        for i, s in enumerate(uidsets):
            if refs and refs <= s:
                return i
        return None

    edges = []  # (leaf_i, leaf_j, expr_i, expr_j)
    leftover = list(others)
    for a, b in eqs:
        ia, ib = owner(_refs(a)), owner(_refs(b))
        if ia is None or ib is None or ia == ib:
            leftover.append(Call(type_=BOOL, op="eq", args=(a, b)))
        else:
            edges.append((ia, ib, a, b))
    return edges, leftover


def _join_step_cost(l_rows: float, r_rows: float, out_rows: float,
                    n_parts: int) -> float:
    """Mesh-aware cost of one join step: output cardinality plus the
    exchange volume the executor will pay. A hash shuffle repartitions
    BOTH sides over ICI (l + r rows); broadcasting the smaller side
    replicates it to every shard (small * n_parts) and skips the
    repartition — charge whichever the executor would pick (ref:
    planner/core's cop/mpp cost factors for exchange types)."""
    from tidb_tpu.parallel.fragment import BROADCAST_LIMIT

    shuffle = l_rows + r_rows
    small = min(l_rows, r_rows)
    exch = shuffle
    if small <= BROADCAST_LIMIT:
        exch = min(exch, small * n_parts)
    return out_rows + exch


def _greedy_order(leaves, eqs, others, n_parts: int = 1) -> LogicalPlan:
    from tidb_tpu.planner.physical import _estimate, eq_join_rows

    n = len(leaves)
    edges, leftover = _classify_edges(leaves, eqs, others)

    est = [_estimate(l) for l in leaves]
    start = min(range(n), key=lambda i: est[i])
    cur_set = {start}
    tree, cur_rows = leaves[start], est[start]
    remaining = set(range(n)) - cur_set

    while remaining:
        def conn_edges(c):
            out = []
            for ia, ib, a, b in edges:
                if ia in cur_set and ib == c:
                    out.append((a, b))
                elif ib in cur_set and ia == c:
                    out.append((b, a))
            return out

        def join_rows(c, conds):
            if not conds:
                return cur_rows * est[c]  # forced cross join
            return eq_join_rows(tree, leaves[c], conds, cur_rows, est[c])

        def step_cost(c, conds):
            return _join_step_cost(cur_rows, est[c], join_rows(c, conds),
                                   n_parts)

        cands = [(c, conn_edges(c)) for c in remaining]
        connected = [(c, e) for c, e in cands if e]
        pool = connected or cands  # avoid cross joins whenever possible
        best, conds = min(pool, key=lambda ce: step_cost(*ce))
        cur_rows = join_rows(best, conds)
        tree = LJoin(
            schema=list(tree.schema) + list(leaves[best].schema),
            children=[tree, leaves[best]],
            kind="inner", eq_conds=conds,
        )
        cur_set.add(best)
        remaining.discard(best)

    if leftover:
        sel = LSelection(schema=list(tree.schema), children=[tree],
                         cond=_conj_join(leftover))
        return _rule_pushdown(sel)  # re-extract eq keys / push filters
    return tree


def _leaf_name(leaf: LogicalPlan) -> Optional[str]:
    """Dominant table alias of a join leaf (for LEADING hint matching)."""
    for c in leaf.schema:
        if c.qualifier:
            return c.qualifier.lower()
    return None


def _forced_order(leaves, eqs, others, leading) -> LogicalPlan:
    """LEADING(a, b, ...) hint: join in exactly the given order (a
    prefix — unmentioned leaves follow in source order), using whatever
    equi-edges connect at each step. Mirrors the reference's
    leading-hint override of the join-reorder rule. Callers check the
    hint matches at least one leaf (_match_leading) first."""
    matched = _match_leading(leaves, leading)
    seq = matched + [i for i in range(len(leaves)) if i not in matched]

    edges, leftover = _classify_edges(leaves, eqs, others)

    cur_set = {seq[0]}
    tree = leaves[seq[0]]
    for c in seq[1:]:
        conds = []
        for ia, ib, a, b in edges:
            if ia in cur_set and ib == c:
                conds.append((a, b))
            elif ib in cur_set and ia == c:
                conds.append((b, a))
        tree = LJoin(
            schema=list(tree.schema) + list(leaves[c].schema),
            children=[tree, leaves[c]],
            kind="inner", eq_conds=conds,
        )
        cur_set.add(c)
    if leftover:
        sel = LSelection(schema=list(tree.schema), children=[tree],
                         cond=_conj_join(leftover))
        return _rule_pushdown(sel)
    return tree


def _match_leading(leaves, leading):
    """Leaf indices the LEADING names resolve to, in hint order."""
    by_name = {}
    for i, l in enumerate(leaves):
        nm = _leaf_name(l)
        if nm is not None and nm not in by_name:
            by_name[nm] = i
    # dict.fromkeys: a repeated alias in the hint must not join a leaf twice
    return list(dict.fromkeys(
        by_name[n.lower()] for n in leading if n.lower() in by_name))


def _rule_reorder(plan: LogicalPlan, leading=None, cascades=False,
                  n_parts: int = 1) -> LogicalPlan:
    if getattr(plan, "_block_boundary", False):
        leading = None  # hints don't cross into derived query blocks
    if isinstance(plan, LJoin) and plan.kind in ("inner", "cross"):
        leaves, eqs, others = [], [], []
        _flatten_inner(plan, leaves, eqs, others)
        # the hint applies to ITS query block — the topmost join group
        # here — not to derived tables / subquery joins below. A hint
        # matching no leaf (typo'd alias) is ignored entirely.
        if leading and len(leaves) >= 2 and _match_leading(leaves, leading):
            # the hint pins THIS block's order; subtrees keep the
            # session's planner mode
            leaves = [_rule_reorder(l, cascades=cascades, n_parts=n_parts)
                      for l in leaves]
            return _forced_order(leaves, eqs, others, leading)
        if len(leaves) > 2:
            leaves = [_rule_reorder(l, cascades=cascades, n_parts=n_parts)
                      for l in leaves]
            if cascades:
                from tidb_tpu.planner.cascades import memo_join_search

                best = memo_join_search(leaves, eqs, others, _classify_edges,
                                        _conj_join, _rule_pushdown,
                                        n_parts=n_parts)
                if best is not None:
                    return best
            return _greedy_order(leaves, eqs, others, n_parts=n_parts)
    plan.children = [_rule_reorder(c, leading, cascades, n_parts)
                     for c in plan.children]
    return plan


# ---------------------------------------------------------------------------

def _rule_distinct_two_phase(plan: LogicalPlan) -> LogicalPlan:
    """Rewrite DISTINCT aggregates into two stacked aggregations (ref:
    the reference planner's distinct-agg-to-two-phase transform):

        Agg[G; f(DISTINCT d), sum(x), ...]
          -> Agg[G; count(d)/sum(d), sum(sx), ...]      (outer, small)
               Agg[G + d; sum(x) AS sx, ...]            (inner)

    The inner agg has no DISTINCT, so it is distributable as a mesh
    fragment; the outer agg reduces one row per (G, d) group. Applies
    when every DISTINCT agg shares one argument and the remaining aggs
    are sum/count/min/max (each re-aggregates losslessly from the
    inner's per-group value). NULL semantics hold: the NULL-d group's
    key column is NULL, which outer count()/sum() skip."""
    from tidb_tpu.planner.binder import PlanCol

    plan.children = [_rule_distinct_two_phase(c) for c in plan.children]
    if not isinstance(plan, LAggregate) or not any(a.distinct for a in plan.aggs):
        return plan
    d_args = [a.arg for a in plan.aggs if a.distinct]
    if any(a is None for a in d_args) or len({repr(a) for a in d_args}) != 1:
        return plan
    if any(a.func not in ("count", "sum", "avg")
           for a in plan.aggs if a.distinct):
        return plan
    if any(a.func not in ("sum", "count", "min", "max")
           for a in plan.aggs if not a.distinct):
        return plan
    if not plan.group_uids and any(
            a.func == "count" and not a.distinct for a in plan.aggs):
        # a global COUNT re-aggregates as sum(inner counts), which is
        # NULL over an empty inner — SQL requires 0; keep the direct path
        return plan
    d_arg = d_args[0]

    child = plan.children[0]
    group_cols = list(plan.schema[:len(plan.group_uids)])
    # uids derive from the original agg uids: re-planning the same query
    # must produce identical fragment signatures or every execution pays
    # a fresh XLA compile (fragment/growth caches key on the plan repr)
    d_uid = "d2p_" + next(a.uid for a in plan.aggs if a.distinct)
    d_col = PlanCol(uid=d_uid, name="d2p", type_=d_arg.type_,
                    dict_=getattr(d_arg, "_dict", None))

    inner_aggs, inner_cols, outer_aggs = [], [], []
    outer_func = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}
    for a in plan.aggs:
        if a.distinct:
            # d is unique per outer group in the inner output
            f = "count" if a.func == "count" else a.func
            outer_aggs.append(AggSpec(
                uid=a.uid, func=f,
                arg=ColumnRef(type_=d_arg.type_, name=d_uid), type_=a.type_))
        else:
            iuid = "d2p_" + a.uid
            inner_aggs.append(AggSpec(uid=iuid, func=a.func, arg=a.arg,
                                      type_=a.type_))
            inner_cols.append(PlanCol(uid=iuid, name="d2p", type_=a.type_))
            outer_aggs.append(AggSpec(
                uid=a.uid, func=outer_func[a.func],
                arg=ColumnRef(type_=a.type_, name=iuid), type_=a.type_))

    inner = LAggregate(
        schema=group_cols + [d_col] + inner_cols,
        children=[child],
        group_exprs=list(plan.group_exprs) + [d_arg],
        group_uids=list(plan.group_uids) + [d_uid],
        aggs=inner_aggs,
    )
    return LAggregate(
        schema=plan.schema,
        children=[inner],
        group_exprs=[c.ref() for c in group_cols],
        group_uids=list(plan.group_uids),
        aggs=outer_aggs,
    )




def _rule_eager_agg(plan: LogicalPlan, cost_based: bool = False,
                    n_parts: int = 1) -> LogicalPlan:
    """Eager aggregation: push a partial aggregate below a join when
    every aggregate argument comes from one join side (ref: planner/
    core's aggregation-pushdown rule; the canonical win is Q18's
    lineitem pre-aggregated by l_orderkey before joining orders — the
    join input shrinks by the average group size BEFORE the expensive
    exchange/build).

    For inner joins, grouping side S by (its join-key exprs on the path
    + the upper group keys it supplies) and summing partials upstream
    is exact: all rows of one partial group share their join keys, so
    each partial joins to the same match set, and SUM/COUNT partials
    multiplied out by matches reproduce the row-level totals (MIN/MAX
    are duplicate-insensitive). Gated on fresh-stats evidence that the
    partial actually shrinks its side (<70%); bails on DISTINCT / AVG /
    non-inner joins on the path / expressions straddling both sides /
    global COUNT (an empty join must still report 0, not NULL)."""
    if plan.children:
        plan.children[:] = [_rule_eager_agg(c, cost_based, n_parts)
                            for c in plan.children]
    if not (isinstance(plan, LAggregate) and isinstance(plan.children[0], LJoin)):
        return plan
    agg = plan
    if any(a.distinct or a.func not in ("sum", "count", "min", "max")
           for a in agg.aggs):
        return plan
    if not agg.group_exprs and any(a.func == "count" for a in agg.aggs):
        return plan  # global COUNT over an empty join must be 0
    arg_refs: Set[str] = set()
    for a in agg.aggs:
        if a.arg is not None:
            arg_refs |= _refs(a.arg)
    if not arg_refs:
        return plan  # COUNT(*) only: no side owns it more than another

    # descend the join tree to the unique subtree S holding every agg
    # argument; every join on the path must be inner, and its conds must
    # not mix S columns with the other side inside one expression
    path = []  # (join, side) from top to S's parent
    node = agg.child if hasattr(agg, "child") else agg.children[0]
    while isinstance(node, LJoin):
        luids = {c.uid for c in node.children[0].schema}
        ruids = {c.uid for c in node.children[1].schema}
        if arg_refs <= luids:
            side = 0
        elif arg_refs <= ruids:
            side = 1
        else:
            return plan
        # inner joins preserve the multiplicity math on either side;
        # left/semi/anti joins never DUPLICATE their left rows (they
        # filter or NULL-pad), so descending their left side is exact —
        # their right side would change partial-group membership
        if node.kind != "inner" and not (
                side == 0 and node.kind in ("left", "semi", "anti")):
            return plan
        path.append((node, side))
        node = node.children[side]
    if not path:
        return plan
    S = node
    s_uids = {c.uid for c in S.schema}

    # collect S-side join-key exprs along the path and upper group keys
    # that S supplies; anything else touching S bails
    key_exprs: List[Expr] = []  # identity-ordered

    def add_key(e: Expr) -> int:
        for i, k in enumerate(key_exprs):
            if k is e or (isinstance(k, ColumnRef) and isinstance(e, ColumnRef)
                          and k.name == e.name):
                return i
        key_exprs.append(e)
        return len(key_exprs) - 1

    join_key_slots = []  # (join, side-expr index in eq_conds, key slot)
    for join, side in path:
        if join.other_cond is not None and _refs(join.other_cond) & s_uids:
            return plan
        for ci, (le, re_) in enumerate(join.eq_conds):
            se = le if side == 0 else re_
            oe = re_ if side == 0 else le
            if _refs(oe) & s_uids:
                return plan
            if _refs(se) & s_uids:
                if not _refs(se) <= s_uids:
                    return plan
                join_key_slots.append((join, ci, add_key(se)))
    group_slots = []  # (upper group index, key slot)
    for gi, g in enumerate(agg.group_exprs):
        r = _refs(g)
        if r & s_uids:
            if not r <= s_uids:
                return plan
            group_slots.append((gi, add_key(g)))

    # first half of the shrink gate: every key must be a ColumnRef with
    # a known NDV (heuristic fallbacks would fire the rewrite blind) —
    # checked BEFORE construction so uid derivation below can rely on it
    from tidb_tpu.planner.physical import _eq_ndv, _estimate

    s_rows = _estimate(S)
    if not all(isinstance(e, ColumnRef)
               and _eq_ndv(S, e, s_rows) is not None for e in key_exprs):
        return plan

    # build the partial aggregate over S. Uids derive from the inputs
    # (NOT a global counter): re-planning the same SQL must produce the
    # same uids, or the fragment/JIT caches — keyed on expr reprs — miss
    # on every execution (the _rule_distinct_two_phase invariant)
    from tidb_tpu.planner.binder import PlanCol

    key_uids = [f"eaggk.{e.name}" for e in key_exprs]
    key_cols = [PlanCol(uid=u, name=u, type_=e.type_,
                        dict_=getattr(e, "_dict", None))
                for u, e in zip(key_uids, key_exprs)]
    p_aggs: List[AggSpec] = []
    p_cols: List[PlanCol] = []
    upper_aggs: List[AggSpec] = []
    for a in agg.aggs:
        u = f"eagg.{a.uid}"
        p_aggs.append(AggSpec(uid=u, func=a.func, arg=a.arg, type_=a.type_))
        p_cols.append(PlanCol(uid=u, name=u, type_=a.type_,
                              dict_=(getattr(a.arg, "_dict", None)
                                     if a.func in ("min", "max") and a.arg is not None
                                     else None)))
        ref = ColumnRef(type_=a.type_, name=u)
        if getattr(a.arg, "_dict", None) is not None and a.func in ("min", "max"):
            object.__setattr__(ref, "_dict", a.arg._dict)
        # partials combine upstream: SUM/COUNT re-sum (each partial row
        # re-counts once per join match — the multiplicity the original
        # row-level aggregation saw), MIN/MAX re-extremize
        upper_func = "sum" if a.func in ("sum", "count") else a.func
        upper_aggs.append(AggSpec(uid=a.uid, func=upper_func, arg=ref,
                                  type_=a.type_))
    partial = LAggregate(
        schema=key_cols + p_cols, children=[S],
        group_exprs=list(key_exprs), group_uids=list(key_uids),
        aggs=p_aggs,
    )

    # placement decision. Cascades mode prices BOTH alternatives with
    # the memo's shared cost model (_join_step_cost + LOCAL_WORK, the
    # terms the join-order search itself minimizes) over the join path
    # the partial would ride — pre-agg vs post-agg trades off against
    # the same units as join order and access paths (SURVEY.md:88-89).
    # The heuristic mode keeps the fresh-stats 70% shrink gate.
    p_rows = _estimate(partial)
    if cost_based:
        from tidb_tpu.planner.cascades import LOCAL_WORK

        def path_cost(side_rows: float) -> float:
            # join outputs scale linearly in the S-side cardinality
            # under the key-join model the estimator already assumes
            cost, cur = 0.0, side_rows
            scale = side_rows / max(s_rows, 1.0)
            for join, side in reversed(path):
                o_rows = float(_estimate(join.children[1 - side]))
                out = float(_estimate(join)) * scale
                cost += (_join_step_cost(cur, o_rows, out, n_parts)
                         + LOCAL_WORK * (cur + o_rows))
                cur = out
            return cost

        build = LOCAL_WORK * s_rows + p_rows  # partial's own pass
        if build + path_cost(p_rows) >= path_cost(s_rows):
            return plan
    elif not (p_rows < 0.7 * s_rows):
        return plan

    # splice: replace S, rebuild path joins bottom-up with rewritten
    # S-side key exprs and recomposed schemas
    child: LogicalPlan = partial

    def key_ref(slot: int) -> Expr:
        e = key_exprs[slot]
        ref = ColumnRef(type_=e.type_, name=key_uids[slot])
        d = getattr(e, "_dict", None)
        if d is not None:
            object.__setattr__(ref, "_dict", d)
        return ref

    for join, side in reversed(path):
        new_eq = list(join.eq_conds)
        for j, ci, slot in join_key_slots:
            if j is join:
                le, re_ = new_eq[ci]
                new_eq[ci] = (key_ref(slot), re_) if side == 0 \
                    else (le, key_ref(slot))
        kids = list(join.children)
        kids[side] = child
        child = LJoin(
            schema=list(kids[0].schema) + list(kids[1].schema),
            children=kids, kind=join.kind, eq_conds=new_eq,
            other_cond=join.other_cond, exists_sem=join.exists_sem,
            index_join=getattr(join, "index_join", None),
        )

    new_groups = list(agg.group_exprs)
    for gi, slot in group_slots:
        new_groups[gi] = key_ref(slot)
    return LAggregate(
        schema=agg.schema, children=[child],
        group_exprs=new_groups, group_uids=list(agg.group_uids),
        aggs=upper_aggs,
    )


def optimize_logical(plan: LogicalPlan, hints=(), cascades=False,
                     n_parts: int = 1, agg_push_down: bool = True) -> LogicalPlan:
    plan = _rule_distinct_two_phase(plan)
    plan = _rule_fold(plan)
    plan = _rule_pushdown(plan)
    leading = next((args for name, args in hints if name == "leading"), None)
    plan = _rule_reorder(plan, leading, cascades, n_parts)
    if agg_push_down:
        plan = _rule_eager_agg(plan, cost_based=cascades, n_parts=n_parts)
    plan = _rule_prune(plan, None)
    return plan
