"""Physical plan (ref: planner/core Physical* operators + EXPLAIN).

Lowering is algorithm selection: aggregation picks a device strategy
(packed-code segment-sum vs generic), joins pick a build side from row
estimates, Sort+Limit fuses to TopN. Every node is annotated with `task`:
"device" operators run inside jitted fragments on TPU; "root" operators
run host-side on materialized (small) results — mirroring the reference's
coprocessor-vs-root split.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from tidb_tpu.planner.binder import PlanCol
from tidb_tpu.planner.logical import (
    AggSpec,
    LAggregate,
    LJoin,
    LLimit,
    LProjection,
    LScan,
    LSelection,
    LSort,
    LUnion,
    LWindow,
    LogicalPlan,
)

__all__ = [
    "PhysicalPlan", "PScan", "PSelection", "PProjection", "PHashAgg",
    "PHashJoin", "PSort", "PTopN", "PLimit", "PUnion", "PWindow",
    "PPointGet", "PIndexRangeScan", "PPartitionScan", "PIndexJoin",
    "lower", "explain_text",
]


@dataclass
class PhysicalPlan:
    schema: List[PlanCol] = field(default_factory=list)
    children: List["PhysicalPlan"] = field(default_factory=list)
    est_rows: float = 0.0
    task: str = "device"

    @property
    def child(self) -> "PhysicalPlan":
        return self.children[0]

    def op_name(self) -> str:
        return type(self).__name__[1:]

    def op_info(self) -> str:
        return ""


@dataclass
class PScan(PhysicalPlan):
    db: str = ""
    table_name: str = ""
    table: object = None
    pushed_cond: object = None

    def op_name(self):
        return "TableFullScan"

    def op_info(self):
        info = f"table:{self.table_name}"
        if self.pushed_cond is not None:
            info += ", pushed_filter"
        return info


@dataclass
class PPointGet(PScan):
    """Unique-index point access (ref: planner/core point_get_plan.go →
    PointGetExecutor; SURVEY.md:91 IndexLookUp's index→row path). The
    full pushed_cond is retained, so every execution path — including
    ones that treat this as a plain scan — stays correct; the point
    executor is the O(log n) fast path."""

    index_name: str = ""
    key_values: Tuple = ()
    # the pushed filter is EXACTLY the key equalities: the unique-index
    # probe already enforces it, so the executor skips the residual
    # evaluation (ref: PointGetExecutor reads by key, no Selection)
    cond_covered: bool = False

    def op_name(self):
        return "PointGet"

    def op_info(self):
        return (f"table:{self.table_name}, index:{self.index_name}, "
                f"key:{tuple(self.key_values)!r}"
                + (", key_only" if self.cond_covered else ""))


@dataclass
class PIndexRangeScan(PScan):
    """Index range access (ref: planner/core's IndexRangeScan feeding
    IndexLookUpExecutor, SURVEY.md:91): equality literals pin a prefix
    of the index key, an optional [lo, hi] interval bounds the next key
    column, and the executor binary-searches the sorted index cache
    (storage/table.py index_range_lookup) into a compact row-id set.
    The full pushed_cond is retained so residual conjuncts compose and
    plain-scan fallback paths stay correct."""

    index_name: str = ""
    eq_values: Tuple = ()
    range_lo: object = None
    range_hi: object = None
    lo_incl: bool = True
    hi_incl: bool = True

    def op_name(self):
        return "IndexRangeScan"

    def op_info(self):
        parts = [f"table:{self.table_name}", f"index:{self.index_name}"]
        if self.eq_values:
            parts.append(f"eq:{tuple(self.eq_values)!r}")
        if self.range_lo is not None or self.range_hi is not None:
            lo = "-inf" if self.range_lo is None else str(self.range_lo)
            hi = "+inf" if self.range_hi is None else str(self.range_hi)
            lb = "[" if self.lo_incl else "("
            rb = "]" if self.hi_incl else ")"
            parts.append(f"range:{lb}{lo},{hi}{rb}")
        return ", ".join(parts)


@dataclass
class PPartitionScan(PScan):
    """Pruned access over a partitioned table (ref: the planner's
    partition pruning feeding per-partition scans): the WHERE's bounds
    on the partition column keep only matching partitions; the executor
    reads those partitions' cached row-id sets (storage/table.py
    partition_rows) instead of the full table."""

    part_ids: Tuple[int, ...] = ()
    part_names: Tuple[str, ...] = ()

    def op_name(self):
        return "PartitionScan"

    def op_info(self):
        return (f"table:{self.table_name}, "
                f"partitions:{','.join(self.part_names)}")


# a gathered index row costs more than a streamed scan row (random access
# + eager residual eval); range access must be selective enough to pay it
_RANGE_ROW_COST = 4.0


def inject_point_get(plan: PhysicalPlan) -> PhysicalPlan:
    """Access-path selection over base scans: replace full scans with
    PPointGet where the pushed filter pins a unique index with
    integer-typed equality literals, else with PIndexRangeScan where
    equalities pin an index prefix (plus an optional interval on the
    next key column) selectively enough to beat the scan."""
    from tidb_tpu.expression.expr import Call, ColumnRef, Literal
    from tidb_tpu.statistics import table_stats, _range_fraction
    from tidb_tpu.types import TypeKind
    import numpy as np

    def _int_col_lit(a, b, uid_to_col):
        """Resolved (PlanCol, int literal) for an int-typed
        col-vs-literal compare, else None. Plain INT columns compared
        to INT literals only: other int64-backed kinds (DECIMAL scale,
        DATE epoch days, ...) store RESCALED encodings that a raw
        literal does not match — the compiler rescales at eval time,
        but an index key probe built from the literal would miss."""
        if not (isinstance(a, ColumnRef) and isinstance(b, Literal)
                and b.value is not None):
            return None
        col = uid_to_col.get(a.name)
        if col is None:
            return None
        if (col.type_.kind != TypeKind.INT or b.type_.kind != TypeKind.INT
                or not isinstance(b.value, (int, np.integer))):
            return None
        return col, b

    def collect_bounds(cond, uid_to_col):
        """Per column name: equality literal and/or accumulated range
        bounds from the AND-tree of the pushed filter."""
        eqs, los, his = {}, {}, {}

        def visit(e):
            if isinstance(e, Call) and e.op == "and":
                for a in e.args:
                    visit(a)
                return
            if isinstance(e, Call) and e.op in ("eq", "lt", "le", "gt", "ge") \
                    and len(e.args) == 2:
                a, b = e.args
                op = e.op
                if isinstance(a, Literal):
                    a, b = b, a
                    op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                          "eq": "eq"}[op]
                hit = _int_col_lit(a, b, uid_to_col)
                if hit is None:
                    return
                col, lit = hit
                v = int(lit.value)
                name = col.name
                if op == "eq":
                    if name not in eqs:
                        eqs[name] = v
                elif op in ("gt", "ge"):
                    cur = los.get(name)
                    cand = (v, op == "ge")
                    # tightest lower bound wins; exclusivity breaks ties
                    if cur is None or cand[0] > cur[0] or (
                            cand[0] == cur[0] and not cand[1]):
                        los[name] = cand
                else:
                    cur = his.get(name)
                    cand = (v, op == "le")
                    if cur is None or cand[0] < cur[0] or (
                            cand[0] == cur[0] and not cand[1]):
                        his[name] = cand

        visit(cond)
        return eqs, los, his

    def cond_covered_by_key(cond, key_cols, eqs, uid_to_col):
        """True when EVERY conjunct of the pushed filter is an integer
        equality on a key column matching the probe value — then the
        unique-index lookup subsumes the filter and the executor can
        skip the residual evaluation. A conjunct on a key column with a
        DIFFERENT value (`a = 5 AND a = 6`) fails the check, and the
        plan cache's sentinel diff turns the same situation with
        parameters (`a = ? AND a = ?`) into a shape change, so a
        covered plan can never be rebound into an uncovered one."""
        keyset = set(key_cols)

        def ok(e):
            if isinstance(e, Call) and e.op == "and":
                return all(ok(a) for a in e.args)
            if not (isinstance(e, Call) and e.op == "eq"
                    and len(e.args) == 2):
                return False
            a, b = e.args
            if isinstance(a, Literal):
                a, b = b, a
            hit = _int_col_lit(a, b, uid_to_col)
            if hit is None:
                return False
            col, lit = hit
            return col.name in keyset and int(lit.value) == eqs.get(col.name)

        return ok(cond)

    def best_access(node):
        uid_to_col = {c.uid: c for c in node.schema}
        eqs, los, his = collect_bounds(node.pushed_cond, uid_to_col)
        if not eqs and not los and not his:
            return None
        table = node.table
        stats = table_stats(table)
        n_rows = float(stats.n_rows) if stats is not None \
            else float(table.live_rows)
        best = None  # (est, node)
        for idx in getattr(table, "indexes", {}).values():
            if not idx.columns:
                continue
            if getattr(idx, "state", "public") != "public":
                continue  # online-DDL write_only: not readable yet
            prefix = []
            for cname in idx.columns:
                if cname in eqs:
                    prefix.append(eqs[cname])
                else:
                    break
            if idx.unique and len(prefix) == len(idx.columns):
                return (0.0, PPointGet(
                    schema=node.schema, est_rows=1.0, db=node.db,
                    table_name=node.table_name, table=node.table,
                    pushed_cond=node.pushed_cond,
                    index_name=idx.name, key_values=tuple(prefix),
                    cond_covered=cond_covered_by_key(
                        node.pushed_cond, idx.columns, eqs, uid_to_col)))
            # range access: eq prefix plus optional interval on the
            # next key column
            lo = hi = None
            lo_incl = hi_incl = True
            if len(prefix) < len(idx.columns):
                nxt = idx.columns[len(prefix)]
                if nxt in los:
                    lo, lo_incl = los[nxt]
                if nxt in his:
                    hi, hi_incl = his[nxt]
            if not prefix and lo is None and hi is None:
                continue
            # selectivity: product of 1/ndv per eq column, times the
            # histogram fraction of the interval
            sel = 1.0
            for i, _ in enumerate(prefix):
                cs = stats.cols.get(idx.columns[i]) if stats else None
                sel *= 1.0 / max(cs.ndv, 1) if cs is not None else 0.1
            if lo is not None or hi is not None:
                nxt = idx.columns[len(prefix)]
                cs = stats.cols.get(nxt) if stats else None
                if cs is not None:
                    sel *= _range_fraction(
                        cs, -np.inf if lo is None else float(lo),
                        np.inf if hi is None else float(hi))
                else:
                    sel *= 0.33
            est = max(n_rows * sel, 1.0)
            if est * _RANGE_ROW_COST >= n_rows:
                continue  # not selective enough: the full scan wins
            if best is None or est < best[0]:
                best = (est, PIndexRangeScan(
                    schema=node.schema, est_rows=est, db=node.db,
                    table_name=node.table_name, table=node.table,
                    pushed_cond=node.pushed_cond,
                    index_name=idx.name, eq_values=tuple(prefix),
                    range_lo=lo, range_hi=hi,
                    lo_incl=lo_incl, hi_incl=hi_incl))
        return best

    def prune_partitions(node):
        """Matching partition ids for the scan's pushed bounds on the
        partition column, or None when nothing prunes."""
        import bisect

        pi = getattr(node.table.schema, "partition", None)
        if pi is None:
            return None
        uid_to_col = {c.uid: c for c in node.schema}
        eqs, los, his = collect_bounds(node.pushed_cond, uid_to_col)
        name = pi.column
        total = pi.count()
        if name in eqs:
            v = eqs[name]
            if pi.kind == "hash":
                return [v % max(pi.n_parts, 1)]
            pid = int(pi.ids_of_values(
                np.array([v]), np.array([True]))[0])
            return [pid] if pid < total else []
        if pi.kind == "hash":
            return None  # hash prunes on equality only
        lo, hi = los.get(name), his.get(name)
        if lo is None and hi is None:
            return None
        bounds = [u for u in pi.uppers if u is not None]
        lo_pid, hi_pid = 0, total - 1
        if lo is not None:
            v, incl = lo
            lo_pid = bisect.bisect_right(bounds, v if incl else v + 1)
        if hi is not None:
            v, incl = hi
            hi_pid = min(bisect.bisect_right(bounds, v if incl else v - 1),
                         total - 1)
        if lo_pid > hi_pid or lo_pid >= total:
            return []
        return list(range(lo_pid, hi_pid + 1))

    def rewrite(node):
        node.children = [rewrite(c) for c in node.children]
        if (type(node) is PScan and node.table is not None
                and node.pushed_cond is not None):
            best = best_access(node)
            if best is not None:
                return best[1]
            kept = prune_partitions(node)
            pi = getattr(node.table.schema, "partition", None)
            if kept is not None and pi is not None \
                    and len(kept) < pi.count():
                frac = max(len(kept), 0) / max(pi.count(), 1)
                return PPartitionScan(
                    schema=node.schema,
                    est_rows=max(node.est_rows * frac, 0.0),
                    db=node.db, table_name=node.table_name,
                    table=node.table, pushed_cond=node.pushed_cond,
                    part_ids=tuple(kept),
                    part_names=tuple(pi.part_name(p) for p in kept))
        return node

    return rewrite(plan)


@dataclass
class PSelection(PhysicalPlan):
    cond: object = None


@dataclass
class PProjection(PhysicalPlan):
    exprs: List = field(default_factory=list)
    n_visible: Optional[int] = None


@dataclass
class PHashAgg(PhysicalPlan):
    group_exprs: List = field(default_factory=list)
    group_uids: List[str] = field(default_factory=list)
    aggs: List[AggSpec] = field(default_factory=list)
    strategy: str = "generic"  # "segment" (packed small key space) | "generic"

    def op_name(self):
        return "HashAgg"

    def op_info(self):
        funcs = ", ".join(
            f"{a.func}({'distinct ' if a.distinct else ''}{'*' if a.arg is None else '...'})"
            for a in self.aggs
        )
        return f"group:{len(self.group_exprs)} [{funcs}] strategy:{self.strategy}"


@dataclass
class PHashJoin(PhysicalPlan):
    kind: str = "inner"
    eq_left: List = field(default_factory=list)   # exprs over probe child
    eq_right: List = field(default_factory=list)  # exprs over build child
    other_cond: object = None
    build_side: int = 1  # child index used as build side
    exists_sem: bool = False  # see LJoin.exists_sem

    def op_name(self):
        return "HashJoin"

    def op_info(self):
        return f"{self.kind} join, build:child[{self.build_side}], keys:{len(self.eq_left)}"


@dataclass
class PIndexJoin(PhysicalPlan):
    """Index-lookup join (ref: executor's IndexLookUpJoin / the memo's
    access-path alternative, SURVEY.md:88-89): ONE child — the outer —
    plus a static inner base-table scan probed through the sorted index
    cache, O(log n) per outer row. Chosen by the cascades memo when the
    probe cost beats the hash join's exchange + local work."""

    kind: str = "inner"
    eq_outer: List = field(default_factory=list)   # exprs over the outer
    index_name: str = ""
    inner_table: object = None
    inner_table_name: str = ""
    inner_schema: List[PlanCol] = field(default_factory=list)
    inner_key_cols: List[str] = field(default_factory=list)  # index order
    inner_cond: object = None        # inner scan's pushed filter (residual)
    other_cond: object = None
    task: str = "root"

    def op_name(self):
        return "IndexJoin"

    def op_info(self):
        return (f"inner table:{self.inner_table_name}, "
                f"index:{self.index_name}, keys:{len(self.eq_outer)}")


def _lower_index_join(plan, l, est):
    """LJoin annotated by the memo -> PIndexJoin; None if the shape
    drifted since annotation (falls back to the hash join)."""
    from tidb_tpu.expression.expr import ColumnRef

    inner = plan.children[1]
    if not isinstance(inner, LScan) or inner.table is None:
        return None
    idx = getattr(inner.table, "indexes", {}).get(plan.index_join)
    if idx is None:
        return None
    uid_to_name = {c.uid: c.name for c in inner.schema}
    by_col = {}
    for oe, ie in plan.eq_conds:
        if not isinstance(ie, ColumnRef):
            return None
        name = uid_to_name.get(ie.name)
        if name is None or name in by_col:
            return None
        by_col[name] = oe
    key_cols = list(idx.columns[: len(by_col)])
    if set(key_cols) != set(by_col):
        return None
    return PIndexJoin(
        schema=plan.schema, children=[l], est_rows=est,
        kind=plan.kind, eq_outer=[by_col[c] for c in key_cols],
        index_name=idx.name, inner_table=inner.table,
        inner_table_name=inner.table_name, inner_schema=list(inner.schema),
        inner_key_cols=key_cols, inner_cond=inner.pushed_cond,
        other_cond=plan.other_cond)


@dataclass
class PSort(PhysicalPlan):
    items: List[Tuple[object, bool]] = field(default_factory=list)
    task: str = "root"


@dataclass
class PWindow(PhysicalPlan):
    func: str = "row_number"
    args: List[object] = field(default_factory=list)
    partition_by: List[object] = field(default_factory=list)
    order_by: List[Tuple[object, bool]] = field(default_factory=list)
    out_uid: str = ""
    out_type: object = None
    params: tuple = ()
    frame: object = None
    task: str = "root"

    def op_info(self):
        return (f"{self.func} over(partition:{len(self.partition_by)} "
                f"order:{len(self.order_by)})")


@dataclass
class PTopN(PhysicalPlan):
    items: List[Tuple[object, bool]] = field(default_factory=list)
    count: int = 0
    offset: int = 0
    task: str = "root"
    # per-shard partial top-k descriptor (resolve_topn_pushdown): each
    # sort item mapped onto the distributed agg's group-key/state slots
    pushdown: object = None

    def op_info(self):
        info = f"limit:{self.count} offset:{self.offset}"
        if self.pushdown is not None:
            info += ", partial_topn:device"
        return info


def resolve_topn_pushdown(topn: PTopN):
    """Map a TopN's sort items onto the group-key/agg-state slots of a
    generic-strategy HashAgg reached through pass-through projections —
    the mesh analogue of the reference's TopN-into-coprocessor pushdown
    (SURVEY.md:93). Returns (agg, [(kind, index, desc), ...]) with kind
    in {key, cnt, sum, min, max, avg}, or None when any item fails to
    resolve (a Selection/HAVING between TopN and agg, a computed sort
    expression, DISTINCT aggregates). The per-shard top-k is a superset
    filter: the root TopNExec still applies the exact host ordering."""
    from tidb_tpu.expression.expr import ColumnRef

    k = topn.count + topn.offset
    if k <= 0 or k > (1 << 18):
        return None  # a huge k gains nothing over fetching every group
    node = topn.child
    # walk pass-through projections, accumulating uid -> expr maps;
    # projections are 1:1 on rows so they never change which groups
    # belong in the top k — a Selection (HAVING) would, so it bails
    maps = []
    while isinstance(node, PProjection):
        maps.append({c.uid: e for c, e in zip(node.schema, node.exprs)})
        node = node.child
    if not isinstance(node, PHashAgg) or node.strategy != "generic":
        return None
    if not node.group_exprs or any(a.distinct for a in node.aggs):
        return None
    key_of = {uid: i for i, uid in enumerate(node.group_uids)}
    agg_of = {a.uid: j for j, a in enumerate(node.aggs)}
    resolved = []
    for expr, desc in topn.items:
        e = expr
        for m in maps:  # outermost projection first
            if not isinstance(e, ColumnRef):
                return None
            e = m.get(e.name)
            if e is None:
                return None
        if not isinstance(e, ColumnRef):
            return None
        if e.name in key_of:
            resolved.append(("key", key_of[e.name], desc))
        elif e.name in agg_of:
            j = agg_of[e.name]
            func = node.aggs[j].func
            kind = {"count": "cnt", "sum": "sum", "min": "min",
                    "max": "max", "avg": "avg"}.get(func)
            if kind is None:
                return None
            resolved.append((kind, j, desc))
        else:
            return None
    return node, resolved


@dataclass
class PLimit(PhysicalPlan):
    count: int = 0
    offset: int = 0
    task: str = "root"


@dataclass
class PUnion(PhysicalPlan):
    all: bool = True


# ---------------------------------------------------------------------------
# row estimation (ref: statistics feeding the cost model; here: live row
# counts + fixed selectivities — ANALYZE histograms can refine later)
# ---------------------------------------------------------------------------

_SEL_FILTER = 0.25


def resolve_scan_col(plan: LogicalPlan, uid: str):
    """Trace a column uid to its defining base-table column (through
    pass-through projections). Returns (table, column_name) or None."""
    from tidb_tpu.expression.expr import ColumnRef

    if isinstance(plan, LScan):
        for c in plan.schema:
            if c.uid == uid:
                return (plan.table, c.name) if plan.table is not None else None
        return None
    if isinstance(plan, LProjection):
        for c, e in zip(plan.schema, plan.exprs):
            if c.uid == uid:
                if isinstance(e, ColumnRef):
                    return resolve_scan_col(plan.child, e.name)
                return None
    for ch in plan.children:
        r = resolve_scan_col(ch, uid)
        if r is not None:
            return r
    return None


def _eq_ndv(child: LogicalPlan, expr, child_rows: float) -> Optional[float]:
    """NDV of a join-key expression over `child`, clamped by the child's
    estimated rows (filters reduce distinct counts)."""
    from tidb_tpu.expression.expr import ColumnRef, Lookup

    from tidb_tpu.statistics import column_ndv

    # a collation-canon (or other dictionary) gather cannot raise the
    # distinct count: estimate through to the underlying column
    while isinstance(expr, Lookup):
        expr = expr.arg
    if not isinstance(expr, ColumnRef):
        return None
    r = resolve_scan_col(child, expr.name)
    if r is None:
        return None
    ndv = column_ndv(r[0], r[1])
    if ndv is None:
        return None
    return max(min(ndv, child_rows), 1.0)


def _key_col_stats(child: LogicalPlan, expr):
    """(TableStats, ColumnStats) for a join-key column with FRESH stats,
    else None. Fresh matters: MCV values are only meaningful against the
    analyzed snapshot."""
    from tidb_tpu.expression.expr import ColumnRef

    from tidb_tpu.statistics import table_stats

    if not isinstance(expr, ColumnRef):
        return None
    r = resolve_scan_col(child, expr.name)
    if r is None:
        return None
    s = table_stats(r[0])
    if s is None:
        return None
    cs = s.cols.get(r[1])
    return (s, cs) if cs is not None else None


def eq_join_rows(left: LogicalPlan, right: LogicalPlan, eq_conds,
                 l: float, r: float, kind: str = "inner") -> float:
    """Equi-join output estimate shared by the cost display (_estimate)
    and both join orderers (rules._greedy_order, cascades).

    Per key pair, in preference order: MCV-matched selectivity when both
    sides have fresh analyzed stats (statistics.eq_join_selectivity —
    catches skewed keys the uniformity rule misestimates by orders of
    magnitude), else |L|*|R| / max(ndv_l, ndv_r) from whichever side has
    an NDV (sketch-maintained under churn), else skipped. With no usable
    key the estimate falls back to max(|L|,|R|). A LEFT join emits every
    left row at least once, so its estimate floors at |L|.

    Plan feedback (ISSUE 15): when a previous execution RECORDED this
    join's actual output cardinality (keyed by the base-table columns
    its equalities resolve to) and planning runs with
    tidb_tpu_plan_feedback hints installed, the observed count
    overrides the heuristic — runtime truth beats any selectivity
    model (correlated filters shift key distributions no per-column
    statistic can see)."""
    from tidb_tpu.statistics import eq_join_selectivity

    from tidb_tpu.planner import feedback as _fb

    hints = _fb.current_hints()
    if hints is not None:
        got = hints.join_rows(left, right, eq_conds)
        if got is not None:
            out = max(min(float(got), l * r), 1.0)
            return max(out, l) if kind == "left" else out

    sel = None
    for le, re_ in eq_conds:
        kl = _key_col_stats(left, le)
        kr = _key_col_stats(right, re_)
        if kl is not None and kr is not None and (
                kl[1].mcv is not None or kr[1].mcv is not None):
            s = eq_join_selectivity(kl[0], kl[1], kr[0], kr[1])
            sel = (sel if sel is not None else 1.0) * max(s, 1e-18)
            continue
        nl = _eq_ndv(left, le, l)
        nr = _eq_ndv(right, re_, r)
        if nl is None and nr is None:
            continue
        d = max(nl or 1.0, nr or 1.0)
        sel = (sel if sel is not None else 1.0) / d
    out = max(l, r) if sel is None else max(l * r * sel, 1.0)
    if kind == "left":
        out = max(out, l)
    return out


def _estimate(plan: LogicalPlan) -> float:
    from tidb_tpu.statistics import scan_selectivity, table_stats

    if isinstance(plan, LScan):
        if plan.table is None:
            return 1.0
        s = table_stats(plan.table)
        n = float(s.n_rows) if s is not None else float(plan.table.live_rows)
        if plan.pushed_cond is not None:
            # plan feedback (ISSUE 15): an observed selectivity for this
            # (table, filter) shape — recorded where a past execution
            # knew the actual — beats the histogram guess
            from tidb_tpu.planner import feedback as _fb

            hints = _fb.current_hints()
            if hints is not None:
                uid_to_name = {c.uid: c.name for c in plan.schema}
                got = hints.scan_rows(plan.table, plan.table_name,
                                      plan.pushed_cond, uid_to_name, n)
                if got is not None:
                    return max(min(got, n), 1.0)
            if s is not None:
                uid_to_col = {c.uid: c.name for c in plan.schema}
                n *= scan_selectivity(plan.table, plan.pushed_cond, uid_to_col)
            else:
                n *= _SEL_FILTER
        return max(n, 1.0)
    if isinstance(plan, LSelection):
        return max(_estimate(plan.child) * _SEL_FILTER, 1.0)
    if isinstance(plan, LAggregate):
        n = _estimate(plan.child)
        if not plan.group_exprs:
            return 1.0
        # with stats: groups bounded by the product of key NDVs
        prod = 1.0
        known = True
        for g in plan.group_exprs:
            ndv = _eq_ndv(plan.child, g, n)
            if ndv is None:
                known = False
                break
            prod = min(prod * ndv, 1e18)
        if known:
            return max(min(n, prod), 1.0)
        return max(min(n, n ** 0.75), 1.0)
    if isinstance(plan, LJoin):
        l = _estimate(plan.children[0])
        r = _estimate(plan.children[1])
        if plan.kind in ("semi", "anti"):
            return max(l * 0.5, 1.0)
        if plan.eq_conds:
            return eq_join_rows(plan.children[0], plan.children[1],
                                plan.eq_conds, l, r, plan.kind)
        return l * r
    if isinstance(plan, LUnion):
        return sum(_estimate(c) for c in plan.children)
    if isinstance(plan, LLimit):
        return float(plan.count)
    if plan.children:
        return _estimate(plan.children[0])
    return 1.0


# packed-code segment aggregation applies when every group key is a dict
# code or bool with known small cardinality; bound on the packed domain:
SEGMENT_DOMAIN_LIMIT = 1 << 22  # 4M accumulator slots


def _segment_domain(agg: LAggregate) -> Optional[List[int]]:
    """If all group keys have small known domains, return their sizes."""
    from tidb_tpu.expression.expr import ColumnRef, Lookup
    from tidb_tpu.types import TypeKind

    sizes = []
    child_cols = {c.uid: c for c in agg.child.schema}
    for g in agg.group_exprs:
        d = getattr(g, "_dict", None)
        if d is None and isinstance(g, ColumnRef):
            c = child_cols.get(g.name)
            d = c.dict_ if c else None
        if d is not None:
            sizes.append(max(len(d), 1))
        elif (isinstance(g, Lookup) and g.type_.kind == TypeKind.STRING
                and g.table):
            # a string-typed gather (collation canon, UPPER, ...) maps
            # into code space bounded by its LUT's largest output —
            # plan rewrites drop attached _dict objects, so read the
            # domain off the table itself
            sizes.append(int(max(g.table)) + 1)
        elif g.type_.kind == TypeKind.BOOL:
            sizes.append(2)
        else:
            return None
    prod = 1
    for s in sizes:
        prod *= s
    if prod == 0 or prod > SEGMENT_DOMAIN_LIMIT:
        return None
    return sizes


# ---------------------------------------------------------------------------
# lowering
# ---------------------------------------------------------------------------

def lower(plan: LogicalPlan) -> PhysicalPlan:
    est = _estimate(plan)

    if isinstance(plan, LScan):
        return PScan(
            schema=plan.schema, est_rows=est, db=plan.db,
            table_name=plan.table_name, table=plan.table,
            pushed_cond=plan.pushed_cond,
        )
    if isinstance(plan, LSelection):
        return PSelection(
            schema=plan.schema, children=[lower(plan.child)], est_rows=est,
            cond=plan.cond,
        )
    if isinstance(plan, LProjection):
        return PProjection(
            schema=plan.schema, children=[lower(plan.child)], est_rows=est,
            exprs=plan.exprs, n_visible=plan.n_visible,
        )
    if isinstance(plan, LAggregate):
        from tidb_tpu.planner.logical import CORE_AGGS

        sizes = _segment_domain(plan)
        has_distinct = any(a.distinct for a in plan.aggs)
        # extended aggregates (bit_*, group_concat) only have host
        # generic-path implementations
        core_only = all(a.func in CORE_AGGS for a in plan.aggs)
        strategy = ("segment" if sizes is not None and not has_distinct
                    and core_only else "generic")
        node = PHashAgg(
            schema=plan.schema, children=[lower(plan.child)], est_rows=est,
            group_exprs=plan.group_exprs, group_uids=plan.group_uids,
            aggs=plan.aggs, strategy=strategy,
        )
        if sizes is not None:
            node.segment_sizes = sizes
        return node
    if isinstance(plan, LJoin):
        l = lower(plan.children[0])
        if plan.index_join is not None and plan.kind == "inner":
            ij = _lower_index_join(plan, l, est)
            if ij is not None:
                return ij
        r = lower(plan.children[1])
        eq_l = [lc for lc, _ in plan.eq_conds]
        eq_r = [rc for _, rc in plan.eq_conds]
        build = 1
        if plan.kind == "inner" and l.est_rows < r.est_rows:
            # probe the bigger side; semi/anti/left must build the inner side
            build = 0
        return PHashJoin(
            schema=plan.schema, children=[l, r], est_rows=est, kind=plan.kind,
            eq_left=eq_l, eq_right=eq_r, other_cond=plan.other_cond,
            build_side=build, exists_sem=plan.exists_sem,
        )
    if isinstance(plan, LSort):
        return PSort(schema=plan.schema, children=[lower(plan.child)], est_rows=est, items=plan.items)
    if isinstance(plan, LWindow):
        return PWindow(
            schema=plan.schema, children=[lower(plan.child)], est_rows=est,
            func=plan.func, args=plan.args, partition_by=plan.partition_by,
            order_by=plan.order_by, out_uid=plan.out_uid, out_type=plan.out_type,
            params=plan.params, frame=plan.frame)
    if isinstance(plan, LLimit):
        c = lower(plan.child)
        if isinstance(c, PSort):
            return PTopN(
                schema=plan.schema, children=c.children, est_rows=min(est, float(plan.count)),
                items=c.items, count=plan.count, offset=plan.offset,
            )
        return PLimit(schema=plan.schema, children=[c], est_rows=min(est, float(plan.count)), count=plan.count, offset=plan.offset)
    if isinstance(plan, LUnion):
        return PUnion(schema=plan.schema, children=[lower(c) for c in plan.children], est_rows=est, all=plan.all)

    raise NotImplementedError(f"lower: {type(plan).__name__}")


# ---------------------------------------------------------------------------
# EXPLAIN
# ---------------------------------------------------------------------------

def explain_text(plan: PhysicalPlan) -> str:
    """TiDB-style EXPLAIN table: id, estRows, task, operator info."""
    rows: List[Tuple[str, str, str, str]] = []

    def visit(p: PhysicalPlan, depth: int, last: bool):
        indent = ""
        if depth:
            indent = "  " * (depth - 1) + ("└─" if last else "├─")
        rows.append((indent + p.op_name(), f"{p.est_rows:.2f}", p.task, p.op_info()))
        for i, c in enumerate(p.children):
            visit(c, depth + 1, i == len(p.children) - 1)

    visit(plan, 0, True)
    w0 = max(len(r[0]) for r in rows) + 2
    w1 = max(len(r[1]) for r in rows) + 2
    w2 = max(len(r[2]) for r in rows) + 2
    lines = [f"{'id':<{w0}}{'estRows':<{w1}}{'task':<{w2}}operator info"]
    for r in rows:
        lines.append(f"{r[0]:<{w0}}{r[1]:<{w1}}{r[2]:<{w2}}{r[3]}")
    return "\n".join(lines)
