"""Optimize() entry point (ref: planner.Optimize -> logical rules -> cost
based physical search; here rules + deterministic lowering)."""

from __future__ import annotations

from typing import Callable, Optional

from tidb_tpu.parser import ast as A
from tidb_tpu.planner.binder import Binder
from tidb_tpu.planner.logical import BuildContext, build_select
from tidb_tpu.planner.physical import (
    PhysicalPlan,
    PTopN,
    inject_point_get,
    lower,
    resolve_topn_pushdown,
)
from tidb_tpu.planner.rules import optimize_logical

__all__ = ["plan_statement"]


def plan_statement(
    stmt,
    catalog,
    db: str = "test",
    execute_subplan: Optional[Callable] = None,
    cascades: bool = False,
    n_parts: int = 1,
    session_info: Optional[dict] = None,
    agg_push_down: bool = True,
) -> PhysicalPlan:
    """SELECT/UNION AST -> optimized physical plan."""
    assert isinstance(stmt, (A.SelectStmt, A.UnionStmt)), type(stmt)
    binder = Binder()
    binder.session_info = dict(session_info or {}, db=db)
    ctx = BuildContext(
        catalog=catalog, db=db, binder=binder, execute_subplan=execute_subplan
    )
    logical = build_select(stmt, ctx)
    logical = optimize_logical(logical, hints=getattr(stmt, "hints", ()) or (),
                               cascades=cascades, n_parts=n_parts,
                               agg_push_down=agg_push_down)
    phys = inject_point_get(lower(logical))
    if n_parts > 1:
        _annotate_topn(phys)
    return phys


def _annotate_topn(plan: PhysicalPlan) -> None:
    """Mark TopN nodes whose sort keys resolve onto a distributable
    generic agg below (per-shard partial top-k; SURVEY.md:93). The
    dist builder consumes the descriptor; EXPLAIN shows the intent."""
    if isinstance(plan, PTopN):
        plan.pushdown = resolve_topn_pushdown(plan)
    for c in plan.children:
        _annotate_topn(c)
