"""Optimize() entry point (ref: planner.Optimize -> logical rules -> cost
based physical search; here rules + deterministic lowering)."""

from __future__ import annotations

from typing import Callable, Optional

from tidb_tpu.parser import ast as A
from tidb_tpu.planner.binder import Binder
from tidb_tpu.planner.logical import BuildContext, build_select
from tidb_tpu.planner.physical import PhysicalPlan, inject_point_get, lower
from tidb_tpu.planner.rules import optimize_logical

__all__ = ["plan_statement"]


def plan_statement(
    stmt,
    catalog,
    db: str = "test",
    execute_subplan: Optional[Callable] = None,
    cascades: bool = False,
    n_parts: int = 1,
) -> PhysicalPlan:
    """SELECT/UNION AST -> optimized physical plan."""
    assert isinstance(stmt, (A.SelectStmt, A.UnionStmt)), type(stmt)
    ctx = BuildContext(
        catalog=catalog, db=db, binder=Binder(), execute_subplan=execute_subplan
    )
    logical = build_select(stmt, ctx)
    logical = optimize_logical(logical, hints=getattr(stmt, "hints", ()) or (),
                               cascades=cascades, n_parts=n_parts)
    return inject_point_get(lower(logical))
