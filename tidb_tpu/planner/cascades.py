"""Memo-based join-order search (ref: planner/cascades — the memo/
group/group-expression machinery, applied here to the rule set that
matters most at this engine's scale: join commutativity/associativity).

The cascades engine's core is a memo of *groups* of logically equivalent
expressions, explored by transformation rules and costed bottom-up. For
inner-join trees every equivalent expression is characterized by the set
of base leaves it joins, so the memo groups are keyed by leaf subsets
(a bitmask) and exploration enumerates every connected split of each
group — exhaustive join ordering, guaranteed no worse than the greedy
orderer under the same cost model. Enabled per session via
tidb_enable_cascades_planner (the reference's sysvar of the same name);
falls back to greedy beyond MAX_LEAVES (memo size is exponential).

Cost model: shared with the greedy orderer (statistics-driven row
estimates; cost = sum over join steps of output cardinality + the
exchange volume the mesh executor would pay — hash-shuffle of both
sides vs broadcast of the smaller side, whichever is cheaper; see
rules._join_step_cost)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from tidb_tpu.planner.logical import LJoin, LogicalPlan

__all__ = ["memo_join_search", "MAX_LEAVES"]

MAX_LEAVES = 10  # 2^10 groups tops; greedy handles wider joins

# memo-only local-work factor: every hash join step touches both inputs
# locally (sort/build) on top of the exchange _join_step_cost charges;
# an index join touches only outer * log2(inner) probe work. Charging
# the term uniformly keeps memo costs comparable across splits while
# letting access-path and join-order choice trade off (SURVEY.md:88-89).
LOCAL_WORK = 0.25


def _index_path(leaf, inner_exprs) -> Optional[str]:
    """Name of an index on `leaf`'s base table whose key prefix equals
    the inner-side join key columns, or None. Mirrors the PointGet
    restrictions: plain INT key columns only (other int64-backed kinds
    store rescaled encodings, and float bit patterns do not sort
    numerically, so the sorted-cache binary search would miss)."""
    from tidb_tpu.expression.expr import ColumnRef
    from tidb_tpu.planner.logical import LScan
    from tidb_tpu.types import TypeKind

    if not isinstance(leaf, LScan) or leaf.table is None or not inner_exprs:
        return None
    uid_to_col = {c.uid: c for c in leaf.schema}
    cols = set()
    for e in inner_exprs:
        if not isinstance(e, ColumnRef):
            return None
        col = uid_to_col.get(e.name)
        if col is None or col.type_.kind != TypeKind.INT:
            return None
        cols.add(col.name)
    for idx in getattr(leaf.table, "indexes", {}).values():
        if getattr(idx, "state", "public") != "public":
            continue  # online-DDL write_only: not an access path yet
        if len(idx.columns) >= len(cols) and set(
                idx.columns[:len(cols)]) == cols:
            return idx.name
    return None


@dataclass
class GroupExpr:
    """One explored expression of a group: a join of two child groups
    (or a leaf)."""

    plan: LogicalPlan
    cost: float
    rows: float


class Memo:
    """Groups keyed by the bitmask of base leaves they cover; each group
    keeps only its winner (pruned memo — dominated expressions are
    discarded immediately, which is safe because cost is monotone in
    child cost for this rule set)."""

    def __init__(self):
        self.groups: Dict[int, GroupExpr] = {}

    def offer(self, mask: int, expr: GroupExpr) -> None:
        cur = self.groups.get(mask)
        if cur is None or expr.cost < cur.cost:
            self.groups[mask] = expr

    def best(self, mask: int) -> Optional[GroupExpr]:
        return self.groups.get(mask)


def _splits(mask: int):
    """All (s1, s2) partitions of mask into two non-empty halves,
    each pair once (s1 contains mask's lowest set bit)."""
    lowest = mask & -mask
    sub = (mask - 1) & mask
    while sub:
        if sub & lowest:
            yield sub, mask ^ sub
        sub = (sub - 1) & mask

def memo_join_search(leaves: List[LogicalPlan], eqs, others,
                     classify_edges, conj_join, pushdown_rule,
                     n_parts: int = 1):
    """Exhaustive join-order search over the memo. Returns the best
    plan, or None when the search doesn't apply (too many leaves).

    classify_edges/conj_join/pushdown_rule are the shared helpers from
    rules.py (passed in to avoid a circular import)."""
    from tidb_tpu.planner.logical import LSelection
    from tidb_tpu.planner.physical import _estimate, eq_join_rows

    n = len(leaves)
    if n < 2:
        return None
    if n > MAX_LEAVES:
        return _idp_search(leaves, eqs, others, classify_edges,
                           conj_join, pushdown_rule, n_parts)
    edges, leftover = classify_edges(leaves, eqs, others)

    memo = Memo()
    for i, leaf in enumerate(leaves):
        memo.offer(1 << i, GroupExpr(leaf, 0.0, float(_estimate(leaf))))

    full = (1 << n) - 1
    # bottom-up by subset size; Python ints as masks
    by_size: List[List[int]] = [[] for _ in range(n + 1)]
    for mask in range(1, full + 1):
        by_size[mask.bit_count()].append(mask)

    for size in range(2, n + 1):
        for mask in by_size[size]:
            # EVERY split is enumerated, cross splits included: the best
            # plan for a disconnected graph may require crossing late
            # ((a JOIN b) x c beats (a x c) JOIN b when ab is tiny), and
            # the cost model already penalizes cartesian blowups — a
            # "connected splits only" gate would wrongly prune them
            for s1, s2 in _splits(mask):
                g1, g2 = memo.best(s1), memo.best(s2)
                if g1 is None or g2 is None:
                    continue
                conds = []
                for ia, ib, a, b in edges:
                    if (mask >> ia & 1) and (mask >> ib & 1):
                        if (s1 >> ia & 1) and (s2 >> ib & 1):
                            conds.append((a, b))
                        elif (s1 >> ib & 1) and (s2 >> ia & 1):
                            conds.append((b, a))
                if conds:
                    rows = float(eq_join_rows(
                        g1.plan, g2.plan, conds, g1.rows, g2.rows))
                else:
                    rows = g1.rows * g2.rows
                from tidb_tpu.planner.rules import _join_step_cost

                hash_cost = (_join_step_cost(g1.rows, g2.rows, rows, n_parts)
                             + LOCAL_WORK * (g1.rows + g2.rows))
                step = hash_cost
                idx_name = None
                idx_children = None
                if conds:
                    # access-path alternative: a single-leaf side whose
                    # base table indexes the join key set can be probed
                    # O(log n) per outer row on the host — no exchange,
                    # no touch of unmatched inner rows
                    import math

                    for outer_g, inner_g, inner_mask, oriented in (
                            (g1, g2, s2, conds),
                            (g2, g1, s1, [(b, a) for a, b in conds])):
                        if inner_mask.bit_count() != 1:
                            continue
                        name = _index_path(inner_g.plan,
                                           [b for _, b in oriented])
                        if name is None:
                            continue
                        idx_cost = (LOCAL_WORK * outer_g.rows
                                    * math.log2(max(inner_g.rows, 2.0))
                                    + rows)
                        if idx_cost < step:
                            step = idx_cost
                            idx_name = name
                            idx_children = (outer_g, inner_g, oriented)
                cost = g1.cost + g2.cost + step
                cur = memo.best(mask)
                if cur is not None and cost >= cur.cost:
                    continue
                # build-side choice is lower()'s job (it compares
                # post-pushdown estimates and sets build_side)
                # kind stays "inner" even with no conds — the lowering
                # treats empty eq_conds as the cross join, matching the
                # greedy orderer's convention
                if idx_name is not None:
                    og, ig, oriented = idx_children
                    plan = LJoin(
                        schema=list(og.plan.schema) + list(ig.plan.schema),
                        children=[og.plan, ig.plan],
                        kind="inner", eq_conds=oriented,
                        index_join=idx_name,
                    )
                else:
                    plan = LJoin(
                        schema=list(g1.plan.schema) + list(g2.plan.schema),
                        children=[g1.plan, g2.plan],
                        kind="inner", eq_conds=conds,
                    )
                memo.offer(mask, GroupExpr(plan, cost, rows))

    win = memo.best(full)
    if win is None:  # disconnected graph with no cross pass hit (unreachable)
        return None
    tree = win.plan
    if leftover:
        sel = LSelection(schema=list(tree.schema), children=[tree],
                         cond=conj_join(leftover))
        return pushdown_rule(sel)
    return tree


def _idp_search(leaves, eqs, others, classify_edges, conj_join,
                pushdown_rule, n_parts):
    """Iterative dynamic programming beyond MAX_LEAVES (IDP-1, the
    standard widening of exhaustive join DP): memo-optimize a CONNECTED
    window of MAX_LEAVES leaves (BFS over join edges from the
    smallest-estimate leaf), collapse the winner into one composite
    leaf, and repeat until the remaining graph fits the memo. Each
    window is exhaustively ordered under the shared cost model; only
    cross-window orderings are approximated — an 11+-table query still
    optimizes instead of falling back to greedy wholesale."""
    from tidb_tpu.planner.physical import _estimate
    from tidb_tpu.planner.rules import _refs

    leaves, eqs, others = list(leaves), list(eqs), list(others)
    while len(leaves) > MAX_LEAVES:
        edges, _leftover = classify_edges(leaves, eqs, others)
        adj = {i: set() for i in range(len(leaves))}
        for ia, ib, _a, _b in edges:
            adj[ia].add(ib)
            adj[ib].add(ia)
        est = [float(_estimate(l)) for l in leaves]  # once per round
        window, seen = [], set()
        # BFS whole components smallest-estimate-first: padding must
        # stay connectivity-aware — a leaf windowed without its join
        # partners would force a REAL cartesian product inside the
        # collapsed composite
        while len(window) < MAX_LEAVES and len(seen) < len(leaves):
            start = min((i for i in range(len(leaves)) if i not in seen),
                        key=est.__getitem__)
            frontier = [start]
            while frontier and len(window) < MAX_LEAVES:
                i = frontier.pop(0)
                if i in seen:
                    continue
                seen.add(i)
                window.append(i)
                frontier.extend(sorted(adj[i] - seen, key=est.__getitem__))
        uid_w = set()
        for i in window:
            uid_w |= {c.uid for c in leaves[i].schema}
        in_eqs = [p for p in eqs if (_refs(p[0]) | _refs(p[1])) <= uid_w]
        in_others = [o for o in others if _refs(o) <= uid_w]
        sub = memo_join_search([leaves[i] for i in window], in_eqs,
                               in_others, classify_edges, conj_join,
                               pushdown_rule, n_parts=n_parts)
        if sub is None:
            return None
        wset = set(window)
        in_ids = {id(p) for p in in_eqs} | {id(o) for o in in_others}
        leaves = [l for i, l in enumerate(leaves) if i not in wset] + [sub]
        eqs = [p for p in eqs if id(p) not in in_ids]
        others = [o for o in others if id(o) not in in_ids]
    return memo_join_search(leaves, eqs, others, classify_edges,
                            conj_join, pushdown_rule, n_parts=n_parts)
