"""Logical plan nodes and the AST -> logical-plan builder
(ref: planner/core PlanBuilder + logical operators).

Subquery strategy (round 1): uncorrelated IN-subqueries in WHERE conjuncts
become semi/anti joins (the decorrelation the reference's planner does);
uncorrelated EXISTS and scalar subqueries are evaluated eagerly through a
session-provided callback and folded to constants (the reference likewise
evaluates "max-one-row" subqueries at optimize time). Correlated
subqueries raise UnsupportedError.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tidb_tpu.errors import PlanError, SchemaError, UnsupportedError
from tidb_tpu.expression.expr import Call, ColumnRef, Expr, Literal, Lookup, walk
from tidb_tpu.chunk.dictionary import Dictionary
from tidb_tpu.parser import ast as A
from tidb_tpu.planner.binder import AGG_FUNCS, Binder, PlanCol, Scope, ast_key
from tidb_tpu.types import (
    BOOL,
    FLOAT64,
    INT64,
    STRING,
    SQLType,
    TypeKind,
    common_type,
    decimal_type,
)

__all__ = [
    "LogicalPlan", "LScan", "LSelection", "LProjection", "LAggregate",
    "AggSpec", "LJoin", "LSort", "LLimit", "LUnion", "LWindow",
    "build_select", "BuildContext", "expr_display",
]


# ---------------------------------------------------------------------------
# nodes
# ---------------------------------------------------------------------------

@dataclass
class LogicalPlan:
    schema: List[PlanCol] = field(default_factory=list)
    children: List["LogicalPlan"] = field(default_factory=list)

    @property
    def child(self) -> "LogicalPlan":
        return self.children[0]


@dataclass
class LScan(LogicalPlan):
    db: str = ""
    table_name: str = ""
    table: object = None  # storage.Table
    # predicate pushed into the scan fragment (the coprocessor analogue)
    pushed_cond: Optional[Expr] = None


@dataclass
class LSelection(LogicalPlan):
    cond: Expr = None


@dataclass
class LProjection(LogicalPlan):
    exprs: List[Expr] = field(default_factory=list)
    n_visible: Optional[int] = None  # hidden ORDER BY helper columns follow


CORE_AGGS = ("sum", "count", "avg", "min", "max")


def core_generic_agg(group_exprs, aggs) -> bool:
    """THE plan-static eligibility predicate for the device generic-agg
    kernels (sort-based grouping): grouped, no DISTINCT, core funcs
    only. One definition shared by the routing gates in
    executor/builder.py, executor/pipeline.py and executor/aggregate.py
    — context-dependent gates (tidb_enable_tpu_exec etc.) stay at the
    call sites."""
    return bool(group_exprs) and not any(a.distinct for a in aggs) \
        and all(a.func in CORE_AGGS for a in aggs)


@dataclass
class AggSpec:
    uid: str
    func: str            # sum | count | avg | min | max | bit_* | group_concat
    arg: Optional[Expr]  # None for COUNT(*)
    distinct: bool = False
    type_: SQLType = INT64
    # GROUP_CONCAT runtime info: (separator, order_desc_or_None,
    # output RuntimeDictionary to fill at execution)
    extra: Optional[tuple] = None


@dataclass
class LAggregate(LogicalPlan):
    group_exprs: List[Expr] = field(default_factory=list)  # over child schema
    group_uids: List[str] = field(default_factory=list)
    aggs: List[AggSpec] = field(default_factory=list)


@dataclass
class LJoin(LogicalPlan):
    kind: str = "inner"  # inner | left | semi | anti | cross
    # equi conditions as (left_expr, right_expr) over the resp. child schemas
    eq_conds: List[Tuple[Expr, Expr]] = field(default_factory=list)
    other_cond: Optional[Expr] = None
    # anti joins from NOT EXISTS keep NULL-key probe rows (no match ->
    # EXISTS is false -> NOT EXISTS true), unlike NOT IN's NULL semantics
    exists_sem: bool = False
    # memo-chosen index access path for the INNER (right-child) side:
    # index name on the right child's base table whose key prefix is the
    # join key set — the lowering emits an IndexJoin instead of a hash
    # join (planner/cascades.py; SURVEY.md:88-89 access-path search)
    index_join: Optional[str] = None


@dataclass
class LWindow(LogicalPlan):
    """One window function: child schema + one output column (out_uid).
    Default frames: whole partition without ORDER BY; RANGE UNBOUNDED
    PRECEDING .. CURRENT ROW (peers included) with it."""

    func: str = "row_number"
    args: List[Expr] = field(default_factory=list)
    partition_by: List[Expr] = field(default_factory=list)
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)
    out_uid: str = ""
    out_type: SQLType = INT64
    # positional params: LEAD/LAG -> (offset, default_value_or_None,
    # default_is_null); NTILE -> (n,)
    params: tuple = ()
    # explicit ROWS frame bounds (ast.EWindow.frame); None = defaults
    frame: object = None


@dataclass
class LSort(LogicalPlan):
    items: List[Tuple[Expr, bool]] = field(default_factory=list)  # (expr, desc)


@dataclass
class LLimit(LogicalPlan):
    count: int = 0
    offset: int = 0


@dataclass
class LUnion(LogicalPlan):
    all: bool = False


# ---------------------------------------------------------------------------
# display helper (EXPLAIN / auto column names)
# ---------------------------------------------------------------------------

def expr_display(e) -> str:
    """Reconstruct readable SQL-ish text from an AST expression."""
    if isinstance(e, A.EName):
        return f"{e.qualifier}.{e.name}" if e.qualifier else e.name
    if isinstance(e, A.ENum):
        return e.text
    if isinstance(e, A.EStr):
        return f"'{e.value}'"
    if isinstance(e, A.ENull):
        return "NULL"
    if isinstance(e, A.EBool):
        return "TRUE" if e.value else "FALSE"
    if isinstance(e, A.EStar):
        return f"{e.qualifier}.*" if e.qualifier else "*"
    if isinstance(e, A.EBinary):
        return f"{expr_display(e.left)} {e.op} {expr_display(e.right)}"
    if isinstance(e, A.EUnary):
        return f"{e.op} {expr_display(e.arg)}"
    if isinstance(e, A.EFunc):
        inner = ", ".join(expr_display(a) for a in e.args)
        if e.distinct:
            inner = "distinct " + inner
        return f"{e.name}({inner})"
    if isinstance(e, A.ECase):
        return "case ... end"
    if isinstance(e, A.ECast):
        return f"cast({expr_display(e.arg)} as {e.type_name})"
    if isinstance(e, A.EIn):
        return f"{expr_display(e.arg)} in (...)"
    if isinstance(e, A.EBetween):
        return f"{expr_display(e.arg)} between ..."
    if isinstance(e, A.ELike):
        return f"{expr_display(e.arg)} like {expr_display(e.pattern)}"
    if isinstance(e, A.EIsNull):
        return f"{expr_display(e.arg)} is {'not ' if e.negated else ''}null"
    if isinstance(e, (A.EExists,)):
        return "exists(...)"
    if isinstance(e, (A.ESubquery,)):
        return "(subquery)"
    if isinstance(e, A.EInterval):
        return f"interval {expr_display(e.value)} {e.unit}"
    return type(e).__name__


# ---------------------------------------------------------------------------
# build context
# ---------------------------------------------------------------------------

@dataclass
class BuildContext:
    catalog: object
    db: str = "test"
    binder: Binder = field(default_factory=Binder)
    # session-provided: execute a logical plan, return list of row tuples of
    # python values in device repr (used for scalar/EXISTS subqueries)
    execute_subplan: Optional[Callable] = None
    ctes: Dict[str, object] = field(default_factory=dict)  # name -> AST select
    cte_multi: set = field(default_factory=set)   # names referenced >= 2x
    cte_tables: Dict[str, tuple] = field(default_factory=dict)  # materialized
    # body ids whose every reference is duplicate-insensitive (all inside
    # IN/EXISTS semi-join zones): materialization may dedup + rewrite
    cte_duponly: set = field(default_factory=set)


def _conjuncts(e) -> List:
    if isinstance(e, A.EBinary) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _and_ir(parts: List[Expr]) -> Optional[Expr]:
    out = None
    for p in parts:
        out = p if out is None else Call(type_=BOOL, op="and", args=(out, p))
    return out


# ---------------------------------------------------------------------------
# FROM clause
# ---------------------------------------------------------------------------

def _count_table_refs(node, name: str) -> int:
    """Occurrences of `name` as an unqualified TableName in the AST,
    NOT descending into scopes where an inner WITH shadows the name."""
    import dataclasses as _dc

    count = 0
    stack = [node]
    seen_root = node
    while stack:
        e = stack.pop()
        if isinstance(e, A.TableName):
            if e.name == name and e.schema is None:
                count += 1
            continue
        if (isinstance(e, A.SelectStmt) and e is not seen_root
                and any(c.name == name for c in e.ctes)):
            continue  # inner WITH shadows the name: out of scope
        if _dc.is_dataclass(e) and not isinstance(e, type):
            for f in _dc.fields(e):
                v = getattr(e, f.name)
                vs = v if isinstance(v, (list, tuple)) else [v]
                for item in vs:
                    if isinstance(item, tuple):
                        stack.extend(item)
                    elif _dc.is_dataclass(item):
                        stack.append(item)
    return count


_AGG_FUNC_NAMES = {"sum", "count", "avg", "min", "max", "group_concat",
                   "stddev", "std", "stddev_pop", "stddev_samp", "variance",
                   "var_pop", "var_samp", "bit_and", "bit_or", "bit_xor",
                   "any_value"}


def _multiplicity_sensitive(node) -> bool:
    """Does any select inside `node` aggregate, window, or LIMIT? If so,
    row multiplicity of its inputs can change its result and inputs must
    not be deduplicated."""
    import dataclasses as _dc

    stack = [node]
    while stack:
        e = stack.pop()
        if isinstance(e, A.SelectStmt) and (
                e.group_by or e.having is not None or e.limit is not None
                or e.offset is not None):
            return True
        if isinstance(e, A.UnionStmt) and (
                e.limit is not None or e.offset is not None
                or e.all or e.op != "union"):
            # LIMIT/OFFSET pick rows by position; UNION ALL / EXCEPT /
            # INTERSECT have bag semantics — all multiplicity-dependent
            return True
        if isinstance(e, A.EFunc) and e.name in _AGG_FUNC_NAMES:
            return True
        if isinstance(e, A.EWindow):
            return True
        if _dc.is_dataclass(e) and not isinstance(e, type):
            for f in _dc.fields(e):
                v = getattr(e, f.name)
                vs = v if isinstance(v, (list, tuple)) else [v]
                for item in vs:
                    if isinstance(item, tuple):
                        stack.extend(item)
                    elif _dc.is_dataclass(item):
                        stack.append(item)
    return False


def _cte_semi_only(stmt, name: str) -> bool:
    """True when EVERY reference to CTE `name` sits inside an IN/EXISTS
    subquery that contains no aggregate/window/LIMIT — a pure semi-join
    zone where only the DISTINCT row set matters. Such a CTE may be
    deduplicated at materialization (set(join(A,B)) == set(join(set(A),
    set(B))), and filters/projections commute with dedup likewise).
    Ref: the reference planner's semi-join dedup of subquery sources."""
    import dataclasses as _dc

    stack = [stmt]
    while stack:
        e = stack.pop()
        if isinstance(e, A.TableName):
            if e.name == name and e.schema is None:
                return False  # a reference OUTSIDE every semi zone
            continue
        if (isinstance(e, A.SelectStmt) and e is not stmt
                and any(c.name == name for c in e.ctes)):
            continue  # inner WITH shadows the name
        sub_zones = []
        if isinstance(e, A.EIn) and e.subquery is not None:
            sub_zones.append(e.subquery)
        elif isinstance(e, A.EExists):
            sub_zones.append(e.subquery)
        for z in sub_zones:
            if _count_table_refs(z, name) and _multiplicity_sensitive(z):
                return False  # referenced where multiplicity matters
        if sub_zones:
            # zone contents are dup-safe; outer parts (e.g. IN's lhs arg
            # and value list) still need scanning
            if isinstance(e, A.EIn):
                stack.append(e.arg)
                stack.extend(e.values or [])
            continue
        if _dc.is_dataclass(e) and not isinstance(e, type):
            for f in _dc.fields(e):
                v = getattr(e, f.name)
                vs = v if isinstance(v, (list, tuple)) else [v]
                for item in vs:
                    if isinstance(item, tuple):
                        stack.extend(item)
                    elif _dc.is_dataclass(item):
                        stack.append(item)
    return True


def _try_selfjoin_distinctness(stmt):
    """Rewrite the duplicate-detection self-join — TPC-DS Q95's ws_wh
    shape (SURVEY.md:131) — into a grouped min/max distinctness test:

        SELECT t1.a FROM t t1, t t2
        WHERE t1.a = t2.a AND t1.b <> t2.b
      =set=
        SELECT a FROM t WHERE a IS NOT NULL AND b IS NOT NULL
        GROUP BY a HAVING MIN(b) <> MAX(b)

    Set-equal only (the join multiplies rows per matching pair), so
    callers must be in a duplicate-insensitive context (semi-join zones,
    dedup'd CTE materialization). The join form is O(sum of group^2)
    rows through a hash join; the grouped form is one segment min/max.
    Returns the rewritten SelectStmt or None if the shape doesn't match.
    """
    if not isinstance(stmt, A.SelectStmt) or stmt.group_by or stmt.having \
            or stmt.limit is not None or stmt.offset is not None \
            or len(stmt.items) != 1 or stmt.ctes:
        return None
    f = stmt.from_
    if not (isinstance(f, A.Join) and f.kind in ("cross", "inner")
            and f.on is None and f.using is None
            and isinstance(f.left, A.TableName)
            and isinstance(f.right, A.TableName)
            and f.left.name == f.right.name
            and f.left.schema == f.right.schema):
        return None
    a1 = f.left.alias or f.left.name
    a2 = f.right.alias or f.right.name
    if a1 == a2:
        return None
    aliases = {a1, a2}

    def _same_col_pair(e, op):
        """e is `q1.x <op> q2.x` with {q1,q2} == aliases -> x, else None."""
        if (isinstance(e, A.EBinary) and e.op == op
                and isinstance(e.left, A.EName) and isinstance(e.right, A.EName)
                and e.left.name == e.right.name
                and {e.left.qualifier, e.right.qualifier} == aliases):
            return e.left.name
        return None

    key_cols, diff_cols, other = [], [], []
    for conj in _conjuncts(stmt.where) if stmt.where is not None else []:
        k = _same_col_pair(conj, "=")
        if k is not None:
            key_cols.append(k)
            continue
        d = _same_col_pair(conj, "<>") or _same_col_pair(conj, "!=")
        if d is not None:
            diff_cols.append(d)
            continue
        other.append(conj)
    if not key_cols or len(diff_cols) != 1 or other:
        return None
    item = stmt.items[0]
    if not (isinstance(item.expr, A.EName)
            and (item.expr.qualifier in aliases or item.expr.qualifier is None)
            and item.expr.name in key_cols):
        return None
    diff = diff_cols[0]
    not_null = None
    for c in dict.fromkeys(key_cols + [diff]):  # ordered, unique
        cond = A.EIsNull(arg=A.EName(name=c), negated=True)
        not_null = cond if not_null is None else A.EBinary(
            op="and", left=not_null, right=cond)
    return A.SelectStmt(
        items=[A.SelectItem(expr=A.EName(name=item.expr.name),
                            alias=item.alias or item.expr.name)],
        from_=A.TableName(name=f.left.name, schema=f.left.schema),
        where=not_null,
        group_by=[A.EName(name=k) for k in key_cols],
        having=A.EBinary(
            op="<>",
            left=A.EFunc(name="min", args=[A.EName(name=diff)]),
            right=A.EFunc(name="max", args=[A.EName(name=diff)])),
    )


def _materialized_cte_scan(name: str, ctx: BuildContext) -> LogicalPlan:
    """Plan + run the CTE body once; later references scan the
    materialized rows from an anonymous host table."""
    body_ast = ctx.ctes[name]
    hit = ctx.cte_tables.get(id(body_ast))
    if hit is None:
        from tidb_tpu.storage.table import ColumnInfo, Table, TableSchema

        dup_only = id(body_ast) in ctx.cte_duponly
        run_ast = body_ast
        if dup_only:
            # every consumer is a semi-join zone: the duplicate-detection
            # self-join may collapse to a grouped min/max distinctness
            # test, and the materialized rows may dedup either way
            run_ast = _try_selfjoin_distinctness(body_ast) or body_ast
        body = build_select(run_ast, ctx, None)
        rows = ctx.execute_subplan(body)
        if dup_only and rows:
            rows = list(dict.fromkeys(map(tuple, rows)))
        schema = TableSchema(
            name=f"__cte_{name}__",
            columns=[ColumnInfo(name=c.name or c.uid, type_=c.type_)
                     for c in body.schema])
        # uniquify duplicate display names (SELECT a, a ...)
        seen = {}
        for c in schema.columns:
            if c.name in seen:
                seen[c.name] += 1
                c.name = f"{c.name}_{seen[c.name]}"
            else:
                seen[c.name] = 0
        table = Table(schema)
        table._anonymous = True  # plan-time temp: exempt from priv walk
        if rows:
            table.insert_rows(rows)
        # one materialization per body, observable: the regression test
        # for the ws_wh rescan asserts this site fires once however
        # many consumers scan the result (a site EVENT, not a device
        # round trip — EXPLAIN's dispatch accounting must stay honest)
        from tidb_tpu.utils import dispatch

        dispatch.event("cte.materialize")
        # segment the materialized result (ISSUE 8): every consumer
        # then scans the encoded, zone-mapped form instead of raw rows.
        # The session threads its columnar sysvars through session_info
        # so SET tidb_tpu_columnar_enable=0 skips the encode entirely.
        si = ctx.binder.session_info
        if si.get("columnar_enable", True):
            from tidb_tpu.columnar.store import build_for_result

            build_for_result(
                table, segment_rows=int(si.get("segment_rows", 1 << 16)))
        hit = (table, [c.name for c in schema.columns])
        ctx.cte_tables[id(body_ast)] = hit
    table, names = hit
    cols = [
        PlanCol(uid=ctx.binder.new_uid(n), name=n,
                type_=table.schema.col(n).type_,
                dict_=table.dicts.get(n))
        for n in names
    ]
    return LScan(schema=cols, db=ctx.db, table_name=f"__cte_{name}__",
                 table=table)


def build_from(src, ctx: BuildContext, outer: Optional[Scope]) -> Tuple[LogicalPlan, Scope]:
    if src is None:
        # SELECT without FROM: one-row dual table
        return LScan(schema=[], db=ctx.db, table_name="", table=None), Scope([], outer)

    if isinstance(src, A.TableName):
        alias = src.alias or src.name
        if src.name in ctx.ctes and src.schema is None:
            if (id(ctx.ctes[src.name]) in ctx.cte_multi
                    and ctx.execute_subplan is not None):
                sub = _materialized_cte_scan(src.name, ctx)
            else:
                sub = build_select(ctx.ctes[src.name], ctx, outer)
            cols = [
                dataclasses.replace(c, qualifier=alias) for c in sub.schema
            ]
            sub = _realias(sub, cols)
            return sub, Scope(cols, outer)
        db = src.schema or ctx.db
        view = ctx.catalog.view(db, src.name)
        if view is not None:
            # a view is a stored SELECT expanded like a derived table
            # (ref: the view expansion in planner/core's PlanBuilder)
            vcols, vstmt, _sql = view
            depth = getattr(ctx, "_view_depth", 0)
            if depth > 16:
                raise PlanError(f"view nesting too deep at {src.name!r}")
            # the body resolves in the view's DEFINING database with a
            # clean name space: no caller CTEs (they must not shadow the
            # view's tables) and no outer correlation
            ctx._view_depth = depth + 1
            saved_db, saved_ctes = ctx.db, ctx.ctes
            ctx.db, ctx.ctes = db, {}
            try:
                sub = build_select(vstmt, ctx, None)
            finally:
                ctx._view_depth = depth
                ctx.db, ctx.ctes = saved_db, saved_ctes
            cols = [dataclasses.replace(c, qualifier=alias) for c in sub.schema]
            if vcols is not None:
                if len(vcols) != len(cols):
                    raise PlanError(
                        f"view {src.name!r} has {len(vcols)} columns, "
                        f"SELECT yields {len(cols)}")
                cols = [dataclasses.replace(c, name=n)
                        for c, n in zip(cols, vcols)]
            sub = _realias(sub, cols)
            sub._block_boundary = True
            return sub, Scope(cols, outer)
        table = ctx.catalog.table(db, src.name)
        cols = [
            PlanCol(
                uid=ctx.binder.new_uid(f"{src.name}.{c.name}"),
                name=c.name,
                type_=c.type_,
                qualifier=alias,
                dict_=table.dicts.get(c.name),
            )
            for c in table.schema.public_columns()
        ]
        # hidden physical-rowid pseudo-column: resolvable by name (the
        # multi-table DML path selects it through joins), invisible to
        # SELECT * and pruned away when unreferenced
        cols.append(PlanCol(
            uid=ctx.binder.new_uid(f"{src.name}.__rowid__"),
            name="__rowid__", type_=INT64, qualifier=alias, hidden=True))
        return (
            LScan(schema=cols, db=db, table_name=src.name, table=table),
            Scope(cols, outer),
        )

    if isinstance(src, A.SubqueryTable):
        sub = build_select(src.select, ctx, outer)
        cols = [dataclasses.replace(c, qualifier=src.alias) for c in sub.schema]
        sub = _realias(sub, cols)
        # query-block boundary: outer optimizer hints (LEADING) stop here
        sub._block_boundary = True
        return sub, Scope(cols, outer)

    if isinstance(src, A.Join):
        if src.kind == "full":
            return _build_full_join(src, ctx, outer)
        left, lscope = build_from(src.left, ctx, outer)
        right, rscope = build_from(src.right, ctx, outer)
        if src.kind == "right":
            left, right = right, left
            lscope, rscope = rscope, lscope
            kind = "left"
        else:
            kind = src.kind
        combined = Scope(lscope.cols + rscope.cols, outer)
        eq, other = [], []
        cond_asts = []
        if src.on is not None:
            cond_asts = _conjuncts(src.on)
        elif src.using:
            for name in src.using:
                cond_asts.append(
                    A.EBinary("=", A.EName(name, _qual_of(lscope, name)),
                              A.EName(name, _qual_of(rscope, name)))
                )
        left_uids = {c.uid for c in lscope.cols}
        right_uids = {c.uid for c in rscope.cols}
        for cast_ in cond_asts:
            bound = ctx.binder.bind_expr(cast_, combined)
            side = _classify_eq(bound, left_uids, right_uids)
            if side == "lr":
                eq.append((bound.args[0], bound.args[1]))
            elif side == "rl":
                eq.append((bound.args[1], bound.args[0]))
            else:
                other.append(bound)
        join = LJoin(
            schema=lscope.cols + rscope.cols,
            children=[left, right],
            kind=kind,
            eq_conds=eq,
            other_cond=_and_ir(other),
        )
        if kind == "left":
            # right-side columns become nullable — semantics only, repr same
            pass
        return join, combined

    raise PlanError(f"unknown FROM source {type(src).__name__}")


def _qual_of(scope: Scope, name: str) -> Optional[str]:
    c = scope.try_resolve(name, None)
    return c.qualifier if c else None


def _classify_eq(bound: Expr, left_uids, right_uids) -> Optional[str]:
    if not (isinstance(bound, Call) and bound.op == "eq"):
        return None
    a, b = bound.args
    ua = {n.name for n in walk(a) if isinstance(n, ColumnRef)}
    ub = {n.name for n in walk(b) if isinstance(n, ColumnRef)}
    if ua and ub:
        if ua <= left_uids and ub <= right_uids:
            return "lr"
        if ua <= right_uids and ub <= left_uids:
            return "rl"
    return None


def _realias(plan: LogicalPlan, cols: List[PlanCol]) -> LogicalPlan:
    """Wrap a subplan so its schema carries new qualifiers (same uids)."""
    plan.schema = cols
    return plan


# ---------------------------------------------------------------------------
# aggregate extraction
# ---------------------------------------------------------------------------

# normalization of aggregate aliases; variance/stddev are REAL agg funcs
# (two-pass m2 states in the executor — the E[x^2]-E[x]^2 decomposition
# cancels catastrophically on large-magnitude data and is NOT used)
VARIANCE_AGGS = ("var_pop", "var_samp", "stddev_pop", "stddev_samp")
_AGG_ALIASES = {"variance": "var_pop", "std": "stddev_pop",
                "stddev": "stddev_pop", "any_value": "min"}


def _rewrite_extended_aggs(e):
    """Normalize aggregate aliases on select/having/order-by ASTs before
    collection: VARIANCE->VAR_POP, STD/STDDEV->STDDEV_POP,
    ANY_VALUE->MIN (ref: the reference's aggfuncs name canonicalization).
    """
    if not hasattr(e, "__dataclass_fields__") or isinstance(
            e, (A.SelectStmt, A.UnionStmt)):
        return e
    for f in e.__dataclass_fields__:
        v = getattr(e, f)
        if isinstance(v, list):
            setattr(e, f, [
                _rewrite_extended_aggs(x) if hasattr(x, "__dataclass_fields__")
                else (tuple(_rewrite_extended_aggs(y) if hasattr(y, "__dataclass_fields__")
                            else y for y in x) if isinstance(x, tuple) else x)
                for x in v])
        elif hasattr(v, "__dataclass_fields__") and not isinstance(
                v, (A.SelectStmt, A.UnionStmt)):
            setattr(e, f, _rewrite_extended_aggs(v))
    if isinstance(e, A.EFunc) and e.name in _AGG_ALIASES and len(e.args) == 1:
        return A.EFunc(_AGG_ALIASES[e.name], e.args, distinct=e.distinct)
    return e


def _collect_agg_calls(e, out: Dict[str, A.EFunc]):
    if isinstance(e, A.EFunc) and e.name in AGG_FUNCS:
        out.setdefault(ast_key(e), e)
        return  # no nested aggregates
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, list):
            for x in v:
                if hasattr(x, "__dataclass_fields__"):
                    _collect_agg_calls(x, out)
                elif isinstance(x, tuple):
                    for y in x:
                        if hasattr(y, "__dataclass_fields__"):
                            _collect_agg_calls(y, out)
        elif hasattr(v, "__dataclass_fields__") and not isinstance(v, (A.SelectStmt, A.UnionStmt)):
            _collect_agg_calls(v, out)


def _substitute(e, mapping: Dict[str, str]):
    """Replace AST subtrees (by structural key) with EName(uid) references."""
    k = ast_key(e)
    if k in mapping:
        return A.EName(mapping[k])
    if not hasattr(e, "__dataclass_fields__"):
        return e
    if isinstance(e, (A.SelectStmt, A.UnionStmt)):
        return e
    kwargs = {}
    for f in e.__dataclass_fields__:
        v = getattr(e, f)
        if isinstance(v, list):
            kwargs[f] = [
                tuple(_substitute(y, mapping) for y in x) if isinstance(x, tuple)
                else _substitute(x, mapping) if hasattr(x, "__dataclass_fields__")
                else x
                for x in v
            ]
        elif hasattr(v, "__dataclass_fields__") and not isinstance(v, (A.SelectStmt, A.UnionStmt)):
            kwargs[f] = _substitute(v, mapping)
        else:
            kwargs[f] = v
    return type(e)(**kwargs)


_WINDOW_FUNCS = {"row_number", "rank", "dense_rank",
                 "count", "sum", "avg", "min", "max",
                 "lead", "lag", "first_value", "last_value", "ntile"}


def _collect_window_calls(e, out: Dict[str, A.EWindow]) -> None:
    if isinstance(e, A.EWindow):
        if e.func not in _WINDOW_FUNCS:
            raise UnsupportedError(f"window function {e.func.upper()}")
        out.setdefault(ast_key(e), e)
        return  # no windows nested inside windows
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, list):
            for x in v:
                if hasattr(x, "__dataclass_fields__"):
                    _collect_window_calls(x, out)
        elif hasattr(v, "__dataclass_fields__") and not isinstance(v, (A.SelectStmt, A.UnionStmt)):
            _collect_window_calls(v, out)


def _window_default_repr(binder, d0: Literal, arg: Expr, fname: str):
    """LEAD/LAG default literal -> the argument column's DEVICE
    representation (dict code for strings, scaled int for decimals),
    since the executor substitutes it directly into the value array.
    Returns (value, is_null)."""
    if d0.value is None:
        return None, True
    t = arg.type_
    k = t.kind
    if k in (TypeKind.STRING, TypeKind.JSON):
        d = binder._dict_of(arg)
        if d is None:
            raise UnsupportedError(
                f"{fname.upper()} string default without dictionary context")
        s = str(d0.value) if d0.type_.kind == TypeKind.STRING else str(int(d0.value))
        code = d.code_of(s)
        if code < 0:
            raise UnsupportedError(
                f"{fname.upper()} default {s!r} not in the column dictionary")
        return int(code), False
    if k == TypeKind.DECIMAL:
        if d0.type_.kind == TypeKind.DECIMAL:
            return int(d0.value) * 10 ** (t.scale - d0.type_.scale) \
                if t.scale >= d0.type_.scale else \
                int(round(int(d0.value) / 10 ** (d0.type_.scale - t.scale))), False
        if d0.type_.kind == TypeKind.INT:
            return int(d0.value) * 10 ** t.scale, False
        if d0.type_.kind == TypeKind.FLOAT:
            return int(round(float(d0.value) * 10 ** t.scale)), False
    if k == TypeKind.FLOAT:
        v = d0.value
        if d0.type_.kind == TypeKind.DECIMAL:
            v = int(v) / 10 ** d0.type_.scale
        return float(v), False
    return d0.value, False


def _normalize_frame(w: A.EWindow):
    """One rule for both LWindow construction sites: frames don't
    apply to ranking functions or LEAD/LAG (MySQL ignores them), and
    RANGE UNBOUNDED PRECEDING..CURRENT ROW IS the default — every other
    combination executes explicitly."""
    frame = getattr(w, "frame", None)
    if frame is None:
        return None
    if w.func in ("row_number", "rank", "dense_rank", "ntile",
                  "lead", "lag"):
        return None
    if frame[0] == "range" and frame[1] == ("unbounded_preceding",) \
            and frame[2] == ("current",):
        return None
    return frame


def _plan_window(w: A.EWindow, plan: LogicalPlan, scope: Scope,
                 ctx: BuildContext):
    """Stack one LWindow node; returns (plan, widened scope, out uid)."""
    binder = ctx.binder
    part = [binder.bind_expr(e, scope) for e in w.partition_by]
    order = [(binder.bind_expr(oi.expr, scope), oi.desc) for oi in w.order_by]
    params: tuple = ()
    if w.func in ("lead", "lag", "first_value", "last_value"):
        if not w.args:
            raise PlanError(f"{w.func.upper()} needs an argument")
        if w.func in ("first_value", "last_value") and len(w.args) != 1:
            raise PlanError(f"{w.func.upper()} takes exactly one argument")
        arg = binder.bind_expr(w.args[0], scope)
        if w.func in ("lead", "lag"):
            off = 1
            if len(w.args) > 1:
                o = binder.bind_expr(w.args[1], scope)
                if not isinstance(o, Literal) or o.value is None \
                        or int(o.value) < 0:
                    raise PlanError(
                        f"{w.func.upper()} offset must be a nonnegative constant")
                off = int(o.value)
            dval, dnull = None, True
            if len(w.args) > 2:
                d0 = binder.coerce_untyped_literal(
                    binder.bind_expr(w.args[2], scope), arg.type_)
                if not isinstance(d0, Literal):
                    raise PlanError(f"{w.func.upper()} default must be constant")
                dval, dnull = _window_default_repr(binder, d0, arg, w.func)
            params = (off, dval, dnull)
        node_args = [arg]
        uid = binder.new_uid(f"win.{w.func}")
        col = PlanCol(uid=uid, name=uid, type_=arg.type_,
                      dict_=binder._dict_of(arg))
        frame = _normalize_frame(w)
        node = LWindow(schema=list(plan.schema) + [col], children=[plan],
                       func=w.func, args=node_args, partition_by=part,
                       order_by=order, out_uid=uid, out_type=arg.type_,
                       params=params, frame=frame)
        return node, Scope(list(scope.cols) + [col], scope.parent), uid
    if w.func == "ntile":
        if len(w.args) != 1:
            raise PlanError("NTILE takes one constant argument")
        nlit = binder.bind_expr(w.args[0], scope)
        if not isinstance(nlit, Literal) or nlit.value is None \
                or int(nlit.value) < 1:
            raise PlanError("NTILE argument must be a positive constant")
        uid = binder.new_uid("win.ntile")
        col = PlanCol(uid=uid, name=uid, type_=INT64)
        node = LWindow(schema=list(plan.schema) + [col], children=[plan],
                       func="ntile", args=[], partition_by=part,
                       order_by=order, out_uid=uid, out_type=INT64,
                       params=(int(nlit.value),))
        return node, Scope(list(scope.cols) + [col], scope.parent), uid
    if w.func in ("row_number", "rank", "dense_rank"):
        if w.args:
            raise PlanError(f"{w.func.upper()} takes no arguments")
        args: List[Expr] = []
        out_type = INT64
        d = None
    else:
        if w.func == "count" and (not w.args or isinstance(w.args[0], A.EStar)):
            args = []
            out_type = INT64
            d = None
        else:
            if len(w.args) != 1:
                raise PlanError(f"window {w.func.upper()} takes one argument")
            arg = binder.bind_expr(w.args[0], scope)
            args = [arg]
            out_type = (INT64 if w.func == "count"
                        else _agg_result_type(w.func, arg))
            d = binder._dict_of(arg) if w.func in ("min", "max") else None
    uid = binder.new_uid(f"win.{w.func}")
    col = PlanCol(uid=uid, name=uid, type_=out_type, dict_=d)
    frame = _normalize_frame(w)
    node = LWindow(
        schema=list(plan.schema) + [col], children=[plan],
        func=w.func, args=args, partition_by=part, order_by=order,
        out_uid=uid, out_type=out_type, frame=frame,
    )
    return node, Scope(list(scope.cols) + [col], scope.parent), uid


def _agg_result_type(func: str, arg: Optional[Expr]) -> SQLType:
    if func == "count":
        return INT64
    if func == "avg":
        return FLOAT64
    if func in ("min", "max"):
        return arg.type_
    if func in ("bit_and", "bit_or", "bit_xor"):
        # MySQL result is BIGINT UNSIGNED; we keep the int64 bit pattern
        return INT64
    if func == "group_concat":
        return STRING
    if func in VARIANCE_AGGS:
        return FLOAT64
    # sum
    k = arg.type_.kind
    if k == TypeKind.DECIMAL:
        return decimal_type(18, arg.type_.scale)
    if k == TypeKind.FLOAT:
        return FLOAT64
    return INT64


# ---------------------------------------------------------------------------
# SELECT builder
# ---------------------------------------------------------------------------

def build_select(stmt, ctx: BuildContext, outer: Optional[Scope] = None) -> LogicalPlan:
    if isinstance(stmt, A.UnionStmt):
        return _build_union(stmt, ctx, outer)
    assert isinstance(stmt, A.SelectStmt)

    # CTEs visible in this select; single-reference CTEs inline (MERGE),
    # multi-reference ones materialize once at plan time (the reference
    # planner's CTE MATERIALIZE default for shared CTEs) so an expensive
    # body — e.g. TPC-DS Q95's web_sales self-join — computes once
    old_ctes = dict(ctx.ctes)
    for cte in stmt.ctes:
        if cte.columns:
            raise UnsupportedError("CTE column lists not supported yet")
        ctx.ctes[cte.name] = cte.select
        if _count_table_refs(stmt, cte.name) >= 2:
            # keyed by the BODY's identity: a same-named CTE in another
            # scope is a different object and never aliases this one
            ctx.cte_multi.add(id(cte.select))
            if _cte_semi_only(stmt, cte.name):
                ctx.cte_duponly.add(id(cte.select))
    try:
        return _build_select_core(stmt, ctx, outer)
    finally:
        ctx.ctes = old_ctes


def _build_select_core(stmt: A.SelectStmt, ctx: BuildContext, outer) -> LogicalPlan:
    binder = ctx.binder
    plan, scope = build_from(stmt.from_, ctx, outer)

    # ---- WHERE: subquery conjuncts become joins/gates ----
    if stmt.where is not None:
        plain = []
        conjuncts = [x for c in _conjuncts(stmt.where) for x in _factor_or(c)]
        for conj in conjuncts:
            conj = _normalize_not(conj)
            if isinstance(conj, A.EIn) and conj.subquery is not None:
                # scalar subqueries inside the IN's left-hand side fold first
                conj = dataclasses.replace(conj, arg=_fold_subqueries(conj.arg, ctx, scope))
                plan, scope = _in_subquery_to_join(conj, plan, scope, ctx)
                continue
            if isinstance(conj, A.EExists):
                join = _exists_to_join(conj, plan, scope, ctx)
                if join == "const":
                    plain.append(A.EBool(not conj.negated))
                elif join is not None:
                    plan = join
                else:
                    plain.append(A.EBool(_exists_value(conj, ctx, scope)))
                continue
            hit = _try_scalar_corr(conj, plan, scope, ctx)
            if hit is not None:
                conj, plan, scope = hit
            conj = _fold_subqueries(conj, ctx, scope)
            plain.append(conj)
        if plain:
            cond = _and_ir([binder.bind_expr(c, scope) for c in plain])
            plan = LSelection(schema=plan.schema, children=[plan], cond=cond)

    # ---- aggregate detection ----
    for item in stmt.items:
        new = _rewrite_extended_aggs(item.expr)
        if new is not item.expr and item.alias is None:
            item.alias = expr_display(item.expr)
        item.expr = new
    if stmt.having is not None:
        stmt.having = _rewrite_extended_aggs(stmt.having)
    for oi in stmt.order_by:
        oi.expr = _rewrite_extended_aggs(oi.expr)

    agg_calls: Dict[str, A.EFunc] = {}
    for item in stmt.items:
        _collect_agg_calls(item.expr, agg_calls)
    if stmt.having is not None:
        _collect_agg_calls(stmt.having, agg_calls)
    for oi in stmt.order_by:
        _collect_agg_calls(oi.expr, agg_calls)

    has_agg = bool(agg_calls) or bool(stmt.group_by)
    alias_map = {
        item.alias.lower(): item.expr for item in stmt.items if item.alias
    }

    post_scope = scope
    if has_agg:
        plan, post_scope, mapping = _build_aggregate(stmt, plan, scope, ctx, agg_calls, alias_map)
    else:
        mapping = {}

    # ---- HAVING ----
    if stmt.having is not None:
        if not has_agg:
            raise PlanError("HAVING without aggregation")
        # uncorrelated scalar subqueries in HAVING fold to constants now
        h_ast = _fold_subqueries(stmt.having, ctx, scope)
        h_ast = _substitute(h_ast, mapping)
        cond = binder.bind_expr(h_ast, post_scope)
        plan = LSelection(schema=plan.schema, children=[plan], cond=cond)

    # ---- window functions (evaluate after grouping + HAVING) ----
    win_calls: Dict[str, A.EWindow] = {}
    for item in stmt.items:
        _collect_window_calls(item.expr, win_calls)
    for oi in stmt.order_by:
        _collect_window_calls(oi.expr, win_calls)
    if win_calls:
        for key, w in win_calls.items():
            w2 = _substitute(w, mapping) if mapping else w
            plan, post_scope, uid = _plan_window(w2, plan, post_scope, ctx)
            mapping[key] = uid

    subst = bool(mapping)

    # ---- SELECT items ----
    items: List[Tuple[str, object]] = []  # (display name, ast)
    for item in stmt.items:
        if isinstance(item.expr, A.EStar):
            src_scope = scope if not has_agg else None
            if src_scope is None:
                raise PlanError("SELECT * with GROUP BY requires explicit columns")
            for c in src_scope.cols:
                if c.hidden:
                    continue
                if item.expr.qualifier and (c.qualifier or "").lower() != item.expr.qualifier.lower():
                    continue
                items.append((c.name, A.EName(c.name, c.qualifier)))
            if not items:
                raise PlanError("* expanded to nothing")
        else:
            name = item.alias or expr_display(item.expr)
            items.append((name, _substitute(item.expr, mapping) if subst else item.expr))

    proj_exprs: List[Expr] = []
    proj_cols: List[PlanCol] = []
    for name, ast_e in items:
        bound = binder.codify_output_literal(binder.bind_expr(ast_e, post_scope))
        uid = binder.new_uid(name)
        proj_exprs.append(bound)
        proj_cols.append(
            PlanCol(uid=uid, name=name, type_=bound.type_, qualifier=None,
                    dict_=getattr(bound, "_dict", None))
        )
    n_visible = len(proj_cols)

    # ---- ORDER BY (may add hidden projection columns) ----
    sort_items: List[Tuple[Expr, bool]] = []
    if stmt.order_by:
        by_alias = {c.name.lower(): i for i, c in enumerate(proj_cols)}
        for oi in stmt.order_by:
            target_idx = None
            if isinstance(oi.expr, A.ENum) and "." not in oi.expr.text:
                pos = int(oi.expr.text)
                if not 1 <= pos <= n_visible:
                    raise PlanError(f"ORDER BY position {pos} out of range")
                target_idx = pos - 1
            elif isinstance(oi.expr, A.EName) and oi.expr.qualifier is None and oi.expr.name.lower() in by_alias:
                target_idx = by_alias[oi.expr.name.lower()]
            if target_idx is not None:
                pc = proj_cols[target_idx]
                sort_items.append((ColumnRef(type_=pc.type_, name=pc.uid), oi.desc))
                continue
            ast_e = _substitute(oi.expr, mapping) if subst else oi.expr
            bound = binder.bind_expr(ast_e, post_scope)
            uid = binder.new_uid("sort")
            proj_exprs.append(bound)
            proj_cols.append(PlanCol(uid=uid, name=uid, type_=bound.type_,
                                     dict_=getattr(bound, "_dict", None)))
            sort_items.append((ColumnRef(type_=bound.type_, name=uid), oi.desc))

    plan = LProjection(
        schema=proj_cols, children=[plan], exprs=proj_exprs, n_visible=n_visible
    )

    # ---- DISTINCT ----
    if stmt.distinct:
        if len(proj_cols) != n_visible:
            raise UnsupportedError("DISTINCT with ORDER BY on hidden columns")
        plan = LAggregate(
            schema=list(proj_cols),
            children=[plan],
            group_exprs=_canon_group_refs(proj_cols),
            group_uids=[c.uid for c in proj_cols],
            aggs=[],
        )

    if sort_items:
        plan = LSort(schema=plan.schema, children=[plan], items=sort_items)

    if stmt.limit is not None:
        plan = LLimit(
            schema=plan.schema, children=[plan],
            count=stmt.limit, offset=stmt.offset or 0,
        )
    return plan


def _canon_group_refs(cols) -> List[Expr]:
    """Group-key exprs for DISTINCT / set-operation dedup: _ci string
    columns dedup by CANONICAL code so fold-equal rows collapse into one
    (MySQL's case-insensitive DISTINCT); other columns pass through."""
    out = []
    for c in cols:
        e = c.ref()
        d = getattr(e, "_dict", None) or c.dict_
        if d is not None and getattr(d, "is_ci", False):
            ne = Lookup.build(e, d.canon_lut(), STRING)
            object.__setattr__(ne, "_dict", d)
            e = ne
        out.append(e)
    return out


def _build_aggregate(stmt, plan, scope, ctx, agg_calls, alias_map):
    binder = ctx.binder
    mapping: Dict[str, str] = {}
    group_exprs: List[Expr] = []
    group_uids: List[str] = []
    group_cols: List[PlanCol] = []

    for g_ast in stmt.group_by:
        # ordinal / alias resolution
        if isinstance(g_ast, A.ENum) and "." not in g_ast.text:
            pos = int(g_ast.text)
            if not 1 <= pos <= len(stmt.items):
                raise PlanError(f"GROUP BY position {pos} out of range")
            g_ast = stmt.items[pos - 1].expr
        elif (
            isinstance(g_ast, A.EName)
            and g_ast.qualifier is None
            and g_ast.name.lower() in alias_map
            and scope.try_resolve(g_ast.name, None) is None
        ):
            g_ast = alias_map[g_ast.name.lower()]
        bound = binder.bind_expr(g_ast, scope)
        gdict = getattr(bound, "_dict", None)
        if gdict is not None and gdict.is_ci:
            # group CANONICAL codes so fold-equal strings land in one
            # group (MySQL _ci GROUP BY); the canonical code decodes to
            # the class representative in the same dictionary
            bound = binder.attach_dict(
                Lookup.build(bound, gdict.canon_lut(), STRING), gdict)
        uid = binder.new_uid("group")
        mapping[ast_key(g_ast)] = uid
        group_exprs.append(bound)
        group_uids.append(uid)
        name = expr_display(g_ast)
        if isinstance(g_ast, A.EName):
            name = g_ast.name
        group_cols.append(
            PlanCol(uid=uid, name=name, type_=bound.type_,
                    dict_=getattr(bound, "_dict", None))
        )

    aggs: List[AggSpec] = []
    agg_cols: List[PlanCol] = []
    for key, call in agg_calls.items():
        if key in mapping:
            continue
        func = call.name
        if func == "count" and (not call.args or isinstance(call.args[0], A.EStar)):
            arg = None
        else:
            if len(call.args) != 1:
                raise UnsupportedError(f"{func.upper()} with {len(call.args)} args")
            arg = binder.bind_expr(call.args[0], scope)
            adict = getattr(arg, "_dict", None)
            if (call.distinct and adict is not None and adict.is_ci
                    and func not in ("min", "max", "group_concat")):
                # DISTINCT dedups fold-equal strings under _ci (MySQL);
                # min/max keep raw codes — code order already collates —
                # and group_concat keeps its raw arg (its two-phase
                # rewrite owns the arg shape; its DISTINCT stays bytewise)
                arg = binder.attach_dict(
                    Lookup.build(arg, adict.canon_lut(), STRING), adict)
        t = _agg_result_type(func, arg)
        uid = binder.new_uid(func)
        mapping[key] = uid
        extra = None
        out_dict = (getattr(arg, "_dict", None)
                    if func in ("min", "max") and arg is not None else None)
        if func == "group_concat":
            # result strings exist only at execution time: attach a
            # RuntimeDictionary the executor fills per run
            from tidb_tpu.chunk.dictionary import RuntimeDictionary

            order_desc = None
            if call.agg_order is not None:
                if (len(call.agg_order) != 1
                        or ast_key(call.agg_order[0][0]) != ast_key(call.args[0])):
                    raise UnsupportedError(
                        "GROUP_CONCAT ORDER BY must be the concatenated "
                        "expression itself")
                order_desc = call.agg_order[0][1]
            out_dict = RuntimeDictionary([])
            extra = (call.separator if call.separator is not None else ",",
                     order_desc, out_dict)
        aggs.append(AggSpec(uid=uid, func=func, arg=arg,
                            distinct=call.distinct, type_=t, extra=extra))
        agg_cols.append(
            PlanCol(uid=uid, name=expr_display(call), type_=t, dict_=out_dict)
        )

    node = LAggregate(
        schema=group_cols + agg_cols,
        children=[plan],
        group_exprs=group_exprs,
        group_uids=group_uids,
        aggs=aggs,
    )
    return node, Scope(node.schema, None), mapping


# ---------------------------------------------------------------------------
# WHERE-clause rewrites
# ---------------------------------------------------------------------------

def _disjuncts(e) -> List:
    if isinstance(e, A.EBinary) and e.op == "or":
        return _disjuncts(e.left) + _disjuncts(e.right)
    return [e]


def _and_ast(parts: List) -> Optional[object]:
    out = None
    for p in parts:
        out = p if out is None else A.EBinary("and", out, p)
    return out


def _factor_or(conj) -> List:
    """(a AND b) OR (a AND c) -> [a, b OR c]: conjuncts common to every OR
    branch factor out, so join keys hidden under OR (TPC-H Q19's shape)
    become extractable equi-join conditions instead of forcing a cross
    join (ref: planner/core expression_rewriter's extractFiltersFromDNF)."""
    if not (isinstance(conj, A.EBinary) and conj.op == "or"):
        return [conj]
    branches = _disjuncts(conj)
    keyed = [{ast_key(c): c for c in _conjuncts(b)} for b in branches]
    common_keys = set(keyed[0])
    for k in keyed[1:]:
        common_keys &= set(k)
    if not common_keys:
        return [conj]
    common = [keyed[0][k] for k in sorted(common_keys)]
    residuals = []
    for k in keyed:
        rest = [c for key, c in k.items() if key not in common_keys]
        if not rest:
            return common  # one branch is exactly the common part: OR is true
        residuals.append(_and_ast(rest))
    out = None
    for r in residuals:
        out = r if out is None else A.EBinary("or", out, r)
    return common + [out]


def _normalize_not(conj):
    """Push NOT into EXISTS/IN so the join rewrites below see them."""
    while isinstance(conj, A.EUnary) and conj.op == "not":
        arg = conj.arg
        if isinstance(arg, A.EExists):
            conj = dataclasses.replace(arg, negated=not arg.negated)
        elif isinstance(arg, A.EIn):
            conj = dataclasses.replace(arg, negated=not arg.negated)
        elif isinstance(arg, A.EUnary) and arg.op == "not":
            conj = arg.arg
        else:
            return conj
    return conj


def _ast_names(e, out: List):
    """Collect EName nodes, not descending into nested selects."""
    if isinstance(e, A.EName):
        out.append(e)
        return
    if not hasattr(e, "__dataclass_fields__") or isinstance(e, (A.SelectStmt, A.UnionStmt)):
        return
    for f in e.__dataclass_fields__:
        v = getattr(e, f)
        if isinstance(v, list):
            for x in v:
                if isinstance(x, tuple):
                    for y in x:
                        _ast_names(y, out)
                else:
                    _ast_names(x, out)
        elif isinstance(v, tuple):
            for y in v:
                _ast_names(y, out)
        else:
            _ast_names(v, out)


def _has_subquery(e) -> bool:
    if isinstance(e, (A.ESubquery, A.EExists, A.SelectStmt, A.UnionStmt)):
        return True
    if isinstance(e, A.EIn) and e.subquery is not None:
        return True
    if not hasattr(e, "__dataclass_fields__"):
        return False
    for f in e.__dataclass_fields__:
        v = getattr(e, f)
        if isinstance(v, list):
            for x in v:
                if isinstance(x, tuple):
                    if any(_has_subquery(y) for y in x):
                        return True
                elif _has_subquery(x):
                    return True
        elif isinstance(v, tuple):
            if any(_has_subquery(y) for y in v):
                return True
        elif _has_subquery(v):
            return True
    return False


def _expr_side(e, inner_scope: Scope, outer_scope: Scope) -> str:
    """Which scope an expression's column refs live in: 'inner', 'outer',
    'const' (no refs), 'mixed', or 'unknown'. Inner shadows outer, matching
    SQL name resolution."""
    names: List = []
    _ast_names(e, names)
    if not names:
        return "const"
    sides = set()
    for n in names:
        if inner_scope.try_resolve(n.name, n.qualifier) is not None:
            sides.add("inner")
        elif outer_scope.try_resolve(n.name, n.qualifier) is not None:
            sides.add("outer")
        else:
            return "unknown"
    return sides.pop() if len(sides) == 1 else "mixed"


def _align_dicts(outer_expr: Expr, inner_expr: Expr, inner_dict) -> Tuple[Expr, Expr]:
    """Translate both sides of a cross-plan string equality onto a union
    dictionary so codes compare correctly."""
    od = getattr(outer_expr, "_dict", None)
    idd = inner_dict if inner_dict is not None else getattr(inner_expr, "_dict", None)
    if od is None and idd is None:
        return outer_expr, inner_expr
    if od is None or idd is None:
        raise UnsupportedError("subquery join mixing string and non-string")
    if od != idd:
        import numpy as np

        union = Dictionary.union(od, idd)
        outer_expr = Lookup.build(outer_expr, od.translate_canon_to(union).astype(np.int32), STRING)
        inner_expr = Lookup.build(inner_expr, idd.translate_canon_to(union).astype(np.int32), STRING)
    elif od.is_ci:
        # same dictionary on both sides still needs canon codes: raw
        # codes would compare case-sensitively under a _ci collation
        lut = od.canon_lut()
        outer_expr = Lookup.build(outer_expr, lut, STRING)
        inner_expr = Lookup.build(inner_expr, lut, STRING)
    return outer_expr, inner_expr


def _split_correlation(sub: A.SelectStmt, ctx: BuildContext, outer_scope: Scope):
    """Build the subquery's FROM and classify its WHERE conjuncts against
    (inner, outer) scopes. Returns None if any conjunct defeats the
    decorrelation (nested subquery, unknown name, non-equality mix), else
    (inner_plan, inner_scope, local, corr_eq, corr_other) where corr_eq is
    [(outer_ast, inner_ast)] equalities and corr_other the remaining
    outer-referencing conjuncts."""
    inner_plan, inner_scope = build_from(sub.from_, ctx, None)
    local, corr_eq, corr_other = [], [], []
    for c in (_conjuncts(sub.where) if sub.where is not None else []):
        if _has_subquery(c):
            return None
        side = _expr_side(c, inner_scope, outer_scope)
        if side in ("inner", "const"):
            local.append(c)
        elif side == "unknown":
            return None
        elif side == "mixed" and isinstance(c, A.EBinary) and c.op == "=":
            ls = _expr_side(c.left, inner_scope, outer_scope)
            rs = _expr_side(c.right, inner_scope, outer_scope)
            if {ls, rs} == {"inner", "outer"}:
                oa, ia = (c.right, c.left) if ls == "inner" else (c.left, c.right)
                corr_eq.append((oa, ia))
            else:
                corr_other.append(c)
        else:  # outer-only or non-equality mixed
            corr_other.append(c)
    return inner_plan, inner_scope, local, corr_eq, corr_other


def _exists_to_join(conj: A.EExists, plan, scope: Scope, ctx: BuildContext):
    """Correlated [NOT] EXISTS -> semi/anti join on the correlation
    equalities (the decorrelation the reference's planner performs); other
    correlated conjuncts ride along as the join's other_cond. Returns None
    for uncorrelated subqueries (eager evaluation handles those)."""
    sub = conj.subquery
    if not isinstance(sub, A.SelectStmt) or sub.from_ is None:
        return None
    if sub.group_by or sub.having is not None or sub.limit is not None:
        return None
    agg_calls: Dict[str, A.EFunc] = {}
    for it in sub.items:
        if not isinstance(it.expr, A.EStar):
            _collect_agg_calls(it.expr, agg_calls)
    if agg_calls:
        # an ungrouped aggregate select always yields exactly one row, so
        # EXISTS over it is constant TRUE whatever the correlation matches
        return "const"
    split = _split_correlation(sub, ctx, scope)
    if split is None:
        return None
    inner_plan, inner_scope, local, corr_eq, corr_other = split
    if not corr_eq and not corr_other:
        return None  # uncorrelated
    if not corr_eq:
        raise UnsupportedError("correlated EXISTS without an equality correlation")
    binder = ctx.binder
    if local:
        cond = _and_ir([binder.bind_expr(c, inner_scope) for c in local])
        inner_plan = LSelection(schema=inner_plan.schema, children=[inner_plan], cond=cond)
    eq = []
    for oa, ia in corr_eq:
        oe = binder.bind_expr(oa, scope)
        ie = binder.bind_expr(ia, inner_scope)
        inner_dict = getattr(ie, "_dict", None)
        oe, ie = _align_dicts(oe, ie, inner_dict)
        eq.append((oe, ie))
    other = None
    if corr_other:
        combined = Scope(list(scope.cols) + list(inner_scope.cols), scope.parent)
        other = _and_ir([binder.bind_expr(c, combined) for c in corr_other])
    return LJoin(
        schema=list(plan.schema),
        children=[plan, inner_plan],
        kind="anti" if conj.negated else "semi",
        eq_conds=eq,
        other_cond=other,
        exists_sem=True,
    )


_CMP_OPS = {"=", "<>", "!=", "<", "<=", ">", ">="}


def _try_scalar_corr(conj, plan, scope: Scope, ctx: BuildContext):
    """Rewrite `expr cmp (correlated scalar aggregate subquery)` as an inner
    join against the subquery re-grouped by its correlation keys, with the
    comparison referencing the joined aggregate column (classic scalar-agg
    decorrelation; ref: planner/core decorrelate rule). Returns
    (new_conj_ast, plan, scope) or None."""
    if not (isinstance(conj, A.EBinary) and conj.op in _CMP_OPS):
        return None
    if isinstance(conj.right, A.ESubquery) and not isinstance(conj.left, A.ESubquery):
        sub_node, other_side, sub_on_right = conj.right, conj.left, True
    elif isinstance(conj.left, A.ESubquery) and not isinstance(conj.right, A.ESubquery):
        sub_node, other_side, sub_on_right = conj.left, conj.right, False
    else:
        return None
    sel = sub_node.select
    if not isinstance(sel, A.SelectStmt) or sel.from_ is None:
        return None
    if sel.group_by or sel.having is not None or len(sel.items) != 1:
        return None
    agg_calls: Dict[str, A.EFunc] = {}
    _collect_agg_calls(sel.items[0].expr, agg_calls)
    if not agg_calls:
        return None  # not guaranteed single-row; only agg subqueries rewrite
    if any(c.name == "count" for c in agg_calls.values()):
        # COUNT over an empty group is 0, not NULL — the inner-join rewrite
        # below would drop zero-match outer rows instead of comparing 0
        return None
    split = _split_correlation(sel, ctx, scope)
    if split is None:
        return None
    _, _, local, corr_eq, corr_other = split
    if not corr_eq or corr_other:
        return None
    # regroup the subquery by its correlation keys and join on them
    new_sel = A.SelectStmt(
        items=[A.SelectItem(ia) for _, ia in corr_eq] + [sel.items[0]],
        from_=sel.from_,
        where=_and_ast(local),
        group_by=[ia for _, ia in corr_eq],
    )
    sub_plan = build_select(new_sel, ctx, None)
    value_col = sub_plan.schema[len(corr_eq)]
    binder = ctx.binder
    eq = []
    for i, (oa, _ia) in enumerate(corr_eq):
        oe = binder.bind_expr(oa, scope)
        ic = sub_plan.schema[i]
        ie = ic.ref()
        oe, ie = _align_dicts(oe, ie, ic.dict_)
        eq.append((oe, ie))
    join = LJoin(
        schema=list(plan.schema) + [value_col],
        children=[plan, sub_plan],
        kind="inner",
        eq_conds=eq,
    )
    # rows with no group simply drop out of the inner join — identical to
    # the NULL-comparison semantics of the original scalar subquery for
    # the agg functions this rewrite accepts (empty agg -> NULL)
    vref = A.EName(value_col.uid)
    new_conj = A.EBinary(conj.op, other_side, vref) if sub_on_right else A.EBinary(conj.op, vref, other_side)
    new_scope = Scope(list(scope.cols) + [value_col], scope.parent)
    return new_conj, join, new_scope


# ---------------------------------------------------------------------------
# subqueries
# ---------------------------------------------------------------------------

def _fold_subqueries(conj, ctx: BuildContext, scope: Scope):
    """Replace uncorrelated scalar subqueries (ESubquery) inside an AST
    conjunct with literal AST nodes by executing them now."""
    if isinstance(conj, A.ESubquery):
        rows = _run_subplan(conj.select, ctx, scope)
        if len(rows) > 1:
            raise PlanError("scalar subquery returned more than one row")
        if not rows or rows[0][0] is None:
            return A.ENull()
        v = rows[0][0]
        if isinstance(v, str):
            return A.EStr(v)
        if isinstance(v, float):
            return A.ENum(f"{v:.17e}")  # exponent form binds as FLOAT64
        return A.ENum(repr(v))
    if not hasattr(conj, "__dataclass_fields__") or isinstance(conj, (A.SelectStmt, A.UnionStmt)):
        return conj
    kwargs = {}
    for f in conj.__dataclass_fields__:
        v = getattr(conj, f)
        if hasattr(v, "__dataclass_fields__") and not isinstance(v, (A.SelectStmt, A.UnionStmt)):
            kwargs[f] = _fold_subqueries(v, ctx, scope)
        elif isinstance(v, list):
            kwargs[f] = [
                _fold_subqueries(x, ctx, scope) if hasattr(x, "__dataclass_fields__") and not isinstance(x, (A.SelectStmt, A.UnionStmt)) else x
                for x in v
            ]
        else:
            kwargs[f] = v
    return type(conj)(**kwargs)


def _run_subplan(select_ast, ctx: BuildContext, scope: Scope) -> list:
    if ctx.execute_subplan is None:
        raise UnsupportedError("subquery execution not wired (no session)")
    sub = build_select(select_ast, ctx, scope)  # scope as parent: correlation detection
    return ctx.execute_subplan(sub)


def _exists_value(conj: A.EExists, ctx: BuildContext, scope: Scope) -> bool:
    limited = dataclasses.replace(conj.subquery) if isinstance(conj.subquery, A.SelectStmt) else conj.subquery
    if isinstance(limited, A.SelectStmt) and limited.limit is None:
        limited.limit = 1
    rows = _run_subplan(limited, ctx, scope)
    val = bool(rows)
    return (not val) if conj.negated else val


def _in_subquery_to_join(conj: A.EIn, plan, scope, ctx: BuildContext):
    # IN is duplicate-insensitive: an inline duplicate-detection
    # self-join collapses to the grouped distinctness form
    sub_ast = _try_selfjoin_distinctness(conj.subquery) or conj.subquery
    sub = build_select(sub_ast, ctx, scope)
    if len(sub.schema) != 1:
        raise PlanError("IN subquery must return exactly one column")
    outer_expr = ctx.binder.bind_expr(conj.arg, scope)
    inner_col = sub.schema[0]
    inner_expr: Expr = inner_col.ref()

    # align string dictionaries across the two sides
    od = getattr(outer_expr, "_dict", None)
    idd = inner_col.dict_
    if od is not None or idd is not None:
        if od is None or idd is None:
            raise UnsupportedError("IN subquery mixing string and non-string")
        if od != idd:
            import numpy as np

            union = Dictionary.union(od, idd)
            outer_expr = Lookup.build(outer_expr, od.translate_canon_to(union).astype(np.int32), STRING)
            inner_expr = Lookup.build(inner_expr, idd.translate_canon_to(union).astype(np.int32), STRING)
        elif od.is_ci:
            # same dictionary still needs canonical codes under _ci
            lut = od.canon_lut()
            outer_expr = Lookup.build(outer_expr, lut, STRING)
            inner_expr = Lookup.build(inner_expr, lut, STRING)

    kind = "anti" if conj.negated else "semi"
    join = LJoin(
        schema=list(plan.schema),  # semi/anti joins keep the outer schema
        children=[plan, sub],
        kind=kind,
        eq_conds=[(outer_expr, inner_expr)],
    )
    return join, Scope(join.schema, scope.parent)


# ---------------------------------------------------------------------------
# UNION
# ---------------------------------------------------------------------------

def _build_full_join(src: A.Join, ctx: BuildContext, outer):
    """L FULL JOIN R = (L LEFT JOIN R) UNION ALL (rows of R with no
    qualified L match, left payload all-NULL) — the same rewrite the
    reference's planner applies; there is no native full-join operator.
    Both branches rebuild their sources (fresh uid spaces); branch B
    projects onto branch A's uids so the union is pure concatenation."""
    left_join = A.Join("left", src.left, src.right, src.on, src.using)
    plan_a, scope_a = build_from(left_join, ctx, outer)
    acols = scope_a.cols

    # branch B: anti join with probe = right side
    left2, lscope2 = build_from(src.left, ctx, outer)
    right2, rscope2 = build_from(src.right, ctx, outer)
    combined2 = Scope(lscope2.cols + rscope2.cols, outer)
    cond_asts = _conjuncts(src.on) if src.on is not None else []
    if src.using:
        for name in src.using:
            cond_asts.append(
                A.EBinary("=", A.EName(name, _qual_of(lscope2, name)),
                          A.EName(name, _qual_of(rscope2, name))))
    left_uids = {c.uid for c in lscope2.cols}
    right_uids = {c.uid for c in rscope2.cols}
    eq, other = [], []
    for cast_ in cond_asts:
        bound = ctx.binder.bind_expr(cast_, combined2)
        side = _classify_eq(bound, left_uids, right_uids)
        if side == "lr":
            eq.append((bound.args[1], bound.args[0]))  # probe=right first
        elif side == "rl":
            eq.append((bound.args[0], bound.args[1]))
        else:
            other.append(bound)
    anti = LJoin(
        schema=list(rscope2.cols), children=[right2, left2], kind="anti",
        eq_conds=eq, other_cond=_and_ir(other),
        exists_sem=True,  # an unmatched NULL right key still appears
    )
    n_left = len(acols) - len(rscope2.cols)
    exprs_b: List[Expr] = [
        Literal(type_=c.type_, value=None) for c in acols[:n_left]
    ] + [c.ref() for c in rscope2.cols]
    bcols = [dataclasses.replace(c) for c in acols]
    proj_b = LProjection(schema=bcols, children=[anti], exprs=exprs_b)

    union = LUnion(schema=list(acols), children=[plan_a, proj_b], all=True)
    return union, Scope(acols, outer)


def _build_union(stmt: A.UnionStmt, ctx: BuildContext, outer) -> LogicalPlan:
    if stmt.op not in ("union", "except", "intersect"):
        raise UnsupportedError(f"{stmt.op.upper()} not supported yet")
    if stmt.op in ("except", "intersect") and stmt.all:
        raise UnsupportedError(f"{stmt.op.upper()} ALL not supported yet")
    sides: List[LogicalPlan] = []

    def flatten(s):
        if (stmt.op == "union" and isinstance(s, A.UnionStmt)
                and s.op == "union" and s.all == stmt.all
                and not s.order_by and s.limit is None):
            flatten(s.left)
            flatten(s.right)
        else:
            sides.append(build_select(s, ctx, outer))

    flatten(stmt.left)
    flatten(stmt.right)

    arity = len(sides[0].schema)
    for s in sides:
        if len(s.schema) != arity:
            raise PlanError("UNION arity mismatch")

    # result types + dictionaries per position
    out_cols: List[PlanCol] = []
    for i in range(arity):
        t = sides[0].schema[i].type_
        for s in sides[1:]:
            t = common_type(t, s.schema[i].type_)
        d = None
        if t.kind == TypeKind.STRING:
            d = sides[0].schema[i].dict_ or Dictionary([])
            for s in sides[1:]:
                d = Dictionary.union(d, s.schema[i].dict_ or Dictionary([]))
        out_cols.append(
            PlanCol(uid=ctx.binder.new_uid(f"union.{sides[0].schema[i].name}"),
                    name=sides[0].schema[i].name, type_=t, dict_=d)
        )

    # coerce each side through a projection
    import numpy as np
    from tidb_tpu.expression.expr import Cast

    coerced = []
    for s in sides:
        exprs = []
        for i, oc in enumerate(out_cols):
            src = s.schema[i]
            e: Expr = src.ref()
            if oc.type_.kind == TypeKind.STRING:
                sd = src.dict_ or Dictionary([])
                if sd != oc.dict_:
                    e = Lookup.build(e, sd.translate_to(oc.dict_).astype(np.int32), STRING)
            elif src.type_ != oc.type_:
                e = Cast(type_=oc.type_, arg=e)
            exprs.append(e)
        cols = [dataclasses.replace(c) for c in out_cols]
        coerced.append(LProjection(schema=cols, children=[s], exprs=exprs))
        # all sides project onto the SAME uids so union is pure concat
        for c, oc in zip(cols, out_cols):
            c.uid = oc.uid

    if stmt.op in ("except", "intersect"):
        # set semantics via a marked union: tag each side, group by all
        # columns, keep groups by side counts (NULLs group together, so
        # NULL rows compare equal — exactly set-operation semantics)
        binder = ctx.binder
        # one side-tag column: per group, sl = SUM(tag) counts left-side
        # rows and COUNT(*) - sl counts right-side rows
        l_uid = binder.new_uid("__settag")
        lcol = PlanCol(uid=l_uid, name=l_uid, type_=INT64)
        for i, proj in enumerate(coerced):
            proj.exprs = list(proj.exprs) + [
                Literal(type_=INT64, value=1 if i == 0 else 0)]
            proj.schema = list(proj.schema) + [dataclasses.replace(lcol)]
        ext_cols = out_cols + [lcol]
        node = LUnion(schema=ext_cols, children=coerced, all=True)
        sl_uid, cnt_uid = binder.new_uid("sum.__settag"), binder.new_uid("cnt")
        agg_schema = list(out_cols) + [
            PlanCol(uid=sl_uid, name=sl_uid, type_=INT64),
            PlanCol(uid=cnt_uid, name=cnt_uid, type_=INT64),
        ]
        node = LAggregate(
            schema=agg_schema, children=[node],
            group_exprs=_canon_group_refs(out_cols),
            group_uids=[c.uid for c in out_cols],
            aggs=[AggSpec(uid=sl_uid, func="sum", arg=lcol.ref(), type_=INT64),
                  AggSpec(uid=cnt_uid, func="count", arg=None, type_=INT64)],
        )
        sl = ColumnRef(type_=INT64, name=sl_uid)
        cnt = ColumnRef(type_=INT64, name=cnt_uid)
        zero = Literal(type_=INT64, value=0)
        left_present = Call(type_=BOOL, op="gt", args=(sl, zero))
        sr = Call(type_=INT64, op="sub", args=(cnt, sl))
        right_test = Call(type_=BOOL,
                          op="eq" if stmt.op == "except" else "gt",
                          args=(sr, zero))
        cond = Call(type_=BOOL, op="and", args=(left_present, right_test))
        node = LSelection(schema=list(agg_schema), children=[node], cond=cond)
        node = LProjection(schema=list(out_cols), children=[node],
                           exprs=[c.ref() for c in out_cols],
                           n_visible=len(out_cols))
    else:
        node = LUnion(schema=out_cols, children=coerced, all=stmt.all)
        if not stmt.all:
            node = LAggregate(
                schema=list(out_cols),
                children=[node],
                group_exprs=_canon_group_refs(out_cols),
                group_uids=[c.uid for c in out_cols],
                aggs=[],
            )

    plan = node
    if stmt.order_by:
        by_alias = {c.name.lower(): c for c in out_cols}
        items = []
        for oi in stmt.order_by:
            if isinstance(oi.expr, A.ENum):
                pos = int(oi.expr.text)
                c = out_cols[pos - 1]
            elif isinstance(oi.expr, A.EName) and oi.expr.name.lower() in by_alias:
                c = by_alias[oi.expr.name.lower()]
            else:
                raise UnsupportedError("UNION ORDER BY must use output columns")
            items.append((c.ref(), oi.desc))
        plan = LSort(schema=plan.schema, children=[plan], items=items)
    if stmt.limit is not None:
        plan = LLimit(schema=plan.schema, children=[plan], count=stmt.limit, offset=stmt.offset or 0)
    return plan
