"""Expression evaluation (ref: expression/ — Expression, ScalarFunction,
VecEvalInt/Real/... and VectorizedFilter).

The reference hand-writes vectorized Go loops per (function, type) pair —
100k+ lines, much generated. On TPU all of that collapses: a scalar
expression tree compiles to a composition of jnp ops over whole columns,
and XLA fuses the lot into the surrounding kernel. The vectorized-eval
framework is therefore ~three small modules:

  expr.py      -- the typed expression IR the planner produces
  compiler.py  -- IR -> pure (Chunk -> Column) function, null-aware
  dates.py     -- civil calendar decomposition in integer jnp ops

Null semantics: every compiled node yields (data, valid); strict functions
AND validity, AND/OR implement Kleene three-valued logic, and a WHERE mask
is `data & valid` (NULL rows never match).

String semantics: by the time IR reaches the compiler, the planner has
rewritten string predicates into integer-code operations (sorted-dict
ranges, equality on codes, LUT gathers for LIKE/functions) — the compiler
never sees a raw string.
"""

from tidb_tpu.expression.expr import (
    Expr,
    ColumnRef,
    Literal,
    Call,
    Case,
    Cast,
    Lookup,
    InList,
    AggRef,
)
from tidb_tpu.expression.compiler import compile_expr, compile_predicate

__all__ = [
    "Expr",
    "ColumnRef",
    "Literal",
    "Call",
    "Case",
    "Cast",
    "Lookup",
    "InList",
    "AggRef",
    "compile_expr",
    "compile_predicate",
]
