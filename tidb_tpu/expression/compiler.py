"""Compile the typed expression IR to pure jnp functions over chunks.

Each node evaluates to (data, valid) — dense arrays of the chunk capacity.
`data` is unspecified where ~valid; consumers must never branch on invalid
lanes (WHERE masks are `data & valid`). Everything composes into whatever
jitted fragment calls it, and XLA fuses the arithmetic into neighboring
kernels — this is the whole of the reference's generated VecEval* layer.

Decimal discipline: the IR carries scales in types; the compiler inserts
power-of-ten rescales so that
    add/sub  operate at the result scale,
    mul      naturally lands on scale_a + scale_b == result scale,
    div      leaves fixed point and produces float64 (MySQL widens scale
             instead; we document the deviation — exactness is kept for
             +,-,* which is what aggregation pipelines need).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.errors import PlanError
from tidb_tpu.expression import dates
from tidb_tpu.expression.expr import (
    AggRef,
    Call,
    Case,
    Cast,
    ColumnRef,
    Expr,
    InList,
    Literal,
    Lookup,
)
from tidb_tpu.chunk.column import Column
from tidb_tpu.types import SQLType, TypeKind

__all__ = ["compile_expr", "compile_predicate", "eval_expr"]

Pair = Tuple[jax.Array, jax.Array]  # (data, valid)


def _rescale(data: jax.Array, from_scale: int, to_scale: int) -> jax.Array:
    if from_scale == to_scale:
        return data
    if to_scale > from_scale:
        return data * (10 ** (to_scale - from_scale))
    # scale-down rounds half away from zero like MySQL
    f = 10 ** (from_scale - to_scale)
    return jnp.where(data >= 0, (data + f // 2) // f, -((-data + f // 2) // f))


def _to_kind(data: jax.Array, frm: SQLType, to: SQLType) -> jax.Array:
    """Numeric representation change frm -> to (validity unchanged)."""
    if frm.kind == to.kind:
        if frm.kind == TypeKind.DECIMAL:
            return _rescale(data, frm.scale, to.scale)
        return data.astype(to.np_dtype)
    k_from, k_to = frm.kind, to.kind
    if k_to == TypeKind.FLOAT:
        if k_from == TypeKind.DECIMAL:
            return data.astype(jnp.float64) / (10**frm.scale)
        return data.astype(jnp.float64)
    if k_to == TypeKind.DECIMAL:
        if k_from == TypeKind.FLOAT:
            scaled = data * (10**to.scale)
            return jnp.where(scaled >= 0, scaled + 0.5, scaled - 0.5).astype(jnp.int64)
        return data.astype(jnp.int64) * (10**to.scale)
    if k_to == TypeKind.INT:
        if k_from == TypeKind.DECIMAL:
            return _rescale(data, frm.scale, 0)
        if k_from == TypeKind.FLOAT:
            return jnp.where(data >= 0, data + 0.5, data - 0.5).astype(jnp.int64)
        return data.astype(jnp.int64)
    if k_to == TypeKind.BOOL:
        return data != 0
    if k_to == TypeKind.DATETIME and k_from == TypeKind.DATE:
        return data.astype(jnp.int64) * 86_400_000_000
    if k_to == TypeKind.DATE and k_from == TypeKind.DATETIME:
        return jnp.floor_divide(data, 86_400_000_000).astype(jnp.int32)
    raise PlanError(f"unsupported cast {frm} -> {to}")


def _days(data: jax.Array, t: SQLType) -> jax.Array:
    """Temporal value -> days-since-epoch."""
    if t.kind == TypeKind.DATETIME:
        return jnp.floor_divide(data, 86_400_000_000)
    return data.astype(jnp.int64)


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


def eval_expr(e: Expr, chunk) -> Pair:
    """Evaluate IR node `e` over `chunk` -> (data, valid). Pure; call under
    jit."""
    cap = chunk.capacity

    if isinstance(e, ColumnRef):
        col = chunk.columns[e.name]
        return col.data, col.valid

    if isinstance(e, AggRef):
        col = chunk.columns[e.name]
        return col.data, col.valid

    if isinstance(e, Literal):
        if e.value is None:
            return (
                jnp.zeros(cap, dtype=e.type_.np_dtype),
                jnp.zeros(cap, dtype=jnp.bool_),
            )
        return (
            jnp.full(cap, e.value, dtype=e.type_.np_dtype),
            jnp.ones(cap, dtype=jnp.bool_),
        )

    if isinstance(e, Cast):
        data, valid = eval_expr(e.arg, chunk)
        return _to_kind(data, e.arg.type_, e.type_), valid

    if isinstance(e, Lookup):
        data, valid = eval_expr(e.arg, chunk)
        table = jnp.asarray(np.asarray(e.table, dtype=e.type_.np_dtype))
        idx = jnp.clip(data.astype(jnp.int32), 0, len(e.table) - 1)
        out = jnp.take(table, idx)
        if e.table_valid is not None:
            tv = jnp.asarray(np.asarray(e.table_valid, dtype=np.bool_))
            valid = valid & jnp.take(tv, idx)
        # codes outside the table (e.g. -1 absent sentinel) are invalid
        valid = valid & (data >= 0) & (data < len(e.table))
        return out, valid

    if isinstance(e, InList):
        data, valid = eval_expr(e.arg, chunk)
        vals = np.asarray(e.values, dtype=e.arg.type_.np_dtype)
        hit = jnp.zeros(cap, dtype=jnp.bool_)
        for v in vals:  # static unroll; planner uses Lookup for long lists
            hit = hit | (data == v)
        return (~hit if e.negated else hit), valid

    if isinstance(e, Case):
        if e.else_ is not None:
            out, ov = eval_expr(e.else_, chunk)
            out = _to_kind(out, e.else_.type_, e.type_)
        else:
            out = jnp.zeros(cap, dtype=e.type_.np_dtype)
            ov = jnp.zeros(cap, dtype=jnp.bool_)
        taken = jnp.zeros(cap, dtype=jnp.bool_)
        for cond, res in e.whens:
            cd, cv = eval_expr(cond, chunk)
            rd, rv = eval_expr(res, chunk)
            rd = _to_kind(rd, res.type_, e.type_)
            fire = cd & cv & ~taken
            out = jnp.where(fire, rd, out)
            ov = jnp.where(fire, rv, ov)
            taken = taken | fire
        return out, ov

    if isinstance(e, Call):
        fn = FUNCS.get(e.op)
        if fn is None:
            raise PlanError(f"unknown scalar function {e.op!r}")
        return fn(e, chunk)

    raise PlanError(f"cannot evaluate node {type(e).__name__}")


def compile_expr(e: Expr) -> Callable:
    """IR -> (chunk -> Column)."""

    def run(chunk) -> Column:
        data, valid = eval_expr(e, chunk)
        return Column(data, valid, e.type_)

    return run


def compile_predicate(e: Expr) -> Callable:
    """IR -> (chunk -> bool mask); NULL predicate rows are excluded."""

    def run(chunk) -> jax.Array:
        data, valid = eval_expr(e, chunk)
        return data & valid
    return run


# ---------------------------------------------------------------------------
# scalar function registry
# ---------------------------------------------------------------------------


def _strict2(op):
    """Binary strict function: valid = va & vb."""

    def fn(e: Call, chunk) -> Pair:
        a, b = e.args
        (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
        if e.type_.kind == TypeKind.DECIMAL:
            da = _rescale(da, a.type_.scale, e.type_.scale) if e.op in ("add", "sub") else da
            db = _rescale(db, b.type_.scale, e.type_.scale) if e.op in ("add", "sub") else db
        elif e.type_.kind == TypeKind.FLOAT:
            da = _to_kind(da, a.type_, e.type_)
            db = _to_kind(db, b.type_, e.type_)
        return op(da, db), va & vb

    return fn


def _cmp(op):
    """Comparison: builder guarantees comparable kinds; align decimal scales."""

    def fn(e: Call, chunk) -> Pair:
        a, b = e.args
        (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
        if a.type_.kind == TypeKind.DECIMAL or b.type_.kind == TypeKind.DECIMAL:
            s = max(a.type_.scale, b.type_.scale)
            da = _rescale(da, a.type_.scale, s) if a.type_.kind == TypeKind.DECIMAL else da * 10**s
            db = _rescale(db, b.type_.scale, s) if b.type_.kind == TypeKind.DECIMAL else db * 10**s
        return op(da, db), va & vb

    return fn


def _nseq(e: Call, chunk) -> Pair:
    """Null-safe equal <=> : NULL<=>NULL is TRUE, never returns NULL."""
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    if a.type_.kind == TypeKind.DECIMAL or b.type_.kind == TypeKind.DECIMAL:
        s = max(a.type_.scale, b.type_.scale)
        da = _rescale(da, a.type_.scale, s) if a.type_.kind == TypeKind.DECIMAL else da * 10**s
        db = _rescale(db, b.type_.scale, s) if b.type_.kind == TypeKind.DECIMAL else db * 10**s
    both_null = ~va & ~vb
    eq = va & vb & (da == db)
    return both_null | eq, jnp.ones_like(va)


def _truncate(e: Call, chunk) -> Pair:
    """TRUNCATE(x, d): toward zero, unlike ROUND."""
    a = e.args[0]
    nd = 0
    if len(e.args) > 1:
        lit = e.args[1]
        if not isinstance(lit, Literal):
            raise PlanError("TRUNCATE digits must be a constant")
        nd = int(lit.value)
    d, v = eval_expr(a, chunk)
    if a.type_.kind == TypeKind.DECIMAL:
        f = 10 ** max(a.type_.scale - nd, 0)
        out = jax.lax.div(d, jnp.int64(f)) * f if f > 1 else d
        return _rescale(out, a.type_.scale, e.type_.scale), v
    f = 10.0**nd
    return jnp.trunc(d.astype(jnp.float64) * f) / f, v


def _and(e: Call, chunk) -> Pair:
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    ta, tb = da & va, db & vb  # definitely-true lanes
    fa, fb = ~da & va, ~db & vb  # definitely-false lanes
    return ta & tb, (va & vb) | fa | fb


def _or(e: Call, chunk) -> Pair:
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    ta, tb = da & va, db & vb
    return ta | tb, (va & vb) | ta | tb


def _not(e: Call, chunk) -> Pair:
    d, v = eval_expr(e.args[0], chunk)
    return ~d, v


def _is_null(e: Call, chunk) -> Pair:
    _, v = eval_expr(e.args[0], chunk)
    return ~v, jnp.ones_like(v)


def _is_not_null(e: Call, chunk) -> Pair:
    _, v = eval_expr(e.args[0], chunk)
    return v, jnp.ones_like(v)


def _div(e: Call, chunk) -> Pair:
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    da = _to_kind(da, a.type_, e.type_)
    db = _to_kind(db, b.type_, e.type_)
    zero = db == 0
    safe = jnp.where(zero, 1, db)
    return da / safe, va & vb & ~zero  # x/0 -> NULL (MySQL)


def _intdiv(e: Call, chunk) -> Pair:
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    zero = db == 0
    safe = jnp.where(zero, 1, db)
    q = jnp.trunc(da.astype(jnp.float64) / safe.astype(jnp.float64)) if e.type_.kind == TypeKind.FLOAT else jax.lax.div(da.astype(jnp.int64), safe.astype(jnp.int64))
    return q, va & vb & ~zero


def _mod(e: Call, chunk) -> Pair:
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    # align operands on the result representation (decimal scale / float)
    da = _to_kind(da, a.type_, e.type_)
    db = _to_kind(db, b.type_, e.type_)
    zero = db == 0
    safe = jnp.where(zero, 1, db)
    # MySQL MOD takes the sign of the dividend (C semantics), not python's
    if e.type_.kind == TypeKind.FLOAT:
        r = da - jnp.trunc(da / safe) * safe
    else:
        r = da - jax.lax.div(da, safe) * safe
    return r, va & vb & ~zero


def _neg(e: Call, chunk) -> Pair:
    d, v = eval_expr(e.args[0], chunk)
    return -d, v


def _strict1(op, cast_float=False):
    def fn(e: Call, chunk) -> Pair:
        a = e.args[0]
        d, v = eval_expr(a, chunk)
        if cast_float:
            d = _to_kind(d, a.type_, e.type_)
        return op(d), v

    return fn


def _coalesce(e: Call, chunk) -> Pair:
    out = None
    for a in e.args:
        d, v = eval_expr(a, chunk)
        d = _to_kind(d, a.type_, e.type_)
        if out is None:
            out, ov = d, v
        else:
            out = jnp.where(ov, out, d)
            ov = ov | v
    return out, ov


def _if(e: Call, chunk) -> Pair:
    c, t, f = e.args
    cd, cv = eval_expr(c, chunk)
    (td, tv), (fd, fv) = eval_expr(t, chunk), eval_expr(f, chunk)
    td = _to_kind(td, t.type_, e.type_)
    fd = _to_kind(fd, f.type_, e.type_)
    cond = cd & cv
    return jnp.where(cond, td, fd), jnp.where(cond, tv, fv)


def _ifnull(e: Call, chunk) -> Pair:
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    da = _to_kind(da, a.type_, e.type_)
    db = _to_kind(db, b.type_, e.type_)
    return jnp.where(va, da, db), va | vb


def _nullif(e: Call, chunk) -> Pair:
    a, b = e.args
    (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
    eq = (da == db) & va & vb
    return da, va & ~eq


def _temporal_extract(which):
    def fn(e: Call, chunk) -> Pair:
        a = e.args[0]
        d, v = eval_expr(a, chunk)
        days = _days(d, a.type_)
        y, m, dd = dates.civil_from_days(days)
        if which == "quarter":
            out = (m - 1) // 3 + 1
        elif which == "dayofweek":
            # 1970-01-01 was a Thursday; MySQL: 1=Sunday .. 7=Saturday
            out = (days + 4) % 7 + 1
        elif which == "weekday":
            # MySQL WEEKDAY(): 0=Monday .. 6=Sunday
            out = (days + 3) % 7
        elif which == "dayofyear":
            out = days - dates.days_from_civil(y, jnp.ones_like(m), jnp.ones_like(dd)) + 1
        else:
            out = {"year": y, "month": m, "day": dd}[which]
        return out.astype(jnp.int64), v

    return fn


def _time_extract(which):
    """HOUR/MINUTE/SECOND/MICROSECOND over DATETIME micros (0 for DATE)."""

    def fn(e: Call, chunk) -> Pair:
        a = e.args[0]
        d, v = eval_expr(a, chunk)
        if a.type_.kind == TypeKind.TIME:
            # durations: HOUR('-120:30:00') = 120 (magnitude, unbounded)
            mag = jnp.abs(d.astype(jnp.int64))
            div, mod_ = {
                "hour": (3_600_000_000, None),
                "minute": (60_000_000, 60),
                "second": (1_000_000, 60),
                "microsecond": (1, 1_000_000),
            }[which]
            out = jnp.floor_divide(mag, div)
            return (out if mod_ is None else out % mod_), v
        if a.type_.kind != TypeKind.DATETIME:
            return jnp.zeros_like(d, dtype=jnp.int64), v
        micros = d.astype(jnp.int64)
        div, mod_ = {
            "hour": (3_600_000_000, 24),
            "minute": (60_000_000, 60),
            "second": (1_000_000, 60),
            "microsecond": (1, 1_000_000),
        }[which]
        out = jnp.floor_divide(micros, div) % mod_
        return out, v

    return fn


def _week(e: Call, chunk) -> Pair:
    """WEEK(d) mode 0 (MySQL default): Sunday-first; week 1 starts at the
    first Sunday of the year, earlier days are week 0."""
    a = e.args[0]
    d, v = eval_expr(a, chunk)
    days = _days(d, a.type_)
    y, _, _ = dates.civil_from_days(days)
    jan1 = dates.days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    dow_jan1 = (jan1 + 4) % 7  # 0=Sunday
    first_sunday = jan1 + (7 - dow_jan1) % 7
    wk = jnp.where(days < first_sunday, 0, (days - first_sunday) // 7 + 1)
    return wk.astype(jnp.int64), v


def _iso_week(e: Call, chunk) -> Pair:
    """WEEKOFYEAR(d) = ISO-8601 week number (MySQL WEEK(d, 3)): Monday
    first, week 1 contains Jan 4. Handles the year-boundary weeks."""
    a = e.args[0]
    d, v = eval_expr(a, chunk)
    days = _days(d, a.type_)
    y, _, _ = dates.civil_from_days(days)

    def week1_monday(year):
        jan4 = dates.days_from_civil(year, jnp.full_like(year, 1),
                                     jnp.full_like(year, 4))
        return jan4 - (jan4 + 3) % 7  # Monday on/before Jan 4

    w_this, w_next, w_prev = week1_monday(y), week1_monday(y + 1), week1_monday(y - 1)
    wk = jnp.where(
        days >= w_next, 1,
        jnp.where(days < w_this,
                  (days - w_prev) // 7 + 1,
                  (days - w_this) // 7 + 1))
    return wk.astype(jnp.int64), v


_DAYS_0000 = 719_528  # days from year 0 ("0000-01-01") to 1970-01-01


def _to_days(e: Call, chunk) -> Pair:
    a = e.args[0]
    d, v = eval_expr(a, chunk)
    return _days(d, a.type_) + _DAYS_0000, v


def _from_days(e: Call, chunk) -> Pair:
    d, v = eval_expr(e.args[0], chunk)
    return (d.astype(jnp.int64) - _DAYS_0000).astype(jnp.int32), v


def _last_day(e: Call, chunk) -> Pair:
    """LAST_DAY(d): the final day of d's month, as a DATE."""
    a = e.args[0]
    d, v = eval_expr(a, chunk)
    days = _days(d, a.type_)
    y, m, _ = dates.civil_from_days(days)
    one = jnp.ones_like(m)
    next_start = dates.days_from_civil(
        jnp.where(m == 12, y + 1, y), jnp.where(m == 12, one, m + 1), one)
    return (next_start - 1).astype(jnp.int32), v


def _unix_timestamp(e: Call, chunk) -> Pair:
    a = e.args[0]
    d, v = eval_expr(a, chunk)
    if a.type_.kind == TypeKind.DATE:
        return d.astype(jnp.int64) * 86_400, v
    return jnp.floor_divide(d.astype(jnp.int64), 1_000_000), v


def _from_unixtime(e: Call, chunk) -> Pair:
    d, v = eval_expr(e.args[0], chunk)
    return d.astype(jnp.int64) * 1_000_000, v


def _tsdiff_months(e: Call, chunk) -> Pair:
    """TIMESTAMPDIFF(MONTH, a, b): whole months from a to b, boundary-
    aware the MySQL way — the raw (y,m) delta, minus one when b's
    (day, time-of-day) hasn't reached a's yet (symmetrically for
    negative spans)."""
    a, b = e.args

    def decompose(x):
        d, v = eval_expr(x, chunk)
        if x.type_.kind == TypeKind.DATETIME:
            micros = d.astype(jnp.int64)
            days = jnp.floor_divide(micros, 86_400_000_000)
            tod = micros - days * 86_400_000_000
        else:
            days = d.astype(jnp.int64)
            tod = jnp.zeros_like(days)
        y, m, dd = dates.civil_from_days(days)
        return y, m, dd, tod, v

    ya, ma, da, ta, va = decompose(a)
    yb, mb, db, tb, vb = decompose(b)
    months = (yb - ya) * 12 + (mb - ma)
    # fractional-month adjustment toward zero
    frac_b = db * 86_400_000_000 + tb
    frac_a = da * 86_400_000_000 + ta
    months = jnp.where((months > 0) & (frac_b < frac_a), months - 1, months)
    months = jnp.where((months < 0) & (frac_b > frac_a), months + 1, months)
    return months.astype(jnp.int64), va & vb


def _time_to_sec(e: Call, chunk) -> Pair:
    a = e.args[0]
    d, v = eval_expr(a, chunk)
    micros = d.astype(jnp.int64)
    if a.type_.kind == TypeKind.DATETIME:
        # seconds OF DAY, not epoch seconds
        micros = micros % 86_400_000_000
    # truncate toward zero (MySQL drops fractional seconds)
    q = jnp.where(micros >= 0, micros // 1_000_000,
                  -((-micros) // 1_000_000))
    return q, v


# MySQL TIME range: +-838:59:59
_TIME_MAX_SECS = 838 * 3600 + 59 * 60 + 59


def _sec_to_time(e: Call, chunk) -> Pair:
    d, v = eval_expr(e.args[0], chunk)
    secs = jnp.clip(d.astype(jnp.int64), -_TIME_MAX_SECS, _TIME_MAX_SECS)
    return secs * 1_000_000, v


def _makedate(e: Call, chunk) -> Pair:
    """MAKEDATE(year, dayofyear): day 0 or negative -> NULL (MySQL)."""
    y, vy = eval_expr(e.args[0], chunk)
    dn, vd = eval_expr(e.args[1], chunk)
    y = y.astype(jnp.int64)
    dn = dn.astype(jnp.int64)
    jan1 = dates.days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    out = (jan1 + dn - 1).astype(jnp.int32)
    return out, vy & vd & (dn >= 1)


def _addtime(sign: int):
    def fn(e: Call, chunk) -> Pair:
        a, b = e.args
        (da, va), (db, vb) = eval_expr(a, chunk), eval_expr(b, chunk)
        out = da.astype(jnp.int64) + sign * db.astype(jnp.int64)
        return out, va & vb

    return fn


def _add_months(e: Call, chunk) -> Pair:
    """date/datetime + N months with end-of-month clamping (the device
    path for +/- INTERVAL MONTH/QUARTER/YEAR on column dates)."""
    a, n_lit = e.args
    d, v = eval_expr(a, chunk)
    n = jnp.int64(int(n_lit.value))
    if a.type_.kind == TypeKind.DATETIME:
        micros = d.astype(jnp.int64)
        days = jnp.floor_divide(micros, 86_400_000_000)
        tod = micros - days * 86_400_000_000
    else:
        days = d.astype(jnp.int64)
        tod = None
    y, m, dd = dates.civil_from_days(days)
    total = y * 12 + (m - 1) + n
    ny = jnp.floor_divide(total, 12)
    nm = total - ny * 12 + 1
    month_start = dates.days_from_civil(ny, nm, jnp.ones_like(dd))
    next_start = dates.days_from_civil(
        jnp.where(nm == 12, ny + 1, ny), jnp.where(nm == 12, 1, nm + 1), jnp.ones_like(dd))
    dd = jnp.minimum(dd, next_start - month_start)
    out_days = month_start + dd - 1
    if tod is not None:
        return out_days * 86_400_000_000 + tod, v
    return out_days, v


def _nary_extreme(pick):
    """GREATEST/LEAST: strict (NULL if any arg NULL), over the common
    type the binder computed for the Call."""

    def fn(e: Call, chunk) -> Pair:
        rt = e.type_
        acc_d = acc_v = None
        for a in e.args:
            d, v = eval_expr(a, chunk)
            if rt.kind == TypeKind.DECIMAL and a.type_.kind == TypeKind.DECIMAL:
                d = _rescale(d, a.type_.scale, rt.scale)
            elif rt.kind == TypeKind.DECIMAL:
                d = d.astype(jnp.int64) * 10**rt.scale
            elif rt.kind == TypeKind.FLOAT:
                d = _to_kind(d, a.type_, rt)
            if acc_d is None:
                acc_d, acc_v = d, v
            else:
                acc_d, acc_v = pick(acc_d, d), acc_v & v
        return acc_d, acc_v

    return fn


def _ushift(op):
    """MySQL shifts are on BIGINT UNSIGNED: logical (zero-fill) via the
    uint64 bit pattern, and counts >= 64 are defined to give 0 (XLA
    leaves oversize shifts undefined)."""
    def f(a, b):
        ua = jax.lax.bitcast_convert_type(a.astype(jnp.int64), jnp.uint64)
        bi = b.astype(jnp.int64)
        # the count is BIGINT UNSIGNED too: a negative count wraps to
        # >= 2^63, which is >= 64 -> zero
        cnt = jnp.where(bi < 0, jnp.int64(64), jnp.clip(bi, 0, 64))
        out = op(ua, jnp.minimum(cnt, 63).astype(jnp.uint64))
        out = jnp.where(cnt >= 64, jnp.uint64(0), out)
        return jax.lax.bitcast_convert_type(out, jnp.int64)

    return f


def _ubitnot(a):
    return jax.lax.bitcast_convert_type(
        ~jax.lax.bitcast_convert_type(a.astype(jnp.int64), jnp.uint64),
        jnp.int64)


def _sign(e: Call, chunk) -> Pair:
    d, v = eval_expr(e.args[0], chunk)
    return jnp.sign(d).astype(jnp.int64), v


def _round(e: Call, chunk) -> Pair:
    a = e.args[0]
    nd = 0
    if len(e.args) > 1:
        lit = e.args[1]
        if not isinstance(lit, Literal):
            raise PlanError("ROUND digits must be a constant")
        nd = int(lit.value)
    d, v = eval_expr(a, chunk)
    if a.type_.kind == TypeKind.DECIMAL:
        out = _rescale(d, a.type_.scale, nd)
        out = _rescale(out, nd, e.type_.scale)
        return out, v
    f = 10.0**nd
    scaled = d.astype(jnp.float64) * f
    return jnp.where(scaled >= 0, jnp.floor(scaled + 0.5), jnp.ceil(scaled - 0.5)) / f, v


FUNCS = {
    "add": _strict2(jnp.add),
    "sub": _strict2(jnp.subtract),
    "mul": _strict2(jnp.multiply),
    "div": _div,
    "intdiv": _intdiv,
    "mod": _mod,
    "neg": _neg,
    "eq": _cmp(lambda a, b: a == b),
    "ne": _cmp(lambda a, b: a != b),
    "lt": _cmp(lambda a, b: a < b),
    "le": _cmp(lambda a, b: a <= b),
    "gt": _cmp(lambda a, b: a > b),
    "ge": _cmp(lambda a, b: a >= b),
    "and": _and,
    "or": _or,
    "not": _not,
    "nseq": _nseq,
    "is_null": _is_null,
    "is_not_null": _is_not_null,
    "truncate": _truncate,
    "coalesce": _coalesce,
    "if": _if,
    "ifnull": _ifnull,
    "nullif": _nullif,
    "abs": _strict1(jnp.abs),
    "ceil": _strict1(jnp.ceil, cast_float=True),
    "floor": _strict1(jnp.floor, cast_float=True),
    "sqrt": _strict1(jnp.sqrt, cast_float=True),
    "exp": _strict1(jnp.exp, cast_float=True),
    "ln": _strict1(jnp.log, cast_float=True),
    "log2": _strict1(jnp.log2, cast_float=True),
    "log10": _strict1(jnp.log10, cast_float=True),
    "sin": _strict1(jnp.sin, cast_float=True),
    "cos": _strict1(jnp.cos, cast_float=True),
    "pow": _strict2(jnp.power),
    "round": _round,
    "year": _temporal_extract("year"),
    "month": _temporal_extract("month"),
    "day": _temporal_extract("day"),
    "quarter": _temporal_extract("quarter"),
    "dayofweek": _temporal_extract("dayofweek"),
    "weekday": _temporal_extract("weekday"),
    "dayofyear": _temporal_extract("dayofyear"),
    "hour": _time_extract("hour"),
    "minute": _time_extract("minute"),
    "second": _time_extract("second"),
    "microsecond": _time_extract("microsecond"),
    "add_months": _add_months,
    "greatest": _nary_extreme(jnp.maximum),
    "least": _nary_extreme(jnp.minimum),
    "sign": _sign,
    "tan": _strict1(jnp.tan, cast_float=True),
    "atan": _strict1(jnp.arctan, cast_float=True),
    "asin": _strict1(jnp.arcsin, cast_float=True),
    "acos": _strict1(jnp.arccos, cast_float=True),
    "atan2": _strict2(jnp.arctan2),
    "radians": _strict1(jnp.radians, cast_float=True),
    "degrees": _strict1(jnp.degrees, cast_float=True),
    # MySQL bit ops are BIGINT UNSIGNED: ~ and >> operate on the uint64
    # bit pattern (logical shift, not arithmetic), and shift counts >= 64
    # are defined to produce 0 (XLA leaves them undefined)
    "week": _week,
    "weekofyear": _iso_week,
    "to_days": _to_days,
    "from_days": _from_days,
    "last_day": _last_day,
    "unix_timestamp": _unix_timestamp,
    "from_unixtime": _from_unixtime,
    "tsdiff_months": _tsdiff_months,
    "time_to_sec": _time_to_sec,
    "sec_to_time": _sec_to_time,
    "makedate": _makedate,
    "addtime": _addtime(1),
    "subtime": _addtime(-1),
    "cot": _strict1(lambda x: 1.0 / jnp.tan(x), cast_float=True),
    "sinh": _strict1(jnp.sinh, cast_float=True),
    "cosh": _strict1(jnp.cosh, cast_float=True),
    "tanh": _strict1(jnp.tanh, cast_float=True),
    "bitand": _strict2(jnp.bitwise_and),
    "bitor": _strict2(jnp.bitwise_or),
    "bitxor": _strict2(jnp.bitwise_xor),
    "shl": _strict2(_ushift(jnp.left_shift)),
    "shr": _strict2(_ushift(jnp.right_shift)),
    "bitnot": _strict1(_ubitnot),
}
