"""Calendar decomposition as branch-free integer jnp ops.

Days-since-epoch -> (year, month, day) using the civil-from-days algorithm
(era/400-year-cycle arithmetic), fully vectorized — this is how YEAR()/
MONTH()/DAY()/EXTRACT run on device without any host round-trip.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["civil_from_days", "days_from_civil", "year_of", "month_of", "day_of"]


def civil_from_days(z):
    """z: int array of days since 1970-01-01 -> (y, m, d) int arrays."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097  # [0, 146096]
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def days_from_civil(y, m, d):
    """(y, m, d) int arrays -> days since 1970-01-01."""
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def year_of(days):
    return civil_from_days(days)[0]


def month_of(days):
    return civil_from_days(days)[1]


def day_of(days):
    return civil_from_days(days)[2]
