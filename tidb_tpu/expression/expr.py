"""Typed expression IR.

Produced by the planner's expression builder (name resolution + type
inference + string-predicate rewriting already done); consumed by
expression.compiler. Everything here is static/trace-time data — literals
hold *device representations* (scaled ints for decimals, day counts for
dates); raw python strings never appear (the builder rewrites them to
dictionary codes or LUTs).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

from tidb_tpu.types import BOOL, SQLType

__all__ = [
    "Expr",
    "ColumnRef",
    "Literal",
    "Call",
    "Case",
    "Cast",
    "Lookup",
    "InList",
    "AggRef",
    "walk",
]


@dataclass(frozen=True)
class Expr:
    """Base node. `type_` is the SQL result type of the node."""

    type_: SQLType


@dataclass(frozen=True)
class ColumnRef(Expr):
    """Reference to a chunk column by its resolved unique name."""

    name: str = ""


@dataclass(frozen=True)
class Literal(Expr):
    """Host scalar constant in device representation; value=None is NULL."""

    value: Any = None


@dataclass(frozen=True)
class Call(Expr):
    """Scalar function application; `op` is a key in compiler.FUNCS."""

    op: str = ""
    args: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class Case(Expr):
    """CASE WHEN c1 THEN r1 ... ELSE e END (searched form)."""

    whens: Tuple[Tuple[Expr, Expr], ...] = ()
    else_: Optional[Expr] = None


@dataclass(frozen=True)
class Cast(Expr):
    arg: Expr = None
    # target type is `type_`; for decimals the scale shift is derived from
    # arg.type_.scale vs type_.scale


@dataclass(frozen=True)
class Lookup(Expr):
    """Gather `arg`'s int codes through a host-built lookup table.

    The planner lowers dictionary-dependent string operations (LIKE, LENGTH,
    UPPER comparisons, cross-dictionary translation) to this: O(|dict|) host
    work builds `table`, the device does one gather. table_valid marks
    entries that map to NULL/absent.
    """

    arg: Expr = None
    table: Tuple[float, ...] = ()  # stored as tuple for hashability
    table_valid: Optional[Tuple[bool, ...]] = None

    @staticmethod
    def build(arg: Expr, table: np.ndarray, type_: SQLType, table_valid=None) -> "Lookup":
        return Lookup(
            type_=type_,
            arg=arg,
            table=tuple(table.tolist()),
            table_valid=tuple(table_valid.tolist()) if table_valid is not None else None,
        )


@dataclass(frozen=True)
class InList(Expr):
    """arg IN (v1, v2, ...) over literal device-repr values."""

    arg: Expr = None
    values: Tuple[Any, ...] = ()
    negated: bool = False


@dataclass(frozen=True)
class AggRef(Expr):
    """Reference to a computed aggregate output column (post-agg exprs like
    HAVING sum(x) > 1 or SELECT sum(a)/sum(b) refer to agg slots by name)."""

    name: str = ""


def walk(e: Expr):
    """Yield every node in the tree (pre-order)."""
    yield e
    if isinstance(e, Call):
        for a in e.args:
            yield from walk(a)
    elif isinstance(e, Case):
        for c, r in e.whens:
            yield from walk(c)
            yield from walk(r)
        if e.else_ is not None:
            yield from walk(e.else_)
    elif isinstance(e, (Cast, Lookup, InList)):
        yield from walk(e.arg)
