"""Shard placement policy: where every row of a SHARD BY table lives.

The map is pure metadata (``storage/table.py``'s ``ShardByInfo``
persists it; this module is the math): a row's shard comes from its
shard-key value, a shard's owner comes from round-robin over the worker
fleet, and both sides of every exchange — the coordinator routing
loads/DML, and the workers partitioning shuffle sends — MUST agree on
the same functions, so they all live here.

Hash placement uses the same 64-bit odd-multiplier mix as the fragment
tier's all_to_all repartition (``parallel/distsql._hash_dest``): a
hash-placed table whose shard column IS the join key and whose shard
count is a multiple of the worker count is therefore CO-LOCATED with a
hash shuffle's destinations — ``(mix(k) % (m*W)) % W == mix(k) % W`` —
and the planner skips its exchange entirely.

NULL shard keys land in shard 0 (MySQL's NULL-partition convention);
they are placed, scanned, and joined like any other value — a NULL key
simply never matches in a join, which the local executors already
handle."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ShardMap", "shard_of_array", "shard_of_value",
           "worker_of_shard", "owners_by_worker", "with_n_workers",
           "shards_to_move"]

# keep in sync with parallel/distsql._HASH_MULT — co-location between a
# hash placement and a hash shuffle depends on the identical mix
_HASH_MULT = np.int64(-7046029254386353131)  # 0x9E3779B97F4A7C15 as int64


@dataclass(frozen=True)
class ShardMap:
    """Immutable snapshot of one table's placement: the ShardByInfo
    fields plus the worker-fleet width it was resolved against. Frozen
    so a statement that captured a map mid-reshard keeps routing
    consistently until it finishes; `version` tells stale from fresh."""

    kind: str                       # "hash" | "range"
    column: str
    shards: int
    n_workers: int
    bounds: Tuple[int, ...] = ()    # range only: ascending uppers
    version: int = 0

    @classmethod
    def from_info(cls, info, n_workers: int) -> "ShardMap":
        return cls(kind=info.kind, column=info.column, shards=info.shards,
                   n_workers=n_workers, bounds=tuple(info.bounds),
                   version=info.version)

    def to_wire(self) -> Dict:
        """DCN-codec-serializable form: scatter RPCs ship the map so
        both ends of an exchange route with identical arithmetic."""
        return {"kind": self.kind, "column": self.column,
                "shards": self.shards, "n_workers": self.n_workers,
                "bounds": list(self.bounds), "version": self.version}

    @classmethod
    def from_wire(cls, w: Dict) -> "ShardMap":
        return cls(kind=w["kind"], column=w["column"],
                   shards=int(w["shards"]), n_workers=int(w["n_workers"]),
                   bounds=tuple(w.get("bounds") or ()),
                   version=int(w.get("version") or 0))

    def shard_of(self, value: Optional[int]) -> int:
        return shard_of_value(self, value)

    def worker_of(self, shard: int) -> int:
        return worker_of_shard(shard, self.n_workers)

    def owners(self) -> Dict[int, List[int]]:
        return owners_by_worker(self.shards, self.n_workers)

    def colocated_on(self, key_column: str) -> bool:
        """True when a hash shuffle on `key_column` would route every
        row to the worker that already owns it (see module doc)."""
        return (self.kind == "hash" and key_column == self.column
                and self.shards % self.n_workers == 0)


def _mix(values: np.ndarray) -> np.ndarray:
    h = values.astype(np.int64, copy=False) * _HASH_MULT
    return h


def shard_of_array(smap: ShardMap, values: np.ndarray,
                   valid: Optional[np.ndarray] = None) -> np.ndarray:
    """Vectorized row -> shard id. NULL (invalid) rows -> shard 0."""
    values = np.asarray(values)
    if smap.kind == "hash":
        with np.errstate(over="ignore"):
            dest = ((_mix(values) % smap.shards) + smap.shards) % smap.shards
    else:
        bounds = np.asarray(smap.bounds, dtype=np.int64)
        dest = np.searchsorted(bounds, values.astype(np.int64, copy=False),
                               side="right")
    dest = dest.astype(np.int64, copy=False)
    if valid is not None:
        dest = np.where(np.asarray(valid, dtype=bool), dest, 0)
    return dest


def shard_of_value(smap: ShardMap, value: Optional[int]) -> int:
    """Scalar form (shard-key equality pruning on the coordinator)."""
    if value is None:
        return 0
    return int(shard_of_array(smap, np.asarray([value], dtype=np.int64))[0])


def worker_of_shard(shard: int, n_workers: int) -> int:
    """Round-robin shard -> worker assignment. Deterministic and
    fleet-width-pure: every process derives the same owner without a
    placement service round trip."""
    return int(shard) % max(int(n_workers), 1)


def owners_by_worker(shards: int, n_workers: int) -> Dict[int, List[int]]:
    """worker index -> shard ids it owns (workers owning none are
    absent — exactly the set a sharded scan must NOT dispatch to)."""
    out: Dict[int, List[int]] = {}
    for s in range(shards):
        out.setdefault(worker_of_shard(s, n_workers), []).append(s)
    return out


def with_n_workers(smap: ShardMap, n_workers: int) -> ShardMap:
    """Same placement math, re-resolved against a different fleet width
    (membership change): shard ids are untouched — only the round-robin
    shard->worker assignment moves, which keeps the co-location identity
    `(mix(k) % (m*W')) % W' == mix(k) % W'` intact for the NEW W'.
    Bumps `version` so cached plans demote like any other map change."""
    return ShardMap(kind=smap.kind, column=smap.column, shards=smap.shards,
                    n_workers=int(n_workers), bounds=smap.bounds,
                    version=smap.version + 1)


def shards_to_move(old: ShardMap, new: ShardMap) -> Dict[int, List[int]]:
    """Online-reshard work list: NEW-map shard id -> the old-map workers
    whose live rows can contain that shard's keys (the backfill sources).

    When only the fleet width changed (same kind/column/shards/bounds),
    each new shard IS an old shard, so its single source is its old
    owner — and shards whose owner doesn't move are skipped entirely.
    When the shard function itself changed (count, kind, or bounds),
    any old shard can contribute rows to any new shard, so every new
    shard backfills from every old owner."""
    same_fn = (old.kind == new.kind and old.column == new.column
               and old.shards == new.shards and old.bounds == new.bounds)
    out: Dict[int, List[int]] = {}
    old_workers = sorted(old.owners())
    for s in range(new.shards):
        if same_fn:
            src = worker_of_shard(s, old.n_workers)
            if src == worker_of_shard(s, new.n_workers):
                continue  # owner unchanged: nothing moves
            out[s] = [src]
        else:
            out[s] = list(old_workers)
    return out
