"""Sharded table placement + cross-process shuffle (ISSUE 13).

The DCN tier (PRs 4-5) executed every query over fully replicated or
row-range-partitioned data: adding workers added failover paths but no
capacity. This package makes data placement a first-class catalog
concept:

  * ``placement.py`` — the policy layer: hash/range shard maps driven
    by DDL (``SHARD BY HASH(col) SHARDS n``), persisted on
    ``TableSchema.shard_by`` and versioned so plan caches and placement
    snapshots invalidate on resharding; shard -> worker assignment and
    owner-set computation (scans dispatch ONLY to shard owners).
  * ``shuffle.py`` — the cross-process exchange generalizing the
    fragment tier's all_to_all repartition to DCN workers: rows
    partition by key on the sender, per-destination batches travel
    FoR-compressed (the PR 9 encoded staging format), and the receiver
    reassembles them into staged chunks with backpressure charged to a
    MemTracker.

The coordinator half (owner-pruned dispatch, shuffle-join planning,
2PC distributed writes with crash recovery) lives in
``parallel/dcn.py`` — see README "Sharded placement"."""

from tidb_tpu.sharding.placement import (  # noqa: F401
    ShardMap,
    owners_by_worker,
    shard_of_array,
    shard_of_value,
    worker_of_shard,
)
