"""Cross-process shuffle: the data plane of the DCN exchange.

Generalizes the fragment tier's all_to_all repartition
(``parallel/distsql.repartition_by_key``) to workers in separate
processes: the sender partitions its live rows by the join/placement
key with the SAME hash the device exchange uses, encodes each
destination's batch frame-of-reference compressed (the PR 9
``tidb_tpu_stage_encoded`` format — ``columnar.encoding.encode_column``
is the one encoder), and ships it over the DCN codec (numpy arrays are
first-class there). The receiver reassembles batches into staged
chunks through a ``ShuffleInbox`` whose bytes are charged to a
MemTracker — backpressure is a typed OOM on the sender's stage RPC,
never silent growth.

Transport stays in ``parallel/dcn.py``; this module is pure data:
extract -> partition -> encode | decode -> assemble. That split keeps
every socket call OUTSIDE the placement/inbox locks (the
blocking-under-lock pass enforces it — see
tests/analysis_fixtures/bad_shuffle_lock.py for the violation shape).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from tidb_tpu.columnar.encoding import (
    INT_BACKED_KINDS,
    decode_host,
    Encoding,
    encode_column,
)
from tidb_tpu.types import TypeKind

__all__ = ["extract_live_columns", "partition_rows", "encode_batch",
           "decode_batch", "batch_wire_bytes", "ShuffleInbox",
           "assemble_into_table"]


def extract_live_columns(table, columns: Optional[List[str]] = None
                         ) -> Tuple[Dict[str, np.ndarray],
                                    Dict[str, np.ndarray],
                                    Dict[str, list], int]:
    """(arrays, valids, strings, n_live) of a table's LIVE committed
    rows. String columns decode to python lists (their dict codes are
    process-local — codes from one worker mean nothing on another);
    everything else ships in its device repr."""
    names = columns or table.schema.public_names()
    n = table.n
    live = table.live_mask(0, n) if n else np.zeros(0, dtype=bool)
    idx = np.nonzero(live)[0]
    arrays: Dict[str, np.ndarray] = {}
    valids: Dict[str, np.ndarray] = {}
    strings: Dict[str, list] = {}
    for name in names:
        info = table.schema.col(name)
        d = table.data[name][:n][idx]
        v = table.valid[name][:n][idx]
        if info.type_.kind == TypeKind.STRING:
            strings[name] = table.dicts[name].decode(d, v)
        else:
            arrays[name] = d
            valids[name] = np.asarray(v, dtype=bool)
    return arrays, valids, strings, len(idx)


def partition_rows(arrays: Dict[str, np.ndarray],
                   valids: Dict[str, np.ndarray],
                   strings: Dict[str, list],
                   dest: np.ndarray, n_dests: int
                   ) -> List[Optional[Tuple[Dict, Dict, Dict]]]:
    """Split one extracted row set into per-destination row sets.
    ``dest`` is the row -> destination vector (from
    ``placement.shard_of_array`` composed with ``worker_of_shard``, or
    a broadcast constant). Destinations with no rows get None."""
    out: List[Optional[Tuple[Dict, Dict, Dict]]] = [None] * n_dests
    for w in range(n_dests):
        idx = np.nonzero(dest == w)[0]
        if len(idx) == 0:
            continue
        a = {k: v[idx] for k, v in arrays.items()}
        va = {k: v[idx] for k, v in valids.items()}
        st = {k: [v[i] for i in idx] for k, v in strings.items()}
        out[w] = (a, va, st)
    return out


def encode_batch(types: Dict[str, object], arrays: Dict[str, np.ndarray],
                 valids: Dict[str, np.ndarray],
                 strings: Dict[str, list]) -> Dict:
    """One destination's rows -> codec-serializable wire batch. Integer
    device reprs travel FoR-encoded in the narrowest dtype that covers
    their range (same selection rule as segment/staging encoding); the
    decode is ``stored + ref`` on the receiver."""
    cols: Dict[str, Dict] = {}
    n = 0
    for name, d in arrays.items():
        v = valids[name]
        n = len(d)
        t = types[name]
        if t.kind in INT_BACKED_KINDS and np.issubdtype(d.dtype, np.integer):
            enc, stored = encode_column(d, v, t)
            cols[name] = {"d": stored, "v": v, "ref": int(enc.ref),
                          "enc": enc.kind, "dt": enc.dtype}
        else:
            cols[name] = {"d": np.ascontiguousarray(d), "v": v,
                          "ref": 0, "enc": "raw", "dt": str(d.dtype)}
    for name, vals in strings.items():
        n = len(vals)
        cols[name] = {"s": list(vals)}
    return {"n": n, "cols": cols}


def decode_batch(types: Dict[str, object], batch: Dict
                 ) -> Tuple[Dict[str, np.ndarray], Dict[str, np.ndarray],
                            Dict[str, list]]:
    """Wire batch -> (arrays, valids, strings) in full device reprs,
    ready for ``Table.insert_columns``."""
    arrays: Dict[str, np.ndarray] = {}
    valids: Dict[str, np.ndarray] = {}
    strings: Dict[str, list] = {}
    for name, col in batch["cols"].items():
        if "s" in col:
            strings[name] = col["s"]
            continue
        enc = Encoding(col["enc"], col["dt"], col["ref"])
        arrays[name] = decode_host(enc, col["d"], types.get(name))
        valids[name] = np.asarray(col["v"], dtype=bool)
    return arrays, valids, strings


def batch_wire_bytes(batch: Dict) -> int:
    """Approximate payload bytes of a wire batch — the number both the
    SHUFFLE_BYTES_TOTAL metric and the inbox MemTracker charge account
    in, so the observability and the backpressure agree."""
    total = 0
    for col in batch["cols"].values():
        if "s" in col:
            total += sum(len(s) + 1 if s is not None else 1
                         for s in col["s"])
        else:
            total += col["d"].nbytes + col["v"].nbytes
    return total


class ShuffleInbox:
    """Receiver-side staging area: batches arriving from peer workers,
    grouped by (shuffle id, side), charged to a MemTracker as they
    land and released when drained or closed.

    Lock discipline: ``_lock`` is a LEAF — batch bytes are charged to
    the tracker BEFORE the lock is taken (consume re-enters spill past
    the budget, and no socket recv ever happens under it; the
    transport hands fully-received batches in). A typed OOM from the
    tracker travels back to the sender as the stage RPC's error: that
    IS the backpressure.

    Abandoned shuffles (coordinator crashed between scatter and
    gather) reap on a TTL like worker cursors, releasing their
    tracker charge — chaos tests assert zero retained entries."""

    TTL_S = 600.0

    def __init__(self, tracker=None):
        self.tracker = tracker
        self._lock = threading.Lock()
        # shuffle id -> {"ts": last activity, "bytes": charged,
        #               "sides": {side: [batch, ...]}}
        self._entries: Dict[str, Dict] = {}

    def stage(self, shuffle_id: str, side: str, batch: Dict) -> int:
        """Accept one batch; returns its accounted bytes. Charges the
        tracker first (typed OOM propagates to the sender un-staged)."""
        nbytes = batch_wire_bytes(batch)
        if self.tracker is not None:
            try:
                self.tracker.consume(nbytes)
            except BaseException:
                # consume records the charge BEFORE the budget check
                # raises: undo it, or the refused batch's bytes would
                # poison every later stage (undo-and-reraise shape)
                self.tracker.release(nbytes)
                raise
        try:
            with self._lock:
                self._reap_locked()
                ent = self._entries.setdefault(
                    shuffle_id, {"ts": time.time(), "bytes": 0, "sides": {}})
                ent["ts"] = time.time()
                ent["bytes"] += nbytes
                ent["sides"].setdefault(side, []).append(batch)
        except Exception:
            if self.tracker is not None:
                self.tracker.release(nbytes)
            raise
        return nbytes

    def drain(self, shuffle_id: str, side: str) -> List[Dict]:
        """All batches staged for one side; the entry stays (other
        sides may still be pending) until close()."""
        with self._lock:
            ent = self._entries.get(shuffle_id)
            if ent is None:
                return []
            ent["ts"] = time.time()
            return list(ent["sides"].get(side, []))

    def close(self, shuffle_id: str) -> None:
        """Release one shuffle's staged batches and tracker charge.
        Idempotent — the coordinator's finally block and the TTL reaper
        may both reach a dead shuffle."""
        with self._lock:
            ent = self._entries.pop(shuffle_id, None)
        if ent is not None and self.tracker is not None and ent["bytes"]:
            self.tracker.release(ent["bytes"])

    def open_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def staged_bytes(self) -> int:
        with self._lock:
            return sum(e["bytes"] for e in self._entries.values())

    def _reap_locked(self) -> None:
        now = time.time()
        stale = [sid for sid, e in self._entries.items()
                 if now - e["ts"] > self.TTL_S]
        for sid in stale:
            ent = self._entries.pop(sid)
            if self.tracker is not None and ent["bytes"]:
                # release under the lock is fine (pure accounting); the
                # CHARGE is what must stay outside
                self.tracker.release(ent["bytes"])


def assemble_into_table(session, table_name: str, types: Dict[str, object],
                        batches: List[Dict]) -> int:
    """Decode staged batches and bulk-insert them into `table_name` on
    the worker's catalog (the reassembled co-partitioned slice a
    shuffle_gather runs its partial SQL over). Returns rows landed."""
    t = session.catalog.table(session.db, table_name)
    total = 0
    for batch in batches:
        arrays, valids, strings = decode_batch(types, batch)
        if batch["n"] == 0:
            continue
        total += t.insert_columns(arrays, valids, strings=strings)
    return total
