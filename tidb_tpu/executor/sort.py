"""Root-task operators: Sort / TopN / Limit / Union
(ref: executor/sort.go, topn, limit; these sit at the plan root over small
results, so they run host-side — the reference similarly runs root
executors on the SQL node while coprocessors do the heavy scans).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.base import ExecContext, Executor
from tidb_tpu.utils.jitcache import cached_jit
from tidb_tpu.expression.compiler import compile_expr
from tidb_tpu.types import TypeKind

__all__ = ["SortExec", "TopNExec", "LimitExec", "UnionExec"]


class _Materializing(Executor):
    """Shared: drain child to host-compacted runs (spillable under the
    query memory budget — the RowContainer + SpillDiskAction shape)."""

    _runs = None

    def _drain_to_runs(self, sort_items: List[Tuple[object, bool]]):
        from tidb_tpu.utils import dispatch as _dsp
        from tidb_tpu.utils.memory import SpillableRuns

        child = self.children[0]
        uids = [c.uid for c in self.schema]
        key_fns = [compile_expr(e) for e, _ in sort_items]

        def eval_chunk(ch):
            keys = [f(ch) for f in key_fns]
            return keys, ch

        eval_chunk = cached_jit("sortkeys", repr(sort_items), lambda: eval_chunk)

        runs = SpillableRuns(self.ctx.mem_tracker.child("sort"), "sort")
        self._runs = runs
        for ch in child.chunks():
            # host-sync: sort materializes to HOST runs (spillable under
            # the query budget), so each chunk crosses once by design;
            # ONE device_get per chunk (Chunk/Column are pytrees) — the
            # per-column np.asarray calls below then see numpy and cost
            # nothing (was 2 syncs per column)
            kcols, ch = _dsp.record_fetch(jax.device_get(eval_chunk(ch)))
            sel = np.asarray(ch.sel)
            live = np.nonzero(sel)[0]
            named = {}
            for uid in uids:
                col = ch.columns[uid]
                named[f"c.{uid}.d"] = np.asarray(col.data)[live]
                named[f"c.{uid}.v"] = np.asarray(col.valid)[live]
            for i, kc in enumerate(kcols):
                named[f"k.{i}.d"] = np.asarray(kc.data)[live]
                named[f"k.{i}.v"] = np.asarray(kc.valid)[live]
            runs.append(named)
        return runs

    def _global_keys(self, runs, n_keys: int):
        """Concatenate sort keys across runs (keys stay in host memory;
        only the payload gather is mmap-backed)."""
        host_keys = []
        for i in range(n_keys):
            ds, vs = [], []
            for loader, _rows in runs.all_runs():
                ds.append(np.asarray(loader(f"k.{i}.d")))
                vs.append(np.asarray(loader(f"k.{i}.v")))
            host_keys.append(
                (ds[0] if len(ds) == 1 else np.concatenate(ds) if ds else np.zeros(0),
                 vs[0] if len(vs) == 1 else np.concatenate(vs) if vs else np.zeros(0, dtype=np.bool_))
            )
        return host_keys

    def _emit(self, runs, order: Optional[np.ndarray], n: int):
        """Emit output chunks by gathering `order` rows from the runs."""
        cap = self.ctx.chunk_capacity
        self._chunks = []
        idx = order if order is not None else np.arange(n)
        run_list = runs.all_runs()
        bases = np.cumsum([0] + [rows for _, rows in run_list])
        handles = {}

        def col_of(ri, name):
            key = (ri, name)
            if key not in handles:
                handles[key] = run_list[ri][0](name)
            return handles[key]

        for s in range(0, len(idx), cap):
            part = idx[s : s + cap]
            cols = {}
            for c in self.schema:
                d_out = v_out = None
                for ri in range(len(run_list)):
                    m = (part >= bases[ri]) & (part < bases[ri + 1])
                    if not m.any():
                        continue
                    local = part[m] - bases[ri]
                    d = col_of(ri, f"c.{c.uid}.d")
                    if d_out is None:
                        d_out = np.empty(len(part), dtype=d.dtype)
                        v_out = np.empty(len(part), dtype=np.bool_)
                    d_out[m] = d[local]
                    v_out[m] = col_of(ri, f"c.{c.uid}.v")[local]
                if d_out is None:
                    d_out = np.zeros(len(part), dtype=c.type_.np_dtype)
                    v_out = np.zeros(len(part), dtype=np.bool_)
                cols[c.uid] = Column.from_numpy(d_out, c.type_, valid=v_out, capacity=cap)
            sel = np.zeros(cap, dtype=np.bool_)
            sel[: len(part)] = True
            self._chunks.append(Chunk(cols, sel))

    def _close_runs(self) -> None:
        if self._runs is not None:
            self._runs.close()
            self._runs = None

    def close(self) -> None:
        self._close_runs()
        super().close()

    def next(self) -> Optional[Chunk]:
        if self._chunks:
            return self._chunks.pop(0)
        return None


def _sort_order(host_keys, items) -> np.ndarray:
    """np.lexsort with MySQL NULL ordering (NULLs first ASC, last DESC)."""
    lex = []
    for (data, valid), (_, desc) in zip(host_keys, items):
        d = data
        if np.issubdtype(d.dtype, np.bool_):
            d = d.astype(np.int64)
        if desc:
            d = -d.astype(np.float64) if np.issubdtype(d.dtype, np.floating) else -d.astype(np.int64)
            nullrank = (~valid).astype(np.int64)  # nulls last on desc
        else:
            d = d.astype(np.float64) if np.issubdtype(d.dtype, np.floating) else d.astype(np.int64)
            nullrank = valid.astype(np.int64)  # nulls (0) first on asc
        d = np.where(valid, d, 0)
        # within one sort key, null-rank dominates the value
        lex.append(nullrank)
        lex.append(d)
    # np.lexsort: last key is primary; our items[0] is primary
    return np.lexsort(lex[::-1]) if lex else np.arange(len(host_keys[0][0]) if host_keys else 0)


class SortExec(_Materializing):
    def __init__(self, schema, child, items):
        super().__init__(schema, [child])
        self.items = items

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        runs = self._drain_to_runs(self.items)
        n = sum(rows for _, rows in runs.all_runs())
        order = None
        if self.items:
            host_keys = self._global_keys(runs, len(self.items))
            order = _sort_order(host_keys, self.items)
        self._emit(runs, order, n)
        self._close_runs()  # output chunks own copies; free the charge now


class TopNExec(_Materializing):
    def __init__(self, schema, child, items, count: int, offset: int):
        super().__init__(schema, [child])
        self.items = items
        self.count = count
        self.offset = offset

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        runs = self._drain_to_runs(self.items)
        n = sum(rows for _, rows in runs.all_runs())
        host_keys = self._global_keys(runs, len(self.items))
        order = _sort_order(host_keys, self.items)
        order = order[self.offset : self.offset + self.count]
        self._emit(runs, order, n)
        self._close_runs()


class LimitExec(Executor):
    def __init__(self, schema, child, count: int, offset: int):
        super().__init__(schema, [child])
        self.count = count
        self.offset = offset

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        self._skipped = 0
        self._taken = 0

    def next(self) -> Optional[Chunk]:
        import jax.numpy as jnp

        while self._taken < self.count:
            ch = self.children[0].next()
            if ch is None:
                return None
            sel = np.asarray(ch.sel)
            live = np.nonzero(sel)[0]
            m = len(live)
            if m == 0:
                continue
            drop = min(self._skipped_remaining(), m)
            take = min(self.count - self._taken, m - drop)
            self._skipped += drop
            self._taken += take
            if take <= 0:
                continue
            keep = np.zeros_like(sel)
            keep[live[drop : drop + take]] = True
            return ch.with_sel(ch.sel & jnp.asarray(keep))
        return None

    def _skipped_remaining(self) -> int:
        return max(0, self.offset - self._skipped)


class UnionExec(Executor):
    """UNION ALL: chain child streams (children project onto shared uids)."""

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self._i = 0

    def next(self) -> Optional[Chunk]:
        while self._i < len(self.children):
            ch = self.children[self._i].next()
            if ch is not None:
                return ch
            self._i += 1
        return None
