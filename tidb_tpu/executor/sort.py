"""Root-task operators: Sort / TopN / Limit / Union
(ref: executor/sort.go, topn, limit; these sit at the plan root over small
results, so they run host-side — the reference similarly runs root
executors on the SQL node while coprocessors do the heavy scans).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.base import ExecContext, Executor
from tidb_tpu.utils.jitcache import cached_jit
from tidb_tpu.expression.compiler import compile_expr
from tidb_tpu.types import TypeKind

__all__ = ["SortExec", "TopNExec", "LimitExec", "UnionExec"]


class _Materializing(Executor):
    """Shared: drain child to host-compacted column arrays."""

    def _drain_to_host(self, sort_items: List[Tuple[object, bool]]):
        child = self.children[0]
        uids = [c.uid for c in self.schema]
        key_fns = [compile_expr(e) for e, _ in sort_items]

        def eval_chunk(ch):
            keys = [f(ch) for f in key_fns]
            return keys, ch

        eval_chunk = cached_jit("sortkeys", repr(sort_items), lambda: eval_chunk)

        cols = {uid: ([], []) for uid in uids}
        keys: List[Tuple[List, List]] = [([], []) for _ in sort_items]
        for ch in child.chunks():
            kcols, ch = eval_chunk(ch)
            sel = np.asarray(ch.sel)
            live = np.nonzero(sel)[0]
            for uid in uids:
                col = ch.columns[uid]
                cols[uid][0].append(np.asarray(col.data)[live])
                cols[uid][1].append(np.asarray(col.valid)[live])
            for i, kc in enumerate(kcols):
                keys[i][0].append(np.asarray(kc.data)[live])
                keys[i][1].append(np.asarray(kc.valid)[live])

        host_cols = {}
        n = 0
        for uid in uids:
            d = np.concatenate(cols[uid][0]) if cols[uid][0] else np.zeros(0)
            v = np.concatenate(cols[uid][1]) if cols[uid][1] else np.zeros(0, dtype=np.bool_)
            host_cols[uid] = (d, v)
            n = len(d)
        host_keys = [
            (np.concatenate(k[0]) if k[0] else np.zeros(0),
             np.concatenate(k[1]) if k[1] else np.zeros(0, dtype=np.bool_))
            for k in keys
        ]
        return host_cols, host_keys, n

    def _emit(self, host_cols, order: Optional[np.ndarray], n: int):
        cap = self.ctx.chunk_capacity
        self._chunks = []
        idx = order if order is not None else np.arange(n)
        for s in range(0, len(idx), cap):
            part = idx[s : s + cap]
            cols = {}
            for c in self.schema:
                d, v = host_cols[c.uid]
                cols[c.uid] = Column.from_numpy(d[part], c.type_, valid=v[part], capacity=cap)
            sel = np.zeros(cap, dtype=np.bool_)
            sel[: len(part)] = True
            self._chunks.append(Chunk(cols, sel))

    def next(self) -> Optional[Chunk]:
        if self._chunks:
            return self._chunks.pop(0)
        return None


def _sort_order(host_keys, items) -> np.ndarray:
    """np.lexsort with MySQL NULL ordering (NULLs first ASC, last DESC)."""
    lex = []
    for (data, valid), (_, desc) in zip(host_keys, items):
        d = data
        if np.issubdtype(d.dtype, np.bool_):
            d = d.astype(np.int64)
        if desc:
            d = -d.astype(np.float64) if np.issubdtype(d.dtype, np.floating) else -d.astype(np.int64)
            nullrank = (~valid).astype(np.int64)  # nulls last on desc
        else:
            d = d.astype(np.float64) if np.issubdtype(d.dtype, np.floating) else d.astype(np.int64)
            nullrank = valid.astype(np.int64)  # nulls (0) first on asc
        d = np.where(valid, d, 0)
        # within one sort key, null-rank dominates the value
        lex.append(nullrank)
        lex.append(d)
    # np.lexsort: last key is primary; our items[0] is primary
    return np.lexsort(lex[::-1]) if lex else np.arange(len(host_keys[0][0]) if host_keys else 0)


class SortExec(_Materializing):
    def __init__(self, schema, child, items):
        super().__init__(schema, [child])
        self.items = items

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        host_cols, host_keys, n = self._drain_to_host(self.items)
        order = _sort_order(host_keys, self.items) if self.items else None
        self._emit(host_cols, order, n)


class TopNExec(_Materializing):
    def __init__(self, schema, child, items, count: int, offset: int):
        super().__init__(schema, [child])
        self.items = items
        self.count = count
        self.offset = offset

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        host_cols, host_keys, n = self._drain_to_host(self.items)
        order = _sort_order(host_keys, self.items)
        order = order[self.offset : self.offset + self.count]
        self._emit(host_cols, order, n)


class LimitExec(Executor):
    def __init__(self, schema, child, count: int, offset: int):
        super().__init__(schema, [child])
        self.count = count
        self.offset = offset

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        self._skipped = 0
        self._taken = 0

    def next(self) -> Optional[Chunk]:
        import jax.numpy as jnp

        while self._taken < self.count:
            ch = self.children[0].next()
            if ch is None:
                return None
            sel = np.asarray(ch.sel)
            live = np.nonzero(sel)[0]
            m = len(live)
            if m == 0:
                continue
            drop = min(self._skipped_remaining(), m)
            take = min(self.count - self._taken, m - drop)
            self._skipped += drop
            self._taken += take
            if take <= 0:
                continue
            keep = np.zeros_like(sel)
            keep[live[drop : drop + take]] = True
            return ch.with_sel(ch.sel & jnp.asarray(keep))
        return None

    def _skipped_remaining(self) -> int:
        return max(0, self.offset - self._skipped)


class UnionExec(Executor):
    """UNION ALL: chain child streams (children project onto shared uids)."""

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self._i = 0

    def next(self) -> Optional[Chunk]:
        while self._i < len(self.children):
            ch = self.children[self._i].next()
            if ch is not None:
                return ch
            self._i += 1
        return None
