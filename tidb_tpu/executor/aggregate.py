"""HashAggExec (ref: executor/aggregate.go — partial/final worker
pipeline).

Two strategies, chosen by the planner:

  segment  -- every group key has a small known domain (dictionary codes,
              bools). Keys pack into one dense code; aggregation is
              jnp scatter-adds into [G]-shaped accumulators per chunk, on
              device, inside one jitted update. NULL gets its own slot per
              key (domain+1) so SQL NULL-group semantics hold. This is the
              partial-agg kernel that psum-merges across chips in the
              distributed path.

  generic  -- arbitrary keys (wide ints, floats, many distinct). Chunks
              compact to host and a vectorized numpy groupby finalizes.
              This is the root-task fallback, like reference root HashAgg
              over coprocessor partials.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.errors import ExecutionError, UnsupportedError
from tidb_tpu.executor.base import ExecContext, Executor
from tidb_tpu.expression.compiler import eval_expr
from tidb_tpu.planner.logical import AggSpec
from tidb_tpu.types import FLOAT64, SQLType, TypeKind
from tidb_tpu.utils.jitcache import cached_jit

__all__ = ["HashAggExec", "make_segment_kernel", "MERGE_OPS", "merge_op_for"]


def _min_identity(dtype):
    if np.issubdtype(dtype, np.floating):
        return np.inf
    return np.iinfo(dtype).max


def _max_identity(dtype):
    if np.issubdtype(dtype, np.floating):
        return -np.inf
    return np.iinfo(dtype).min


# How each piece of segment-agg state merges across partial aggregators.
# Key suffix -> collective: the distributed path (parallel/distsql.py) maps
# these onto lax.psum / lax.pmin / lax.pmax over the shard mesh axis —
# exactly the partial/final split of the reference's HashAggExec pipeline.
MERGE_OPS = {".sumhi": "sum", ".sum": "sum", ".cnt": "sum",
             ".min": "min", ".max": "max"}

# host ufunc + identity per bitwise aggregate (generic host path only;
# the fragment tier rejects these so routing falls back cleanly)
_BIT_AGGS = {"bit_and": (np.bitwise_and, -1),
             "bit_or": (np.bitwise_or, 0),
             "bit_xor": (np.bitwise_xor, 0)}

_VAR_AGGS = ("var_pop", "var_samp", "stddev_pop", "stddev_samp")


def _var_m2(vals: np.ndarray, inverse: np.ndarray, ngroups: int):
    """Two-pass per-group variance core: (cnt, sum, m2) with
    m2 = sum((x - group_mean)^2). Numerically stable — never forms
    E[x^2]-E[x]^2, whose cancellation destroys large-magnitude data
    (epoch timestamps, money-in-cents)."""
    v = vals.astype(np.float64)
    cnt = np.zeros(ngroups, dtype=np.int64)
    np.add.at(cnt, inverse, 1)
    s = np.zeros(ngroups, dtype=np.float64)
    np.add.at(s, inverse, v)
    with np.errstate(divide="ignore", invalid="ignore"):
        mean = np.where(cnt > 0, s / np.maximum(cnt, 1), 0.0)
    m2 = np.zeros(ngroups, dtype=np.float64)
    np.add.at(m2, inverse, (v - mean[inverse]) ** 2)
    return cnt, s, m2


def _var_finalize(func: str, cnt: np.ndarray, m2: np.ndarray):
    """(values, valid) per MySQL: VAR_POP needs n>=1, VAR_SAMP n>=2."""
    with np.errstate(divide="ignore", invalid="ignore"):
        if func in ("var_pop", "stddev_pop"):
            out = np.where(cnt > 0, m2 / np.maximum(cnt, 1), 0.0)
            valid = cnt > 0
        else:
            out = np.where(cnt > 1, m2 / np.maximum(cnt - 1, 1), 0.0)
            valid = cnt > 1
    if func.startswith("stddev"):
        out = np.sqrt(np.maximum(out, 0.0))
    return out, valid


def merge_op_for(key: str) -> str:
    if key == "occ":
        return "sum"
    for suffix, op in MERGE_OPS.items():
        if key.endswith(suffix):
            return op
    raise ExecutionError(f"no merge op for state key {key!r}")


# ---------------------------------------------------------------------------
# two-limb exact accumulation for scaled-int64 DECIMAL sums (SURVEY.md:309
# hard-part 3). A value v splits into lo = v & (2^32-1) in [0, 2^32) and
# hi = v >> 32 (arithmetic), with v == hi * 2^32 + lo exactly. Sums of each
# limb stay far from int64 range for any realistic row count (lo adds < 2^32
# per row, hi adds < 2^31), the pair is psum-mergeable like any other state,
# and the true total spans ~94 bits — SUM can now be COMPUTED at magnitudes
# where the old f64-shadow guard could only detect-and-fail.
# ---------------------------------------------------------------------------

_LO_BITS = 32
_LO_MASK = (1 << _LO_BITS) - 1


def needs_sum_limbs(a: AggSpec) -> bool:
    """DECIMAL SUM/AVG accumulates in two int64 limbs."""
    return (a.func in ("sum", "avg") and a.arg is not None
            and a.arg.type_.kind == TypeKind.DECIMAL)


def split_limbs(v):
    """(lo, hi) limb decomposition — works on jnp and np int64 alike."""
    return v & _LO_MASK, v >> _LO_BITS


def normalize_limbs(lo, hi):
    """Carry lo's overflow into hi, restoring lo in [0, 2^32)."""
    return lo & _LO_MASK, hi + (lo >> _LO_BITS)


def limbs_to_float(lo, hi) -> np.ndarray:
    """Approximate float64 value of (lo, hi) pairs (for AVG and guards)."""
    return (np.asarray(hi, dtype=np.float64) * float(1 << _LO_BITS)
            + np.asarray(lo, dtype=np.float64))


def combine_limbs_exact(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """Exact int64 totals from limb pairs; totals outside int64 raise
    (the DECIMAL result column is scaled int64 — a value that cannot be
    REPRESENTED is a true out-of-range error, unlike the old accumulator
    wrap, which hit ~2^62 of summed magnitude even when every group's
    total was small)."""
    tf = limbs_to_float(lo, hi)
    # f64 ulp at 2^63 is 1024: stay 4096 clear of the boundary so a
    # wrapped value can never masquerade as in-range
    if np.any(np.abs(tf) > float(1 << 63) - 4096.0):
        raise ExecutionError(
            "DECIMAL SUM value is out of range of the result type")
    t = ((np.asarray(hi).astype(np.uint64) << np.uint64(_LO_BITS))
         + np.asarray(lo).astype(np.uint64))
    return t.view(np.int64)


def scatter_limbs(vals: np.ndarray, inverse: np.ndarray, n: int):
    """Host limb accumulation: scatter-add each value's limbs into n
    group slots (shared by the spill-partial and resident agg paths)."""
    vlo, vhi = split_limbs(vals.astype(np.int64))
    lo = np.zeros(n, dtype=np.int64)
    hi = np.zeros(n, dtype=np.int64)
    np.add.at(lo, inverse, vlo)
    np.add.at(hi, inverse, vhi)
    return normalize_limbs(lo, hi)


def _lexsort_groups(cols: List[np.ndarray]):
    """Group rows by exact multi-column keys via one lexsort — several
    times faster than np.unique(axis=0)'s void-dtype row comparisons.
    Returns (ngroups, first_idx, inverse): representative original row
    per group (first in sort order) and each row's dense group id."""
    n = len(cols[0])
    if n == 0:
        return 0, np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    order = np.lexsort(cols[::-1])  # last key primary per np convention
    newseg = np.zeros(n, dtype=np.bool_)
    newseg[0] = True
    for c in cols:
        sc = c[order]
        newseg[1:] |= sc[1:] != sc[:-1]
    inverse = np.empty(n, dtype=np.int64)
    inverse[order] = np.cumsum(newseg) - 1
    first_idx = order[newseg]
    return int(newseg.sum()), first_idx, inverse


def _partial_nbytes(p: dict) -> int:
    return int(
        p["mat"].nbytes
        + sum(a.nbytes for a in p["keys"])
        + sum(a.nbytes for a in p["kvalids"])
        + sum(a.nbytes for st in p["states"] for a in st.values())
    )


def make_segment_kernel(group_exprs, aggs: List[AggSpec], domains: List[int]):
    """Build (init_state, update, G) for segment-strategy aggregation.

    `update(state, chunk) -> state` is a pure function over [G]-shaped
    accumulators — usable per-chunk on one chip (HashAggExec) or per-shard
    under shard_map with a collective merge (the partial-agg kernel of the
    distributed path; see merge_op_for)."""
    G = 1
    for d in domains:
        G *= d
    G = max(G, 1)

    def init_state():
        st = {"occ": jnp.zeros(G, dtype=jnp.int64)}
        for a in aggs:
            if a.func in ("sum", "avg"):
                dt = jnp.float64 if a.arg.type_.kind == TypeKind.FLOAT else jnp.int64
                st[f"{a.uid}.sum"] = jnp.zeros(G, dtype=dt)
                if needs_sum_limbs(a):
                    # two-limb exact accumulation: .sum holds the low
                    # 32-bit limb, .sumhi the high — see split_limbs
                    st[f"{a.uid}.sumhi"] = jnp.zeros(G, dtype=jnp.int64)
                st[f"{a.uid}.cnt"] = jnp.zeros(G, dtype=jnp.int64)
            elif a.func == "count":
                st[f"{a.uid}.cnt"] = jnp.zeros(G, dtype=jnp.int64)
            elif a.func == "min":
                dt = a.arg.type_.np_dtype
                st[f"{a.uid}.min"] = jnp.full(G, _min_identity(dt), dtype=dt)
                st[f"{a.uid}.cnt"] = jnp.zeros(G, dtype=jnp.int64)
            elif a.func == "max":
                dt = a.arg.type_.np_dtype
                st[f"{a.uid}.max"] = jnp.full(G, _max_identity(dt), dtype=dt)
                st[f"{a.uid}.cnt"] = jnp.zeros(G, dtype=jnp.int64)
        return st

    def update(state, chunk: Chunk):
        from tidb_tpu.ops import segment_count

        packed = jnp.zeros(chunk.capacity, dtype=jnp.int64)
        stride = 1
        for g, dom in zip(group_exprs, domains):
            data, valid = eval_expr(g, chunk)
            idx = jnp.where(valid, jnp.clip(data.astype(jnp.int64), 0, dom - 2), dom - 1)
            packed = packed + idx * stride
            stride *= dom
        sel = chunk.sel
        out = dict(state)
        # count-shaped accumulators route through the Pallas one-hot
        # kernel on TPU (ops/segment_sum.py; the XLA int64 scatter is
        # 10x+ slower there) — elementwise add merges it into the state
        out["occ"] = state["occ"] + segment_count(sel, packed, G)
        for a in aggs:
            if a.arg is not None:
                d, v = eval_expr(a.arg, chunk)
                ok = sel & v
            if a.func in ("sum", "avg"):
                acc = state[f"{a.uid}.sum"]
                contrib = jnp.where(ok, d, 0).astype(acc.dtype)
                if f"{a.uid}.sumhi" in state:
                    # two-limb exact decimal path: scatter each limb via
                    # the Pallas kernel, then carry-normalize so the lo
                    # accumulator never approaches int64 range no matter
                    # how many chunks stream through
                    from tidb_tpu.ops import segment_sum_i64

                    clo, chi = split_limbs(contrib)
                    lo = acc + segment_sum_i64(clo, packed, G)
                    hi = (state[f"{a.uid}.sumhi"]
                          + segment_sum_i64(chi, packed, G))
                    lo, hi = normalize_limbs(lo, hi)
                    out[f"{a.uid}.sum"] = lo
                    out[f"{a.uid}.sumhi"] = hi
                elif acc.dtype == jnp.int64:
                    # int sums: exact Pallas limb kernel on TPU
                    from tidb_tpu.ops import segment_sum_i64

                    out[f"{a.uid}.sum"] = acc + segment_sum_i64(
                        contrib, packed, G)
                else:
                    out[f"{a.uid}.sum"] = acc.at[packed].add(contrib)
                out[f"{a.uid}.cnt"] = state[f"{a.uid}.cnt"] + segment_count(ok, packed, G)
            elif a.func == "count":
                cm = sel if a.arg is None else ok
                out[f"{a.uid}.cnt"] = state[f"{a.uid}.cnt"] + segment_count(cm, packed, G)
            elif a.func == "min":
                acc = state[f"{a.uid}.min"]
                contrib = jnp.where(ok, d, _min_identity(np.dtype(acc.dtype))).astype(acc.dtype)
                out[f"{a.uid}.min"] = acc.at[packed].min(contrib)
                out[f"{a.uid}.cnt"] = state[f"{a.uid}.cnt"] + segment_count(ok, packed, G)
            elif a.func == "max":
                acc = state[f"{a.uid}.max"]
                contrib = jnp.where(ok, d, _max_identity(np.dtype(acc.dtype))).astype(acc.dtype)
                out[f"{a.uid}.max"] = acc.at[packed].max(contrib)
                out[f"{a.uid}.cnt"] = state[f"{a.uid}.cnt"] + segment_count(ok, packed, G)
        return out

    return init_state, update, G


class HashAggExec(Executor):
    def __init__(self, schema, child, group_exprs, group_uids, aggs: List[AggSpec],
                 strategy: str, segment_sizes: Optional[List[int]] = None):
        super().__init__(schema, [child])
        self.group_exprs = group_exprs
        self.group_uids = group_uids
        self.aggs = aggs
        self.strategy = strategy
        self.segment_sizes = segment_sizes
        self._out: List[Chunk] = []
        self._emitted = False
        self._runs = None

    # ------------------------------------------------------------------

    def open(self, ctx: ExecContext) -> None:
        super().open(ctx)
        self.ctx = ctx
        self._out = []
        self._emitted = False
        if self.strategy == "segment":
            self._run_segment()
        else:
            self._run_generic()

    def next(self) -> Optional[Chunk]:
        if self._out:
            return self._out.pop(0)
        return None

    # ------------------------------------------------------------------
    # segment strategy (device)
    # ------------------------------------------------------------------

    def _run_segment(self):
        sizes = self.segment_sizes or []
        domains = [s + 1 for s in sizes]  # +1 slot for NULL keys
        init_state, update, _ = make_segment_kernel(self.group_exprs, self.aggs, domains)

        update = cached_jit(
            "segagg", repr((self.group_exprs, self.aggs, domains)),
            lambda: update, donate_argnums=0,
        )
        state = init_state()
        for chunk in self.children[0].chunks():
            state = update(state, chunk)
        self._finalize_segment_state(state, domains)

    def _finalize_segment_state(self, state, domains):
        """Host finalize of [G]-shaped accumulators: unpack occupied groups.
        Shared with the distributed executors (parallel/executor.py), which
        produce the same state via collective merge."""
        # one batched fetch: on a remote/tunneled device, per-key np.asarray
        # would pay a round trip per state array
        import jax

        from tidb_tpu.utils import dispatch as dsp

        host = dsp.record_fetch(jax.device_get(state))
        dsp.record(site="fetch")
        if self.group_exprs:
            occupied = np.nonzero(host["occ"] > 0)[0]
        else:
            occupied = np.array([0], dtype=np.int64)  # global agg: 1 row always
        self._emit_groups_from_packed(occupied, domains, host)

    def _emit_groups_from_packed(self, occupied, domains, host):
        n = len(occupied)
        cap = max(self.ctx.chunk_capacity, 1)
        group_cols = {}
        rem = occupied.copy()
        for (uid, dom) in zip(self.group_uids, domains):
            idx = rem % dom
            rem = rem // dom
            valid = idx != (dom - 1)
            group_cols[uid] = (idx, valid)
        out_arrays: Dict[str, tuple] = {}
        for c, (uid) in zip(self.schema[: len(self.group_uids)], self.group_uids):
            idx, valid = group_cols[uid]
            out_arrays[uid] = (idx.astype(c.type_.np_dtype), valid)
        for a in self.aggs:
            out_arrays[a.uid] = self._finalize_agg_host(a, host, occupied)
        self._chunks_from_host(out_arrays, n, cap)

    def _finalize_agg_host(self, a: AggSpec, host, occupied):
        cnt = host.get(f"{a.uid}.cnt")
        cnt = cnt[occupied] if cnt is not None else None
        if a.func == "count":
            return cnt.astype(np.int64), np.ones(len(occupied), dtype=np.bool_)
        if a.func in ("sum",):
            s = host[f"{a.uid}.sum"][occupied]
            hi = host.get(f"{a.uid}.sumhi")
            if hi is not None:
                s = combine_limbs_exact(s, hi[occupied])
            return s.astype(a.type_.np_dtype), cnt > 0
        if a.func == "avg":
            hi = host.get(f"{a.uid}.sumhi")
            if hi is not None:
                s = limbs_to_float(host[f"{a.uid}.sum"][occupied],
                                   hi[occupied])
            else:
                s = host[f"{a.uid}.sum"][occupied].astype(np.float64)
            if a.arg.type_.kind == TypeKind.DECIMAL:
                s = s / (10 ** a.arg.type_.scale)
            with np.errstate(divide="ignore", invalid="ignore"):
                avg = np.where(cnt > 0, s / np.maximum(cnt, 1), 0.0)
            return avg, cnt > 0
        if a.func == "min":
            return host[f"{a.uid}.min"][occupied].astype(a.type_.np_dtype), cnt > 0
        if a.func == "max":
            return host[f"{a.uid}.max"][occupied].astype(a.type_.np_dtype), cnt > 0
        raise ExecutionError(f"unknown aggregate {a.func}")

    def _chunks_from_host(self, out_arrays: Dict[str, tuple], n: int, cap: int):
        # plan feedback: the group count is host-known here for free —
        # every finalize path (segment, generic host, device tables,
        # external merge batches) funnels through this emit
        self.stats.add_out_rows(n)
        for start in range(0, max(n, 1), cap):
            end = min(start + cap, n)
            if n == 0 and self.group_exprs:
                break
            cols = {}
            for c in self.schema:
                data, valid = out_arrays[c.uid]
                cols[c.uid] = Column.from_numpy(
                    data[start:end], c.type_, valid=valid[start:end], capacity=cap
                )
            m = end - start
            sel = np.zeros(cap, dtype=np.bool_)
            sel[:m] = True
            self._out.append(Chunk(cols, sel))
            if n == 0:
                break

    # ------------------------------------------------------------------
    # generic strategy (host groupby)
    # ------------------------------------------------------------------

    def _run_generic(self):
        from tidb_tpu.utils import dispatch as dsp
        from tidb_tpu.utils.memory import SpillableRuns

        group_exprs, aggs = self.group_exprs, self.aggs
        from tidb_tpu.planner.logical import core_generic_agg

        if self.ctx.device_agg and core_generic_agg(group_exprs, aggs):
            self._run_generic_device()
            return

        def eval_all(chunk):
            outs = []
            for g in group_exprs:
                outs.append(eval_expr(g, chunk))
            for a in aggs:
                if a.arg is not None:
                    outs.append(eval_expr(a.arg, chunk))
            return outs, chunk.sel

        eval_all = cached_jit(
            "genagg", repr((group_exprs, [a.arg for a in aggs])), lambda: eval_all
        )

        runs = SpillableRuns(self.ctx.mem_tracker.child("hashagg"), "hashagg")
        self._runs = runs
        total = 0
        for chunk in self.children[0].chunks():
            # host-sync: host-groupby tier — the host accumulates raw
            # values, so each chunk's (outs, sel) pytree must land
            # host-side; ONE device_get per chunk replaces the 2K+1
            # per-column np.asarray syncs this loop used to pay. The
            # device tiers (fused pipeline / _run_generic_device) are
            # the no-per-chunk-fetch paths
            outs, sel = dsp.record_fetch(jax.device_get(eval_all(chunk)))
            sel = np.asarray(sel)
            live = np.nonzero(sel)[0]
            total += len(live)
            named = {}
            i = 0
            for k in range(len(group_exprs)):
                d, v = outs[i]; i += 1
                named[f"k{k}.d"] = np.asarray(d)[live]
                named[f"k{k}.v"] = np.asarray(v)[live]
            for j, a in enumerate(aggs):
                if a.arg is not None:
                    d, v = outs[i]; i += 1
                    named[f"a{j}.d"] = np.asarray(d)[live]
                    named[f"a{j}.v"] = np.asarray(v)[live]
                else:
                    named[f"a{j}.d"] = np.ones(len(live), dtype=np.bool_)
                    named[f"a{j}.v"] = np.ones(len(live), dtype=np.bool_)
            runs.append(named)

        cap = self.ctx.chunk_capacity
        if total == 0:
            runs.close()
            if self.group_exprs:
                self._out = []  # grouped agg over empty input -> no rows
                return
            # global aggregate over empty input: one row
            out_arrays = {}
            for c, a in zip(self.schema, self.aggs):
                if a.func == "count":
                    out_arrays[a.uid] = (np.zeros(1, dtype=np.int64), np.ones(1, dtype=np.bool_))
                elif a.func in _BIT_AGGS:
                    # BIT_* never return NULL: empty input keeps the
                    # identity (MySQL: BIT_AND()=all ones, others 0)
                    ident = _BIT_AGGS[a.func][1]
                    out_arrays[a.uid] = (np.full(1, ident, dtype=np.int64),
                                         np.ones(1, dtype=np.bool_))
                else:
                    out_arrays[a.uid] = (np.zeros(1, dtype=a.type_.np_dtype), np.zeros(1, dtype=np.bool_))
            self._chunks_from_host(out_arrays, 1, cap)
            return

        run_list = runs.all_runs()
        has_distinct = any(a.distinct for a in aggs)
        if len(run_list) > 1 and not has_distinct:
            # spilled: per-run partial groupby states merged like the
            # reference's partial/final HashAgg worker split. When the
            # TOTAL group state overflows the budget (near-unique keys),
            # fall to a key-RANGE-partitioned external merge: each run's
            # partial is key-sorted, so a range is a contiguous slice of
            # every run — merge one range at a time with O(state/ranges)
            # memory (the external grouped aggregation the reference's
            # spill-to-disk agg performs; SURVEY.md:315 hard part 6).
            tracker = self.ctx.mem_tracker.child("hashagg.final")
            tracked = 0
            budget = getattr(self.ctx.mem_tracker, "budget", 0) or 0
            # per-group partial bytes: mat + keys + kvalids + states
            nk_ = len(self.group_exprs)
            per_group = 8 * (2 * nk_ + 1) + nk_ + 24 * max(len(aggs), 1)
            go_external = False
            if budget:
                # estimate total group state from a bounded sample of
                # the first run (its partial keys/rows ratio); a
                # worst-case rows-based bound would send LOW-cardinality
                # aggregations external too (round-5 review)
                l0, r0 = run_list[0]
                samp = min(r0, 1 << 14)

                def _s(name, _l=l0, _n=samp):
                    return np.asarray(_l(name))[:_n]

                p0 = self._partial_states(_s)
                density = max(len(p0["mat"]), 1) / max(samp, 1)
                del p0
                total_rows = sum(r for _, r in run_list)
                go_external = (density * total_rows * per_group
                               > budget // 2)
            try:
                merged = None
                if not go_external:
                    for loader, _rows in run_list:
                        p = self._partial_states(loader)
                        b_p = _partial_nbytes(p)
                        # the pairwise merge transiently holds old
                        # merged + p + the new merged (~2x their sum) ON
                        # TOP of whatever the rest of the query already
                        # consumes on the root tracker: bail to the
                        # external path BEFORE that peak when the
                        # sampled estimate undershot (sorted or skewed
                        # keys make early rows look low-card)
                        root_used = self.ctx.mem_tracker.consumed
                        if budget and root_used + 2 * b_p + tracked > budget:
                            del p
                            tracker.release(tracked)
                            tracked = 0
                            merged = None
                            go_external = True
                            break
                        tracker.consume(b_p)
                        tracked += b_p
                        if merged is not None:
                            merged = self._merge_partials([merged, p])
                            b_m = _partial_nbytes(merged)
                            tracker.consume(b_m)
                            tracker.release(tracked)  # merged + p dead
                            tracked = b_m
                        else:
                            merged = p
                if go_external:
                    self._external_range_merge(run_list, cap, tracker,
                                               budget)
                elif merged is not None:
                    self._emit_merged(merged, cap)
            finally:
                tracker.release(tracked)
            runs.close()
            return

        # resident (or DISTINCT, which needs raw values): whole-input path.
        # Spilled runs rematerialize here — charge the budget so quota
        # violations surface as OOM instead of silent host growth.
        fallback_tracker = self.ctx.mem_tracker.child("hashagg.distinct")
        fallback_bytes = 0

        def cat(name):
            nonlocal fallback_bytes
            arrays = [np.asarray(l(name)) for l, _ in run_list]
            out = arrays[0] if len(arrays) == 1 else np.concatenate(arrays)
            if runs.spilled:
                fallback_tracker.consume(out.nbytes)
                fallback_bytes += out.nbytes
            return out

        try:
            self._run_generic_resident(run_list, cat, cap)
        finally:
            fallback_tracker.release(fallback_bytes)
            runs.close()

    def _run_generic_device(self):
        """Sort-based grouping on device (agg_device.py): per-chunk
        partial group tables, pairwise device merges, one batched fetch,
        host finalize through the shared partial-state path."""
        import jax

        from tidb_tpu.executor.agg_device import (
            GroupTableStack,
            make_partial_kernel,
            table_to_host_partial,
        )

        sig = repr((self.group_exprs, self.aggs))
        partial_fn = cached_jit(
            "aggpart", sig, lambda: make_partial_kernel(self.group_exprs, self.aggs)
        )
        stack = GroupTableStack(len(self.group_exprs), self.aggs, sig)
        for chunk in self.children[0].chunks():
            stack.push(partial_fn(chunk))
        self._finalize_group_tables(stack.tables())

    def _finalize_group_tables(self, tables):
        """ONE batched fetch of the device group tables, host merge,
        emit. Shared by the pull-based device path above and the fused
        scan→partial-agg pipeline (executor/pipeline.py), which
        accumulates the same tables from its fused chunk programs."""
        import jax

        from tidb_tpu.executor.agg_device import table_to_host_partial
        from tidb_tpu.utils import dispatch as dsp

        cap = self.ctx.chunk_capacity
        if not tables:
            self._out = []  # grouped agg over empty input -> no rows
            return
        host_tables = dsp.record_fetch(
            jax.device_get(tables))  # ONE round trip (finalize)
        # account the durable (ngroups-sliced) partial tables with the
        # same incremental discipline as the host spill-merge path; the
        # padded slot arrays are transients
        tracker = self.ctx.mem_tracker.child("hashagg.device")
        tracked = 0
        try:
            merged = None
            for t in host_tables:
                p = table_to_host_partial(t, len(self.group_exprs), self.aggs)
                b_p = _partial_nbytes(p)
                tracker.consume(b_p)
                tracked += b_p
                if merged is None:
                    merged = p
                else:
                    merged = self._merge_partials([merged, p])
                    b_m = _partial_nbytes(merged)
                    tracker.consume(b_m)
                    tracker.release(tracked)  # old merged + p are dead
                    tracked = b_m
            if len(host_tables) == 1 and len(self.group_exprs) > 1:
                # multi-key device tables order by a mixed hash; a
                # collision can split a group — exact-dedup on host
                merged = self._merge_partials([merged])
                b_m = _partial_nbytes(merged)
                tracker.consume(b_m)
                tracker.release(tracked)
                tracked = b_m
            self._emit_merged(merged, cap)
        finally:
            tracker.release(tracked)

    def _run_generic_resident(self, run_list, cat, cap):
        group_exprs, aggs = self.group_exprs, self.aggs
        total = sum(rows for _, rows in run_list)
        keys = [cat(f"k{k}.d") for k in range(len(group_exprs))]
        kvalids = [cat(f"k{k}.v") for k in range(len(group_exprs))]
        avals = [cat(f"a{j}.d") for j in range(len(aggs))]
        avalids = [cat(f"a{j}.v") for j in range(len(aggs))]

        if keys:
            cols = ([self._to_int64_bits(k, kv) for k, kv in zip(keys, kvalids)]
                    + [kv.astype(np.int64) for kv in kvalids])
            ngroups, first_idx, inverse = _lexsort_groups(cols)
        else:
            ngroups = 1
            inverse = np.zeros(total, dtype=np.int64)
            first_idx = np.zeros(1, dtype=np.int64)

        out_arrays: Dict[str, tuple] = {}
        for uid, k, kv, c in zip(self.group_uids, keys, kvalids, self.schema):
            out_arrays[uid] = (k[first_idx].astype(c.type_.np_dtype), kv[first_idx])

        for a, vals, valids in zip(self.aggs, avals, avalids):
            out_arrays[a.uid] = self._generic_agg(a, vals, valids, inverse, ngroups)

        self._chunks_from_host(out_arrays, ngroups, cap)

    def _external_range_merge(self, run_list, cap, tracker, budget) -> None:
        """External grouped aggregation: spill each run's key-sorted
        partial to disk, then merge and emit one KEY RANGE at a time.
        Ranges slice on the first key column (the lexsorted mat's major
        key), so every run contributes a contiguous, cheap-to-load
        mmap slice; resident state is ~total/ranges instead of total."""
        from tidb_tpu.utils.memory import SpillFile
        from tidb_tpu.utils.metrics import EXTERNAL_AGG

        EXTERNAL_AGG.inc()

        flat_files = []  # (SpillFile, state field names per agg)
        total = 0
        nk = len(self.group_exprs)
        # sub-slice runs so even a near-unique-key partial stays inside
        # the budget while it is being built
        step = max((budget // 8) // 64 if budget else (1 << 20), 1 << 13)
        for loader, rows in run_list:
            for i0 in range(0, rows, step):
                i1 = min(i0 + step, rows)

                def sub(name, _l=loader, _a=i0, _b=i1):
                    return np.asarray(_l(name))[_a:_b]

                p = self._partial_states(sub)
                b = _partial_nbytes(p)
                tracker.consume(b)
                arrays = {"mat": p["mat"]}
                for ki in range(nk):
                    arrays[f"k{ki}"] = p["keys"][ki]
                    arrays[f"kv{ki}"] = p["kvalids"][ki]
                for j, st in enumerate(p["states"]):
                    for f, a in st.items():
                        arrays[f"s{j}.{f}"] = a
                fields = [sorted(st.keys()) for st in p["states"]]
                flat_files.append((SpillFile(arrays), fields))
                total += b
                tracker.release(b)
                del p, arrays
        try:
            # pivots: quantiles of the major key, estimated from a
            # BOUNDED per-file sample (each file's mat[:, 0] is already
            # sorted, so a strided sample is itself quantile-spaced) —
            # materializing every group's key here would allocate the
            # very state the budget forbids (round-5 review)
            per_range = max(budget // 8, 1 << 17)
            n_ranges = max(1, int(np.ceil(total / per_range)))
            if nk and n_ranges > 1:
                samples = []
                for f, _ in flat_files:
                    col0 = np.asarray(f.load("mat"))[:, 0]
                    stride = max(len(col0) // 256, 1)
                    samples.append(np.array(col0[::stride]))
                majors = np.concatenate(samples)
                majors.sort()
                qs = np.linspace(0, len(majors) - 1, n_ranges + 1)[1:-1]
                pivots = np.unique(majors[qs.astype(np.int64)])
            else:
                # keyless partials have a single logical group: one range
                pivots = np.zeros(0, dtype=np.int64)
            bounds = ([None] + list(pivots), list(pivots) + [None])
            for lo, hi in zip(*bounds):
                slices = []
                sliced_bytes = 0
                for f, fields in flat_files:
                    mat = np.asarray(f.load("mat"))
                    col0 = mat[:, 0] if mat.shape[1] else mat[:, :0]
                    a = 0 if lo is None else int(
                        np.searchsorted(col0, lo, "left"))
                    b_ = len(mat) if hi is None else int(
                        np.searchsorted(col0, hi, "left"))
                    if a >= b_:
                        continue
                    p = {
                        "mat": mat[a:b_],
                        "keys": [np.asarray(f.load(f"k{ki}"))[a:b_]
                                 for ki in range(nk)],
                        "kvalids": [np.asarray(f.load(f"kv{ki}"))[a:b_]
                                    for ki in range(nk)],
                        "states": [
                            {fl: np.asarray(f.load(f"s{j}.{fl}"))[a:b_]
                             for fl in fields[j]}
                            for j in range(len(fields))],
                    }
                    sliced_bytes += _partial_nbytes(p)
                    slices.append(p)
                if not slices:
                    continue
                tracker.consume(sliced_bytes)
                try:
                    merged = (slices[0] if len(slices) == 1
                              else self._merge_partials(slices))
                    self._emit_merged(merged, cap)
                finally:
                    tracker.release(sliced_bytes)
        finally:
            for f, _ in flat_files:
                f.close()

    def _partial_states(self, loader):
        """Groupby one run into (group key table, mergeable agg states)."""
        nk = len(self.group_exprs)
        keys = [np.asarray(loader(f"k{k}.d")) for k in range(nk)]
        kvalids = [np.asarray(loader(f"k{k}.v")) for k in range(nk)]
        n = len(keys[0]) if keys else len(np.asarray(loader("a0.d")))
        if keys:
            cols = ([self._to_int64_bits(k, kv) for k, kv in zip(keys, kvalids)]
                    + [kv.astype(np.int64) for kv in kvalids])
            g, first_idx, inverse = _lexsort_groups(cols)
            uniq = np.stack([c[first_idx] for c in cols], axis=1)
        else:
            uniq = np.zeros((1, 0), dtype=np.int64)
            inverse = np.zeros(n, dtype=np.int64)
            g = 1
            first_idx = np.zeros(1, dtype=np.int64)
        states = []
        for j, a in enumerate(self.aggs):
            vals = np.asarray(loader(f"a{j}.d"))
            ok = np.asarray(loader(f"a{j}.v")).astype(np.bool_)
            cnt = np.zeros(g, dtype=np.int64)
            np.add.at(cnt, inverse[ok], 1)
            st = {"cnt": cnt}
            if needs_sum_limbs(a):
                st["sum"], st["sumhi"] = scatter_limbs(
                    vals[ok], inverse[ok], g)
            elif a.func in ("sum", "avg"):
                dt = np.float64 if a.arg.type_.kind == TypeKind.FLOAT else np.int64
                s = np.zeros(g, dtype=dt)
                np.add.at(s, inverse[ok], vals[ok])
                st["sum"] = s
            elif a.func == "min":
                m = np.full(g, _min_identity(vals.dtype), dtype=vals.dtype)
                np.minimum.at(m, inverse[ok], vals[ok])
                st["min"] = m
            elif a.func == "max":
                m = np.full(g, _max_identity(vals.dtype), dtype=vals.dtype)
                np.maximum.at(m, inverse[ok], vals[ok])
                st["max"] = m
            elif a.func in _BIT_AGGS:
                op, ident = _BIT_AGGS[a.func]
                m = np.full(g, ident, dtype=np.int64)
                op.at(m, inverse[ok], vals[ok].astype(np.int64))
                st[a.func] = m
            elif a.func in _VAR_AGGS:
                v = vals[ok]
                if a.arg.type_.kind == TypeKind.DECIMAL:
                    v = v.astype(np.float64) / (10 ** a.arg.type_.scale)
                _, s, m2 = _var_m2(v, inverse[ok], g)
                st["vsum"] = s
                st["vm2"] = m2
            elif a.func == "group_concat":
                raise ExecutionError(
                    "GROUP_CONCAT exceeded the in-memory aggregation "
                    "budget (spill partials are not supported for it); "
                    "raise tidb_mem_quota_query")
            states.append(st)
        return {
            "mat": uniq,
            "keys": [k[first_idx] for k in keys],
            "kvalids": [kv[first_idx] for kv in kvalids],
            "states": states,
        }

    def _merge_partials(self, partials):
        """Merge partial group tables into one (final-agg merge step)."""
        mats = np.concatenate([p["mat"] for p in partials], axis=0)
        ntotal = len(mats)
        if mats.shape[1]:
            ngroups, first_idx, inverse = _lexsort_groups(
                [mats[:, j] for j in range(mats.shape[1])])
            uniq = mats[first_idx]
        else:
            uniq = np.zeros((1, 0), dtype=np.int64)
            ngroups = 1
            inverse = np.zeros(ntotal, dtype=np.int64)
            first_idx = np.zeros(1, dtype=np.int64)

        nk = len(self.group_exprs)
        keys, kvalids = [], []
        for ki in range(nk):
            kcat = np.concatenate([p["keys"][ki] for p in partials])
            vcat = np.concatenate([p["kvalids"][ki] for p in partials])
            keys.append(kcat[first_idx])
            kvalids.append(vcat[first_idx])

        states = []
        for j, a in enumerate(self.aggs):
            cnt = np.zeros(ngroups, dtype=np.int64)
            np.add.at(cnt, inverse, np.concatenate([p["states"][j]["cnt"] for p in partials]))
            st = {"cnt": cnt}
            if a.func in ("sum", "avg"):
                parts = np.concatenate([p["states"][j]["sum"] for p in partials])
                s = np.zeros(ngroups, dtype=parts.dtype)
                np.add.at(s, inverse, parts)
                st["sum"] = s
                if "sumhi" in partials[0]["states"][j]:
                    ph = np.concatenate(
                        [p["states"][j]["sumhi"] for p in partials])
                    h = np.zeros(ngroups, dtype=np.int64)
                    np.add.at(h, inverse, ph)
                    # carry-normalize per merge so lo never wraps across
                    # arbitrarily deep merge chains (streaming batches)
                    st["sum"], st["sumhi"] = normalize_limbs(s, h)
            elif a.func in ("min", "max"):
                op, ident = (
                    (np.minimum, _min_identity) if a.func == "min" else (np.maximum, _max_identity)
                )
                parts = np.concatenate([p["states"][j][a.func] for p in partials])
                m = np.full(ngroups, ident(parts.dtype), dtype=parts.dtype)
                op.at(m, inverse, parts)
                st[a.func] = m
            elif a.func in _BIT_AGGS:
                op, ident = _BIT_AGGS[a.func]
                parts = np.concatenate([p["states"][j][a.func] for p in partials])
                m = np.full(ngroups, ident, dtype=np.int64)
                op.at(m, inverse, parts)
                st[a.func] = m
            elif a.func in _VAR_AGGS:
                # exact m2 combine: sum_i [m2_i + n_i (mean_i - mean)^2]
                # == sum over all x of (x - mean)^2
                pc = np.concatenate(
                    [p["states"][j]["cnt"] for p in partials]).astype(np.float64)
                ps = np.concatenate([p["states"][j]["vsum"] for p in partials])
                pm2 = np.concatenate([p["states"][j]["vm2"] for p in partials])
                tot_s = np.zeros(ngroups, dtype=np.float64)
                np.add.at(tot_s, inverse, ps)
                with np.errstate(divide="ignore", invalid="ignore"):
                    mean_t = np.where(cnt > 0, tot_s / np.maximum(cnt, 1), 0.0)
                    mean_i = np.where(pc > 0, ps / np.maximum(pc, 1), 0.0)
                m2 = np.zeros(ngroups, dtype=np.float64)
                np.add.at(m2, inverse,
                          pm2 + pc * (mean_i - mean_t[inverse]) ** 2)
                st["vsum"] = tot_s
                st["vm2"] = m2
            states.append(st)
        return {"mat": uniq, "keys": keys, "kvalids": kvalids, "states": states}

    def _emit_merged(self, merged, cap):
        """Finalize a merged partial table into output chunks."""
        ngroups = len(merged["mat"]) if merged["mat"].shape[1] else 1
        out_arrays: Dict[str, tuple] = {}
        nk = len(self.group_exprs)
        for ki, (uid, c) in enumerate(zip(self.group_uids, self.schema[:nk])):
            out_arrays[uid] = (
                merged["keys"][ki].astype(c.type_.np_dtype),
                merged["kvalids"][ki],
            )
        for j, a in enumerate(self.aggs):
            st = merged["states"][j]
            cnt = st["cnt"]
            if a.func == "count":
                out_arrays[a.uid] = (cnt, np.ones(ngroups, dtype=np.bool_))
            elif a.func == "sum":
                s = st["sum"]
                if "sumhi" in st:
                    s = combine_limbs_exact(s, st["sumhi"])
                out_arrays[a.uid] = (s.astype(a.type_.np_dtype), cnt > 0)
            elif a.func == "avg":
                sf = (limbs_to_float(st["sum"], st["sumhi"])
                      if "sumhi" in st else st["sum"].astype(np.float64))
                if a.arg.type_.kind == TypeKind.DECIMAL:
                    sf = sf / (10 ** a.arg.type_.scale)
                with np.errstate(divide="ignore", invalid="ignore"):
                    avg = np.where(cnt > 0, sf / np.maximum(cnt, 1), 0.0)
                out_arrays[a.uid] = (avg, cnt > 0)
            elif a.func in _BIT_AGGS:
                out_arrays[a.uid] = (st[a.func],
                                     np.ones(ngroups, dtype=np.bool_))
            elif a.func in _VAR_AGGS:
                out_arrays[a.uid] = _var_finalize(a.func, cnt, st["vm2"])
            else:
                out_arrays[a.uid] = (st[a.func].astype(a.type_.np_dtype), cnt > 0)
        self._chunks_from_host(out_arrays, ngroups, cap)

    def close(self) -> None:
        if getattr(self, "_runs", None) is not None:
            self._runs.close()
            self._runs = None
        super().close()

    @staticmethod
    def _to_int64_bits(arr: np.ndarray, valid: np.ndarray) -> np.ndarray:
        a = np.where(valid, arr, 0)
        if np.issubdtype(a.dtype, np.floating):
            return a.astype(np.float64).view(np.int64)
        return a.astype(np.int64)

    def _generic_agg(self, a: AggSpec, vals, valids, inverse, ngroups):
        ok = valids.astype(np.bool_)
        if a.func == "group_concat":
            return self._group_concat(a, vals, ok, inverse, ngroups)
        if a.distinct:
            if a.func not in ("count", "sum", "avg", "min", "max",
                              "bit_and", "bit_or", "bit_xor") + _VAR_AGGS:
                raise UnsupportedError(f"DISTINCT {a.func}")
            bits = self._to_int64_bits(vals, ok)
            trip = np.stack([inverse[ok], bits[ok]], axis=1)
            uniq = np.unique(trip, axis=0)
            inverse = uniq[:, 0]
            vals = uniq[:, 1].astype(vals.dtype) if not np.issubdtype(vals.dtype, np.floating) else uniq[:, 1].view(np.float64)
            ok = np.ones(len(vals), dtype=np.bool_)

        cnt = np.zeros(ngroups, dtype=np.int64)
        np.add.at(cnt, inverse[ok], 1)
        if a.func == "count":
            return cnt, np.ones(ngroups, dtype=np.bool_)
        if a.func in ("sum", "avg"):
            if a.arg.type_.kind == TypeKind.DECIMAL:
                # two-limb exact host accumulation (same scheme as the
                # device states — see split_limbs)
                lo, hi = scatter_limbs(vals[ok], inverse[ok], ngroups)
                if a.func == "sum":
                    return (combine_limbs_exact(lo, hi).astype(
                        a.type_.np_dtype), cnt > 0)
                s = limbs_to_float(lo, hi) / (10 ** a.arg.type_.scale)
                with np.errstate(divide="ignore", invalid="ignore"):
                    return np.where(cnt > 0, s / np.maximum(cnt, 1), 0.0), cnt > 0
            s = np.zeros(ngroups, dtype=np.int64 if a.arg.type_.kind != TypeKind.FLOAT else np.float64)
            np.add.at(s, inverse[ok], vals[ok])
            if a.func == "sum":
                return s.astype(a.type_.np_dtype), cnt > 0
            s = s.astype(np.float64)
            with np.errstate(divide="ignore", invalid="ignore"):
                return np.where(cnt > 0, s / np.maximum(cnt, 1), 0.0), cnt > 0
        if a.func == "min":
            m = np.full(ngroups, _min_identity(vals.dtype), dtype=vals.dtype)
            np.minimum.at(m, inverse[ok], vals[ok])
            return m.astype(a.type_.np_dtype), cnt > 0
        if a.func == "max":
            m = np.full(ngroups, _max_identity(vals.dtype), dtype=vals.dtype)
            np.maximum.at(m, inverse[ok], vals[ok])
            return m.astype(a.type_.np_dtype), cnt > 0
        if a.func in _BIT_AGGS:
            op, ident = _BIT_AGGS[a.func]
            m = np.full(ngroups, ident, dtype=np.int64)
            op.at(m, inverse[ok], vals[ok].astype(np.int64))
            # MySQL BIT_* ignore NULLs and never return NULL; an empty
            # group keeps the identity (BIT_AND of nothing = all ones —
            # we keep the int64 bit pattern of the unsigned value)
            return m, np.ones(ngroups, dtype=np.bool_)
        if a.func in _VAR_AGGS:
            v = vals[ok]
            if a.arg.type_.kind == TypeKind.DECIMAL:
                v = v.astype(np.float64) / (10 ** a.arg.type_.scale)
            gcnt, _, m2 = _var_m2(v, inverse[ok], ngroups)
            return _var_finalize(a.func, gcnt, m2)
        raise ExecutionError(f"unknown aggregate {a.func}")

    def _gc_strings(self, a: AggSpec, vv: np.ndarray):
        """Decode GROUP_CONCAT argument values to their MySQL string
        forms (strings via the argument's dictionary; numerics/temporals
        formatted host-side)."""
        k = a.arg.type_.kind
        if k in (TypeKind.STRING, TypeKind.JSON):
            d = getattr(a.arg, "_dict", None)
            if d is None:
                raise UnsupportedError("GROUP_CONCAT over dictionary-less string")
            vals = d.values
            return [vals[int(c)] for c in vv]
        if k == TypeKind.DECIMAL:
            # integer divmod keeps scaled values > 2^53 exact (float
            # formatting would round them)
            s = a.arg.type_.scale
            f = 10 ** s

            def fmt(v):
                v = int(v)
                sign = "-" if v < 0 else ""
                q, r = divmod(abs(v), f)
                return f"{sign}{q}.{r:0{s}d}" if s else f"{sign}{q}"

            return [fmt(v) for v in vv]
        if k == TypeKind.FLOAT:
            return [repr(float(v)) for v in vv]
        if k in (TypeKind.INT, TypeKind.BOOL):
            return [str(int(v)) for v in vv]
        raise UnsupportedError(f"GROUP_CONCAT over {a.arg.type_}")

    def _group_concat(self, a: AggSpec, vals, ok, inverse, ngroups):
        """GROUP_CONCAT(x [ORDER BY x [DESC]] [SEPARATOR s]): per-group
        string joins on the host generic path. The output dictionary is
        a RuntimeDictionary filled per execution (result strings cannot
        exist at plan time)."""
        sep, order_desc, rdict = a.extra
        gi = inverse[ok]
        vv = np.asarray(vals)[ok]
        if order_desc is None:
            perm = np.argsort(gi, kind="stable")  # keep input order
        else:
            vkey = np.argsort(vv, kind="stable")
            if order_desc:
                vkey = vkey[::-1]
            perm = vkey[np.argsort(gi[vkey], kind="stable")]
        gi, vv = gi[perm], vv[perm]
        if a.distinct and len(gi):
            keep = np.ones(len(gi), dtype=np.bool_)
            seen = {}
            for i, (g, v) in enumerate(zip(gi.tolist(), vv.tolist())):
                if (g, v) in seen:
                    keep[i] = False
                seen[(g, v)] = True
            gi, vv = gi[keep], vv[keep]
        strs = self._gc_strings(a, vv)
        out = [None] * ngroups
        starts = np.flatnonzero(np.diff(gi, prepend=-1)) if len(gi) else []
        max_len = self.ctx.group_concat_max_len
        for si, s0 in enumerate(starts):
            s1 = starts[si + 1] if si + 1 < len(starts) else len(gi)
            joined = sep.join(strs[s0:s1])
            out[int(gi[s0])] = joined[: max_len]
        valid = np.array([o is not None for o in out], dtype=np.bool_)
        rdict.fill([o for o in out if o is not None])
        codes = np.array([rdict.code_of(o) if o is not None else 0
                          for o in out], dtype=np.int32)
        return codes, valid
