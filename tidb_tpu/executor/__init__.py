"""Executor (ref: executor/ — the Open/Next/Close Volcano operators).

The reference pulls 1024-row chunks through per-operator Next() calls with
goroutine pipelines inside the heavy operators. The TPU redesign keeps the
pull protocol at the Python level (operator scheduling, memory control)
but fuses all map-style work between pipeline breakers into single jitted
device fragments:

  scan.py      -- TableScanExec: partition streaming + fused filter/project
                  fragment (the coprocessor analogue)
  aggregate.py -- HashAggExec: packed-code segment strategy on device, or
                  generic host groupby fallback
  join.py      -- HashJoinExec: device sort+searchsorted build/probe with
                  static-capacity windowed expansion
  sort.py      -- SortExec / TopNExec / LimitExec / UnionExec (root, host)
  pipeline.py  -- FusedScanAggExec: push-based scan→filter→project→
                  partial-agg fragments (one program per chunk, device
                  state, one finalize fetch), double-buffered staging,
                  cross-statement device buffer cache (ISSUE 9)
  builder.py   -- physical plan -> executor tree (ref: executorBuilder)
  base.py      -- Executor protocol, ExecContext, ResultSet, RuntimeStats
"""

from tidb_tpu.executor.base import ExecContext, Executor, ResultSet, run_plan
from tidb_tpu.executor.builder import build_executor

__all__ = ["ExecContext", "Executor", "ResultSet", "build_executor", "run_plan"]
