"""Pipelined device-resident fragment execution (ISSUE 9 / ROADMAP 3).

Three pieces collapse the per-chunk host ping-pong of the single-chip
executor spine into a push-based, device-resident pipeline:

  * ``FusedScanAggExec`` — scan→filter→project→partial-agg as ONE
    module-level jitted program per chunk. The scan's staged inputs
    (encoded segment payloads or raw slices) and the running agg state
    are the only things that cross the jit boundary; the [G]-shaped
    (segment strategy) or group-table (generic strategy) state
    accumulates ON DEVICE across chunks and is fetched exactly once at
    finalize. Columnar segments pack SEVERAL per batch at a fixed
    ``seg_cap`` stride inside one capacity-sized buffer, so a fragment
    over a 64k-row segment store still issues ~n/chunk_capacity
    dispatches, not one per segment.

  * ``ChunkPrefetcher`` — double-buffered host→device staging: while
    chunk *k* computes, a staging thread builds chunk *k+1*'s host
    buffers and ``jax.device_put``s them, with the in-flight window
    bounded by ``tidb_tpu_pipeline_prefetch_depth`` and charged to the
    statement MemTracker. KILL/deadline is polled inside the thread
    (``raise_if_cancelled``) so a cancelled statement stops staging,
    not just computing.

  * ``DeviceBufferCache`` — staged scan inputs kept device-resident
    ACROSS statements, keyed and invalidated exactly like the plan
    cache: any ``catalog.schema_version`` bump clears it eagerly (the
    same hook that clears the plan cache), and per-entry identity pins
    ``Table.version`` / ``Table.data_epoch`` / the stats object / the
    segment store generation, so DML, DDL, ANALYZE and TRUNCATE all
    invalidate. A warm TPC-H Q1/Q6 re-run stages nothing.

ISSUE 10 extends fusion past aggregation roots: ``FusedScanProbeExec``
runs an inner hash join's probe side — decode + filter + project + key
pack + probe + first-tile expansion — as ONE jitted program per staged
chunk against a device-resident build table, with the build side itself
parked in the ``DeviceBufferCache`` so a warm repeated join stages and
sorts nothing. See the class docstring for the overflow/deferral
contract.

Glue (finalize, result decode) still runs under ``host_eager`` like the
rest of the executor tier; the staging device is pinned in the MAIN
thread (the prefetch thread does not inherit jax's thread-local default
device) so buffers always land where the fused program runs.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.chunk.column import Column
from tidb_tpu.executor.aggregate import HashAggExec, make_segment_kernel
from tidb_tpu.executor.base import ExecContext, Executor, raise_if_cancelled
from tidb_tpu.executor.join import HashJoinExec
from tidb_tpu.ops import join_kernels as jk
from tidb_tpu.utils.jitcache import cached_jit
from tidb_tpu.utils.memory import QueryOOMError

__all__ = ["DEVICE_CACHE", "DeviceBufferCache", "ChunkPrefetcher",
           "FusedScanAggExec", "FusedScanProbeExec", "FusedScanTopNExec",
           "table_ident"]


def table_ident(table) -> tuple:
    """Everything a cached staged buffer's validity depends on — the
    plan cache's invalidation axes applied to data instead of plans:
    ``version`` moves on every DML (and TRUNCATE), ``data_epoch`` on
    in-place rewrites (column DDL, gc compaction, dict re-encode), the
    stats identity on ANALYZE, and the segment-store generation/coverage
    on columnar rebuilds. Schema-version bumps clear the whole cache
    eagerly via the catalog hook instead."""
    base = getattr(table, "_base", table)
    stats = getattr(base, "stats", None)
    store = getattr(base, "_segment_store", None)
    return (
        getattr(base, "version", None),
        getattr(base, "data_epoch", None),
        None if stats is None else (id(stats), stats.version),
        None if store is None else (store.generation, store.covered),
        getattr(table, "n", None),
    )


def _pytree_nbytes(tree) -> int:
    return int(sum(getattr(leaf, "nbytes", 0)
                   for leaf in jax.tree_util.tree_leaves(tree)))


class DeviceBufferCache:
    """Process-global LRU of staged device scan inputs.

    One entry = one (table, staging layout) pair holding the full list
    of staged per-chunk pytrees a fused fragment consumed, plus the
    identity tuple that proves them current. The entry pins the table
    object (like plan-cache entries) so a recycled ``id()`` can never
    alias a different table; the byte budget
    (``tidb_tpu_device_buffer_cache_bytes``) bounds resident bytes with
    LRU eviction."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, dict]" = OrderedDict()
        self._bytes = 0

    def _count(self, kind: str, n: int = 1) -> None:
        from tidb_tpu.utils.metrics import DEVICE_CACHE_TOTAL

        DEVICE_CACHE_TOTAL.inc(n, kind=kind)

    def get(self, table, tag, ident) -> Optional[List]:
        key = (id(table), tag)
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e["table"] is table and e["ident"] == ident:
                self._entries.move_to_end(key)
                self._count("hit")
                return e["chunks"]
            if e is not None:
                # same statement shape, stale data: the plan cache's
                # stats/DML invalidation analogue
                self._bytes -= e["nbytes"]
                del self._entries[key]
                self._count("invalidate")
        self._count("miss")
        return None

    def put(self, table, tag, ident, chunks: List, nbytes: int,
            budget: int) -> None:
        if budget <= 0 or nbytes > budget:
            return
        key = (id(table), tag)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old["nbytes"]
            self._entries[key] = {"table": table, "ident": ident,
                                  "chunks": chunks, "nbytes": int(nbytes)}
            self._bytes += int(nbytes)
            while self._bytes > budget and len(self._entries) > 1:
                _k, ev = self._entries.popitem(last=False)
                self._bytes -= ev["nbytes"]
                self._count("evict")

    def on_schema_change(self) -> None:
        """Eager clear on any catalog.schema_version bump (DDL) — the
        exact hook the plan cache invalidates through."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
        if n:
            self._count("invalidate", n)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


DEVICE_CACHE = DeviceBufferCache()


class ChunkPrefetcher:
    """Double-buffered host→device staging ahead of the compute loop.

    ``jobs`` is an ordered list of zero-arg callables, each returning
    one chunk's HOST pytree (numpy buffers). A daemon thread runs them
    in order, ``jax.device_put``s the result onto the staging device
    captured in the constructor (thread-locals like ``host_eager`` do
    not cross threads), and parks when ``depth`` buffers sit staged but
    unconsumed. In-flight staged bytes are charged to the statement
    MemTracker — a tight ``tidb_mem_quota_query`` surfaces as the same
    typed OOM/spill behavior as any other operator state. KILL and
    statement deadlines are polled before every job AND while parked,
    so a cancelled statement never keeps staging in the background."""

    POLL_S = 0.05

    def __init__(self, jobs: List[Callable], ctx: ExecContext, stats=None):
        from tidb_tpu.utils.device import host_cpu_device

        self.jobs = jobs
        self.ctx = ctx
        self.stats = stats
        self.depth = max(int(getattr(ctx, "prefetch_depth", 0) or 0), 0)
        self.tracker = ctx.mem_tracker.child("pipeline.prefetch")
        self._device = host_cpu_device()  # None = default backend is CPU
        self._staged: Dict[int, Tuple[object, int]] = {}
        self._err: Optional[BaseException] = None
        self._next_get = 0
        self._cv = threading.Condition()
        self._stop = False
        self._thread = None
        if self.depth > 0 and len(jobs) > 1:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tidb-tpu-prefetch")
            self._thread.start()

    # -- staging -----------------------------------------------------------

    def _stage(self, host) -> Tuple[object, int]:
        from tidb_tpu.utils import dispatch as dsp
        from tidb_tpu.utils.metrics import PIPELINE_PREFETCH_BYTES

        nbytes = _pytree_nbytes(host)
        if self._device is not None:
            staged = jax.device_put(host, self._device)
        else:
            staged = jax.device_put(host)
        dsp.record(site="stage")
        PIPELINE_PREFETCH_BYTES.inc(nbytes)
        return staged, nbytes

    def _run(self) -> None:
        from tidb_tpu.utils.metrics import PIPELINE_PREFETCH_TOTAL

        try:
            for i, job in enumerate(self.jobs):
                with self._cv:
                    while (not self._stop
                           and i - self._next_get >= self.depth):
                        # parked on a full window: keep honoring
                        # KILL/deadline while the consumer computes
                        raise_if_cancelled(self.ctx)
                        self._cv.wait(self.POLL_S)
                    if self._stop:
                        return
                raise_if_cancelled(self.ctx)
                staged, nbytes = self._stage(job())
                self.tracker.consume(nbytes)  # typed OOM propagates below
                with self._cv:
                    if self._stop:
                        self.tracker.release(nbytes)
                        return
                    self._staged[i] = (staged, nbytes)
                    self._cv.notify_all()
        except BaseException as e:  # noqa: BLE001 — relayed to the
            # consumer thread verbatim via get(); the typed
            # kill/deadline/OOM classification must survive the hop
            from tidb_tpu.errors import QueryKilledError, QueryTimeoutError

            # keep the counter honest: "cancelled" means KILL/deadline
            # stopped staging; quota OOM or a staging bug is "error"
            cancelled = isinstance(e, (QueryKilledError, QueryTimeoutError))
            PIPELINE_PREFETCH_TOTAL.inc(
                outcome="cancelled" if cancelled else "error")
            with self._cv:
                self._err = e
                self._cv.notify_all()

    # -- consumption -------------------------------------------------------

    def get(self, i: int):
        """Chunk i's staged device pytree, blocking on in-flight staging."""
        from tidb_tpu.utils import dispatch as dsp
        from tidb_tpu.utils.metrics import PIPELINE_PREFETCH_TOTAL

        if self._thread is None:
            staged, nbytes = self._stage(self.jobs[i]())
            dsp.record_xfer(nbytes, "h2d")
            PIPELINE_PREFETCH_TOTAL.inc(outcome="inline")
            return staged
        with self._cv:
            self._next_get = max(self._next_get, i + 1)
            self._cv.notify_all()
            ready = i in self._staged
            while i not in self._staged and self._err is None:
                raise_if_cancelled(self.ctx)
                self._cv.wait(self.POLL_S)
            if i not in self._staged:
                raise self._err
            staged, nbytes = self._staged.pop(i)
            self._cv.notify_all()
        self.tracker.release(nbytes)
        # h2d accounting lands HERE (the consuming statement thread),
        # not in _stage on the daemon thread — the thread-local profile
        # must attribute the staged bytes to the statement that asked
        dsp.record_xfer(nbytes, "h2d")
        PIPELINE_PREFETCH_TOTAL.inc(outcome="hit" if ready else "wait")
        if ready and self.stats is not None:
            self.stats.staged += 1
        return staged

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
            leftover = sum(n for _v, n in self._staged.values())
            self._staged.clear()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if leftover:
            self.tracker.release(leftover)


# ---------------------------------------------------------------------------
# fused scan→filter→project→partial-agg programs
# ---------------------------------------------------------------------------


def _barrier_chunk(chunk):
    """Materialization boundary between the scan pipeline and the agg
    update INSIDE the fused program. Without it XLA fuses the
    decode+filter+projection chain into every aggregate consumer and
    recomputes it once per state array — a fused Q1 measured ~1.5x
    SLOWER than the two-dispatch tree it replaced. The barrier keeps
    one kernel launch while pinning the scan's outputs to be computed
    once, exactly like the unfused path's intermediate chunk."""
    return jax.tree_util.tree_map(jax.lax.optimization_barrier, chunk)


def _make_fused_segment_fn(stages, col_types, group_exprs, aggs, domains,
                           seg_cap: Optional[int]):
    """(state, data, valid, refs, sel) -> state: decode + pipeline +
    segment-agg update as ONE program.

    Batches whose length is a multiple of ``seg_cap`` stream through an
    INTERNAL ``lax.scan`` over seg_cap-sized blocks: one device
    dispatch covers the whole packed batch (the single-digit dispatch
    budget) while each scan step touches only a cache-sized block —
    running the update over a monolithic 1M-row batch measurably lost
    to the chunk-synced path on XLA:CPU purely on locality (its 64k
    chunks stayed L2-resident). Per-step FoR refs arrive as scan-sliced
    scalars, so the decode is a scalar add, not a gather."""
    from tidb_tpu.ops.segment_scan import make_segment_scan_fn

    scan_fn = make_segment_scan_fn(stages, col_types, seg_stride=seg_cap)
    _init, update, _g = make_segment_kernel(group_exprs, aggs, domains)

    def run(state, data, valid, refs, sel):
        n = sel.shape[0]
        if not seg_cap or n <= seg_cap or n % seg_cap:
            return update(state, _barrier_chunk(scan_fn(data, valid, refs,
                                                        sel)))
        k = n // seg_cap
        bdata = {u: d.reshape((k, seg_cap) + d.shape[1:])
                 for u, d in data.items()}
        bvalid = {u: v.reshape(k, seg_cap) for u, v in valid.items()}
        bsel = sel.reshape(k, seg_cap)

        def step(st, xs):
            d, v, r, sl = xs
            return update(st, _barrier_chunk(scan_fn(d, v, r, sl))), None

        state, _ = jax.lax.scan(step, state, (bdata, bvalid, refs, bsel))
        return state

    return run


def _make_fused_generic_fn(stages, col_types, group_exprs, aggs,
                           seg_cap: Optional[int]):
    """(data, valid, refs, sel) -> group table: decode + pipeline +
    sort-based partial grouping as ONE program. No internal blocking
    here: the partial is a whole-batch sort (one big lax.sort beats
    per-block sorts + extra merge levels), and its output shape is the
    input capacity — per-block tables would just re-create the stack's
    merge work inside the program."""
    from tidb_tpu.executor.agg_device import make_partial_kernel
    from tidb_tpu.ops.segment_scan import make_segment_scan_fn

    scan_fn = make_segment_scan_fn(stages, col_types, seg_stride=seg_cap)
    partial = make_partial_kernel(group_exprs, aggs)

    def run(data, valid, refs, sel):
        return partial(_barrier_chunk(scan_fn(data, valid, refs, sel)))

    return run


class _StagedScanMixin:
    """The scan side of a fused fragment, shared by ``FusedScanAggExec``
    and ``FusedScanProbeExec``: plan the ordered chunk staging schedule
    (packed columnar segments with zone-map pruning, raw slices for the
    uncovered tail), stream the staged device pytrees through the
    prefetcher, and ride the cross-statement ``DeviceBufferCache``.
    Requires ``table``, ``scan_schema``, ``prune_bounds``, ``ctx``,
    ``stats``, and the ``_pin``/``_prefetcher``/``_seg_cap`` slots."""

    def _release_staging(self) -> None:
        it = getattr(self, "_staged_iter", None)
        if it is not None:
            it.close()  # runs the generator's finally (fill release)
            self._staged_iter = None
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
        if self._pin is not None:
            self._pin.close()
            self._pin = None
        if getattr(self, "_staged_scan_counted", False):
            self._staged_scan_counted = False
            self.table.txn_guard.scan_exit()

    # -- staging plan ------------------------------------------------------

    def _plan_staging(self, ctx: ExecContext):
        """The ordered chunk staging schedule (a list of zero-arg host
        staging jobs). Columnar
        segments pack ``k = capacity // seg_cap`` per batch at a fixed
        stride; the uncovered delta tail stages as raw capacity-sized
        slices. Zone maps prune segments BEFORE anything is staged,
        exactly like the unfused scan."""
        cap = ctx.chunk_capacity
        table = self.table
        # count as an open scan for the staging window: raw-tail slices
        # and live_mask reads hit the table's live arrays lock-free, so
        # a CLUSTER BY permute must refuse until _release_staging runs
        guard = getattr(table, "txn_guard", None)
        if guard is not None and not getattr(
                self, "_staged_scan_counted", False):
            guard.scan_enter()
            self._staged_scan_counted = True
        jobs = []
        tail_start = 0
        self._seg_cap = None
        if ctx.columnar_enable:
            from tidb_tpu.columnar.store import ScanPin, store_for

            store = store_for(
                table, segment_rows=ctx.segment_rows,
                delta_rows=ctx.segment_delta_rows,
                spill_dir=ctx.columnar_spill_dir or None,
                compaction=ctx.compaction_enable)
            if store is not None:
                self._pin = ScanPin(store, ctx.mem_tracker)
                segs, pruned, covered = store.plan_scan(
                    self.prune_bounds, pin=self._pin)
                self.stats.segs_scanned += len(segs)
                self.stats.segs_pruned += pruned
                tail_start = covered
                seg_cap = 1
                while seg_cap < min(store.segment_rows, cap):
                    seg_cap *= 2
                self._seg_cap = seg_cap
                k = max(cap // seg_cap, 1)
                slots = []
                for seg in segs:
                    for s in range(0, seg.rows, seg_cap):
                        slots.append((seg, s, min(s + seg_cap, seg.rows)))
                for i in range(0, len(slots), k):
                    batch = slots[i:i + k]
                    # the tail batch sizes to ITS slot count: padding it
                    # to k segments would run the internal scan over
                    # dead all-zero blocks (13/16 of a 1M buffer for a
                    # 3-segment tail — measured ~0.8s of pure waste)
                    jobs.append(self._seg_batch_job(batch, len(batch),
                                                    seg_cap))
                if not slots:
                    self._pin.close()  # nothing to stage: drop refs now
                    self._pin = None
        n = table.n
        for s in range(tail_start, n, cap):
            e = min(s + cap, n)
            jobs.append(self._raw_slice_job(s, e, cap))
        return jobs

    def _seg_batch_job(self, batch, k: int, seg_cap: int):
        """Stage up to k encoded segments into ONE [k * seg_cap] buffer
        set. Payloads keep their narrow encoded dtypes (promoted to the
        widest within the batch); per-segment FoR bases travel as [k]
        vectors, decoded on device against an iota-derived segment id.
        MVCC visibility is read fresh from the table's arrays."""
        table, pin, schema, ctx = self.table, self._pin, self.scan_schema, \
            self.ctx

        def job():
            bcap = k * seg_cap
            sel = np.zeros(bcap, dtype=np.bool_)
            per_col: Dict[str, list] = {c.uid: [] for c in schema}
            for j, (seg, s, e) in enumerate(batch):
                pin.touch(seg)
                off = j * seg_cap
                n = e - s
                for c in schema:
                    if c.name == "__rowid__":
                        per_col[c.uid].append(("rowid", seg.start + s, n))
                    else:
                        enc, sd, sv = seg.col(c.name)
                        # slices VIEW the (immutable) payload arrays;
                        # the views keep them alive past an eviction
                        per_col[c.uid].append((enc, sd[s:e], sv[s:e]))
                sel[off:off + n] = table.live_mask(
                    seg.start + s, seg.start + e,
                    read_ts=ctx.read_ts, marker=ctx.txn_marker)
            data, valid, refs = {}, {}, {}
            for c in schema:
                uid = c.uid
                entries = per_col[uid]
                if c.name == "__rowid__":
                    d = np.zeros(bcap, dtype=np.int64)
                    v = np.zeros(bcap, dtype=np.bool_)
                    for j, (_tag, start0, n) in enumerate(entries):
                        off = j * seg_cap
                        d[off:off + n] = np.arange(start0, start0 + n,
                                                   dtype=np.int64)
                        v[off:off + n] = True
                    data[uid], valid[uid] = d, v
                    continue
                dt = entries[0][1].dtype
                for _enc, sd, _sv in entries[1:]:
                    dt = np.promote_types(dt, sd.dtype)
                any_for = any(enc.kind == "for" for enc, _d, _v in entries)
                d = np.zeros(bcap, dtype=dt)
                v = np.zeros(bcap, dtype=np.bool_)
                rv = np.zeros(k, dtype=np.int64)
                for j, (enc, sd, sv) in enumerate(entries):
                    off = j * seg_cap
                    n = len(sd)
                    d[off:off + n] = sd
                    v[off:off + n] = sv
                    if enc.kind == "for":
                        rv[j] = enc.ref
                data[uid], valid[uid] = d, v
                if any_for:
                    refs[uid] = rv
            return data, valid, refs, sel

        return job

    def _raw_slice_job(self, s: int, e: int, cap: int):
        table, schema, ctx = self.table, self.scan_schema, self.ctx

        def job():
            n = e - s
            data, valid = {}, {}
            for c in schema:
                if c.name == "__rowid__":
                    d = np.zeros(cap, dtype=np.int64)
                    d[:n] = np.arange(s, e, dtype=np.int64)
                    v = np.zeros(cap, dtype=np.bool_)
                    v[:n] = True
                else:
                    cd, cv = table.column_slice(c.name, s, e)
                    d = np.zeros(cap, dtype=cd.dtype)
                    d[:n] = cd
                    v = np.zeros(cap, dtype=np.bool_)
                    v[:n] = cv
                data[c.uid], valid[c.uid] = d, v
            sel = np.zeros(cap, dtype=np.bool_)
            sel[:n] = table.live_mask(
                s, e, read_ts=ctx.read_ts, marker=ctx.txn_marker)
            return data, valid, {}, sel

        return job

    # -- staged chunk stream (prefetch + device buffer cache) --------------

    def _staged_chunks(self, jobs):
        """Yield staged device pytrees in chunk order: from the device
        buffer cache when a warm identical statement already staged
        them, else through the double-buffered prefetcher — filling the
        cache on the way out when everything fits the budget."""
        ctx = self.ctx
        budget = int(getattr(ctx, "device_buffer_cache_bytes", 0) or 0)
        cacheable = (budget > 0 and jobs
                     and ctx.read_ts is None and ctx.txn_marker == 0)
        tag = ident = None
        if cacheable:
            # the chunk-set descriptor (descs) is deliberately NOT part
            # of the tag: it is a deterministic function of (table
            # identity, bounds, capacities), so folding it into the
            # key would turn every DML into a silent key change (stale
            # entry leaks until LRU) instead of a counted invalidation
            tag = ("scanstage",
                   tuple((c.uid, c.name) for c in self.scan_schema),
                   ctx.chunk_capacity, self._seg_cap,
                   repr(self.prune_bounds))
            ident = table_ident(self.table)
            hit = DEVICE_CACHE.get(self.table, tag, ident)
            if hit is not None:
                self.stats.staged += len(hit)
                for staged in hit:
                    yield staged
                return
        pf = ChunkPrefetcher(jobs, ctx, stats=self.stats)
        self._prefetcher = pf
        collect: Optional[list] = [] if cacheable else None
        # the fill holds every staged buffer alive until put(): that
        # working set is charged to the STATEMENT tracker while the
        # fragment runs (ownership transfers to the process-level cache
        # at put). Quota pressure must abandon the fill, never fail the
        # query — and the fill must not even APPROACH the budget, or
        # the other consumers (prefetch window, segment pins) would OOM
        # against consumption the fill inflated: stop filling past half
        # the statement's remaining headroom.
        fill_tracker = ctx.mem_tracker.child("pipeline.cache_fill")
        stmt_budget = getattr(ctx.mem_tracker, "budget", None)
        nbytes = 0

        def abandon():
            nonlocal collect, nbytes
            collect = None
            fill_tracker.release(nbytes)
            nbytes = 0

        try:
            for i in range(len(jobs)):
                staged = pf.get(i)
                if collect is not None:
                    b = _pytree_nbytes(staged)
                    if nbytes + b > budget:
                        abandon()  # too big to pin: stream through
                    elif stmt_budget and (ctx.mem_tracker.consumed + b
                                          > stmt_budget // 2):
                        abandon()  # leave the quota to the real work
                    else:
                        try:
                            fill_tracker.consume(b)
                        except QueryOOMError:
                            abandon()
                        else:
                            nbytes += b
                            collect.append(staged)
                yield staged
            if collect is not None:
                DEVICE_CACHE.put(self.table, tag, ident, collect, nbytes,
                                 budget)
        finally:
            fill_tracker.release(nbytes)


def _collect_feedback_pairs(root) -> list:
    """(plan_node, actual_out_rows) pairs of every annotated exec in a
    transient subtree whose actual is host-known — taken BEFORE the
    subtree is dropped, so plan feedback still sees e.g. the build-side
    join a fused probe drained inside its own open()."""
    out = []
    stack = [root]
    while stack:
        e = stack.pop()
        if e is None:
            continue
        p = getattr(e, "_feedback_plan", None)
        rows = getattr(getattr(e, "stats", None), "out_rows", -1)
        if p is not None and rows >= 0:
            out.append((p, int(rows)))
        out.extend(getattr(e, "_fb_build_pairs", ()))
        stack.extend(getattr(e, "children", ()))
        stack.append(getattr(e, "_delegate", None))
    return out


def _close_delegate(outer) -> None:
    """Close a fused exec's open()-time fallback delegate and preserve
    the feedback truth its subtree learned: the delegate's own
    host-known output count folds onto the OUTER exec's stats (they
    answer for the same plan node), and its annotated children's pairs
    park on _fb_build_pairs — plan feedback harvests after the tree is
    closed, when only the outer exec remains."""
    d, outer._delegate = outer._delegate, None
    if d is None:
        return
    d.close()  # first: nested fused execs fold their own delegates
    # EXPLAIN ANALYZE renders AFTER the tree is closed, so keep the
    # closed delegate reachable — analyze_text walks _fallback_taken to
    # show the classic subtree that actually ran under a [classic] node
    outer._fallback_taken = d
    st = getattr(d, "stats", None)
    if st is not None and st.out_rows >= 0:
        outer.stats.add_out_rows(st.out_rows)
    outer._fb_build_pairs = (tuple(outer._fb_build_pairs)
                             + tuple(_collect_feedback_pairs(d)))


class FusedScanAggExec(_StagedScanMixin, HashAggExec):
    """HashAgg whose child is a fusible scan pipeline, executed as a
    push-based device-resident fragment: staged inputs stream through
    ONE jitted program per chunk and the aggregation state never visits
    the host until finalize. Falls back to the classic pull-based tree
    (``fallback_build``) when the context disables fusion or the
    aggregate shape needs the host paths (DISTINCT, non-core funcs,
    ``tidb_enable_tpu_exec`` off for generic strategy)."""

    def __init__(self, schema, scan_schema, table, stages, prune_bounds,
                 group_exprs, group_uids, aggs, strategy,
                 segment_sizes=None, fallback_build=None):
        super().__init__(schema, None, group_exprs, group_uids, aggs,
                         strategy, segment_sizes=segment_sizes)
        self.children = []
        self.scan_schema = scan_schema
        self.table = table
        self.scan_stages = stages
        self.prune_bounds = prune_bounds
        self._fallback_build = fallback_build
        self._delegate = None
        self._ran_fused = False
        self._fb_build_pairs = ()
        self._pin = None
        self._prefetcher = None
        self._seg_cap = None

    # -- lifecycle ---------------------------------------------------------

    def open(self, ctx: ExecContext) -> None:
        self.ctx = ctx
        self._out = []
        self._emitted = False
        self._delegate = None
        if not self._fuse_eligible(ctx):
            self._ran_fused = False
            d = self._fallback_build()
            d.open(ctx)
            self._delegate = d
            return
        self._ran_fused = True
        try:
            if self.strategy == "segment":
                self._run_segment_fused()
            else:
                self._run_generic_fused()
        finally:
            self._release_staging()

    def next(self):
        if self._delegate is not None:
            return self._delegate.next()
        return super().next()

    def close(self) -> None:
        _close_delegate(self)
        self._release_staging()
        super().close()

    def _fuse_eligible(self, ctx: ExecContext) -> bool:
        if not getattr(ctx, "pipeline_fuse", True) or self.table is None:
            return False
        if self.strategy == "segment":
            return True
        from tidb_tpu.planner.logical import core_generic_agg

        return ctx.device_agg and core_generic_agg(self.group_exprs,
                                                   self.aggs)

    # -- fused execution ---------------------------------------------------

    def _run_segment_fused(self):
        from tidb_tpu.ops.segment_scan import segment_scan_key

        ctx = self.ctx
        domains = [s + 1 for s in (self.segment_sizes or [])]
        jobs = self._plan_staging(ctx)
        col_types = [(c.uid, c.type_) for c in self.scan_schema]
        stages, seg_cap = self.scan_stages, self._seg_cap
        key = ("seg|" + segment_scan_key(stages, col_types, seg_cap)
               + "|" + repr((self.group_exprs, self.aggs, domains)))
        fused = cached_jit(
            "fusedagg", key,
            lambda: _make_fused_segment_fn(stages, col_types,
                                           self.group_exprs, self.aggs,
                                           domains, seg_cap),
            donate_argnums=0)
        init_state, _u, _g = make_segment_kernel(
            self.group_exprs, self.aggs, domains)
        state = init_state()
        for staged in self._staged_chunks(jobs):
            # KILL/deadline polls BETWEEN device steps: the fusion must
            # not turn a chunked fragment into an uninterruptible run
            raise_if_cancelled(ctx)
            state = fused(state, *staged)
        self._finalize_segment_state(state, domains)

    def _run_generic_fused(self):
        from tidb_tpu.executor.agg_device import GroupTableStack
        from tidb_tpu.ops.segment_scan import segment_scan_key

        ctx = self.ctx
        jobs = self._plan_staging(ctx)
        col_types = [(c.uid, c.type_) for c in self.scan_schema]
        stages, seg_cap = self.scan_stages, self._seg_cap
        sig = repr((self.group_exprs, self.aggs))
        key = ("gen|" + segment_scan_key(stages, col_types, seg_cap)
               + "|" + sig)
        fused = cached_jit(
            "fusedagg", key,
            lambda: _make_fused_generic_fn(stages, col_types,
                                           self.group_exprs, self.aggs,
                                           seg_cap))
        stack = GroupTableStack(len(self.group_exprs), self.aggs, sig)
        for staged in self._staged_chunks(jobs):
            raise_if_cancelled(ctx)  # see _run_segment_fused
            stack.push(fused(*staged))
        self._finalize_group_tables(stack.tables())


# ---------------------------------------------------------------------------
# fused scan→probe programs (ISSUE 10: fusion past aggregation roots)
# ---------------------------------------------------------------------------


def _make_fused_probe_fn(stages, col_types, key_irs, modes, probe_uids,
                         direct: bool, probe: str, left: bool,
                         seg_cap: Optional[int]):
    """(staged scan inputs, build arrays) -> (first output tile, totals,
    probe state): decode + filter + project + key pack + probe range
    lookup + count + prefix sum + first-tile expansion as ONE program.

    The expansion emits a single FIXED-capacity tile (the chunk's own
    capacity) inside the same dispatch — for the workhorse PK-FK shape
    (Q18's lineitem→orders) every probe row matches at most once, so the
    whole chunk's output fits and the chunk completes in ONE device
    round trip. The on-device ``total`` doubles as the overflow flag:
    the caller's batched window fetch reads it, and only chunks whose
    expansion overflowed the in-program tile pay classic ``expand_tiles``
    dispatches for the remainder. The probe's range lookup runs through
    ``probe_ranges_any`` — the SAME traced step as the standalone
    probe kernel (direct-address index / open-addressing table /
    searchsorted), so the fused and classic paths cannot drift.

    ISSUE 18 widens the shape: composite keys pack through the SAME
    ``jk.pack_keys`` range packer as the standalone probe (the traced
    pack ranges arrive as args), and LEFT OUTER pads every live
    unmatched probe row with one NULL-build-payload slot in-program —
    ``real_count`` rides the deferral token so the overflow
    re-expansion masks the pad slots identically."""
    from tidb_tpu.expression.compiler import eval_expr
    from tidb_tpu.ops.segment_scan import make_segment_scan_fn

    scan_fn = make_segment_scan_fn(stages, col_types, seg_stride=seg_cap)

    def run(data, valid, refs, sel, sorted_keys, n_build, firsts,
            lo_packed, rng_packed, tkeys, tlos, this, tok,
            los, strides, rngs, b_datas, b_valids):
        ch = _barrier_chunk(scan_fn(data, valid, refs, sel))
        kds, kvs = [], []
        for ir in key_irs:
            kd, kv = eval_expr(ir, ch)
            kds.append(kd)
            kvs.append(kv)
        packed, kvalid, pack_ok = jk.pack_keys(
            kds, kvs, los, strides, rngs, ch.sel, modes, False)
        ok = kvalid & ch.sel
        start, end, range_ok = jk.probe_ranges_any(
            sorted_keys, n_build, packed, firsts, lo_packed, rng_packed,
            tkeys, tlos, this, tok, direct, probe)
        in_range = pack_ok & range_ok
        count = jnp.where(ok & in_range, end - start, 0)
        real_count = count
        if left:
            # unfiltered LEFT JOIN: every live probe row emits >= 1
            # slot; the pad slot carries NULL build payload (the
            # classic probe's left_pad arithmetic, traced here)
            count = jnp.where(ch.sel, jnp.maximum(count, 1), 0)
        cum = jnp.cumsum(count)
        total = cum[-1]
        R = packed.shape[0]
        B = sorted_keys.shape[0]
        valid_out, probe_row, build_pos, k = jk.tile_positions(
            start, count, cum, 0, R, R, B)
        p_cols = tuple((ch.columns[u].data, ch.columns[u].valid)
                       for u in probe_uids)
        out_p = tuple((jnp.take(d, probe_row, mode="clip"),
                       jnp.take(v, probe_row, mode="clip") & valid_out)
                      for d, v in p_cols)
        bmask = valid_out
        if left:
            bmask = bmask & (k < jnp.take(real_count, probe_row,
                                          mode="clip"))
        out_b = tuple((jnp.take(d, build_pos, mode="clip"),
                       jnp.take(v, build_pos, mode="clip") & bmask)
                      for d, v in zip(b_datas, b_valids))
        return (out_p, out_b, valid_out, total, start, count, real_count,
                cum, p_cols)

    return run


class FusedScanProbeExec(_StagedScanMixin, HashJoinExec):
    """Inner or LEFT OUTER hash join (single- or composite-key, ISSUE
    18) whose probe side is a plain scan pipeline, run
    as a push-based device fragment (ISSUE 10): each staged probe chunk
    streams through ONE jitted scan→probe→expand program against a
    device-resident build table, cutting the classic tree's per-chunk
    scan dispatch + probe dispatch + expand dispatch(es) to a single
    round trip for the PK-FK shape. Per-chunk match totals stay on
    device and resolve in one batched fetch per deferral window
    (PROBE_SYNC_CHUNKS), exactly like the classic probe's deferral —
    the fused path adds no per-chunk host syncs.

    The build side runs the classic ``HashJoinExec`` build (drain +
    pack + sort + direct/hash index) and — when the build child is
    itself a plain scan over a stored table — parks the finished device
    arrays in the cross-statement ``DeviceBufferCache`` keyed by the
    build plan's shape and proven current by ``table_ident``, so a warm
    repeated join stages and sorts NOTHING. Ineligible contexts
    (fusion/device engine off) fall back to the classic tree through
    the open()-time ``fallback_build`` delegate, like
    ``FusedScanAggExec``."""

    def __init__(self, schema, scan_schema, table, stages, prune_bounds,
                 probe_schema, probe_keys, build_keys, build_schema,
                 build_child_build, build_table=None, build_tag=None,
                 kind="inner", fallback_build=None):
        Executor.__init__(self, schema, [])
        self.kind = kind
        self.probe_keys = probe_keys
        self.build_keys = build_keys
        self.other_cond = None
        self.probe_schema = probe_schema
        self.build_schema = build_schema
        self.exists_sem = False
        self.scan_schema = scan_schema
        self.table = table
        self.scan_stages = stages
        self.prune_bounds = prune_bounds
        self._build_child_build = build_child_build
        self._build_cache_table = build_table
        self._build_cache_tag = build_tag
        self._fallback_build = fallback_build
        self._delegate = None
        self._ran_fused = False
        self._fb_build_pairs = ()
        self._pin = None
        self._prefetcher = None
        self._staged_iter = None
        self._seg_cap = None

    # -- lifecycle ---------------------------------------------------------

    def open(self, ctx: ExecContext) -> None:
        self.ctx = ctx
        self._delegate = None
        self._pending: List[Chunk] = []
        self._drained = False
        if not self._fuse_eligible(ctx):
            self._ran_fused = False
            d = self._fallback_build()
            d.open(ctx)
            self._delegate = d
            return
        self._ran_fused = True
        try:
            self._open_build(ctx)
            if self._hash_mode:
                # composite-key ranges overflowed int64 range packing
                # (data-dependent, known only after the build drain):
                # hash candidates need the classic probe's exact per-key
                # re-verification after expansion, so keep the classic
                # tree — its feedback pairs were parked by _open_build
                self._ran_fused = False
                d = self._fallback_build()
                d.open(ctx)
                self._delegate = d
                return
            jobs = self._plan_staging(ctx)
            self._fused_fn = self._make_fused()
            self._staged_iter = self._staged_chunks(jobs)
        except BaseException:
            self._release_staging()
            raise

    def next(self) -> Optional[Chunk]:
        if self._delegate is not None:
            return self._delegate.next()
        while True:
            if self._pending:
                return self._pending.pop(0)
            if self._drained:
                return None
            self._fill_pending_fused()

    def close(self) -> None:
        _close_delegate(self)
        self._release_staging()
        super().close()  # releases the build side's tracked bytes

    def _fuse_eligible(self, ctx: ExecContext) -> bool:
        if not getattr(ctx, "pipeline_fuse", True) or self.table is None:
            return False
        # the fused program is a device fragment: host-engine routing
        # (device_agg off) keeps the classic tree and its numpy probe
        return bool(getattr(ctx, "device_agg", True))

    # -- build side (classic build + cross-statement device cache) ---------

    # everything a warm statement needs to probe without re-draining the
    # build child: the staged device arrays AND the host-side pack/index
    # decisions derived from the drained build data
    _BUILD_STATE_FIELDS = (
        "_sorted_keys", "_n_build_dev", "_firsts", "_build_payload",
        "_build_keyvals_dev", "_payload_uids", "_pack_info", "_hash_mode",
        "_modes", "_los", "_strides", "_rngs", "_direct", "_direct_lo",
        "_direct_rng", "_n_build", "_build_had_null", "_has_filter",
        "_probe_mode", "_probe_table", "_build_bytes")

    def _open_build(self, ctx: ExecContext) -> None:
        from tidb_tpu.utils.metrics import JOIN_BUILD_SECONDS

        from tidb_tpu.ops import hash_probe as hp

        budget = int(getattr(ctx, "device_buffer_cache_bytes", 0) or 0)
        bt = self._build_cache_table
        cacheable = (budget > 0 and bt is not None
                     and ctx.read_ts is None and ctx.txn_marker == 0)
        tag = ident = None
        if cacheable:
            # the RESOLVED probe mode joins the tag: the parked state
            # bakes in the mode's table/index decision, and a knob
            # change must mint a fresh build, not serve a stale one
            tag = ("joinbuild", self._build_cache_tag,
                   hp.resolve_mode(getattr(ctx, "join_probe_mode", "off")))
            ident = table_ident(bt)
            hit = DEVICE_CACHE.get(bt, tag, ident)
            if hit is not None:
                t0 = time.perf_counter()
                self._restore_build(hit[0])
                self.stats.staged += 1
                JOIN_BUILD_SECONDS.observe(time.perf_counter() - t0,
                                           tier="cached")
                return
        child = self._build_child_build()
        child.open(ctx)
        self.children = [None, child]
        try:
            self._build()  # HashJoinExec._build: drains children[1]
        finally:
            child.close()
            self.children = []
            # the transient build subtree is gone after this open();
            # park its host-known actuals for the feedback harvest
            self._fb_build_pairs = _collect_feedback_pairs(child)
        if cacheable:
            # ownership of the resident arrays transfers to the process
            # cache; the statement keeps its charge until close() like
            # any other build (the _staged_chunks fill pattern)
            DEVICE_CACHE.put(bt, tag, ident, [self._snapshot_build()],
                             self._build_bytes, budget)

    def _snapshot_build(self) -> dict:
        return {f: getattr(self, f) for f in self._BUILD_STATE_FIELDS}

    def _restore_build(self, state: dict) -> None:
        for f, v in state.items():
            setattr(self, f, v)
        self._sorted_keys_np = None
        self._build_payload_np = {}
        self._build_schema_by_uid = {c.uid: c
                                     for c in (self.build_schema or [])}
        # the resident bytes are owned (and budgeted) by the process
        # cache on a hit — close() must not release them
        self._build_bytes = 0

    # -- fused probe loop --------------------------------------------------

    def _make_fused(self):
        from tidb_tpu.ops.segment_scan import segment_scan_key

        col_types = [(c.uid, c.type_) for c in self.scan_schema]
        probe_uids = tuple(c.uid for c in self.probe_schema)
        stages, seg_cap = self.scan_stages, self._seg_cap
        probe = "sorted" if self._probe_table is None else self._probe_mode
        self._fused_probe_label = "direct" if self._direct else probe
        # per-statement invariants, hoisted off the per-chunk hot loop:
        # the direct-domain device scalars and the payload arg tuples
        # are fixed once the build completes
        self._direct_lo_dev = jnp.asarray(self._direct_lo, dtype=jnp.int64)
        self._direct_rng_dev = jnp.asarray(self._direct_rng,
                                           dtype=jnp.int64)
        self._table_args = (self._probe_table
                            if self._probe_table is not None
                            else jk.no_table())
        self._b_datas = tuple(self._build_payload[u][0]
                              for u in self._payload_uids)
        self._b_valids = tuple(self._build_payload[u][1]
                               for u in self._payload_uids)
        key = ("probe|" + segment_scan_key(stages, col_types, seg_cap)
               + "|" + repr((self.probe_keys, self._modes, self._direct,
                             probe, probe_uids, self.kind,
                             tuple(self._payload_uids))))
        return cached_jit(
            "fusedprobe", key,
            lambda: _make_fused_probe_fn(
                stages, col_types, tuple(self.probe_keys),
                tuple(self._modes), probe_uids, self._direct, probe,
                self.kind == "left", seg_cap))

    def _fill_pending_fused(self) -> None:
        """Pull staged probe chunks until output lands in _pending or
        the scan drains. Every chunk's match total stays a device scalar
        inside its deferral token; ONE batched device_get per window
        resolves the whole window — the fused fragment syncs
        O(chunks / window), the same budget as the classic probe."""
        deferred: List[dict] = []
        dbytes = 0
        while not self._pending and not self._drained:
            raise_if_cancelled(self.ctx)
            staged = next(self._staged_iter, None)
            if staged is None:
                self._drained = True
                break
            tok = self._probe_chunk_fused(staged)
            deferred.append(tok)
            dbytes += tok["nbytes"]
            if (len(deferred) >= self.PROBE_SYNC_CHUNKS
                    or dbytes >= self.PROBE_DEFER_BYTES):
                self._finish_fused_batch(deferred)
                deferred = []
                dbytes = 0
        if deferred:
            self._finish_fused_batch(deferred)

    def _probe_chunk_fused(self, staged) -> dict:
        """Launch the fused scan→probe→expand program for one staged
        chunk; returns the deferral token pinning its device results."""
        from tidb_tpu.utils.metrics import JOIN_PROBE_MODE_TOTAL

        t0 = time.perf_counter()
        JOIN_PROBE_MODE_TOTAL.inc(mode="fused_" + self._fused_probe_label)
        data, valid, refs, sel = staged
        (out_p, out_b, sel_tile, total_dev, start, count, real_count,
         cum, p_cols) = \
            self._fused_fn(data, valid, refs, sel, self._sorted_keys,
                           self._n_build_dev, self._firsts,
                           self._direct_lo_dev, self._direct_rng_dev,
                           *self._table_args, self._los, self._strides,
                           self._rngs, self._b_datas, self._b_valids)
        tok = {"out_p": out_p, "out_b": out_b, "sel_tile": sel_tile,
               "total_dev": total_dev, "start": start, "count": count,
               "real_count": real_count, "cum": cum, "p_cols": p_cols,
               "cap": int(sel_tile.shape[0]), "t0": t0}
        # the window pins the chunk's expanded tile AND the probe state
        # needed for a potential overflow re-expansion
        tok["nbytes"] = _pytree_nbytes(
            (out_p, out_b, sel_tile, start, count, real_count, cum,
             p_cols))
        return tok

    def _finish_fused_batch(self, tokens: List[dict]) -> None:
        from tidb_tpu.utils import dispatch as dsp
        from tidb_tpu.utils.metrics import JOIN_PROBE_SECONDS

        # THE intentional probe sync, batched: one fetch of the
        # accumulated per-chunk match totals per deferred window — the
        # totals double as overflow flags, and fused chunks whose
        # expansion fit their in-program tile need nothing further
        # (sanctioned device_get outside any loop — the chunk-loop
        # sync-budget pass watches the loop form)
        totals = dsp.record_fetch(
            jax.device_get([t["total_dev"] for t in tokens]))
        dsp.record(site="fetch")
        # plan feedback: the fused inner PK-FK shape's summed totals are
        # its exact output cardinality, and total vs tile capacity is
        # the overflow telemetry that sizes join_tiles next time —
        # all host-known from the fetch this window already pays
        self.stats.add_out_rows(int(sum(int(t) for t in totals)))
        for tok, total in zip(tokens, totals):
            self.stats.tile_chunks += 1
            if int(total) > tok["cap"]:
                self.stats.tile_overflows += 1
                need = -(-(int(total) - tok["cap"]) // tok["cap"])
                self.stats.tile_max_need = max(self.stats.tile_max_need,
                                               need)
        for tok, total in zip(tokens, totals):
            try:
                self._emit_fused(tok, int(total))
            finally:
                JOIN_PROBE_SECONDS.observe(time.perf_counter() - tok["t0"],
                                           kind="fused")

    def _emit_fused(self, tok: dict, total: int) -> None:
        """Complete one fused chunk with its host-known total: emit the
        in-program tile, then expand any overflow past the tile through
        the classic fixed-capacity tile dispatches."""
        if total == 0:
            return
        cap = tok["cap"]
        cols = {}
        for c, (d, v) in zip(self.probe_schema, tok["out_p"]):
            cols[c.uid] = Column(d, v, c.type_)
        for uid, (d, v) in zip(self._payload_uids, tok["out_b"]):
            cols[uid] = Column(d, v, self._build_schema_by_uid[uid].type_)
        self._pending.append(Chunk(cols, tok["sel_tile"]))
        self.stats.chunks += 1
        if total <= cap:
            return
        # dup-heavy overflow: slots [cap, total) expand through
        # expand_tiles against the SAME device arrays (start/count/cum
        # and the scan-produced probe columns are already resident)
        p_datas = tuple(d for d, _v in tok["p_cols"])
        p_valids = tuple(v for _d, v in tok["p_cols"])
        b_datas, b_valids = self._b_datas, self._b_valids
        max_tiles = max(1, getattr(self.ctx, "join_tiles", 8))
        w0 = cap
        while w0 < total:
            rem = -(-(total - w0) // cap)  # ceil-div: tiles still needed
            T = min(jk.shape_bucket(rem, floor=1), max_tiles)
            out_p, out_b, sel_t, _pr, _bp = jk.expand_tiles(
                tok["start"], tok["count"], tok["real_count"],
                tok["cum"], w0, p_datas, p_valids, b_datas, b_valids,
                n_tiles=T, tile_cap=cap,
                build_cap=self._sorted_keys.shape[0],
                left=self.kind == "left")
            for i in range(min(T, rem)):
                cols = {}
                for c, (d2, v2) in zip(self.probe_schema, out_p):
                    cols[c.uid] = Column(d2[i], v2[i], c.type_)
                for uid, (d2, v2) in zip(self._payload_uids, out_b):
                    cols[uid] = Column(d2[i], v2[i],
                                       self._build_schema_by_uid[uid].type_)
                self._pending.append(Chunk(cols, sel_t[i]))
                self.stats.chunks += 1
            w0 += T * cap


# ---------------------------------------------------------------------------
# fused scan→top-k programs (ISSUE 18: fusing the operator long tail)
# ---------------------------------------------------------------------------


def _make_fused_topn_fn(stages, col_types, sort_irs, descs, out_uids,
                        seg_cap: Optional[int]):
    """(state, staged scan inputs) -> state: decode + filter + project +
    per-chunk top-k merge as ONE program. The bounded top-k state (the
    C = shape_bucket(offset + count) current winners, ops/topk.py
    layout) is the only thing carried between chunks — exactly the
    fused aggregate's state contract, so the scan never materializes to
    host and the winners are fetched once at finalize."""
    from tidb_tpu.expression.compiler import eval_expr
    from tidb_tpu.ops import topk as tk
    from tidb_tpu.ops.segment_scan import make_segment_scan_fn

    scan_fn = make_segment_scan_fn(stages, col_types, seg_stride=seg_cap)

    def run(state, data, valid, refs, sel):
        ch = _barrier_chunk(scan_fn(data, valid, refs, sel))
        pairs = tuple(tk.rank_operands(*eval_expr(ir, ch), desc)
                      for ir, desc in zip(sort_irs, descs))
        payload = tuple((ch.columns[u].data, ch.columns[u].valid)
                        for u in out_uids)
        return tk.topk_merge(state, pairs, payload, ch.sel, descs)

    return run


class FusedScanTopNExec(_StagedScanMixin, Executor):
    """ORDER BY [+ LIMIT] root whose child is a plain scan pipeline,
    run as a push-based device fragment (ISSUE 18): each staged chunk
    streams through ONE jitted scan→top-k program that folds the
    chunk's rows into a bounded device state of the current
    ``offset + count`` winners; the host fetches the winners exactly
    once at finalize. The classic ``TopNExec`` pays one device_get per
    chunk (it materializes EVERY child row to host runs before
    ``np.lexsort`` keeps k of them) — here the full-table host round
    trip disappears and the sort work per chunk is one cheap
    single-array cut to C candidates (single sort key; ops/topk.py
    ``_cut_single_key``) or one ``lax.sort`` over C + chunk_capacity
    rows (multi-key).

    A full ORDER BY (no LIMIT) takes the same path under a capacity
    gate — when every live row fits the state (``table.n <= capacity``)
    the "top n" IS the complete sort; larger inputs keep the classic
    materializing sort via the open()-time ``fallback_build`` delegate.
    A LIMIT whose ``offset + count`` exceeds the gate falls back the
    same way and records the k-overflow on the exec (plan feedback
    harvests it, so the digest's SECOND execution routes to the classic
    plan up front instead of re-paying the fallback probe).

    Ordering is bit-exact with the classic path: ops/topk.py replicates
    ``_sort_order``'s null-rank/negation semantics and ties resolve by
    global drain position, the device analogue of np.lexsort stability.
    """

    def __init__(self, schema, scan_schema, table, stages, prune_bounds,
                 items, count, offset, full_sort=False,
                 fallback_build=None):
        Executor.__init__(self, schema, [])
        self.scan_schema = scan_schema
        self.table = table
        self.scan_stages = stages
        self.prune_bounds = prune_bounds
        self.items = items
        self.count = count
        self.offset = offset
        self.full_sort = full_sort
        self._fallback_build = fallback_build
        self._delegate = None
        self._ran_fused = False
        self._topn_overflow = 0
        self._fb_build_pairs = ()
        self._pin = None
        self._prefetcher = None
        self._seg_cap = None

    # -- lifecycle ---------------------------------------------------------

    def open(self, ctx: ExecContext) -> None:
        self.ctx = ctx
        self._chunks: List[Chunk] = []
        self._delegate = None
        self._topn_overflow = 0
        k, eligible = self._state_rows(ctx)
        if not eligible:
            self._ran_fused = False
            d = self._fallback_build()
            d.open(ctx)
            self._delegate = d
            return
        self._ran_fused = True
        try:
            self._run_fused(ctx, k)
        finally:
            self._release_staging()

    def next(self) -> Optional[Chunk]:
        if self._delegate is not None:
            return self._delegate.next()
        if self._chunks:
            return self._chunks.pop(0)
        return None

    def close(self) -> None:
        _close_delegate(self)
        self._release_staging()
        super().close()

    def _state_rows(self, ctx: ExecContext):
        """(k, fuse?) — k the live-row bound the device state must hold
        (offset + count, or the whole table for a full sort). The gate
        is the chunk capacity: the per-chunk merge sorts C + capacity
        rows, so a state larger than one chunk loses the asymptotic
        win over the classic path anyway. Overflow is recorded on the
        exec for the feedback harvest (satellite: a digest whose
        LIMIT + offset proved too big plans classic next time)."""
        # no device_agg gate: like the segment-strategy fused agg, the
        # top-k state program wins on every backend (it removes the
        # classic path's per-chunk host materialization), so host-engine
        # routing does not demote it
        if not getattr(ctx, "pipeline_fuse", True) or self.table is None:
            return 0, False
        if not getattr(ctx, "fused_topn", True):
            return 0, False  # plan feedback routed this digest classic
        if not self.items:
            return 0, False
        gate = int(ctx.chunk_capacity)
        if self.full_sort:
            k = int(self.table.n)
        else:
            k = int(self.count) + int(self.offset)
        if k > gate:
            self._topn_overflow = k
            return k, False
        return k, True

    # -- fused execution ---------------------------------------------------

    def _run_fused(self, ctx: ExecContext, k: int) -> None:
        from tidb_tpu.ops import topk as tk
        from tidb_tpu.ops.segment_scan import segment_scan_key
        from tidb_tpu.utils import dispatch as dsp

        jobs = self._plan_staging(ctx)
        col_types = [(c.uid, c.type_) for c in self.scan_schema]
        stages, seg_cap = self.scan_stages, self._seg_cap
        cap_state = jk.shape_bucket(k, floor=64)
        sort_irs = tuple(e for e, _ in self.items)
        descs = tuple(bool(d) for _, d in self.items)
        out_uids = tuple(c.uid for c in self.schema)
        key = ("topn|" + segment_scan_key(stages, col_types, seg_cap)
               + "|" + repr((self.items, out_uids, cap_state)))
        fused = cached_jit(
            "fusedtopk", key,
            lambda: _make_fused_topn_fn(stages, col_types, sort_irs,
                                        descs, out_uids, seg_cap),
            donate_argnums=0)
        key_floats = tuple(tk.key_spec(e.type_) for e in sort_irs)
        dtypes = tuple(c.type_.np_dtype for c in self.schema)
        state = tk.topk_init(cap_state, key_floats, dtypes)
        for staged in self._staged_chunks(jobs):
            # KILL/deadline polls BETWEEN device steps: the fusion must
            # not turn a chunked fragment into an uninterruptible run
            raise_if_cancelled(ctx)
            state = fused(state, *staged)
        # THE intentional top-k sync: ONE fetch of the C winners at
        # finalize, however many chunks streamed through (sanctioned
        # device_get outside any loop — the chunk-loop sync-budget pass
        # watches the loop form)
        dead, _ranks, _pos, _next, payload = state
        host = dsp.record_fetch(jax.device_get((dead, payload)))
        dsp.record(site="fetch")
        self._emit_winners(*host)

    def _emit_winners(self, dead, payload) -> None:
        """Slice [offset, offset + count) of the live winners (the
        state is already in final sort order — dead slots sort last)
        into capacity-sized output chunks."""
        n_live = int((np.asarray(dead) == 0).sum())
        lo = 0 if self.full_sort else min(int(self.offset), n_live)
        hi = n_live if self.full_sort else min(
            int(self.offset) + int(self.count), n_live)
        self.stats.add_out_rows(hi - lo)
        cap = self.ctx.chunk_capacity
        for s in range(lo, hi, cap):
            e = min(s + cap, hi)
            cols = {}
            for c, (d, v) in zip(self.schema, payload):
                cols[c.uid] = Column.from_numpy(
                    np.asarray(d)[s:e], c.type_,
                    valid=np.asarray(v)[s:e], capacity=cap)
            sel = np.zeros(cap, dtype=np.bool_)
            sel[:e - s] = True
            self._chunks.append(Chunk(cols, sel))
            self.stats.chunks += 1
