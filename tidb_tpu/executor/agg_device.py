"""Device-native generic hash aggregation: sort-based grouping.

The segment strategy (aggregate.py) needs a small dense key domain; this
module handles arbitrary / high-cardinality keys ON DEVICE (ref:
executor/aggregate.go HashAggExec's partial/final worker pipeline; the
TPU redesign is SURVEY.md §7.4's sort-based grouping). Hash tables
scatter poorly on TPU; `lax.sort` tiles well, so grouping is:

  per chunk:  multi-key sort (key bits + validity, dead rows last)
              -> segment boundaries (adjacent inequality) -> segment ids
              -> segment_sum / segment_min / segment_max partial states
              -> a dense "group table": slot i < n holds group i's key
              values and mergeable agg states, all [capacity]-shaped.

  across chunks: group tables merge pairwise on device (concat -> same
              sort-reduce over the state arrays) in a binary-counter
              schedule, so compile count is O(log chunks) and slot waste
              is bounded; all state stays device-resident until ONE
              batched fetch at finalize.

  finalize:   remaining level tables fetch in one device_get; the host
              converts them to the partial-state format aggregate.py
              already merges/emits (numpy path kept as oracle).

NULL-key semantics: a key is (bits, valid); valid participates in the
sort and in boundary detection, so NULL forms its own group. Float keys
group by bit pattern (same as the host path's int64 view — -0.0 and
NaN payloads are distinct groups, matching np.unique on bits).
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Tuple

import jax

# merge kernels donate their input tables (halves peak HBM on device);
# the CPU backend can't honor donation and warns once per compile
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")
import jax.numpy as jnp
import numpy as np

from tidb_tpu.chunk.chunk import Chunk
from tidb_tpu.expression.compiler import eval_expr
from tidb_tpu.planner.logical import AggSpec
from tidb_tpu.types import TypeKind
from tidb_tpu.utils.jitcache import cached_jit

__all__ = ["make_partial_kernel", "make_merge_kernel", "GroupTableStack",
           "table_to_host_partial"]


def _bits64(data: jax.Array, valid: jax.Array) -> jax.Array:
    """Group-identity bits: NULLs unify to 0, floats group by bit pattern."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        b = jax.lax.bitcast_convert_type(data.astype(jnp.float64), jnp.int64)
    else:
        b = data.astype(jnp.int64)
    return jnp.where(valid, b, 0)


def _group_hash(kbits: List[jax.Array], kvalids: List[jax.Array]) -> jax.Array:
    """One i64 ordering hash over all key components (validity folded in
    so a NULL key and a live 0 key land in different runs)."""
    h = jnp.zeros_like(kbits[0])
    for b, v in zip(kbits, kvalids):
        hb = b * np.int64(2) + v.astype(jnp.int64)
        h = (h ^ hb) * np.int64(-7046029254386353131) + np.int64(0x165667B19E3779F9)
    return h


def _sort_reduce(kbits: List[jax.Array], kvalids: List[jax.Array],
                 kdatas: List[jax.Array], live: jax.Array,
                 payload: List[jax.Array], reduce_ops: List[str],
                 exact: bool = False):
    """Shared core: sort rows by (dead, key identity), find segment
    boundaries, reduce payload arrays into dense per-group slots.

    Only (dead, order-key, iota) go through the sorting network; key
    values and payloads are gathered by the resulting permutation —
    lax.sort carries every operand through its whole comparison network,
    so this is ~(2+nk*3+npayload)/4 less data movement than sorting the
    carried arrays directly. Single-key inputs order by the exact key
    bits; multi-key inputs order by a mixed 64-bit hash with exact-key
    boundary detection, so a hash collision can only SPLIT a group into
    two partial slots (never merge two groups) — consumers dedup by
    exact key at finalize (host _merge_partials), keeping results exact.

    Returns (ngroups, rep_kdatas, rep_kvalids, reduced_payloads) — all
    slot arrays with groups dense in [0, ngroups)."""
    R = live.shape[0]
    dead = (~live).astype(jnp.int32)
    iota = jnp.arange(R, dtype=jnp.int32)
    if len(kbits) == 1:
        # exact: equal bits tie-break on validity (NULL run != live-0 run)
        out = jax.lax.sort(
            (dead, kbits[0], kvalids[0].astype(jnp.int32), iota), num_keys=3)
    elif exact:
        # hash first (cheap comparisons), exact bits as tie-breaks: equal
        # keys are guaranteed contiguous, so the output table can never
        # hold a collision-split duplicate — consumers may emit it
        # directly without a dedup pass. kvalids must join the tie-break:
        # _bits64 zeroes NULL bits, so a NULL key and a live 0 share bits
        # and differ only in validity — without it a hash collision could
        # interleave the two groups
        keys = ((dead, _group_hash(kbits, kvalids)) + tuple(kbits)
                + tuple(v.astype(jnp.int32) for v in kvalids) + (iota,))
        out = jax.lax.sort(keys, num_keys=len(keys) - 1)
    else:
        out = jax.lax.sort(
            (dead, _group_hash(kbits, kvalids), iota), num_keys=2)
    perm = out[-1]

    def take(a):
        return jnp.take(a, perm, axis=0)

    s_kbits = [take(b) for b in kbits]
    s_kdatas = [take(d) for d in kdatas]
    s_kvalids = [take(v) for v in kvalids]
    s_payload = [take(p) for p in payload]
    s_live = take(live)

    # live rows are a prefix (dead sorts last); a new segment starts at
    # row 0 or where any exact key component differs from the previous row
    idx = jnp.arange(R)
    diff = jnp.zeros(R, dtype=jnp.bool_)
    for b, v in zip(s_kbits, s_kvalids):
        diff = diff | (b != jnp.roll(b, 1)) | (v != jnp.roll(v, 1))
    newseg = s_live & ((idx == 0) | diff)
    seg = jnp.clip(jnp.cumsum(newseg.astype(jnp.int64)) - 1, 0, R - 1)
    ngroups = jnp.sum(newseg.astype(jnp.int64))

    # representative key values per group, scattered from boundary rows
    # only — dead rows share the last group's clipped seg id, and letting
    # them race the scatter would clobber that group's key with zeros
    tgt = jnp.where(newseg, seg, R)  # non-boundary rows drop out of bounds
    rep_kdatas = [jnp.zeros(R, dtype=d.dtype).at[tgt].set(d, mode="drop")
                  for d in s_kdatas]
    rep_kvalids = [jnp.zeros(R, dtype=jnp.bool_).at[tgt].set(v, mode="drop")
                   for v in s_kvalids]

    reduced = []
    for arr, op in zip(s_payload, reduce_ops):
        if op == "sum":
            contrib = jnp.where(s_live, arr, jnp.zeros((), dtype=arr.dtype))
            reduced.append(jax.ops.segment_sum(contrib, seg, num_segments=R))
        elif op == "min":
            reduced.append(jax.ops.segment_min(
                jnp.where(s_live, arr, jnp.full((), _ident_min(arr.dtype), arr.dtype)),
                seg, num_segments=R))
        elif op == "max":
            reduced.append(jax.ops.segment_max(
                jnp.where(s_live, arr, jnp.full((), _ident_max(arr.dtype), arr.dtype)),
                seg, num_segments=R))
        else:  # pragma: no cover
            raise ValueError(op)
    return ngroups, rep_kdatas, rep_kvalids, reduced


def _ident_min(dtype):
    dt = np.dtype(dtype)
    return np.inf if np.issubdtype(dt, np.floating) else np.iinfo(dt).max


def _ident_max(dtype):
    dt = np.dtype(dtype)
    return -np.inf if np.issubdtype(dt, np.floating) else np.iinfo(dt).min


def _state_layout(aggs: List[AggSpec]) -> List[Tuple[str, str]]:
    """Per-agg mergeable state arrays: [(name, merge op)]. Mirrors
    aggregate.py's partial-state dict keys (cnt/sum/min/max)."""
    from tidb_tpu.executor.aggregate import needs_sum_limbs

    layout = []
    for j, a in enumerate(aggs):
        layout.append((f"a{j}.cnt", "sum"))
        if a.func in ("sum", "avg"):
            layout.append((f"a{j}.sum", "sum"))
            if needs_sum_limbs(a):
                # two-limb exact decimal states: .sum = low 32-bit limb
                layout.append((f"a{j}.sumhi", "sum"))
        elif a.func == "min":
            layout.append((f"a{j}.min", "min"))
        elif a.func == "max":
            layout.append((f"a{j}.max", "max"))
    return layout


def make_partial_kernel(group_exprs, aggs: List[AggSpec]):
    """fn(chunk) -> group table dict {"n", "k{i}.d", "k{i}.v", state...}."""
    layout = _state_layout(aggs)

    def partial(chunk: Chunk):
        R = chunk.capacity
        sel = chunk.sel
        kdatas, kvalids, kbits = [], [], []
        for g in group_exprs:
            d, v = eval_expr(g, chunk)
            kdatas.append(d)
            kvalids.append(v)
            kbits.append(_bits64(d, v))

        payload, ops = [], []
        for j, a in enumerate(aggs):
            if a.arg is not None:
                d, v = eval_expr(a.arg, chunk)
                ok = sel & v
            else:  # count(*)
                d, ok = None, sel
            payload.append(ok.astype(jnp.int64))
            ops.append("sum")  # the .cnt slot
            if a.func in ("sum", "avg"):
                from tidb_tpu.executor.aggregate import (
                    needs_sum_limbs,
                    split_limbs,
                )

                dt = jnp.float64 if a.arg.type_.kind == TypeKind.FLOAT else jnp.int64
                contrib = jnp.where(ok, d, 0).astype(dt)
                if needs_sum_limbs(a):
                    clo, chi = split_limbs(contrib)
                    payload.append(clo)
                    ops.append("sum")
                    payload.append(chi)
                    ops.append("sum")
                else:
                    payload.append(contrib)
                    ops.append("sum")
            elif a.func == "min":
                dt = a.arg.type_.np_dtype
                payload.append(jnp.where(ok, d, _ident_min(dt)).astype(dt))
                ops.append("min")
            elif a.func == "max":
                dt = a.arg.type_.np_dtype
                payload.append(jnp.where(ok, d, _ident_max(dt)).astype(dt))
                ops.append("max")

        n, rk, rkv, red = _sort_reduce(kbits, kvalids, kdatas, sel, payload, ops)
        table = {"n": n}
        for i in range(len(group_exprs)):
            table[f"k{i}.d"] = rk[i]
            table[f"k{i}.v"] = rkv[i]
        for (name, _), arr in zip(layout, red):
            table[name] = arr
        return table

    return partial


def make_merge_kernel(nkeys: int, aggs: List[AggSpec]):
    """fn(tableA, tableB) -> merged table with len(A)+len(B) slots."""
    layout = _state_layout(aggs)

    def merge(ta, tb):
        def cat(name):
            return jnp.concatenate([ta[name], tb[name]])

        la = jnp.arange(ta[f"k0.d"].shape[0]) < ta["n"]
        lb = jnp.arange(tb[f"k0.d"].shape[0]) < tb["n"]
        live = jnp.concatenate([la, lb])
        kdatas = [cat(f"k{i}.d") for i in range(nkeys)]
        kvalids = [cat(f"k{i}.v") for i in range(nkeys)]
        kbits = [_bits64(d, v) for d, v in zip(kdatas, kvalids)]
        payload = [cat(name) for name, _ in layout]
        ops = [op for _, op in layout]
        n, rk, rkv, red = _sort_reduce(kbits, kvalids, kdatas, live, payload, ops)
        table = {"n": n}
        for i in range(nkeys):
            table[f"k{i}.d"] = rk[i]
            table[f"k{i}.v"] = rkv[i]
        for (name, _), arr in zip(layout, red):
            table[name] = arr
        _normalize_table_limbs(table, aggs)
        return table

    return merge


def _normalize_table_limbs(table, aggs: List[AggSpec]) -> None:
    """Carry-normalize every (lo, hi) limb pair in a group table, so lo
    stays in [0, 2^32) no matter how many merges stack (a group fed by
    2^31+ rows would otherwise wrap the lo accumulator — the segment
    kernel normalizes per chunk; merge trees must do it per level)."""
    from tidb_tpu.executor.aggregate import normalize_limbs

    for j, a in enumerate(aggs):
        if f"a{j}.sumhi" in table:
            lo, hi = normalize_limbs(table[f"a{j}.sum"],
                                     table[f"a{j}.sumhi"])
            table[f"a{j}.sum"] = lo
            table[f"a{j}.sumhi"] = hi


class GroupTableStack:
    """Binary-counter accumulation of device group tables.

    push() merges equal-sized tables immediately (level L holds one table
    of chunk_capacity * 2^L slots), so at most log2(chunks) tables are
    live and each merge kernel shape compiles once (the cached jit is
    shape-polymorphic; one cache entry retraces per level)."""

    def __init__(self, nkeys: int, aggs: List[AggSpec], cache_key: str):
        self._levels: List[object] = []
        # lint: disable=cache-key-completeness -- nkeys/aggs arrive
        # WITH their key: every caller passes cache_key =
        # repr((group_exprs, aggs)) — the repr of exactly the values
        # nkeys and aggs derive from — so the key names them even
        # though this scope cannot prove it
        self._merge = cached_jit(
            "aggmerge", cache_key, lambda: make_merge_kernel(nkeys, aggs),
            donate_argnums=(0, 1),
        )

    def push(self, table) -> None:
        level = 0
        while level < len(self._levels) and self._levels[level] is not None:
            table = self._merge(self._levels[level], table)
            self._levels[level] = None
            level += 1
        if level == len(self._levels):
            self._levels.append(None)
        self._levels[level] = table

    def tables(self) -> List[object]:
        return [t for t in self._levels if t is not None]


def table_to_host_partial(host_table: Dict[str, np.ndarray], nkeys: int,
                          aggs: List[AggSpec]) -> dict:
    """Convert a fetched group table into aggregate.py's partial-state
    format ({"mat", "keys", "kvalids", "states"}) so the existing host
    merge/emit path finalizes it."""
    n = int(host_table["n"])
    keys = [np.asarray(host_table[f"k{i}.d"][:n]) for i in range(nkeys)]
    kvalids = [np.asarray(host_table[f"k{i}.v"][:n]).astype(np.bool_)
               for i in range(nkeys)]

    def bits(k, kv):
        a = np.where(kv, k, 0)
        if np.issubdtype(a.dtype, np.floating):
            return a.astype(np.float64).view(np.int64)
        return a.astype(np.int64)

    mat = (np.stack([bits(k, kv) for k, kv in zip(keys, kvalids)]
                    + [kv.astype(np.int64) for kv in kvalids], axis=1)
           if nkeys else np.zeros((1, 0), dtype=np.int64))
    states = []
    for j, a in enumerate(aggs):
        st = {"cnt": np.asarray(host_table[f"a{j}.cnt"][:n])}
        if a.func in ("sum", "avg"):
            st["sum"] = np.asarray(host_table[f"a{j}.sum"][:n])
            if f"a{j}.sumhi" in host_table:
                st["sumhi"] = np.asarray(host_table[f"a{j}.sumhi"][:n])
        elif a.func in ("min", "max"):
            st[a.func] = np.asarray(host_table[f"a{j}.{a.func}"][:n])
        states.append(st)
    return {"mat": mat, "keys": keys, "kvalids": kvalids, "states": states}
